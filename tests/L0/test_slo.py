"""SLO-aware preemptive scheduling — priority classes, preempt-to-host
migration, deadline-aware admission (ISSUE 19), hermetic.

The acceptance bar, as tests:

- a preempted-then-resumed greedy request is **bitwise identical** to
  its uninterrupted run, across committed lengths below / at /
  straddling the chunk boundary, on the plain paged engine (resident
  COW retention) AND the host-tier engine (arena swap), at pipeline
  depth 0 and >= 1;
- N preempt/resume cycles on one request leak nothing: the
  :class:`~apex_tpu.serving.PoolAuditor` reconciles after every event,
  the host arena drains to zero records, and the stream stays bitwise;
- the full arrival-driven path: a high-priority arrival preempts
  exactly one strictly-lower victim (ties toward the newest submit),
  equal priority never preempts, and a decode whose committed stream
  outgrew the prefill re-ingest window is never a victim (it could not
  be resumed exactly);
- chaos (the satellite-1 bugfix): ``swap_corruption`` composed with
  preemption churn degrades the resume to a VERIFIED MISS — cold
  re-prefill of the committed stream, never a wrong token, never a
  leaked arena record; and a request rolled back WHILE preempted (the
  drain/quarantine path) clears its resume-ingest stream together with
  its outputs, so it re-enters as a fresh prompt instead of replaying
  a committed stream against a cleared output list (the silent
  wrong-token hazard);
- queue aging bounds starvation under a sustained high-priority flood;
- deadline-aware admission rejects unmeetable deadlines with a typed
  :class:`~apex_tpu.serving.DeadlineUnmeetable` (a ``QueueFull``
  subclass) carrying an honest EMA-derived ``retry_after_s``;
  accepted-then-blown deadlines are recorded honestly
  (``deadline_missed`` + per-class counters);
- tenant quotas cap concurrent slots per tenant (never below one) and
  the weighted-fair ledger admits the least-served tenant first;
- ``slo=None`` keeps the FIFO baseline verbatim: serving through it
  after heavy SLO/preemption churn compiles ZERO new programs and
  emits the identical token stream;
- ``SLOConfig`` pickles (it rides the fleet's wire frames);
  ``TenantLedger`` refuses loudly (process-local shared state).

Everything runs on CPU with a tiny model at policy O0 (exact fp32).
"""

import pickle
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (DeadlineUnmeetable, Engine, FaultPlan,
                              FaultSpec, PoolAuditor, QueueFull,
                              Request, RequestStatus, Scheduler,
                              SLOConfig, TenantLedger)

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

VOCAB = 101
CHUNK = 8
SLO = SLOConfig(classes={"batch": 0, "interactive": 10})


@pytest.fixture(scope="module")
def lm_and_params():
    m = TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                      num_heads=4, max_seq_len=64)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, pool=4, slots=2, seed=5, paged=True,
               **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=64, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=pool, paged=paged,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  **kw)


@pytest.fixture(scope="module")
def engine_pair(lm_and_params):
    """One host-tier engine + one plain paged engine, identical
    geometry (jit caches warm across the module)."""
    return (_mk_engine(lm_and_params, host_tier=1 << 24),
            _mk_engine(lm_and_params))


def _oracle(engine, prompt, n_new):
    """``prompt`` served alone, uninterrupted, retention off — the
    bitwise reference stream."""
    engine.reset(clear_prefixes=True)
    (r,) = Scheduler(engine).run([Request(prompt=list(prompt),
                                          max_new_tokens=n_new)])
    assert r.status == "finished"
    return list(r.output_tokens)


def _step_until(sched, pred, limit=3000):
    for _ in range(limit):
        if pred():
            return
        sched.step()
    raise AssertionError("scheduler never reached the expected state")


# ------------------------------------------------------- the pure policy
def test_slo_config_arithmetic_and_pickle():
    cfg = SLOConfig(classes={"batch": 0, "interactive": 10},
                    aging_s=0.5, tenant_weights={"a": 2.0},
                    tenant_max_share=0.5)
    assert pickle.loads(pickle.dumps(cfg)) == cfg     # rides the wire
    r = Request(prompt=[1], max_new_tokens=1, slo_class="interactive",
                priority=3)
    assert cfg.base_priority(r) == 13          # class base + own field
    assert cfg.base_priority(Request(prompt=[1], max_new_tokens=1)) == 0
    with pytest.raises(ValueError, match="unknown slo_class"):
        cfg.base_priority(Request(prompt=[1], max_new_tokens=1,
                                  slo_class="platinum"))
    # aging: +1 per full aging_s since the ORIGINAL submit
    b = Request(prompt=[1], max_new_tokens=1, slo_class="batch")
    b._t_submit = 100.0
    assert cfg.effective_priority(b, 100.4) == 0
    assert cfg.effective_priority(b, 101.7) == 3
    assert cfg.top_priority == 10


def test_tenant_ledger_wfq_and_pickle_refusal():
    led = TenantLedger({"heavy": 2.0, "zero": 0.0})
    assert led.weight("heavy") == 2.0
    assert led.weight("unknown") == 1.0
    assert led.weight("zero") == 1.0           # guard: never divide by 0
    led.charge("heavy", 100)
    led.charge("light", 50)
    assert led.virtual_served("heavy") == 50.0   # 100 / weight 2
    assert led.virtual_served("light") == 50.0   # same virtual service
    assert led.tokens_served("heavy") == 100
    snap = led.snapshot()
    assert snap["heavy"] == {"tokens": 100, "virtual": 50.0,
                             "weight": 2.0}
    with pytest.raises(TypeError, match="process-local"):
        pickle.dumps(led)


def test_scheduler_slo_validation(engine_pair):
    _, ep = engine_pair
    with pytest.raises(ValueError, match="chunked"):
        Scheduler(ep, chunked=False, slo=SLO)
    with pytest.raises(ValueError, match="retain_prefixes"):
        Scheduler(ep, retain_prefixes=False, slo=SLO)
    # priority-only scheduling works without preemption machinery
    Scheduler(ep, slo=SLOConfig(preempt=False))
    sched = Scheduler(ep, retain_prefixes=True, slo=SLO)
    with pytest.raises(ValueError, match="unknown slo_class"):
        sched.submit(Request(prompt=[1, 2], max_new_tokens=1,
                             slo_class="platinum"))


def test_preempt_requires_paged(lm_and_params):
    flat = _mk_engine(lm_and_params, paged=False, pool=2)
    with pytest.raises(ValueError, match="paged"):
        Scheduler(flat, retain_prefixes=True, slo=SLO)


# ------------------------------------------------ bitwise preempt/resume
@pytest.mark.parametrize("depth", [0, 1], ids=["sync", "pipelined"])
@pytest.mark.parametrize("tiered", [False, True],
                         ids=["paged", "host-tier"])
@pytest.mark.parametrize("n,k", [(5, 2), (11, 6), (11, 3)],
                         ids=["below-chunk", "at-chunk", "straddling"])
def test_preempt_resume_bitwise(engine_pair, tiered, depth, n, k):
    """The tentpole pin: preempt at a controlled committed length
    (below / at / straddling the chunk boundary), resume, and the
    greedy stream is IDENTICAL to the uninterrupted run — plain paged
    and host-tier, sync and dispatch-ahead."""
    engine = engine_pair[0] if tiered else engine_pair[1]
    rng = np.random.default_rng(3)
    prompt = [int(t) for t in rng.integers(1, VOCAB, size=n)]
    oracle = _oracle(engine, prompt, 12)

    committed = n + k - 1      # the last sampled token's K/V is pending
    if n == 5:
        assert committed < CHUNK
    elif k == 6:
        assert committed % CHUNK == 0
    else:
        assert committed > CHUNK and committed % CHUNK != 0

    engine.reset(clear_prefixes=True)
    reg = telemetry.MetricsRegistry()
    engine.set_registry(reg)
    aud = PoolAuditor(every_n=1)
    try:
        sched = Scheduler(engine, retain_prefixes=True, slo=SLO,
                          pipeline_depth=depth, registry=reg,
                          auditor=aud)
        r = Request(prompt=list(prompt), max_new_tokens=12,
                    slo_class="batch")
        sched.submit(r)
        _step_until(sched, lambda: len(r.output_tokens) == k
                    and r.status == "running")
        sched._preempt(sched._running.index(r))
        assert r.status is RequestStatus.PREEMPTED
        assert r.preemptions == 1
        assert len(r.output_tokens) == k       # committed work survives
        _step_until(sched, lambda: r.status.terminal)
        assert r.status == "finished"
        assert list(r.output_tokens) == oracle, \
            "preempt/resume drifted from the uninterrupted stream"
        counters = reg.snapshot()["counters"]
        assert counters.get("serving.preempt.preemptions") == 1
        assert counters.get("serving.preempt.resumes") == 1
        aud.audit(engine)
        if tiered:
            assert engine.host_tier.size == 0, "leaked arena record"
    finally:
        engine.set_registry(None)


def test_preempt_resume_churn_leak_free(engine_pair):
    """Satellite: N preempt/resume cycles on ONE request — audited
    after every event, zero leaked pages or arena records, and the
    stream still bitwise."""
    engine, _ = engine_pair
    rng = np.random.default_rng(9)
    prompt = [int(t) for t in rng.integers(1, VOCAB, size=6)]
    oracle = _oracle(engine, prompt, 12)

    engine.reset(clear_prefixes=True)
    reg = telemetry.MetricsRegistry()
    engine.set_registry(reg)
    aud = PoolAuditor(every_n=1)
    try:
        sched = Scheduler(engine, retain_prefixes=True, slo=SLO,
                          registry=reg, auditor=aud)
        r = Request(prompt=list(prompt), max_new_tokens=12,
                    slo_class="batch")
        sched.submit(r)
        for cycle, k in enumerate((2, 4, 6, 8), start=1):
            _step_until(sched, lambda: len(r.output_tokens) >= k
                        and r.status == "running")
            sched._preempt(sched._running.index(r))
            assert r.preemptions == cycle
        _step_until(sched, lambda: r.status.terminal)
        assert r.status == "finished"
        assert list(r.output_tokens) == oracle
        counters = reg.snapshot()["counters"]
        assert counters.get("serving.preempt.preemptions") == 4
        assert counters.get("serving.preempt.resumes") == 4
        aud.audit(engine)
        assert engine.host_tier.size == 0, \
            "a re-preempted request left a stale arena record behind"
    finally:
        engine.set_registry(None)


def test_arrival_driven_preemption_victim_order(engine_pair):
    """The full admission path: an interactive arrival finds both
    slots held by batch work and preempts EXACTLY ONE victim — the
    newest-submitted equal-priority one (least sunk wait) — and all
    three streams finish bitwise."""
    engine, _ = engine_pair
    rng = np.random.default_rng(7)
    prompts = [[int(t) for t in rng.integers(1, VOCAB, size=sz)]
               for sz in (11, 13, 9)]
    oracles = [_oracle(engine, p, 10) for p in prompts]

    engine.reset(clear_prefixes=True)
    reg = telemetry.MetricsRegistry()
    engine.set_registry(reg)
    try:
        sched = Scheduler(engine, retain_prefixes=True, slo=SLO,
                          registry=reg, auditor=PoolAuditor(every_n=1))
        b0 = Request(prompt=list(prompts[0]), max_new_tokens=10,
                     slo_class="batch")
        b1 = Request(prompt=list(prompts[1]), max_new_tokens=10,
                     slo_class="batch")
        hi = Request(prompt=list(prompts[2]), max_new_tokens=10,
                     slo_class="interactive")
        sched.submit(b0)
        sched.submit(b1)
        _step_until(sched, lambda: b0.status == "running"
                    and b1.status == "running"
                    and len(b1.output_tokens) >= 2)
        sched.submit(hi)
        sched.step()
        assert b1.preemptions == 1 and b0.preemptions == 0, \
            "the newest-submitted equal-priority victim must go"
        assert hi.status in ("prefilling", "running")
        _step_until(sched, lambda: all(r.status.terminal
                                       for r in (b0, b1, hi)))
        for r, want in zip((b0, b1, hi), oracles):
            assert list(r.output_tokens) == want
        assert reg.snapshot()["counters"].get(
            "serving.preempt.preemptions") == 1
        PoolAuditor().audit(engine)
    finally:
        engine.set_registry(None)


def test_deep_decode_is_not_preemptible(engine_pair):
    """The resumability window: once a victim's committed stream
    (prompt + outputs) outgrows prefill_len it cannot be re-ingested
    exactly, so preemption SKIPS it (and ``preemptible_pages`` stops
    counting it) — the arrival waits for a natural slot instead of
    corrupting a resume."""
    engine, _ = engine_pair
    rng = np.random.default_rng(11)
    deep = [[int(t) for t in rng.integers(1, VOCAB, size=20)]
            for _ in range(2)]

    engine.reset(clear_prefixes=True)
    sched = Scheduler(engine, retain_prefixes=True, slo=SLO)
    bs = [Request(prompt=list(p), max_new_tokens=10, slo_class="batch")
          for p in deep]
    for r in bs:
        sched.submit(r)
    # past the window: 20 prompt + 5 outputs = 25 > prefill_len=24
    _step_until(sched, lambda: all(r.status == "running"
                                   and len(r.output_tokens) >= 5
                                   for r in bs))
    assert sched.load_snapshot()["preemptible_pages"] == 0
    hi = Request(prompt=[1, 2, 3], max_new_tokens=4,
                 slo_class="interactive")
    sched.submit(hi)
    sched.step()
    assert all(r.preemptions == 0 for r in bs), \
        "a decode past the re-ingest window must never be preempted"
    assert hi.status == "queued"
    _step_until(sched, lambda: all(r.status.terminal
                                   for r in bs + [hi]))
    assert all(r.status == "finished" for r in bs + [hi])
    PoolAuditor().audit(engine)


def test_load_snapshot_slo_fields(engine_pair):
    """The v2 snapshot columns: None/None without an SLO config;
    with one, ``preemptible_pages`` counts below-top running pages
    inside the resumability window and ``oldest_deadline_s`` is the
    tightest RELATIVE remaining deadline."""
    engine, _ = engine_pair
    engine.reset(clear_prefixes=True)
    fifo = Scheduler(engine, retain_prefixes=True)
    snap = fifo.load_snapshot()
    assert snap["oldest_deadline_s"] is None
    assert snap["preemptible_pages"] is None

    engine.reset(clear_prefixes=True)
    sched = Scheduler(engine, retain_prefixes=True,
                      slo=SLOConfig(classes={"batch": 0,
                                             "interactive": 10},
                                    deadline_admission=False))
    snap = sched.load_snapshot()
    assert snap["oldest_deadline_s"] is None    # nothing live
    assert snap["preemptible_pages"] == 0       # paged, SLO on, idle
    r = Request(prompt=[1, 2, 3, 4, 5], max_new_tokens=8,
                slo_class="batch", deadline_s=30.0)
    sched.submit(r)
    _step_until(sched, lambda: r.status == "running")
    snap = sched.load_snapshot()
    assert snap["preemptible_pages"] >= 1       # its pages reclaimable
    assert 0 < snap["oldest_deadline_s"] <= 30.0
    _step_until(sched, lambda: r.status.terminal)


# --------------------------------------------------- deadline admission
def test_deadline_admission_rejects_with_honest_hint(engine_pair):
    engine, _ = engine_pair
    engine.reset(clear_prefixes=True)
    reg = telemetry.MetricsRegistry()
    sched = Scheduler(engine, retain_prefixes=True, slo=SLO,
                      registry=reg)
    # no EMA yet: the door cannot estimate, so it must admit
    ok = Request(prompt=[1, 2, 3], max_new_tokens=2, slo_class="batch",
                 deadline_s=1e-6)
    sched.submit(ok)
    _step_until(sched, lambda: ok.status.terminal)
    assert sched._step_s_ema is not None
    # saturate the queue so the estimate has positions ahead
    backlog = [Request(prompt=[int(t) for t in range(1, 9)],
                       max_new_tokens=8, slo_class="batch")
               for _ in range(4)]
    for r in backlog:
        sched.submit(r)
    ema, depth = sched._step_s_ema, len(sched._queue)
    tight = Request(prompt=[1, 2, 3, 4], max_new_tokens=8,
                    slo_class="interactive", deadline_s=1e-9)
    with pytest.raises(DeadlineUnmeetable) as ei:
        sched.submit(tight)
    assert isinstance(ei.value, QueueFull)      # rides the same channel
    # retry_after_s is rounded to microseconds before it rides the
    # exception (it is user-facing wire payload)
    assert ei.value.retry_after_s == pytest.approx(
        ema * max(1, depth), abs=5e-7)
    assert ei.value.retry_after_s > 0
    assert reg.snapshot()["counters"].get(
        "serving.slo.deadline_rejected") == 1
    # a meetable deadline admits
    sched.submit(Request(prompt=[1, 2], max_new_tokens=2,
                         slo_class="interactive", deadline_s=60.0))
    _step_until(sched, lambda: all(r.status.terminal for r in backlog))


def test_deadline_missed_verdict_is_honest(engine_pair):
    engine, _ = engine_pair
    engine.reset(clear_prefixes=True)
    reg = telemetry.MetricsRegistry()
    sched = Scheduler(engine, retain_prefixes=True, registry=reg,
                      slo=SLOConfig(classes={"batch": 0},
                                    deadline_admission=False))
    r = Request(prompt=[1, 2, 3], max_new_tokens=3, slo_class="batch",
                deadline_s=1e-9)
    sched.submit(r)
    _step_until(sched, lambda: r.status.terminal)
    assert r.status == "finished" and r.deadline_missed is True
    counters = reg.snapshot()["counters"]
    assert counters.get("serving.slo.deadline_missed") == 1
    assert counters.get("serving.slo.class.batch.deadline_missed") == 1
    assert counters.get("serving.slo.class.batch.completed") == 1


# ------------------------------------------------------ tenant fairness
def test_tenant_quota_caps_concurrency(engine_pair):
    engine, _ = engine_pair
    engine.reset(clear_prefixes=True)
    slo = SLOConfig(classes={"batch": 0}, tenant_max_share=0.5,
                    deadline_admission=False)
    sched = Scheduler(engine, retain_prefixes=True, slo=slo)
    a1 = Request(prompt=[1, 2, 3], max_new_tokens=8, slo_class="batch",
                 tenant="a")
    a2 = Request(prompt=[4, 5, 6], max_new_tokens=8, slo_class="batch",
                 tenant="a")
    b = Request(prompt=[7, 8, 9], max_new_tokens=8, slo_class="batch",
                tenant="b")
    for r in (a1, a2, b):                       # a2 submitted BEFORE b
        sched.submit(r)
    _step_until(sched, lambda: sum(q is not None
                                   for q in sched._running) == 2)
    held = {q.tenant for q in sched._running if q is not None}
    assert held == {"a", "b"}, \
        "the 0.5-share quota (1 of 2 slots) must hold tenant a to one"
    _step_until(sched, lambda: all(r.status.terminal
                                   for r in (a1, a2, b)))
    assert all(r.status == "finished" for r in (a1, a2, b))


def test_weighted_fair_admission_order(engine_pair):
    """Among equal-priority candidates the LEAST-served tenant admits
    first: pre-charging tenant a pushes its request behind tenant b's
    even though a's was submitted earlier."""
    engine, _ = engine_pair
    engine.reset(clear_prefixes=True)
    ledger = TenantLedger({"a": 2.0})
    ledger.charge("a", 1000)                   # virtual 500 owed-less
    slo = SLOConfig(classes={"batch": 0}, deadline_admission=False)
    sched = Scheduler(engine, retain_prefixes=True, slo=slo,
                      tenant_ledger=ledger)
    blockers = [Request(prompt=[1, 2, 3], max_new_tokens=4,
                        slo_class="batch"),
                Request(prompt=[4, 5, 6], max_new_tokens=12,
                        slo_class="batch")]
    for r in blockers:
        sched.submit(r)
    _step_until(sched, lambda: all(r.status == "running"
                                   for r in blockers))
    ra = Request(prompt=[7, 8], max_new_tokens=2, slo_class="batch",
                 tenant="a")
    rb = Request(prompt=[9, 10], max_new_tokens=2, slo_class="batch",
                 tenant="b")
    sched.submit(ra)                           # a first in FIFO order
    sched.submit(rb)
    _step_until(sched, lambda: ra.status != "queued"
                or rb.status != "queued")
    assert rb.status != "queued" and ra.status == "queued", \
        "WFQ must admit the owed-more tenant first, not FIFO"
    _step_until(sched, lambda: all(r.status.terminal
                                   for r in blockers + [ra, rb]))
    # finish-time charging reached the shared ledger, weighted
    assert ledger.tokens_served("b") == len(rb.output_tokens)
    assert ledger.virtual_served("b") == float(len(rb.output_tokens))
    assert ledger.tokens_served("a") == 1000 + len(ra.output_tokens)


# -------------------------------------------------------- aging (starvation)
def test_aging_bounds_starvation_under_flood(engine_pair):
    """A batch request under a sustained interactive flood: strict
    priority alone would starve it indefinitely (fresh priority-10
    arrivals always outrank priority 0); the aging boost (+1 per
    aging_s queued) lifts it past the flood and it finishes WHILE the
    flood is still arriving."""
    engine, _ = engine_pair
    engine.reset(clear_prefixes=True)
    slo = SLOConfig(classes={"batch": 0, "interactive": 10},
                    aging_s=0.02, deadline_admission=False)
    sched = Scheduler(engine, retain_prefixes=True, slo=slo,
                      max_queue=8)
    rng = np.random.default_rng(21)
    batch = Request(prompt=[int(t) for t in rng.integers(1, VOCAB,
                                                         size=6)],
                    max_new_tokens=4, slo_class="batch")
    sched.submit(batch)
    flood_done = 0
    live = []
    deadline = time.perf_counter() + 30.0
    while not batch.status.terminal:
        assert time.perf_counter() < deadline, \
            "batch request starved: aging never lifted it past the flood"
        while len(sched._queue) < 4:
            r = Request(prompt=[int(t) for t in rng.integers(
                1, VOCAB, size=4)], max_new_tokens=2,
                slo_class="interactive")
            sched.submit(r)
            live.append(r)
        sched.step()
        flood_done = sum(r.status.terminal for r in live)
    assert batch.status == "finished"
    assert flood_done >= 5, \
        "the flood never actually contended — the pin proves nothing"
    _step_until(sched, lambda: all(r.status.terminal for r in live),
                limit=20000)
    PoolAuditor().audit(engine)


# ----------------------------------------------------------------- chaos
def test_swap_corruption_during_preemption_chaos(engine_pair):
    """Satellite 1, half one: arena bytes corrupted while a request
    sits PREEMPTED make its resume a VERIFIED MISS — the committed
    stream re-prefills cold (never a wrong token), the corrupt record
    is dropped (never leaked), and the pool audits clean.

    The prompt fits one chunk so prefill registers NO resident prefix
    of its own — the preempt-export's arena record is the only thing
    that can back the resume, which is exactly what the corruption
    must hit (a longer prompt resumes warm off its resident prompt
    entry and the arena copy is released unused)."""
    engine, _ = engine_pair
    rng = np.random.default_rng(17)
    prompt = [int(t) for t in rng.integers(1, VOCAB, size=7)]
    oracle = _oracle(engine, prompt, 12)

    engine.reset(clear_prefixes=True)
    reg = telemetry.MetricsRegistry()
    engine.set_registry(reg)
    try:
        sched = Scheduler(engine, retain_prefixes=True, slo=SLO,
                          registry=reg, auditor=PoolAuditor(every_n=1))
        r = Request(prompt=list(prompt), max_new_tokens=12,
                    slo_class="batch")
        sched.submit(r)
        _step_until(sched, lambda: len(r.output_tokens) == 4
                    and r.status == "running")
        sched._preempt(sched._running.index(r))
        assert engine.host_tier.size == 1       # the export landed
        # let the async swap-out land before rotting the bytes — an
        # armed in-flight corruption resolves the same way, but the
        # resident path is the one the reference chaos test pins
        t0 = time.perf_counter()
        while engine.host_tier.pending_keys():
            time.sleep(0.001)
            assert time.perf_counter() - t0 < 10.0
        sched.fault_plan = FaultPlan(
            [FaultSpec(kind="swap_corruption", tick=sched._tick)])
        _step_until(sched, lambda: r.status.terminal)
        assert r.status == "finished"
        assert list(r.output_tokens) == oracle, \
            "a corrupt resume must re-prefill, never emit wrong tokens"
        counters = reg.snapshot()["counters"]
        assert counters.get("serving.preempt.resumes") == 1
        assert counters.get("serving.preempt.resume_reprefills") == 1
        assert counters.get("serving.swap.verify_failed") == 1
        assert sched.fault_plan.injected_swap_corruptions == 1
        assert engine.host_tier.size == 0, "leaked corrupt record"
        assert not engine.prefix_cache.swapped_keys()
        PoolAuditor().audit(engine)
    finally:
        engine.set_registry(None)


def test_rollback_while_preempted_clears_ingest_stream(engine_pair):
    """Satellite 1, half two (the bugfix pin): a request rolled back
    WHILE preempted (drain/quarantine) clears outputs AND the
    resume-ingest stream together — replaying the committed stream
    against a cleared output list would emit every token shifted. The
    re-serve is bitwise from the prompt, and the orphaned arena record
    is released, not leaked."""
    engine, _ = engine_pair
    rng = np.random.default_rng(23)
    prompt = [int(t) for t in rng.integers(1, VOCAB, size=9)]
    oracle = _oracle(engine, prompt, 10)

    engine.reset(clear_prefixes=True)
    sched = Scheduler(engine, retain_prefixes=True, slo=SLO)
    r = Request(prompt=list(prompt), max_new_tokens=10,
                slo_class="batch")
    sched.submit(r)
    _step_until(sched, lambda: len(r.output_tokens) == 3
                and r.status == "running")
    sched._preempt(sched._running.index(r))
    assert r._ingest_tokens == prompt + oracle[:3]
    assert engine.host_tier.size == 1

    (drained,) = sched.drain_requests()
    assert drained is r
    assert r.status is RequestStatus.QUEUED
    assert r.output_tokens == [] and r._ingest_tokens is None, \
        "the rollback must clear the resume stream WITH the outputs"
    assert engine.host_tier.size == 0, \
        "the drain must release the preempted request's arena record"
    # re-serve through the same scheduler: a fresh prompt, bitwise
    sched.submit(r)
    _step_until(sched, lambda: r.status.terminal)
    assert r.status == "finished"
    assert list(r.output_tokens) == oracle, \
        "the rolled-back resume replayed a stale committed stream"
    PoolAuditor().audit(engine)


# -------------------------------------------------- the FIFO baseline pin
def test_fifo_baseline_verbatim_zero_new_programs(lm_and_params):
    """``slo=None`` is the pre-SLO scheduler verbatim: after heavy
    SLO + preemption churn has exercised every new code path, a FIFO
    serve compiles ZERO new programs and emits the identical stream
    it did before the SLO machinery ever ran."""
    engine = _mk_engine(lm_and_params)
    rng = np.random.default_rng(29)
    prompts = [[int(t) for t in rng.integers(1, VOCAB, size=sz)]
               for sz in (11, 13, 9)]

    def _fifo_serve():
        engine.reset(clear_prefixes=True)
        reqs = [Request(prompt=list(p), max_new_tokens=8)
                for p in prompts]
        Scheduler(engine, retain_prefixes=True).run(reqs)
        return [list(r.output_tokens) for r in reqs]

    before = _fifo_serve()

    # SLO churn: arrival-driven preemption end to end
    engine.reset(clear_prefixes=True)
    sched = Scheduler(engine, retain_prefixes=True, slo=SLO)
    bs = [Request(prompt=list(p), max_new_tokens=8, slo_class="batch")
          for p in prompts[:2]]
    for r in bs:
        sched.submit(r)
    _step_until(sched, lambda: all(r.status == "running" for r in bs)
                and len(bs[1].output_tokens) >= 2)
    hi = Request(prompt=list(prompts[2]), max_new_tokens=8,
                 slo_class="interactive")
    sched.submit(hi)
    _step_until(sched, lambda: all(r.status.terminal
                                   for r in bs + [hi]))
    assert bs[1].preemptions == 1

    n_programs = engine.compiled_programs
    after = _fifo_serve()
    assert engine.compiled_programs == n_programs, \
        "the slo=None path must stay trace-identical (no new programs)"
    assert after == before, \
        "the FIFO baseline stream drifted after SLO churn"
