"""O1 per-op cast engine tests.

Reference: apex's tests/L0/run_amp/test_basic_casts.py + test_promotion.py
assert, op by op, that under O1 FP16_FUNCS outputs are half, FP32_FUNCS
outputs are fp32, and CASTS promote — and that O3 (pure half) disagrees.
Here the same matrix runs against the trace-time engine: policy tables
(amp/lists.py) consulted through amp.autocast by the fused modules.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp import autocast, lists

O1 = amp.resolve_policy("O1", verbose=False)
O3 = amp.resolve_policy("O3", verbose=False)


# ----------------------------------------------------------- table semantics
def test_op_dtype_o1_matrix():
    """Policy.op_dtype reproduces the lists classification under O1."""
    for op in ("matmul", "conv2d", "linear", "bmm", "einsum"):
        assert O1.op_dtype(op) == jnp.bfloat16, op
    for op in ("softmax", "log_softmax", "sum", "mean", "layer_norm",
               "batch_norm", "cross_entropy", "mse_loss", "exp", "pow"):
        assert O1.op_dtype(op) == jnp.float32, op
    # CASTS promote to widest floating operand (apex promote wrapper)
    assert O1.op_dtype("add", jnp.bfloat16, jnp.float32) == jnp.float32
    assert O1.op_dtype("add", jnp.bfloat16, jnp.bfloat16) == jnp.bfloat16
    assert O1.op_dtype("mul", jnp.float16, jnp.float32) == jnp.float32
    # unknown ops: no opinion
    assert O1.op_dtype("relu") is None


def test_op_dtype_only_o1_has_opinions():
    """O0/O2/O3 patch no functions (apex only installs wrappers for
    patch_torch_functions=True)."""
    for level in ("O0", "O2", "O3"):
        pol = amp.resolve_policy(level, verbose=False)
        assert pol.op_dtype("matmul") is None, level
        assert pol.op_dtype("softmax") is None, level
    disabled = amp.resolve_policy("O1", enabled=False, verbose=False)
    assert disabled.op_dtype("matmul") is None


def test_fp16_half_dtype_selectable():
    pol = amp.resolve_policy("O1", half_dtype=jnp.float16, verbose=False)
    assert pol.op_dtype("matmul") == jnp.float16


def test_lists_have_engine_consumers():
    """compute_dtype_for is consulted by Policy.op_dtype — the tables are
    live engine data, not documentation (VERDICT round-1 Missing #1)."""
    with autocast(O1):
        assert amp.op_compute_dtype("matmul") == jnp.bfloat16
        assert amp.op_compute_dtype("softmax") == jnp.float32
    assert amp.op_compute_dtype("matmul") is None  # context scoped


# -------------------------------------------------------- module-level casts
def test_mlp_runs_half_under_o1_fp32_otherwise():
    from apex_tpu.mlp import MLP

    m = MLP(mlp_sizes=[8, 8, 4])
    x = jnp.ones((2, 8), jnp.float32)
    v = m.init(jax.random.PRNGKey(0), x)
    assert m.apply(v, x).dtype == jnp.float32  # engine inert w/o context
    with autocast(O1):
        assert m.apply(v, x).dtype == jnp.bfloat16
    with autocast(O3):
        # O3 has no per-op opinion: dtype follows the (fp32) input
        assert m.apply(v, x).dtype == jnp.float32


def test_fused_dense_runs_half_under_o1():
    from apex_tpu.fused_dense import FusedDense, FusedDenseGeluDense

    x = jnp.ones((2, 8), jnp.float32)
    for mod in (FusedDense(8, 4), FusedDenseGeluDense(8, 16, 4)):
        v = mod.init(jax.random.PRNGKey(0), x)
        assert mod.apply(v, x).dtype == jnp.float32
        with autocast(O1):
            assert mod.apply(v, x).dtype == jnp.bfloat16


def test_layer_norm_lifts_to_fp32_under_o1():
    """apex O1 patches F.layer_norm into fp32: half input, fp32 output.
    O3 (no patching) keeps the half dtype — the defining O1 != O3 case."""
    from apex_tpu.normalization import FusedLayerNorm, FusedRMSNorm

    x = jnp.ones((2, 8), jnp.bfloat16)
    for mod in (FusedLayerNorm(normalized_shape=8),
                FusedRMSNorm(normalized_shape=8)):
        v = mod.init(jax.random.PRNGKey(0), x)
        assert mod.apply(v, x).dtype == jnp.bfloat16  # no context: follow x
        with autocast(O1):
            assert mod.apply(v, x).dtype == jnp.float32
        with autocast(O3):
            assert mod.apply(v, x).dtype == jnp.bfloat16
        # explicit dtype always wins over the table
        mod_explicit = type(mod)(normalized_shape=8, dtype=jnp.bfloat16)
        with autocast(O1):
            assert mod_explicit.apply(v, x).dtype == jnp.bfloat16


def test_sync_batchnorm_lifts_to_fp32_under_o1():
    from apex_tpu.parallel import SyncBatchNorm

    bn = SyncBatchNorm(use_running_average=False)
    x = jnp.ones((4, 3), jnp.bfloat16)
    v = bn.init(jax.random.PRNGKey(0), x)

    def run(x):
        y, _ = bn.apply(v, x, mutable=["batch_stats"])
        return y

    assert run(x).dtype == jnp.bfloat16
    with autocast(O1):
        assert run(x).dtype == jnp.float32
    with autocast(O3):
        assert run(x).dtype == jnp.bfloat16


def test_xentropy_loss_fp32_under_o1():
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 16), jnp.bfloat16)
    labels = jnp.array([1, 2, 3, 4])
    with autocast(O1):
        assert softmax_cross_entropy_loss(logits, labels).dtype == jnp.float32


# ----------------------------------------------------- model op-by-op matrix
def test_resnet_op_by_op_o1_vs_o3():
    """The apex test_basic_casts analogue on a real model: under O1 convs
    emit half and batch norms emit fp32; under an O3-style explicit half
    model both emit half."""
    from apex_tpu.models import create_model

    x = jnp.ones((1, 32, 32, 3), jnp.float32)

    model = create_model("resnet18", num_classes=10)  # dtype=None → engine
    v = model.init(jax.random.PRNGKey(0), x, train=False)
    with autocast(O1):
        _, inter = model.apply(v, x, train=False,
                               capture_intermediates=True)
    inter = inter["intermediates"]
    conv_out = inter["conv_init"]["__call__"][0]
    bn_out = inter["bn_init"]["__call__"][0]
    assert conv_out.dtype == jnp.bfloat16   # FP16_FUNCS conv2d
    assert bn_out.dtype == jnp.float32      # FP32_FUNCS batch_norm

    # O3: blanket half model (explicit dtype, engine has no say)
    model3 = create_model("resnet18", num_classes=10, dtype=jnp.bfloat16,
                          norm_dtype=jnp.bfloat16)
    v3 = model3.init(jax.random.PRNGKey(0), x, train=False)
    with autocast(O3):
        _, inter3 = model3.apply(v3, x, train=False,
                                 capture_intermediates=True)
    inter3 = inter3["intermediates"]
    assert inter3["conv_init"]["__call__"][0].dtype == jnp.bfloat16
    assert inter3["bn_init"]["__call__"][0].dtype == jnp.bfloat16  # != O1


def test_lm_layer_norm_fp32_under_o1():
    from apex_tpu.models import create_lm

    model = create_lm("tiny", vocab_size=64, max_seq_len=16)
    tokens = jnp.zeros((1, 16), jnp.int32)
    v = model.init(jax.random.PRNGKey(0), tokens, train=False)
    with autocast(O1):
        _, inter = model.apply(v, tokens, train=False,
                               capture_intermediates=True)
    inter = inter["intermediates"]
    blk = inter["block_0"]
    assert blk["ln_attn"]["__call__"][0].dtype == jnp.float32
    assert blk["attn"]["qkv"]["__call__"][0].dtype == jnp.bfloat16
    assert inter["ln_f"]["__call__"][0].dtype == jnp.float32


# ------------------------------------------------------------- train step
def test_make_train_step_installs_engine():
    """The step function itself activates the autocast scope: a policy-aware
    module inside loss_fn sees the tables with no user plumbing."""
    import optax
    from apex_tpu.normalization import FusedLayerNorm

    seen = {}
    ln = FusedLayerNorm(normalized_shape=4)

    def loss_fn(params, batch):
        y = ln.apply(params, batch)
        seen["ln_dtype"] = y.dtype
        return jnp.mean(jnp.square(jnp.asarray(y, jnp.float32)))

    x = jnp.ones((2, 4), jnp.float32)
    params = ln.init(jax.random.PRNGKey(0), x)
    init_fn, step_fn = amp.make_train_step(loss_fn, optax.sgd(0.1), O1)
    state = init_fn(params)
    state, metrics = step_fn(state, x)  # traced eagerly: seen is captured
    assert seen["ln_dtype"] == jnp.float32  # lifted despite bf16 batch cast
    assert np.isfinite(float(metrics["loss"]))


def test_promote_casts_entries():
    """cast_op_inputs promotes CASTS ops to the widest floating operand."""
    a = jnp.ones((2,), jnp.bfloat16)
    b = jnp.ones((2,), jnp.float32)
    with autocast(O1):
        ca, cb = amp.cast_op_inputs("add", a, b)
        assert ca.dtype == cb.dtype == jnp.float32
        # ints never participate (apex casts only floating tensors)
        ci, cf = amp.cast_op_inputs("mul", jnp.ones((2,), jnp.int32), a)
        assert ci.dtype == jnp.int32 and cf.dtype == jnp.bfloat16
    # outside the context: no-op
    na, nb = amp.cast_op_inputs("add", a, b)
    assert na.dtype == jnp.bfloat16 and nb.dtype == jnp.float32


def test_sequence_casts_table():
    assert "cat" in lists.SEQUENCE_CASTS and "stack" in lists.SEQUENCE_CASTS
    assert O1.op_dtype("stack", jnp.bfloat16, jnp.float32) == jnp.float32


def test_tensor_parallel_layers_consult_engine(eight_devices):
    """Column/RowParallelLinear run half under O1 when dtype=None, fp32
    otherwise — the Megatron path honors the same tables as the rest."""
    import functools
    from apex_tpu.utils.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.transformer.tensor_parallel import (ColumnParallelLinear,
                                                      RowParallelLinear)

    mesh = Mesh(np.array(eight_devices[:2]), ("model",))
    col = ColumnParallelLinear(input_size=8, output_size=16, world_size=2)
    row = RowParallelLinear(input_size=16, output_size=8, world_size=2,
                            input_is_parallel=True)
    x = jnp.ones((4, 8), jnp.float32)

    @functools.partial(shard_map, mesh=mesh, in_specs=P(),
                       out_specs=(P(), P()), check_vma=False)
    def run(x):
        cv = col.init(jax.random.PRNGKey(0), x)
        h = col.apply(cv, x)
        rv = row.init(jax.random.PRNGKey(1), h)
        return h, row.apply(rv, h)

    h0, y0 = run(x)
    assert h0.dtype == jnp.float32 and y0.dtype == jnp.float32
    with autocast(O1):
        h1, y1 = run(x)
    assert h1.dtype == jnp.bfloat16 and y1.dtype == jnp.bfloat16


def test_policy_model_dtype_property():
    """Recipes pass policy.model_dtype as the flax dtype: None under O1
    (per-op engine), the blanket compute dtype otherwise."""
    assert amp.resolve_policy("O1", verbose=False).model_dtype is None
    assert amp.resolve_policy("O0", verbose=False).model_dtype == jnp.float32
    assert amp.resolve_policy("O2", verbose=False).model_dtype == jnp.bfloat16
    assert amp.resolve_policy("O3", verbose=False).model_dtype == jnp.bfloat16
    off = amp.resolve_policy("O1", enabled=False, verbose=False)
    assert off.model_dtype == jnp.float32


def test_o1_fp16_overflow_skips_step():
    """O1 with half_dtype=fp16: an overflow in the half GEMM trips the
    dynamic scaler and freezes params+opt state (the engine composes with
    the scaler exactly like O2)."""
    import optax
    from apex_tpu.mlp import MLP

    m = MLP(mlp_sizes=[8, 8])
    policy = amp.resolve_policy("O1", half_dtype=jnp.float16, verbose=False)

    def loss_fn(params, batch):
        y = m.apply(params, batch)     # fp16 GEMM under the engine
        return jnp.mean(jnp.square(jnp.asarray(y, jnp.float32)))

    x_ok = jnp.ones((2, 8), jnp.float32)
    x_huge = jnp.full((2, 8), 1e30, jnp.float32)  # overflows in fp16
    params = m.init(jax.random.PRNGKey(0), x_ok)
    init_fn, step_fn = amp.make_train_step(loss_fn, optax.sgd(0.1), policy)
    state = init_fn(params)
    state2, metrics = jax.jit(step_fn)(state, x_huge)
    assert bool(metrics["found_inf"])
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(state2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # scale halved by the schedule
    assert float(state2.scaler.loss_scale) == \
        float(state.scaler.loss_scale) / 2


def test_contrib_mha_consults_engine():
    """Self/Encdec MultiheadAttn GEMMs run half under O1 when dtype=None;
    the pre-norm (include_norm_add) still lifts to fp32 internally."""
    from apex_tpu.contrib.multihead_attn import (EncdecMultiheadAttn,
                                                 SelfMultiheadAttn)

    x = jnp.ones((2, 3, 32), jnp.float32)  # [b, s, H]
    mha = SelfMultiheadAttn(embed_dim=32, num_heads=4, impl="default",
                            include_norm_add=True)
    v = mha.init(jax.random.PRNGKey(0), x, is_training=False)
    assert mha.apply(v, x, is_training=False).dtype == jnp.float32
    with autocast(O1):
        assert mha.apply(v, x, is_training=False).dtype == jnp.bfloat16

    enc = EncdecMultiheadAttn(embed_dim=32, num_heads=4, impl="default")
    ve = enc.init(jax.random.PRNGKey(1), x, x, is_training=False)
    assert enc.apply(ve, x, x, is_training=False).dtype == jnp.float32
    with autocast(O1):
        assert enc.apply(ve, x, x, is_training=False).dtype == jnp.bfloat16


def test_jit_cache_salted_by_ambient_policy():
    """ADVICE r2 #1, engineered (round 4): a USER-jitted policy-aware
    function traced under one ambient policy must not silently reuse its
    stale cast decisions under another — the active policy is part of the
    jit cache key, so re-entry re-traces. apex can't hit this (patches are
    re-applied at every amp.initialize); the trace-time engine must salt
    the cache instead."""
    traces = []

    @jax.jit
    def f(x, w):
        traces.append(1)  # trace-time side effect: counts retraces
        a, b = amp.cast_op_inputs("matmul", x, w)
        return a @ b

    x = jnp.ones((4, 8), jnp.float32)
    w = jnp.ones((8, 4), jnp.float32)

    with autocast(O1):
        assert f(x, w).dtype == jnp.bfloat16   # O1: matmul runs half
    # same jitted fn, no ambient policy: must re-trace and run fp32,
    # NOT reuse the O1 executable
    assert f(x, w).dtype == jnp.float32
    with autocast(O3):                          # O3 patches nothing
        assert f(x, w).dtype == jnp.float32
    with autocast(O1):                          # back to O1: cache hit
        assert f(x, w).dtype == jnp.bfloat16
    assert len(traces) == 3, (
        f"expected 3 traces (O1, none, O3; final O1 cached), got "
        f"{len(traces)}")
