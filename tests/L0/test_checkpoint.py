"""Checkpoint/resume tests.

Mirrors the reference's tests/L0/run_amp/test_checkpointing.py: train, save
(model + optimizer + amp scaler state), restore into a fresh setup, and
assert the resumed trajectory matches the uninterrupted one exactly —
including the loss-scale schedule position.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.optimizers import fused_adam
from apex_tpu.utils import (AsyncCheckpointer, latest_checkpoint,
                            load_checkpoint, save_checkpoint)


def _setup(policy):
    params = {"w": jnp.ones((8, 8)), "b": jnp.zeros((8,))}

    def loss_fn(p, batch):
        x, y = batch
        pred = x @ jnp.asarray(p["w"], x.dtype) + jnp.asarray(p["b"], x.dtype)
        return jnp.mean((jnp.asarray(pred, jnp.float32) - y) ** 2)

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_adam(1e-2), policy)
    return params, init_fn, jax.jit(step_fn)


def _batch(i):
    k = jax.random.PRNGKey(i)
    x = jax.random.normal(k, (4, 8))
    y = jax.random.normal(jax.random.fold_in(k, 1), (4, 8))
    return x, y


@pytest.mark.parametrize("opt_level", ["O0", "O2"])
def test_resume_reproduces_trajectory(tmp_path, opt_level):
    policy = amp.resolve_policy(opt_level=opt_level, loss_scale="dynamic")
    params, init_fn, jit_step = _setup(policy)

    # uninterrupted run: 6 steps
    state = init_fn(params)
    for i in range(6):
        state, m_full = jit_step(state, _batch(i))

    # interrupted: 3 steps, save, restore into a FRESH state, 3 more
    state2 = init_fn(params)
    for i in range(3):
        state2, _ = jit_step(state2, _batch(i))
    path = os.path.join(tmp_path, "ckpt_3.npz")
    save_checkpoint(path, state2, step=3, extra={"note": "mid"})

    fresh = init_fn(params)
    restored, step, extra = load_checkpoint(path, fresh)
    assert step == 3 and extra == {"note": "mid"}
    for i in range(3, 6):
        restored, m_res = jit_step(restored, _batch(i))

    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(m_full["loss"]) == float(m_res["loss"])


def test_scaler_state_survives_checkpoint(tmp_path):
    """The loss-scale position (incl. unskipped counter) must round-trip —
    apex serializes it via amp.state_dict (frontend.py — state_dict)."""
    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic")
    params, init_fn, jit_step = _setup(policy)
    state = init_fn(params)
    for i in range(4):
        state, _ = jit_step(state, _batch(i))
    path = os.path.join(tmp_path, "c.npz")
    save_checkpoint(path, state)
    restored, _, _ = load_checkpoint(path, init_fn(params))
    assert float(restored.scaler.loss_scale) == float(state.scaler.loss_scale)
    assert int(restored.scaler.unskipped) == int(state.scaler.unskipped)


def test_template_mismatch_rejected(tmp_path):
    policy = amp.resolve_policy(opt_level="O0", loss_scale=1.0)
    params, init_fn, jit_step = _setup(policy)
    state = init_fn(params)
    path = os.path.join(tmp_path, "c.npz")
    save_checkpoint(path, state)
    bad_params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    with pytest.raises(ValueError, match="shape"):
        load_checkpoint(path, init_fn(bad_params))
    with pytest.raises(ValueError, match="leaves"):
        load_checkpoint(path, {"just_w": jnp.ones((8, 8))})


def _rewrite_as_round1_blob(path, out_path, state):
    """Rewrite a checkpoint as the round-1 writer would have produced it:
    no ScalerState.hysteresis_left leaf, no "paths" in the metadata."""
    import json

    flat_p = jax.tree_util.tree_flatten_with_path(state)[0]
    drop = {i for i, (p, _) in enumerate(flat_p)
            if jax.tree_util.keystr(p).endswith("hysteresis_left")}
    assert drop, "state has no hysteresis_left leaf — test setup broken"
    with np.load(path) as data:
        meta = json.loads(bytes(data["__apex_tpu_meta__"].tolist())
                          .decode("utf-8"))
        arrays, dtypes, j = {}, [], 0
        for i in range(meta["n_leaves"]):
            if i in drop:
                continue
            arrays[f"leaf_{j}"] = data[f"leaf_{i}"]
            dtypes.append(meta["dtypes"][i])
            j += 1
    meta_old = {"step": meta["step"], "n_leaves": j, "dtypes": dtypes,
                "extra": meta["extra"]}
    arrays["__apex_tpu_meta__"] = np.frombuffer(
        json.dumps(meta_old).encode("utf-8"), dtype=np.uint8)
    with open(out_path, "wb") as f:
        np.savez(f, **arrays)


def test_round1_checkpoint_without_hysteresis_restores(tmp_path):
    """VERDICT round-2 missing #4: a checkpoint written before ScalerState
    gained hysteresis_left must restore (apex pattern: amp.state_dict
    round-trips across versions). The missing leaf keeps the template's
    fresh default; everything else restores exactly."""
    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic")
    params, init_fn, jit_step = _setup(policy)
    state = init_fn(params)
    for i in range(3):
        state, _ = jit_step(state, _batch(i))

    new_path = os.path.join(tmp_path, "new.npz")
    save_checkpoint(new_path, state, step=3)
    old_path = os.path.join(tmp_path, "round1.npz")
    _rewrite_as_round1_blob(new_path, old_path, state)

    fresh = init_fn(params)
    restored, step, _ = load_checkpoint(old_path, fresh)
    assert step == 3
    # migrated field: template default survives
    assert int(restored.scaler.hysteresis_left) == int(
        fresh.scaler.hysteresis_left)
    # every other leaf restored from the blob
    assert float(restored.scaler.loss_scale) == float(state.scaler.loss_scale)
    assert int(restored.scaler.unskipped) == int(state.scaler.unskipped)
    np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                  np.asarray(state.params["w"]))
    # and the resumed state steps normally
    restored, m = jit_step(restored, _batch(3))
    assert np.isfinite(float(m["loss"]))


def test_same_shape_renamed_template_rejected_by_paths(tmp_path):
    """A template with identical leaf count/shapes/dtypes but different key
    names is a configuration mismatch; the recorded key paths catch it."""
    path = os.path.join(tmp_path, "c.npz")
    save_checkpoint(path, {"alpha": jnp.ones((3, 3)), "beta": jnp.zeros((3,))})
    with pytest.raises(ValueError, match="paths do not match"):
        load_checkpoint(path, {"gamma": jnp.ones((3, 3)),
                               "delta": jnp.zeros((3,))})


def test_facade_state_dict_without_hysteresis_key():
    """LossScaler.load_state_dict accepts a round-1 dict (no hysteresis_left)."""
    s = amp.LossScaler("dynamic", hysteresis=2)
    s.load_state_dict({"loss_scale": 1024.0, "unskipped": 7})
    assert s.loss_scale() == 1024.0
    assert int(s._state.hysteresis_left) == 2  # refilled from config


def test_latest_checkpoint_and_async(tmp_path):
    ck = AsyncCheckpointer()
    tree = {"a": jnp.arange(4.0)}
    for step in (1, 5, 3):
        ck.save(os.path.join(tmp_path, f"ckpt_{step}.npz"), tree, step=step)
    ck.wait()
    path = latest_checkpoint(str(tmp_path))
    assert path.endswith("ckpt_5.npz")
    restored, step, _ = load_checkpoint(path, tree)
    assert step == 5
    np.testing.assert_array_equal(np.asarray(restored["a"]),
                                  np.arange(4.0))
    assert latest_checkpoint(str(tmp_path) + "/nope") is None


def test_path_field_parses_dict_and_index_segments():
    """ADVICE r3: keystr terminal segments come in three forms — ".attr"
    (GetAttrKey), "['key']" (DictKey), "[idx]" (SequenceKey) — and all
    must parse to the bare field name, or migratable fields under dict
    nodes are never detected."""
    from apex_tpu.utils.checkpoint import _path_field

    assert _path_field(".scaler.hysteresis_left") == "hysteresis_left"
    assert _path_field(".scaler['hysteresis_left']") == "hysteresis_left"
    assert _path_field('.scaler["hysteresis_left"]') == "hysteresis_left"
    assert _path_field("['opt']['hysteresis_left']") == "hysteresis_left"
    assert _path_field(".stack[3]") == "3"


def test_migration_detects_dict_keyed_field(tmp_path):
    """A migratable field living under a DICT node (keystr
    "…['hysteresis_left']") migrates the same way the dataclass-attribute
    form does — an old checkpoint without the leaf restores, the new
    field keeping the template's default."""
    old = {"w": jnp.arange(3.0), "extras": {"count": jnp.asarray(7)}}
    path = os.path.join(tmp_path, "old.npz")
    save_checkpoint(path, old, step=5)

    template = {"w": jnp.zeros(3), "extras": {
        "count": jnp.asarray(0), "hysteresis_left": jnp.asarray(2)}}
    restored, step, _ = load_checkpoint(path, template)
    assert step == 5
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(3.0))
    assert int(restored["extras"]["count"]) == 7
    assert int(restored["extras"]["hysteresis_left"]) == 2  # template fill


def test_abstract_template_restores_without_materializing(tmp_path):
    """jax.eval_shape output works as the load template (shapes/dtypes
    validated, nothing allocated) — unless migration needs real values,
    which raises a clear error."""
    tree = {"w": jnp.arange(6.0).reshape(2, 3),
            "n": jnp.asarray(4, jnp.int32)}
    path = os.path.join(tmp_path, "t.npz")
    save_checkpoint(path, tree, step=2)

    abstract = jax.eval_shape(lambda: tree)
    restored, step, _ = load_checkpoint(path, abstract)
    assert step == 2
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(tree["w"]))
    assert restored["n"].dtype == jnp.int32

    # migration + abstract template: refused with guidance
    template = {"w": jnp.zeros((2, 3)), "n": jnp.asarray(0, jnp.int32),
                "hysteresis_left": jnp.asarray(2)}
    abstract2 = jax.eval_shape(lambda: template)
    with pytest.raises(ValueError, match="real-valued template"):
        load_checkpoint(path, abstract2)


@pytest.mark.parametrize("typed", [False, True])
def test_train_checkpoint_rng_roundtrip(tmp_path, typed):
    """ADVICE r4: save/resume must handle BOTH rng representations — raw
    uint32 PRNGKey arrays and typed key arrays (jax.random.key) — and
    restore the one that was saved, not silently coerce."""
    from apex_tpu.utils.checkpoint import (resume_train_checkpoint,
                                           save_train_checkpoint)

    rng = jax.random.key(7) if typed else jax.random.PRNGKey(7)
    tree = {"w": jnp.arange(4.0)}
    path = os.path.join(tmp_path, "t.npz")
    save_train_checkpoint(path, tree, step=3, rng=rng)
    _, start, rng2 = resume_train_checkpoint(
        path, tree, jax.random.PRNGKey(0), step_limit=10,
        limit_flag="--iters")
    assert start == 3
    assert jnp.issubdtype(rng2.dtype, jax.dtypes.prng_key) == typed
    # the restored key drives the same stream
    a = jax.random.normal(jax.random.fold_in(rng, 1), (3,))
    b = jax.random.normal(jax.random.fold_in(rng2, 1), (3,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_train_checkpoint_rng_preserves_key_impl(tmp_path):
    """A non-default typed key (rbg) must restore with ITS impl — wrapping
    its data as threefry would raise on shape, or worse, change the
    stream."""
    from apex_tpu.utils.checkpoint import (resume_train_checkpoint,
                                           save_train_checkpoint)

    rng = jax.random.key(7, impl="rbg")
    path = os.path.join(tmp_path, "t.npz")
    save_train_checkpoint(path, {"w": jnp.ones(3)}, step=1, rng=rng)
    _, _, rng2 = resume_train_checkpoint(
        path, {"w": jnp.ones(3)}, jax.random.PRNGKey(0), step_limit=5,
        limit_flag="--iters")
    assert str(jax.random.key_impl(rng2)) == "rbg"
    a = jax.random.normal(jax.random.fold_in(rng, 1), (3,))
    b = jax.random.normal(jax.random.fold_in(rng2, 1), (3,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
