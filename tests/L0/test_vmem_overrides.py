"""Tuned-block override registry (bench_kernels --sweep consumer).

The sweep harness discovers per-kernel block sizes on real silicon and
writes a JSON; vmem.load_overrides / APEX_TPU_TUNED apply it. Correctness
must be block-size-independent: kernels under any override still match
their oracles (the clamps guarantee a stale file can only cost speed).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels import vmem


@pytest.fixture(autouse=True)
def _clean_registry():
    vmem.clear_overrides()
    yield
    vmem.clear_overrides()


def test_registry_roundtrip(tmp_path):
    vmem.set_override("layer_norm.block_rows", 16)
    assert vmem.get_override("layer_norm.block_rows", 99) == 16
    assert vmem.get_override("unknown", 7) == 7
    assert vmem.get_override(None, 5) == 5
    vmem.remove_override("layer_norm.block_rows")
    assert vmem.get_override("layer_norm.block_rows", 99) == 99

    path = tmp_path / "tuned.json"
    path.write_text(json.dumps({"xentropy.block_rows": 32,
                                "flash.block_q": 256}))
    loaded = vmem.load_overrides(str(path))
    assert loaded == {"xentropy.block_rows": 32, "flash.block_q": 256}
    assert vmem.get_override("flash.block_q", 128) == 256


def test_packaged_tuned_file_autoloads_by_device_kind(tmp_path,
                                                      monkeypatch):
    """kernels/tuned/<device_kind>.json applies by default at the first
    get_override() call — but never clobbers an explicit override, and a
    corrupt file degrades to heuristics instead of raising."""
    kind = jax.devices()[0].device_kind.lower().replace(" ", "_")
    (tmp_path / f"{kind}.json").write_text(
        json.dumps({"auto.knob": 48, "auto.other": 16}))
    monkeypatch.setattr(vmem, "_TUNED_DIR", str(tmp_path))
    monkeypatch.setattr(vmem, "_auto_load_done", False)
    vmem.set_override("auto.other", 24)      # explicit wins
    try:
        assert vmem.get_override("auto.knob", 8) == 48
        assert vmem.get_override("auto.other", 8) == 24
    finally:
        vmem.clear_overrides()

    # corrupt file: warn-and-degrade, registry untouched
    (tmp_path / f"{kind}.json").write_text("{not json")
    monkeypatch.setattr(vmem, "_auto_load_done", False)
    with pytest.warns(UserWarning, match="could not be loaded"):
        assert vmem.get_override("auto.knob", 8) == 8

    # one bad value: NOTHING commits (whole-file-first, ADVICE r3 — the
    # same atomicity load_overrides enforces)
    (tmp_path / f"{kind}.json").write_text(
        json.dumps({"auto.good": 32, "auto.bad": "32"}))
    monkeypatch.setattr(vmem, "_auto_load_done", False)
    with pytest.warns(UserWarning, match="not an integer"):
        assert vmem.get_override("auto.good", 8) == 8
    vmem.clear_overrides()

    # no file for this device kind: silent no-op, loaded only once
    monkeypatch.setattr(vmem, "_TUNED_DIR", str(tmp_path / "nothing"))
    monkeypatch.setattr(vmem, "_auto_load_done", False)
    assert vmem.get_override("auto.knob", 8) == 8
    assert vmem._auto_load_done is True


def test_get_override_alignment_and_cap():
    vmem.set_override("k", 100)
    assert vmem.get_override("k", 1, multiple=8) == 96
    assert vmem.get_override("k", 1, multiple=8, cap=64) == 64
    vmem.set_override("k", 3)
    assert vmem.get_override("k", 1, multiple=8) == 8  # floor, never 0


def test_block_rows_override_capped_by_vmem_stack():
    """A tuned value can exceed the heuristic's max_rows preference but not
    ~4x the conservative budget (the physical scoped-VMEM stack): past
    that the 'only ever slower, never broken' invariant would fail at a
    larger shape than the sweep ran at."""
    row_bytes, n_bufs = 4 * 8192, 4          # budget = 4MB/(32KB*4) = 32
    vmem.set_override("k", 1 << 20)
    b = vmem.block_rows(1 << 20, row_bytes=row_bytes, n_bufs=n_bufs,
                        key="k")
    assert b <= 4 * (vmem.VMEM_BUDGET_BYTES // (row_bytes * n_bufs))


def test_bad_tuned_file_does_not_brick_import(tmp_path):
    """APEX_TPU_TUNED pointing at a missing or corrupt file must warn, not
    raise, at import (the env var is set-and-forget in shell profiles)."""
    import subprocess
    import sys

    for content in (None, "{not json"):
        path = tmp_path / "tuned.json"
        if content is None:
            env_path = str(tmp_path / "missing.json")
        else:
            path.write_text(content)
            env_path = str(path)
        r = subprocess.run(
            [sys.executable, "-c",
             "import apex_tpu.kernels.vmem as v; print(v.overrides())"],
            capture_output=True, text=True,
            env={"APEX_TPU_TUNED": env_path, "PATH": "/usr/bin:/bin",
                 "HOME": "/root", "PYTHONPATH": "/root/repo",
                 "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stderr[-500:]
        assert "{}" in r.stdout


def test_override_passes_through_clamps():
    # override larger than the row count clamps to the sublane-padded total
    vmem.set_override("k", 4096)
    assert vmem.block_rows(64, row_bytes=4, n_bufs=1, key="k") == 64
    # and to the divisor constraint
    assert vmem.block_rows(4096, row_bytes=4, n_bufs=1, divisor_of=24,
                           key="k") == 8
    # unaligned override rounds down to the sublane tile
    vmem.set_override("k", 13)
    assert vmem.block_rows(4096, row_bytes=4, n_bufs=1, key="k") == 8


@pytest.mark.parametrize("block", [8, 32, 128])
def test_layer_norm_correct_under_any_block(block):
    from apex_tpu.kernels.layer_norm import layer_norm, layer_norm_reference

    vmem.set_override("layer_norm.block_rows", block)
    x = jax.random.normal(jax.random.PRNGKey(0), (256, 512))
    w = jnp.ones((512,)) * 1.3
    b = jnp.zeros((512,)) + 0.1
    np.testing.assert_allclose(np.asarray(layer_norm(x, w, b)),
                               np.asarray(layer_norm_reference(x, w, b)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("block", [8, 64])
def test_xentropy_correct_under_any_block(block):
    from apex_tpu.kernels.xentropy import (softmax_cross_entropy_loss,
                                           xent_reference)

    vmem.set_override("xentropy.block_rows", block)
    logits = jax.random.normal(jax.random.PRNGKey(1), (64, 256))
    labels = jax.random.randint(jax.random.PRNGKey(2), (64,), 0, 256)
    np.testing.assert_allclose(
        np.asarray(softmax_cross_entropy_loss(logits, labels)),
        np.asarray(xent_reference(logits, labels)), rtol=1e-5, atol=1e-5)


def test_flash_block_override_used():
    """flash_attention defaults resolve through the registry (and stay
    numerically exact)."""
    from apex_tpu.kernels.flash_attention import (flash_attention,
                                                  mha_reference)

    vmem.set_override("flash.block_q", 64)
    vmem.set_override("flash.block_k", 64)
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (1, 2, 256, 128)) for kk in ks)
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True, scale=128 ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_fit_block_shrinks_to_divide():
    """A big tuned block (v5e sweep: block_k=1024) must shrink until it
    divides the sequence — staying on the Pallas path — instead of
    failing _pallas_ok and silently taking the quadratic-memory
    fallback."""
    from apex_tpu.kernels.flash_attention import _fit_block, _pallas_ok

    assert _fit_block(1024, 1536, 128) == 768     # largest divisor <= b
    assert _fit_block(1024, 384, 128) == 384      # clamp to seq
    assert _fit_block(256, 2048, 8) == 256        # already divides
    assert _fit_block(1024, 250, 128) == 128      # floor at alignment
    # divisor scan, not repeated halving: halving 768 at s=1024 misses 512
    # and bottoms out at a near-degenerate block that Mosaic rejects
    assert _fit_block(768, 1024, 8) == 512
    assert _fit_block(768, 1024, 128) == 512
    # the fitted pair passes the Pallas gate at the shrink-needing shape
    assert _pallas_ok(1536, 1536, 128,
                      _fit_block(256, 1536, 8), _fit_block(1024, 1536, 128))


def test_flash_bwd_blocks_resolve_and_respect_dropout():
    """flash.bwd_block_q/_k give the backward its own geometry — except
    under dropout, where the keep-mask is seeded per FORWARD block and a
    different bwd geometry could not replay it."""
    from apex_tpu.kernels.flash_attention import (_resolve_bwd_blocks,
                                                  flash_attention,
                                                  mha_reference)

    vmem.set_override("flash.bwd_block_q", 512)
    vmem.set_override("flash.bwd_block_k", 512)
    try:
        assert _resolve_bwd_blocks(256, 1024, 2048, 2048, 0.0) == (512, 512)
        # dropout ON: forward geometry wins, knobs ignored
        assert _resolve_bwd_blocks(256, 1024, 2048, 2048, 0.3) == (256, 1024)
        # and the knobs still fit-to-divide at short sequences
        assert _resolve_bwd_blocks(256, 1024, 384, 384, 0.0) == (384, 384)

        # EXPLICIT caller blocks win for both passes: the custom_vjp
        # threads blocks_explicit through, and the backward consults
        # _resolve_bwd_blocks ONLY when the caller left geometry unset.
        # Numerics are block-invariant, so observe the gating directly
        # by instrumenting the resolver (this polarity was once shipped
        # inverted — blocks_explicit computed AFTER _resolve_blocks
        # overwrote the Nones — and only this style of test can see it).
        import apex_tpu.kernels.flash_attention as fa

        calls = []
        orig = fa._resolve_bwd_blocks

        def spy(bq, bk, sq, sk, rate):
            calls.append((bq, bk))
            return orig(bq, bk, sq, sk, rate)

        fa._resolve_bwd_blocks = spy
        try:
            ks2 = jax.random.split(jax.random.PRNGKey(12), 3)
            q2, k2, v2 = (jax.random.normal(kk, (1, 1, 512, 128))
                          for kk in ks2)

            def gsum(q, **kw):
                return jax.grad(lambda q: jnp.sum(
                    flash_attention(q, k2, v2, causal=True, **kw)
                    .astype(jnp.float32)))(q)

            gsum(q2, block_q=128, block_k=128)
            assert calls == [], "explicit blocks must skip the bwd knobs"
            gsum(q2)
            assert calls, "default geometry must consult the bwd knobs"
        finally:
            fa._resolve_bwd_blocks = orig

        # numerics under distinct fwd/bwd geometry stay exact
        ks = jax.random.split(jax.random.PRNGKey(11), 3)
        q, k, v = (jax.random.normal(kk, (1, 2, 512, 128)) for kk in ks)

        def loss_k(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True)
                           .astype(jnp.float32))

        def loss_r(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=True,
                                         scale=128 ** -0.5)
                           .astype(jnp.float32))

        gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)
    finally:
        vmem.clear_overrides()


def test_flash_oversized_tuned_block_stays_correct():
    """Numerics with the checked-in v5e tuned blocks at a sequence
    (1536) the tuned block_k=1024 does not divide."""
    from apex_tpu.kernels.flash_attention import (flash_attention,
                                                  mha_reference)

    vmem.set_override("flash.block_q", 256)
    vmem.set_override("flash.block_k", 1024)
    try:
        ks = jax.random.split(jax.random.PRNGKey(7), 3)
        q, k, v = (jax.random.normal(kk, (1, 1, 1536, 128)) for kk in ks)
        out = flash_attention(q, k, v, causal=True)
        ref = mha_reference(q, k, v, causal=True, scale=128 ** -0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        vmem.clear_overrides()


def test_load_overrides_atomic_on_bad_value(tmp_path):
    """ADVICE r3: a file with one non-integer value must leave the
    registry untouched — validate whole, then commit."""
    import os

    vmem.clear_overrides()
    try:
        vmem.set_override("layer_norm.block_rows", 128)
        bad = os.path.join(tmp_path, "tuned.json")
        with open(bad, "w") as f:
            json.dump({"flash.block_q": 256,
                       "flash.block_k": "not-an-int"}, f)
        with pytest.raises(ValueError):
            vmem.load_overrides(bad)
        assert vmem.overrides() == {"layer_norm.block_rows": 128}, \
            "partial override set committed from an invalid file"
    finally:
        vmem.clear_overrides()


@pytest.mark.parametrize("bad", [12.7, "128", True])
def test_load_overrides_rejects_non_integer_values(tmp_path, bad):
    """ADVICE r4: int(v) must not silently truncate floats or accept
    digit strings/bools — every non-integer value fails before commit."""
    import os

    vmem.clear_overrides()
    try:
        path = os.path.join(tmp_path, "tuned.json")
        with open(path, "w") as f:
            json.dump({"flash.block_q": bad}, f)
        with pytest.raises(ValueError):
            vmem.load_overrides(path)
        assert vmem.overrides() == {}
    finally:
        vmem.clear_overrides()


def test_load_overrides_accepts_integral_float(tmp_path):
    """A JSON 128.0 is an exact integer — accepted, stored as int."""
    import os

    vmem.clear_overrides()
    try:
        path = os.path.join(tmp_path, "tuned.json")
        with open(path, "w") as f:
            json.dump({"flash.block_q": 128.0}, f)
        assert vmem.load_overrides(path) == {"flash.block_q": 128}
    finally:
        vmem.clear_overrides()


def test_load_overrides_rejects_infinity_with_valueerror(tmp_path):
    """json accepts bare Infinity; the validator must turn it into the
    documented ValueError, not leak OverflowError from int()."""
    import os

    vmem.clear_overrides()
    try:
        path = os.path.join(tmp_path, "tuned.json")
        with open(path, "w") as f:
            f.write('{"flash.block_q": Infinity}')
        with pytest.raises(ValueError, match="not an integer"):
            vmem.load_overrides(path)
        assert vmem.overrides() == {}
    finally:
        vmem.clear_overrides()
