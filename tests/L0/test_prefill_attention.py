"""apex_tpu.kernels.prefill_attention — chunked-prefill attention kernel.

Kernel-vs-oracle parity (the Pallas path runs interpreted on CPU; Mosaic
lowering is the tests/tpu tier's job), the shifted-causal mask and block
skip, dtype handling, tuned-override plumbing, and the two consistency
contracts that anchor the serving tier:

- offset 0 over the chunk's own K/V == plain causal attention (the
  monolithic prefill's math);
- each chunk row == the decode kernel run token-by-token at the same
  cache state (chunked prefill is N decode steps fused per heartbeat).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels import vmem
from apex_tpu.kernels.decode_attention import decode_attention_reference
from apex_tpu.kernels.flash_attention import mha_reference
from apex_tpu.kernels.prefill_attention import (prefill_attention,
                                                prefill_attention_reference)

pytestmark = pytest.mark.serving


def _rand(shape, dtype=jnp.float32, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape), dtype)


# ------------------------------------------------------------------- oracle
def test_reference_offset_zero_is_plain_causal_attention():
    """With the cache holding exactly the chunk's K/V at offset 0, the
    shifted-causal mask degenerates to the training causal mask."""
    B, h, C, d = 2, 3, 8, 16
    q, k, v = (_rand((B, h, C, d), seed=s) for s in (1, 2, 3))
    scale = d ** -0.5
    got = prefill_attention_reference(q, k, v,
                                      jnp.zeros((B,), jnp.int32),
                                      scale=scale)
    want = mha_reference(q, k, v, causal=True, scale=scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


def test_reference_rows_match_sequential_decode():
    """Row i of a chunk == one decode step at cache length off + i + 1:
    the fused chunk is exactly N single-token steps."""
    B, h, C, L, d = 2, 2, 6, 32, 8
    q = _rand((B, h, C, d), seed=4)
    k = _rand((B, h, L, d), seed=5)
    v = _rand((B, h, L, d), seed=6)
    off = jnp.asarray([0, 11], jnp.int32)
    scale = d ** -0.5
    chunk = prefill_attention_reference(q, k, v, off, scale=scale)
    for i in range(C):
        step = decode_attention_reference(q[:, :, i], k, v, off + i + 1,
                                          scale=scale)
        np.testing.assert_allclose(np.asarray(chunk[:, :, i]),
                                   np.asarray(step), atol=1e-5,
                                   err_msg=f"row {i}")


# ------------------------------------------------------------------- kernel
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_kernel_matches_oracle(dtype):
    B, h, C, L, d = 2, 2, 16, 256, 8
    q = _rand((B, h, C, d), dtype, seed=7)
    k = _rand((B, h, L, d), dtype, seed=8)
    v = _rand((B, h, L, d), dtype, seed=9)
    off = jnp.asarray([0, 37], jnp.int32)
    want = prefill_attention_reference(q, k, v, off, scale=d ** -0.5)
    got = prefill_attention(q, k, v, off, block_q=8, block_k=128)
    atol = 2e-2 if dtype == jnp.bfloat16 else 2e-6
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=atol)
    assert got.dtype == dtype


def test_kernel_never_attends_past_the_row(seed=10):
    """Cache positions beyond every row's reach hold huge poison; the
    mask (and the block skip) must keep them out of the softmax."""
    B, h, C, L, d = 2, 2, 8, 256, 8
    q = _rand((B, h, C, d), seed=seed)
    k = _rand((B, h, L, d), seed=seed + 1)
    v = _rand((B, h, L, d), seed=seed + 2)
    off = jnp.asarray([0, 64], jnp.int32)
    want = prefill_attention(q, k, v, off, block_q=8, block_k=128)
    # poison everything past the farthest reachable position (max offset
    # + C - 1); both k and v, so a leak shows as a blowup either way
    reach = int(off.max()) + C
    kp = k.at[:, :, reach:].set(1e30)
    vp = v.at[:, :, reach:].set(-1e30)
    got = prefill_attention(q, kp, vp, off, block_q=8, block_k=128)
    assert bool(jnp.isfinite(got).all())
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


def test_unaligned_shapes_fall_back_to_reference():
    B, h, C, L, d = 1, 2, 5, 250, 12       # nothing lane/sublane aligned
    q = _rand((B, h, C, d), seed=13)
    k = _rand((B, h, L, d), seed=14)
    v = _rand((B, h, L, d), seed=15)
    off = jnp.asarray([99], jnp.int32)
    got = prefill_attention(q, k, v, off)
    want = prefill_attention_reference(q, k, v, off, scale=d ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6)


def test_tuned_chunk_block_overrides_change_no_math():
    B, h, C, L, d = 1, 2, 16, 256, 8
    q = _rand((B, h, C, d), seed=16)
    k = _rand((B, h, L, d), seed=17)
    v = _rand((B, h, L, d), seed=18)
    off = jnp.asarray([21], jnp.int32)
    base = prefill_attention(q, k, v, off)
    vmem.set_override("decode.chunk_block_q", 16)
    vmem.set_override("decode.chunk_block_k", 128)
    try:
        tuned = prefill_attention(q, k, v, off)
    finally:
        vmem.remove_override("decode.chunk_block_q")
        vmem.remove_override("decode.chunk_block_k")
    np.testing.assert_allclose(np.asarray(base), np.asarray(tuned),
                               atol=1e-6)


def test_shape_validation():
    q = _rand((1, 2, 8, 8))
    k = _rand((1, 2, 32, 8))
    with pytest.raises(ValueError, match="do not match"):
        prefill_attention(q, k, _rand((1, 2, 16, 8)),
                          jnp.zeros((1,), jnp.int32))
    with pytest.raises(ValueError, match="offsets"):
        prefill_attention(q, k, k, jnp.zeros((2,), jnp.int32))


def test_int8_dequant_in_kernel_matches_dequant_oracle():
    """The quantized-cache tier (kv_quant): the chunk kernel's int8
    path with per-head scales vs the dequantize-up-front oracle —
    shifted-causal masking and online softmax unchanged, dequant fused
    into the block loads."""
    rng = np.random.default_rng(12)
    B, h, C, L, d = 2, 4, 16, 256, 16
    q = _rand((B, h, C, d))
    k8 = jnp.asarray(rng.integers(-127, 128, size=(B, h, L, d)), jnp.int8)
    v8 = jnp.asarray(rng.integers(-127, 128, size=(B, h, L, d)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.01, 0.06, size=h), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.06, size=h), jnp.float32)
    off = jnp.asarray([0, 200], jnp.int32)
    ref = prefill_attention_reference(
        jnp.asarray(q, jnp.float32),
        jnp.asarray(k8, jnp.float32) * ks[None, :, None, None],
        jnp.asarray(v8, jnp.float32) * vs[None, :, None, None],
        off, scale=1.0 / d ** 0.5)
    out = prefill_attention(q, k8, v8, off, k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
