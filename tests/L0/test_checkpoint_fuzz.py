"""Property-based checkpoint round-trip fuzzing (hypothesis).

Split out of test_checkpoint.py so that machines without hypothesis
still collect and run the deterministic checkpoint suite — this module
alone skips (pytest.importorskip at collection), instead of one missing
dev dependency erroring the whole file out of `pytest tests/`.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from apex_tpu.utils import load_checkpoint, save_checkpoint  # noqa: E402


@st.composite
def _pytrees(draw, depth=0):
    """Random nested dict pytrees over the dtypes train states carry."""
    if depth >= 2 or (depth > 0 and draw(st.booleans())):
        dtype = draw(st.sampled_from(
            [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int32,
             jnp.uint32, jnp.bool_]))
        shape = tuple(draw(st.lists(st.integers(1, 4), min_size=0,
                                    max_size=3)))
        seed = draw(st.integers(0, 2**31 - 1))
        rng = np.random.RandomState(seed)
        if dtype == jnp.bool_:
            arr = rng.rand(*shape) > 0.5
        elif jnp.issubdtype(dtype, jnp.integer):
            arr = rng.randint(0, 1000, size=shape)
        else:
            arr = rng.randn(*shape) * draw(st.sampled_from([1e-4, 1.0,
                                                            1e4]))
        return jnp.asarray(arr, dtype)
    n = draw(st.integers(1, 3))
    keys = draw(st.lists(st.text(alphabet="abcdef_", min_size=1,
                                 max_size=6), min_size=n, max_size=n,
                         unique=True))
    return {k: draw(_pytrees(depth + 1)) for k in keys}


@given(_pytrees(), st.integers(0, 10**6))
@settings(max_examples=25, deadline=None)
def test_checkpoint_roundtrip_any_pytree(tmp_path_factory, tree, step):
    """Property: save→load is bitwise over ARBITRARY nested pytrees and
    every dtype a train state carries (fp32, bf16 — which rides npz as
    fp32 and must cast back bit-faithfully — fp16, ints, bools), with
    dtype and step preserved exactly."""
    path = os.path.join(tmp_path_factory.mktemp("fuzz"), "t.npz")
    save_checkpoint(path, tree, step=step)
    template = jax.tree_util.tree_map(jnp.zeros_like, tree)
    restored, got_step, _ = load_checkpoint(path, template)
    assert got_step == step

    def check(a, b):
        assert a.dtype == b.dtype, (a.dtype, b.dtype)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    jax.tree_util.tree_map(check, restored, tree)
