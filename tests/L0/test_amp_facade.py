"""amp imperative-facade tests.

Mirror of the reference's tests/L0/run_amp/
test_multiple_models_optimizers_losses.py: several models/optimizers under
one amp.initialize, per-loss scalers (num_losses), scale_loss by loss_id,
state_dict round-trip covering every scaler.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu import amp
from apex_tpu.optimizers import fused_adam, fused_sgd


def _model(seed):
    k = jax.random.PRNGKey(seed)
    params = {"w": jax.random.normal(k, (8, 4)) * 0.1}

    def apply_fn(p, x):
        return x @ jnp.asarray(p["w"], x.dtype)

    return apply_fn, params


def test_initialize_multiple_models_and_losses():
    m0, m1 = _model(0), _model(1)
    (models, optimizers) = amp.initialize(
        [m0, m1], [fused_sgd(0.1), fused_adam(1e-3)],
        opt_level="O2", num_losses=3, verbosity=0)
    assert len(models) == 2 and len(optimizers) == 2
    # three independent scalers registered (amp/_amp_state parity)
    sd = amp.state_dict()
    assert set(sd) == {"loss_scaler0", "loss_scaler1", "loss_scaler2"}

    # per-loss scale_loss: each loss id uses its own scaler
    with amp.scale_loss(jnp.float32(2.0), optimizers[0], loss_id=0) as s0:
        assert float(s0) == 2.0 * sd["loss_scaler0"]["loss_scale"]
    with amp.scale_loss(jnp.float32(1.0), optimizers[1], loss_id=2) as s2:
        assert float(s2) == sd["loss_scaler2"]["loss_scale"]


def test_per_loss_scalers_evolve_independently():
    amp.initialize(_model(0), fused_sgd(0.1), opt_level="O2",
                   num_losses=2, verbosity=0)
    scalers = amp._amp_state.loss_scalers
    # overflow on loss 0 only
    scalers[0].unscale({"g": jnp.array([jnp.inf])})
    scalers[0].update_scale()
    scalers[1].unscale({"g": jnp.array([1.0])})
    scalers[1].update_scale()
    assert scalers[0].loss_scale() == scalers[1].loss_scale() / 2

    # state_dict round-trips BOTH scalers' positions
    sd = amp.state_dict()
    amp.initialize(_model(0), fused_sgd(0.1), opt_level="O2",
                   num_losses=2, verbosity=0)
    amp.load_state_dict(sd)
    assert amp.state_dict() == sd


def test_two_train_states_share_nothing():
    """The dcgan pattern: two make_train_step states advance independently."""
    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic")
    apply0, p0 = _model(3)
    apply1, p1 = _model(4)

    def loss0(p, batch):
        return jnp.mean(apply0(p, batch) ** 2)

    def loss1(p, batch):
        return jnp.mean(jnp.abs(apply1(p, batch)))

    i0, s0 = amp.make_train_step(loss0, fused_sgd(0.1), policy)
    i1, s1 = amp.make_train_step(loss1, optax.adam(1e-3), policy)
    st0, st1 = i0(p0), i1(p1)
    x = jnp.ones((2, 8))
    st0b, _ = jax.jit(s0)(st0, x)
    # advancing model 0 must not touch model 1's state
    for a, b in zip(jax.tree_util.tree_leaves(st1),
                    jax.tree_util.tree_leaves(i1(p1))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    w_before = np.asarray(amp.master_params(st0)["w"])
    w_after = np.asarray(amp.master_params(st0b)["w"])
    assert not np.array_equal(w_before, w_after)


def test_half_float_promote_functions():
    """Legacy registry API (apex/amp/amp.py — half/float/promote_function)."""
    amp.initialize(_model(9), fused_sgd(0.1), opt_level="O2", verbosity=0)

    @amp.half_function
    def matmul(a, b):
        assert a.dtype == jnp.bfloat16 and b.dtype == jnp.bfloat16
        return a @ b

    y = matmul(jnp.ones((2, 3)), jnp.ones((3, 2)))
    assert y.dtype == jnp.bfloat16

    @amp.float_function
    def softmaxish(x):
        assert x.dtype == jnp.float32
        return jax.nn.softmax(x)

    assert softmaxish(jnp.ones((4,), jnp.bfloat16)).dtype == jnp.float32

    @amp.promote_function
    def add(a, b):
        assert a.dtype == b.dtype
        return a + b

    out = add(jnp.ones((2,), jnp.bfloat16), jnp.ones((2,), jnp.float32))
    assert out.dtype == jnp.float32
    # int args untouched
    out = add(jnp.ones((2,), jnp.bfloat16), jnp.ones((2,), jnp.bfloat16))
    assert out.dtype == jnp.bfloat16


def test_register_functions_on_module():
    import types

    mod = types.SimpleNamespace(op=lambda x: x)
    amp.initialize(_model(9), fused_sgd(0.1), opt_level="O2", verbosity=0)
    amp.register_half_function(mod, "op")
    assert mod.op(jnp.ones((2,), jnp.float32)).dtype == jnp.bfloat16
    mod2 = types.SimpleNamespace(op=lambda x: x)
    amp.register_float_function(mod2, "op")
    assert mod2.op(jnp.ones((2,), jnp.bfloat16)).dtype == jnp.float32


def test_registry_noops_when_amp_inactive():
    """apex's wrappers no-op when amp is off (enabled=False / O0)."""
    amp.initialize(_model(9), fused_sgd(0.1), opt_level="O0", verbosity=0)

    @amp.half_function
    def ident(x):
        return x

    assert ident(jnp.ones((2,), jnp.float32)).dtype == jnp.float32
    amp.initialize(_model(9), fused_sgd(0.1), opt_level="O2", enabled=False,
                   verbosity=0)
    assert ident(jnp.ones((2,), jnp.float32)).dtype == jnp.float32


def test_registry_preserves_non_arrays_and_weak_types():
    amp.initialize(_model(9), fused_sgd(0.1), opt_level="O2", verbosity=0)

    @amp.half_function
    def takes_list(lst, x):
        assert isinstance(lst, list)          # native object untouched
        return x

    assert takes_list([1.0, 2.0],
                      jnp.ones((2,), jnp.float32)).dtype == jnp.bfloat16

    @amp.promote_function
    def add(a, b):
        return a + b

    # python scalar + bf16 array: scalar stays weak, no fp32 promotion
    out = add(jnp.ones((2,), jnp.bfloat16), 2.0)
    assert out.dtype == jnp.bfloat16
    # kwargs participate in promotion
    out = add(jnp.ones((2,), jnp.bfloat16), b=jnp.ones((2,), jnp.float32))
    assert out.dtype == jnp.float32
