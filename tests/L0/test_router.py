"""Replica-parallel serving: the :class:`~apex_tpu.serving.Router`'s
contract pins.

The headline guarantees, per ISSUE 12's acceptance criteria:

- **Parity**: the same request stream served through
  ``Router([engine])`` is bitwise identical (per submitted request) to
  a bare :class:`~apex_tpu.serving.Scheduler` on the same engine, and
  an N-replica router's greedy outputs are bitwise identical to the
  1-replica run — replication changes WHERE a request decodes, never
  what it decodes. Zero compiled programs are added per replica, and
  every pool drains leak-free.
- **Affinity**: multi-turn traffic lands on the replica whose prefix
  cache already holds its history (probed READ-ONLY across replicas,
  hashed once), and the probe keys ride into the chosen scheduler so
  admission never re-hashes.
- **Backpressure**: a saturated best replica is a spill, not an error;
  :class:`~apex_tpu.serving.QueueFull` surfaces only when the whole
  fleet is full, carrying the MAX of the replicas' measured
  ``retry_after_s`` hints (and None before any replica has measured a
  decode step — a missing EMA degrades to honest silence, never a
  crash).
- **Containment**: a router-tier ``replica_death`` fault drains the
  victim's queued/in-flight requests onto survivors — every one
  reaches a terminal state there, un-faulted requests stay bitwise vs
  the fault-free run, the dead pool audits with zero leaked pages, and
  the drain never charges the requests' retry budgets.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, FaultPlan, FaultSpec, PoolAuditor,
                              QueueFull, Request, Router, Scheduler)

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

VOCAB = 64
CHUNK = 8


@pytest.fixture(scope="module")
def lm_and_params():
    m = TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                      num_heads=4, max_seq_len=64)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, slots=2, pool=4, seed=5, **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=64, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=pool,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  **kw)


@pytest.fixture(scope="module")
def engines(lm_and_params):
    """One shared PAIR of identically-built paged engines: every test
    resets them (clear_prefixes=True), so bitwise comparisons across
    runs stay within the same compiled executables per replica."""
    return [_mk_engine(lm_and_params), _mk_engine(lm_and_params)]


def _reset(engines):
    for e in engines:
        e.reset(clear_prefixes=True)
        e.set_registry(None)


def _stream(seed=42):
    """Mixed chunk-boundary prompts and budgets — the parity sweep."""
    rng = np.random.default_rng(seed)
    return [Request(prompt=list(rng.integers(1, VOCAB, size=n)),
                    max_new_tokens=b)
            for n, b in [(5, 10), (8, 4), (13, 6), (21, 4), (3, 9),
                         (16, 5), (7, 1), (11, 7)]]


def _session_waves(turns=2, sessions=3):
    """Multi-turn sessions: turn t+1's prompt EXTENDS turn t's, so its
    block-aligned prefix lives exactly where turn t was served. Waves
    are served sequentially (a turn arrives after the previous
    response) — the affinity workload."""
    rng = np.random.default_rng(7)
    base = rng.integers(1, VOCAB, size=CHUNK).tolist()
    prompts = []
    for s in range(sessions):
        srng = np.random.default_rng(100 + s)
        p = base + srng.integers(1, VOCAB, size=CHUNK).tolist()
        turns_s = [list(p)]
        for _ in range(turns - 1):
            p = p + srng.integers(1, VOCAB, size=4).tolist()
            turns_s.append(list(p))
        prompts.append(turns_s)
    return [[Request(prompt=prompts[s][t], max_new_tokens=4)
             for s in range(sessions)] for t in range(turns)]


def _tokens(reqs):
    return [list(r.output_tokens) for r in reqs]


def _audit_drained(engine):
    """The zero-leak pin: the pool's invariants hold, and after a
    clearing reset nothing but the sentinel remains allocated."""
    aud = PoolAuditor()
    aud.audit(engine)               # raises PoolInvariantError on leaks
    engine.reset(clear_prefixes=True)
    assert aud.audit(engine)["pages_in_use"] == 0


# ------------------------------------------------------------- validation
def test_router_validation(lm_and_params, engines):
    _reset(engines)
    with pytest.raises(ValueError, match="at least one engine"):
        Router([])
    with pytest.raises(ValueError, match="route_policy"):
        Router(engines, route_policy="sticky")
    with pytest.raises(ValueError, match="replica_plans"):
        Router(engines, replica_plans=[None])
    odd = _mk_engine(lm_and_params, slots=3)
    with pytest.raises(ValueError, match="geometry"):
        Router([engines[0], odd])
    r = Router(engines)
    with pytest.raises(ValueError, match="out of range"):
        r.kill_replica(7)
    # affinity with retention off degrades to least-loaded, loudly
    # visible as the flag (nothing to probe in empty caches)
    assert not r.affinity_enabled
    assert Router(engines, retain_prefixes=True).affinity_enabled


# ------------------------------------------------------- the parity pins
def test_single_replica_router_is_bitwise_the_bare_scheduler(engines):
    """Router(replicas=1) vs a bare Scheduler on the SAME engine: the
    routing layer adds bookkeeping, never bytes — same tokens per
    submitted request, zero new compiled programs, leak-free drain."""
    _reset(engines)
    eng = engines[0]
    bare = _stream()
    Scheduler(eng, retain_prefixes=True).run(bare)
    programs0 = eng.compiled_programs
    eng.reset(clear_prefixes=True)
    routed = _stream()
    router = Router([eng], retain_prefixes=True)
    router.run(routed)
    assert _tokens(routed) == _tokens(bare)
    assert eng.compiled_programs == programs0, \
        "the router traced new programs"
    assert router.pending == 0
    router.close()
    _audit_drained(eng)


@pytest.mark.parametrize("policy", ["affinity", "least_loaded",
                                    "random"])
def test_n_replica_outputs_bitwise_identical_to_one_replica(engines,
                                                            policy):
    """Scale-out parity under every routing policy: a request decodes
    the same greedy tokens wherever it lands (identically-built
    replicas), so N=2 output is bitwise N=1 output per submitted
    request — and neither replica traced anything new."""
    _reset(engines)
    one = _stream()
    r1 = Router(engines[:1], retain_prefixes=True)
    r1.run(one)
    r1.close()
    pinned = engines[0].compiled_programs
    _reset(engines)
    two = _stream()
    r2 = Router(engines, retain_prefixes=True, route_policy=policy,
                seed=3)
    r2.run(two)
    assert _tokens(two) == _tokens(one), \
        f"{policy} routing changed tokens"
    assert {r2.placements[r.uid] for r in two} <= {0, 1}
    # zero programs beyond the single-replica pin, on EVERY replica
    # (replica 1 may trace its own copies on first contact — the pin is
    # the count, not the warmth)
    assert all(e.compiled_programs == pinned for e in engines)
    r2.close()
    for e in engines:
        _audit_drained(e)


# --------------------------------------------------------------- affinity
def test_affinity_routes_turns_home_and_probe_is_pure(engines):
    """Turn t+1 lands on turn t's replica (longest probed prefix wins),
    reuses its K/V, and counts serving.router.affinity_hits — while the
    LOSING replicas' caches stay untouched by the probe (no counter or
    LRU pollution: their windows read zero consultations)."""
    _reset(engines)
    reg = telemetry.MetricsRegistry()
    router = Router(engines, registry=reg, retain_prefixes=True)
    w1, w2 = _session_waves()
    router.run(w1)
    homes = {i: router.placements[r.uid] for i, r in enumerate(w1)}
    assert set(homes.values()) == {0, 1}, \
        "least-loaded cold start should spread sessions over replicas"
    base = [e.prefix_cache.stats() for e in engines]
    router.run(w2)
    for i, r in enumerate(w2):
        assert router.placements[r.uid] == homes[i], \
            f"session {i} turn 2 did not follow its history"
        assert r.reused_tokens > 0, f"session {i} re-prefilled its history"
    counters = reg.snapshot()["counters"]
    assert counters["serving.router.affinity_hits"] == len(w2)
    assert counters["serving.router.routed"] == len(w1) + len(w2)
    # probe purity, observed through the satellite's delta lens: each
    # replica's cache was CONSULTED (hit+miss) only by the requests
    # that actually landed on it — N-1 probes per request left no trace
    for i, e in enumerate(engines):
        landed = sum(1 for r in w2 if router.placements[r.uid] == i)
        delta = e.prefix_cache.stats_since(base[i])
        assert delta["hits"] + delta["misses"] == landed
        assert delta["hits"] == landed      # every turn 2 is a real hit
    router.close()


# ----------------------------------------------- load + backpressure
def test_least_loaded_spreads_across_replicas(lm_and_params):
    """With affinity out of the picture, routing follows queue depth /
    free slots: an un-stepped fleet splits arrivals evenly."""
    e1 = _mk_engine(lm_and_params, pool=0)
    e2 = _mk_engine(lm_and_params, pool=0)
    router = Router([e1, e2], route_policy="least_loaded", max_queue=2)
    reqs = [Request(prompt=[i + 1, i + 2], max_new_tokens=2)
            for i in range(4)]
    for r in reqs:              # queue capacity 2 per replica, no steps
        router.submit(r)
    placements = [router.placements[r.uid] for r in reqs]
    assert placements.count(0) == placements.count(1) == 2
    with pytest.raises(QueueFull):
        router.submit(Request(prompt=[9], max_new_tokens=2))
    while router.pending:
        router.step()
    assert all(r.status == "finished" for r in reqs)
    router.close()


def test_saturated_affinity_home_spills_to_next_best(engines):
    """Cross-replica backpressure: the replica holding the prefix is
    the first choice, but when its queue is full the request SPILLS to
    the next-best replica (counted, served, no QueueFull surfaced)."""
    _reset(engines)
    reg = telemetry.MetricsRegistry()
    router = Router(engines, registry=reg, retain_prefixes=True,
                    max_queue=1)
    w1, w2 = _session_waves(sessions=1)
    router.run(w1)
    home = router.placements[w1[0].uid]
    # jam the home replica's queue directly (bypassing the router, so
    # the filler itself is not load-balanced away from it)
    filler = Request(prompt=[1, 2, 3], max_new_tokens=2)
    router.replicas[home].submit(filler)
    router.submit(w2[0])
    assert router.placements[w2[0].uid] == 1 - home, \
        "a full home replica must spill, not block"
    counters = reg.snapshot()["counters"]
    assert counters.get("serving.router.spills") == 1
    # an ABSORBED spill is not a caller-visible rejection: the request
    # was placed and served — the rejected counter must not move
    assert counters.get("serving.requests.rejected", 0) == 0
    while router.pending:
        router.step()
    assert w2[0].status == "finished" and filler.status == "finished"
    router.close()


def test_all_saturated_hint_is_max_of_replicas_and_none_safe(
        lm_and_params):
    """Satellite 2: the fleet-level QueueFull carries max(replica
    hints); replicas that never measured a decode step contribute None
    and must degrade the max, not crash it."""
    e1 = _mk_engine(lm_and_params, pool=0)
    e2 = _mk_engine(lm_and_params, pool=0)
    router = Router([e1, e2], route_policy="least_loaded", max_queue=3)
    for i in range(6):      # queue capacity 3 per replica, no steps
        router.submit(Request(prompt=[i + 1], max_new_tokens=2))
    # nothing has decoded yet: every replica's EMA is unmeasured, so
    # the fleet hint is honestly None (no fake number, no TypeError)
    with pytest.raises(QueueFull) as exc:
        router.submit(Request(prompt=[7], max_new_tokens=2))
    assert exc.value.retry_after_s is None
    # the fleet-level raise counts as ONE caller-visible rejection
    # (the per-replica probes are suppressed — no double counting)
    reg2 = telemetry.MetricsRegistry()
    router.registry = reg2
    with pytest.raises(QueueFull):
        router.submit(Request(prompt=[7], max_new_tokens=2))
    assert reg2.snapshot()["counters"][
        "serving.requests.rejected"] == 1
    router.registry = None
    # one replica measured, one still hasn't: max over the known hints
    router.replicas[0]._step_s_ema = 0.25
    with pytest.raises(QueueFull) as exc:
        router.submit(Request(prompt=[7], max_new_tokens=2))
    h0 = router.replicas[0]._retry_after_hint()
    assert exc.value.retry_after_s == pytest.approx(h0)
    # both measured: the max (the fleet frees when its slowest does)
    router.replicas[1]._step_s_ema = 0.75
    with pytest.raises(QueueFull) as exc:
        router.submit(Request(prompt=[7], max_new_tokens=2))
    h1 = router.replicas[1]._retry_after_hint()
    assert exc.value.retry_after_s == pytest.approx(max(h0, h1))
    while router.pending:
        router.step()
    router.close()


# ----------------------------------------------------- replica death
def test_replica_death_chaos_unfaulted_bitwise_zero_leaks(engines):
    """THE chaos pin: a seeded router-tier FaultPlan kills a replica
    mid-stream. Every request that lived on it reaches a terminal
    state on the survivor; un-faulted requests (here: ALL requests —
    greedy decode depends only on a slot's own lineage) stay bitwise
    vs the fault-free run; no retry budget is charged for the drain;
    both pools audit leak-free; zero new programs traced."""
    _reset(engines)
    fault_free = _stream(seed=9)
    r0 = Router(engines, retain_prefixes=True,
                route_policy="least_loaded")
    r0.run(fault_free)
    r0.close()
    placements0 = [r0.placements[r.uid] for r in fault_free]
    programs = [e.compiled_programs for e in engines]
    _reset(engines)
    victim = 0
    plan = FaultPlan([FaultSpec(kind="replica_death", tick=3,
                                replica=victim)])
    reg = telemetry.MetricsRegistry()
    chaos = _stream(seed=9)
    router = Router(engines, registry=reg, retain_prefixes=True,
                    route_policy="least_loaded", fault_plan=plan)
    router.run(chaos)
    assert plan.stats()["injected_replica_deaths"] == 1
    assert router.alive == [False, True]
    snap = reg.snapshot()
    counters = snap["counters"]
    assert counters["serving.router.replica_deaths"] == 1
    # the kill retires the victim's load gauges — a dashboard must
    # never read phantom pre-death load on a drained corpse
    for gauge in ("queue_depth", "slots_busy", "pages_free"):
        assert snap["gauges"][
            f"serving.router.replica{victim}.{gauge}"] == 0.0
    drained = counters.get("serving.router.requeued", 0)
    assert drained > 0, \
        "tick-3 death must catch requests queued/in-flight on the victim"
    for i, r in enumerate(chaos):
        assert r.status == "finished", f"request {i} not terminal"
        assert router.placements[r.uid] != victim, \
            f"request {i} claims to have finished on the dead replica"
        assert r.retries == 0, "a drain is not the request's fault"
        # the bitwise pin, per submitted request
        assert r.output_tokens == fault_free[i].output_tokens, \
            f"request {i} (fault-free home {placements0[i]}) diverged"
    assert [e.compiled_programs for e in engines] == programs
    router.close()
    for e in engines:
        _audit_drained(e)


def test_kill_replica_idempotent_and_last_alive_raises(engines):
    _reset(engines)
    router = Router(engines, route_policy="least_loaded")
    reqs = [Request(prompt=[i + 1, 5], max_new_tokens=3)
            for i in range(4)]
    for r in reqs:
        router.submit(r)
    on_victim = [r.uid for r, p in
                 ((r, router.placements[r.uid]) for r in reqs) if p == 1]
    drained = router.kill_replica(1)
    assert [r.uid for r in drained] == on_victim and drained
    # the kill already re-routed them onto the survivor
    assert all(router.placements[u] == 0 for u in on_victim)
    assert router.kill_replica(1) == []          # already dead: no-op
    with pytest.raises(RuntimeError, match="last one alive"):
        router.kill_replica(0)
    assert router.alive == [True, False]
    while router.pending:
        router.step()
    assert all(r.status == "finished" for r in reqs)
    router.close()


def test_drain_requests_seam_resets_state_and_frees_pages(engines):
    """The scheduler-level drain contract the router builds on:
    running-first-then-queue export, transient rollback with the
    original submit clock kept, empty pipeline, zero pages held."""
    _reset(engines)
    eng = engines[0]
    sched = Scheduler(eng, retain_prefixes=True)
    reqs = [Request(prompt=list(range(1, 12)), max_new_tokens=8),
            Request(prompt=list(range(2, 10)), max_new_tokens=8),
            Request(prompt=[7, 8, 9], max_new_tokens=8)]
    for r in reqs:
        sched.submit(r)
    for _ in range(4):              # partway: slots running, one queued
        sched.step()
    clocks = [r._t_submit for r in reqs]
    assert any(r.output_tokens for r in reqs)
    drained = sched.drain_requests()
    assert {r.uid for r in drained} == {r.uid for r in reqs}
    assert sched.pending == 0
    for r, t0 in zip(reqs, clocks):
        assert r.status == "queued" and r.output_tokens == []
        assert r._prefill_pos == 0 and r.ttft_s is None
        assert r._t_submit == t0, "drain must not reset the clock"
        assert r._not_before is None
    aud = PoolAuditor()
    aud.audit(eng)
    # only prefix-cache holds may remain; a clearing reset zeroes them
    _audit_drained(eng)
    # and re-serving the drained requests elsewhere completes them
    Scheduler(engines[1], retain_prefixes=True).run(drained)
    assert all(r.status == "finished" for r in reqs)


# ------------------------------------------------- lifecycle / threads
def _worker_threads():
    return [t for t in threading.enumerate()
            if t.name == "serving-draft-worker" and t.is_alive()]


def test_router_close_stops_all_workers_no_thread_leak(engines):
    """Satellite 6: one DraftWorker per pipelined replica scheduler,
    ALL stopped by one idempotent Router.close() — construct/serve/
    close leaves the process's worker-thread census unchanged."""
    _reset(engines)
    before = len(_worker_threads())
    router = Router(engines, retain_prefixes=True, pipeline_depth=2)
    assert len(_worker_threads()) == before + len(engines)
    router.run(_stream()[:4])
    router.close()
    router.close()                  # idempotent
    assert len(_worker_threads()) == before, "worker thread leaked"
    # a killed replica's worker stops at the kill, not only at close
    _reset(engines)
    router = Router(engines, retain_prefixes=True, pipeline_depth=1)
    router.kill_replica(0)
    assert len(_worker_threads()) == before + 1
    router.close()
    assert len(_worker_threads()) == before


def test_load_snapshot_is_host_only_truth(engines):
    _reset(engines)
    eng = engines[0]
    sched = Scheduler(eng, retain_prefixes=True, max_queue=4)
    snap = sched.load_snapshot()
    assert snap["slots"] == eng.slots
    assert snap["slots_busy"] == 0 and snap["queue_depth"] == 0
    assert snap["pages_free"] == eng.pool.free_pages
    for _ in range(3):
        sched.submit(Request(prompt=list(range(1, 10)),
                             max_new_tokens=6))
    sched.step()
    snap = sched.load_snapshot()
    assert snap["slots_busy"] == 2 and snap["slots_free"] == 0
    assert snap["queue_depth"] == 1 and snap["queue_free"] == 3
    assert snap["pages_free"] == eng.pool.free_pages < \
        eng.pool.num_pages - 1
    while sched.pending:
        sched.step()


def test_router_over_mesh_sharded_replicas_tp_by_dp(lm_and_params,
                                                    engines):
    """The tp × dp claim, structurally: replicas may each be
    ``mesh=``-sharded engines (here tp=1 meshes on the one CPU device,
    PR 9's bitwise-pinned configuration — tp>1 emulation stays in the
    slow tier) and the router composes with them untouched — same
    greedy stream, bitwise the unsharded fleet's output."""
    from jax.sharding import Mesh

    _reset(engines)
    oracle = _stream(seed=21)
    r_plain = Router(engines, retain_prefixes=True,
                     route_policy="least_loaded")
    r_plain.run(oracle)
    r_plain.close()
    mesh = Mesh(np.array(jax.devices("cpu")[:1]), ("tp",))
    sharded = [_mk_engine(lm_and_params, mesh=mesh) for _ in range(2)]
    got = _stream(seed=21)
    r_mesh = Router(sharded, retain_prefixes=True,
                    route_policy="least_loaded")
    r_mesh.run(got)
    assert _tokens(got) == _tokens(oracle), \
        "tp=1-mesh replicas diverged from the unsharded fleet"
    assert all(e.tp == 1 for e in sharded)
    r_mesh.close()
    for e in sharded:
        _audit_drained(e)


# ------------------------------------------------- FaultPlan satellite
def test_replica_death_spec_validation_and_seeded_replay():
    with pytest.raises(ValueError, match="victim replica"):
        FaultSpec(kind="replica_death", tick=0)
    spec = FaultSpec(kind="replica_death", tick=2, replica=1)
    plan = FaultPlan([spec])
    assert plan.take_replica_deaths(0) == []
    assert plan.take_replica_deaths(2) == [1]
    assert plan.take_replica_deaths(2) == []     # consumed once
    assert plan.stats()["injected_replica_deaths"] == 1
    # the new kwargs leave pre-router seeds byte-identical (the draw is
    # skipped entirely at the default rate 0)
    old = FaultPlan.random(11, 40, slots=4, nonfinite_rate=0.2,
                           exception_rate=0.2, stall_rate=0.1)
    new = FaultPlan.random(11, 40, slots=4, nonfinite_rate=0.2,
                           exception_rate=0.2, stall_rate=0.1,
                           replica_death_rate=0.0, replicas=3)
    assert old.specs == new.specs
    with pytest.raises(ValueError, match="replicas"):
        FaultPlan.random(11, 10, slots=4, replica_death_rate=0.5)
    deadly = FaultPlan.random(11, 60, slots=4, replica_death_rate=0.3,
                              replicas=3)
    deaths = [s for s in deadly.specs if s.kind == "replica_death"]
    assert deaths and all(0 <= s.replica < 3 for s in deaths)
    # and the non-death half of the schedule is unperturbed by rate 0
    assert [s for s in deadly.specs if s.kind != "replica_death"] == []
