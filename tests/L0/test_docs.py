"""The per-symbol API reference (docs/api/, VERDICT round-4 weak #6)
must exist, cover the public surface, and be IN SYNC with the
docstrings — the checked-in pages are regenerated here and diffed, so a
docstring change without `python docs/gen_api.py` fails CI instead of
shipping stale docs."""

import importlib.util
import os

import pytest

_DOCS = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                     "docs")


def _gen():
    spec = importlib.util.spec_from_file_location(
        "gen_api", os.path.join(_DOCS, "gen_api.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_api_reference_in_sync_with_docstrings(tmp_path):
    gen = _gen()
    gen.main(str(tmp_path))
    for page in list(gen.PAGES) + ["index"]:
        fresh = (tmp_path / f"{page}.md").read_text()
        checked_in = os.path.join(_DOCS, "api", f"{page}.md")
        assert os.path.exists(checked_in), \
            f"docs/api/{page}.md missing — run python docs/gen_api.py"
        with open(checked_in) as f:
            if f.read() != fresh:
                pytest.fail(f"docs/api/{page}.md is stale — regenerate "
                            "with: JAX_PLATFORMS=cpu python docs/gen_api.py")


def test_api_reference_covers_the_public_surface():
    gen = _gen()
    # every section of SURVEY's layer map has a page, and the flagship
    # symbols appear with their signatures
    probes = {
        "amp": ["make_train_step", "resolve_policy", "class `LossScaler`"],
        "optimizers": ["fused_adam", "fused_lamb"],
        "transformer": ["ColumnParallelLinear", "forward_backward_1f1b",
                        "kernel_partition_spec"],
        "kernels": ["flash_attention", "memory_efficient",
                    "softmax_cross_entropy_loss"],
        "contrib": ["distributed_fused_adam", "SelfMultiheadAttn"],
        "parallel": ["initialize_distributed", "make_hybrid_mesh",
                     "SyncBatchNorm"],
        "utils": ["save_checkpoint", "AsyncCheckpointer"],
    }
    for page, names in probes.items():
        path = os.path.join(_DOCS, "api", f"{page}.md")
        with open(path) as f:
            text = f.read()
        for n in names:
            assert n in text, f"{n} missing from docs/api/{page}.md"
