"""Quantized serving weights — int8 per-output-channel GEMMs, hermetic.

The acceptance bar from the weight-quantization issue, as tests:

- **config validation + loud calibration failure**: non-int8 dtypes /
  unknown granularities / bad margins are rejected at config time, and
  an all-zero (or non-finite) output channel raises at ENGINE
  construction with the parameter path and channel named — degenerate
  scales must never surface later as NaN logits;
- **per-channel round-trip exactness**: weights already on the
  quantization grid recover their exact codes and values, arbitrary
  weights round-trip within ``scale / 2`` per element, and each output
  channel carries its OWN scale (the epilogue-fold exactness argument
  needs per-channel, not per-tensor);
- **token-match-rate >= threshold vs the bf16 oracle** across
  chunk-boundary prompt lengths (below/at/straddling), the PR 10
  tolerance contract one tier over;
- **zero new compiled programs**: the quantized engine compiles the
  same pinned program set — quantization is a params property;
- **composition is the point**: wq+kv_quant serves within tolerance
  with both tiers' storage shrunk, wq+speculative stays bitwise
  plain-vs-spec (accept-longest-prefix emits the program's own greedy
  targets — quantization moves both modes identically), a wq prefix
  hit matches its cold miss token-for-token, and a tp=1 mesh is
  bitwise vs the unsharded wq engine (tp=2 slow-marked, per the PR 5
  pattern) with the scale leaves sharded next to their kernels;
- **the bf16 default stays the bitwise baseline**: ``weight_quant=
  None`` carries no scale leaves, compiles the same programs, and two
  default engines serve token-identically — none of the quant code is
  on its trace path.

Everything runs on CPU with a tiny model at policy O0 (exact fp32
compute — the match-rate tolerance isolates QUANTIZATION error, not
bf16 rounding); the kernels take their interpret/reference paths.
"""

import copy

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, KVQuantConfig, Request, Scheduler,
                              SpecConfig, WeightQuantConfig)
from apex_tpu.serving.quant_common import QMAX, dequantize, quantize
from apex_tpu.serving.weight_quant import (param_bytes, param_count,
                                           quant_scale_absmax)

pytestmark = pytest.mark.serving

VOCAB = 96          # divisible by the tp sizes under test (1, 2)
CHUNK = 8
# the tolerance of the issue's token-match contract at tiny-model
# scale: a single early argmax flip diverges a request's whole greedy
# tail, so the bound is deliberately below the bench-scale claim
MATCH_THRESHOLD = 0.95


def _tiny_lm(**kw):
    return TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                         num_heads=4, max_seq_len=64, **kw)


@pytest.fixture(scope="module")
def lm_and_params():
    m = _tiny_lm()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, weight_quant=None, pool=2, slots=3,
               seed=5, **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=64, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=pool,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  weight_quant=weight_quant, **kw)


@pytest.fixture(scope="module")
def engine_pair(lm_and_params):
    """bf16(O0) oracle + int8-weights engine, identical geometry — the
    match-rate pair (jit caches warm across the module)."""
    return (_mk_engine(lm_and_params),
            _mk_engine(lm_and_params, weight_quant=WeightQuantConfig()))


def _shared_prefix_stream(seed, n=8, new_tokens=8):
    """Prefix hit/miss/evict shape: every prompt opens with one shared
    16-token (2-page) prefix plus a short unique tail."""
    rng = np.random.default_rng(seed)
    pre = list(rng.integers(1, VOCAB, size=16))
    reqs = []
    for _ in range(n):
        tail = list(rng.integers(1, VOCAB,
                                 size=int(rng.integers(1, 7))))
        reqs.append(Request(prompt=pre + tail,
                            max_new_tokens=new_tokens))
    return reqs


def _serve(engine, seed, **sched_kw):
    engine.reset(clear_prefixes=True)
    sched = Scheduler(engine, retain_prefixes=True, **sched_kw)
    reqs = _shared_prefix_stream(seed)
    sched.run(reqs)
    return [list(r.output_tokens) for r in reqs]


def _match_rate(a_lists, b_lists):
    tot = hit = 0
    for a, b in zip(a_lists, b_lists):
        assert len(a) == len(b)
        tot += len(a)
        hit += sum(int(x == y) for x, y in zip(a, b))
    return hit / tot if tot else 1.0


# ---------------------------------------------- config + loud calibration
def test_config_validation():
    with pytest.raises(ValueError, match="int8"):
        WeightQuantConfig(dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="granularity"):
        WeightQuantConfig(granularity="tensor")
    with pytest.raises(ValueError, match="margin"):
        WeightQuantConfig(margin=0.0)
    with pytest.raises(ValueError, match="margin"):
        WeightQuantConfig(margin=float("nan"))


def test_engine_type_validation(lm_and_params):
    with pytest.raises(TypeError, match="WeightQuantConfig"):
        _mk_engine(lm_and_params, weight_quant="int8")


def test_degenerate_channel_raises_at_construction(lm_and_params):
    """The loud-calibration satellite: an all-zero (or non-finite)
    output channel raises at engine construction with the parameter
    path and channel index named — never deferred to NaN logits."""
    m, params = lm_and_params
    for poison in (0.0, float("nan")):
        bad = copy.deepcopy(jax.device_get(params))
        bad["block_1"]["mlp_in"]["kernel"][:, 7] = poison
        with pytest.raises(ValueError,
                           match=r"degenerate.*mlp_in/kernel output "
                                 r"channel 7"):
            Engine(m, bad, slots=2, max_len=64, prefill_len=24,
                   chunk_len=CHUNK,
                   policy=resolve_policy("O0", verbose=False),
                   weight_quant=WeightQuantConfig())
    # a zero vocab ROW is the embedding's degenerate channel (the tied
    # head's output channel) — same loud contract
    bad = copy.deepcopy(jax.device_get(params))
    bad["wte"]["embedding"][3, :] = 0.0
    with pytest.raises(ValueError,
                       match=r"degenerate.*wte/embedding output "
                             r"channel 3"):
        Engine(m, bad, slots=2, max_len=64, prefill_len=24,
               chunk_len=CHUNK,
               policy=resolve_policy("O0", verbose=False),
               weight_quant=WeightQuantConfig())


def test_unquantizable_tree_raises(lm_and_params):
    """A tree with no recognizable GEMM site must refuse loudly, not
    serve silently unquantized."""
    with pytest.raises(ValueError, match="no quantizable"):
        WeightQuantConfig().quantize_params(
            {"dense": {"kernel": np.ones((4, 4), np.float32)}})


# ------------------------------------------------- round-trip + structure
def test_per_channel_roundtrip_exactness():
    """Grid weights recover exactly; arbitrary weights round-trip
    within scale/2 per element; each output channel carries its own
    scale (per-channel, not per-tensor — channels with wildly
    different ranges must not share a grid)."""
    rng = np.random.default_rng(3)
    # per-channel ranges spanning 3 orders of magnitude
    chan_absmax = np.array([1e-2, 0.5, 2.0, 40.0], np.float32)
    w = rng.uniform(-1, 1, size=(16, 4)).astype(np.float32) * chan_absmax
    # force the absmax onto the grid edge so scales are known exactly
    w[0] = chan_absmax
    # margin=1.0 isolates the GRID's properties (the absmax lands on
    # code 127 exactly, so quantize∘dequantize is a fixed point); the
    # 1.2 production default only stretches the same grid
    cfg = WeightQuantConfig(margin=1.0)
    q = cfg.quantize_params({"mlp_in": {"kernel": w}})["mlp_in"]
    assert q["kernel"].dtype == jnp.int8
    np.testing.assert_allclose(np.asarray(q["kernel_scale"]),
                               chan_absmax / QMAX, rtol=1e-6)
    back = np.asarray(dequantize(q["kernel"], q["kernel_scale"], axis=1))
    bound = chan_absmax / QMAX / 2
    assert (np.abs(back - w) <= bound[None, :] * (1 + 1e-6)).all()
    # grid weights: quantize∘dequantize is the identity (exact code
    # recovery — the engine's storage quantize reproduces the values
    # the GEMM loads)
    q2 = cfg.quantize_params({"mlp_in": {"kernel": back}})["mlp_in"]
    back2 = np.asarray(dequantize(q2["kernel"], q2["kernel_scale"],
                                  axis=1))
    np.testing.assert_allclose(back2, back, rtol=1e-6, atol=1e-9)


def test_quantize_params_structure_and_bytes(lm_and_params):
    """The quantized tree: int8 kernels + fp32 sibling scales at every
    GEMM site, the tied embedding per-vocab-row, everything else
    untouched — and the bf16->int8 weight-bytes reduction clears the
    45% acceptance bar at this geometry."""
    _, params = lm_and_params
    p16 = resolve_policy("O3", verbose=False).cast_params(params)
    q = WeightQuantConfig().quantize_params(p16)
    for site in ("attn/qkv", "attn/proj"):
        a, b = site.split("/")
        node = q["block_0"][a][b]
        assert node["kernel"].dtype == jnp.int8
        assert node["kernel_scale"].dtype == jnp.float32
        assert node["kernel_scale"].shape == (node["kernel"].shape[-1],)
        assert node["bias"].dtype == jnp.bfloat16     # untouched
    for site in ("mlp_in", "mlp_out"):
        node = q["block_1"][site]
        assert node["kernel"].dtype == jnp.int8
        assert node["kernel_scale"].shape == (node["kernel"].shape[-1],)
    assert q["wte"]["embedding"].dtype == jnp.int8
    assert q["wte"]["embedding_scale"].shape == (VOCAB,)   # per row
    assert q["wpe"].dtype == jnp.bfloat16                  # untouched
    assert q["block_0"]["ln_attn"]["scale"].dtype == jnp.bfloat16
    # this fixture's hidden=32 model is overhead-heavy (wpe/LN/bias are
    # a third of it), so the reduction reads low here — pin a floor,
    # and pin the issue's 45% acceptance bar at the bench smoke
    # geometry below
    reduction = 1.0 - param_bytes(q) / param_bytes(p16)
    assert reduction >= 0.40, f"weight-bytes reduction {reduction:.3f}"
    # scale overhead charges the bytes-per-param gauge, not the count
    assert param_count(q) == param_count(p16)
    assert quant_scale_absmax(q) > 0


def test_weight_bytes_reduction_clears_the_bar_at_bench_geometry():
    """The >= 45% acceptance bar, pinned at the geometry the bench
    smoke serves (create_lm('tiny'), vocab 512): bf16 -> int8+scales
    must clear it, and the production 'small' shape sits near the 50%
    construction limit."""
    from apex_tpu.models.transformer_lm import create_lm

    m = create_lm("tiny", vocab_size=512, max_seq_len=128)
    p = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
               train=False)["params"]
    p16 = resolve_policy("O3", verbose=False).cast_params(p)
    q = WeightQuantConfig().quantize_params(p16)
    reduction = 1.0 - param_bytes(q) / param_bytes(p16)
    assert reduction >= 0.45, f"weight-bytes reduction {reduction:.3f}"


# ------------------------------------------------------------- composition
def test_token_match_vs_bf16_oracle_over_hit_miss_evict(engine_pair):
    """THE tentpole pin: the int8-weights engine serves the prefix
    hit/miss/evict stream at greedy token-match-rate >= threshold vs
    the bf16 oracle."""
    oracle, wq = engine_pair
    out_o = _serve(oracle, seed=42)
    out_w = _serve(wq, seed=42)
    rate = _match_rate(out_o, out_w)
    assert rate >= MATCH_THRESHOLD, \
        f"weight-quant token-match-rate {rate:.3f} vs bf16 oracle"


def test_chunk_boundary_prompt_lengths_match(engine_pair):
    """Match-rate across chunk-boundary prompt lengths (below / at /
    straddling / multi-chunk) — both ingest paths quantize the same
    GEMMs, so no boundary may open a divergence cliff."""
    oracle, wq = engine_pair
    rng = np.random.default_rng(17)
    prompts = [list(rng.integers(1, VOCAB, size=n))
               for n in (5, CHUNK, CHUNK + 5, 2 * CHUNK, 21)]
    outs = {}
    for label, eng in (("oracle", oracle), ("wq", wq)):
        eng.reset(clear_prefixes=True)
        reqs = [Request(prompt=p, max_new_tokens=6) for p in prompts]
        Scheduler(eng).run(reqs)
        outs[label] = [list(r.output_tokens) for r in reqs]
    rate = _match_rate(outs["oracle"], outs["wq"])
    assert rate >= MATCH_THRESHOLD, \
        f"chunk-boundary token-match-rate {rate:.3f}"


def test_zero_new_programs(engine_pair):
    """Quantization is a params property: the wq engine compiles the
    SAME pinned paged program set (chunk + decode + the monolithic
    baseline; copy retired) — zero new executables."""
    _, wq = engine_pair
    wq.prefill(0, [5, 9, 2])          # the monolithic baseline compiles
    assert (wq.chunk_traces, wq.decode_traces, wq.prefill_traces,
            wq.copy_traces) == (1, 1, 1, 0)
    assert wq.compiled_programs == 3


def test_wq_composes_with_kv_quant(lm_and_params):
    """The two int8 tiers together: weight bytes AND cache bytes both
    shrink, served output stays within the match-rate contract vs the
    all-bf16 oracle, and still zero new programs."""
    oracle = _mk_engine(lm_and_params, seed=7)
    both = _mk_engine(lm_and_params, weight_quant=WeightQuantConfig(),
                      kv_quant=KVQuantConfig(), seed=7)
    assert jnp.dtype(both.cache.dtype) == jnp.int8
    assert both.params["block_0"]["attn"]["qkv"]["kernel"].dtype \
        == jnp.int8
    # O0 oracle stores fp32 cache; int8 quarters it at this policy
    assert both.cache.nbytes() * 2 <= oracle.cache.nbytes()
    rate = _match_rate(_serve(oracle, seed=33), _serve(both, seed=33))
    assert rate >= MATCH_THRESHOLD, \
        f"wq+kv_quant token-match-rate {rate:.3f}"
    assert both.compiled_programs == both.chunk_traces \
        + both.decode_traces


def test_speculative_is_bitwise_plain_vs_spec_on_wq_engine(
        lm_and_params):
    """Speculative composition: ON the weight-quantized engine,
    spec-vs-plain stays bitwise (the verify program's emitted tokens
    ARE its own greedy targets — weight quantization moves both modes
    identically) with real drafts accepted."""
    eng = _mk_engine(lm_and_params, weight_quant=WeightQuantConfig(),
                     spec=SpecConfig(draft_len=3, ngram=2))
    rng = np.random.default_rng(7)
    hist = list(rng.integers(1, VOCAB, size=10))

    def stream(r):
        reqs = []
        for _ in range(4):
            tail = list(r.integers(1, VOCAB, size=3))
            reqs.append(Request(prompt=(hist + tail + tail)[:24],
                                max_new_tokens=10))
        return reqs

    outs, accepted = {}, {}
    for mode, sp in (("plain", False), ("spec", True)):
        eng.reset(clear_prefixes=True)
        sched = Scheduler(eng, speculative=sp)
        reqs = stream(np.random.default_rng(3))
        sched.run(reqs)
        outs[mode] = [list(r.output_tokens) for r in reqs]
        accepted[mode] = sum(r.spec_accepted for r in reqs)
    assert outs["spec"] == outs["plain"]
    assert accepted["spec"] > 0, "drafter never fired — the exactness " \
        "pin proved nothing"
    assert eng.verify_traces == 1


def test_prefix_hit_matches_cold_miss_on_wq_engine(lm_and_params):
    """COW composition: a prefix hit on the wq engine shares pages as
    usual (weights are engine state, not cache state — the tier adds
    nothing to the hit path) and the hit's tokens match the cold miss
    token-for-token."""
    eng = _mk_engine(lm_and_params, weight_quant=WeightQuantConfig())
    eng.reset(clear_prefixes=True)
    sched = Scheduler(eng, retain_prefixes=True)
    rng = np.random.default_rng(9)
    pre = list(rng.integers(1, VOCAB, size=8))      # exactly one page
    tail = list(rng.integers(1, VOCAB, size=3))
    (miss,) = sched.run([Request(prompt=pre + tail, max_new_tokens=4)])
    assert miss.reused_tokens == 0
    (hit,) = sched.run([Request(prompt=pre + tail, max_new_tokens=4)])
    assert hit.reused_tokens == 8
    assert hit.output_tokens == miss.output_tokens


def test_tp1_mesh_is_bitwise_vs_unsharded_wq_engine(lm_and_params):
    """Tensor-parallel composition (tier-1 half): a 1-device mesh over
    the wq engine — scale leaves sharded next to their kernels under
    the rule table — serves the greedy stream BITWISE identical to the
    unsharded wq engine, the same pin the bf16 and kv-quant tiers
    carry."""
    from jax.sharding import Mesh

    e0 = _mk_engine(lm_and_params, weight_quant=WeightQuantConfig(),
                    seed=11)
    e1 = _mk_engine(lm_and_params, weight_quant=WeightQuantConfig(),
                    seed=11,
                    mesh=Mesh(np.array(jax.devices()[:1]), ("tp",)))
    assert _serve(e1, seed=21) == _serve(e0, seed=21)


@pytest.mark.slow
def test_tp2_mesh_is_token_exact_vs_unsharded_wq_engine(lm_and_params):
    """Tensor-parallel composition (slow half, per the PR 5 pattern):
    tp=2 CPU device emulation over the wq engine is token-exact vs the
    unsharded wq engine, with column-parallel scales SPLIT on the
    output axis (qkv head-group permuted with its kernel) and
    row-parallel scales replicated."""
    from jax.sharding import Mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    e0 = _mk_engine(lm_and_params, weight_quant=WeightQuantConfig(),
                    seed=11)
    e2 = _mk_engine(lm_and_params, weight_quant=WeightQuantConfig(),
                    seed=11,
                    mesh=Mesh(np.array(jax.devices()[:2]), ("tp",)))
    assert _serve(e2, seed=23) == _serve(e0, seed=23)
    b0 = e2.params["block_0"]
    qkv_scale = b0["attn"]["qkv"]["kernel_scale"]     # column-parallel
    assert {s.data.shape for s in qkv_scale.addressable_shards} \
        == {(48,)}                                    # 96 / tp
    # shard 0 holds the head-group-PERMUTED first half: its heads' Q,
    # K and V scales, exactly the kernel's split
    full = np.asarray(
        e0.params["block_0"]["attn"]["qkv"]["kernel_scale"])
    perm = full.reshape(3, 2, 2, 8).transpose(1, 0, 2, 3).reshape(-1)
    np.testing.assert_array_equal(
        np.asarray(qkv_scale.addressable_shards[0].data), perm[:48])
    proj_scale = b0["attn"]["proj"]["kernel_scale"]   # row-parallel
    assert all(s.data.shape == (32,)
               for s in proj_scale.addressable_shards)  # replicated


# ----------------------------------------------------- the bf16 default pin
def test_weight_quant_none_stays_the_bitwise_baseline(lm_and_params):
    """The contract the issue states: weight_quant=None is the DEFAULT
    and the bitwise baseline. Two default engines serve the stream
    token-identically, their params carry NO scale leaves and keep the
    original kernel dtype, and the program set is the pinned one."""
    a = _mk_engine(lm_and_params, seed=11)
    b = _mk_engine(lm_and_params, seed=11)
    assert a.weight_quant is None
    qkv = a.params["block_0"]["attn"]["qkv"]
    assert "kernel_scale" not in qkv
    assert "embedding_scale" not in a.params["wte"]
    assert qkv["kernel"].dtype == jnp.float32         # O0 cast, not int8
    assert _serve(a, seed=31) == _serve(b, seed=31)
    a.prefill(0, [5, 9, 2])           # the monolithic baseline compiles
    assert (a.chunk_traces, a.decode_traces, a.prefill_traces,
            a.copy_traces) == (1, 1, 1, 0)


def test_wq_gauges_report_the_capacity_claim(lm_and_params):
    """serving.wq.* telemetry: bytes_per_param drops below half the
    bf16 figure's 2.0 at this geometry (the measurable weight-capacity
    claim, scale overhead included), quant_scale_absmax reports the
    grid's representable range, and neither gauge exists on the
    default engine (the family doubles as the tier's liveness
    signal)."""
    reg_b, reg_q = telemetry.MetricsRegistry(), telemetry.MetricsRegistry()
    _mk_engine(lm_and_params, registry=reg_b)
    eq = _mk_engine(lm_and_params, weight_quant=WeightQuantConfig(),
                    registry=reg_q)
    gb = reg_b.snapshot()["gauges"]
    gq = reg_q.snapshot()["gauges"]
    assert "serving.wq.bytes_per_param" not in gb
    assert "serving.wq.quant_scale_absmax" not in gb
    # O0 keeps fp32 (4 B) non-kernel leaves, so the quantized mean sits
    # above 1.0 but far below the fp32 tree's 4.0
    assert 1.0 <= gq["serving.wq.bytes_per_param"] < 2.0
    assert gq["serving.wq.quant_scale_absmax"] > 0
    # swap-in registry path (warmup pattern) re-emits the gauges
    reg2 = telemetry.MetricsRegistry()
    eq.set_registry(reg2)
    assert "serving.wq.bytes_per_param" in reg2.snapshot()["gauges"]
