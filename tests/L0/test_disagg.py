"""Disaggregated prefill/decode serving — the role split, hermetic.

The acceptance bar from the disaggregation issue, as tests:

- a SPLIT fleet (1 prefill-role + N decode-role replicas behind one
  ``Router(roles=[...])``) serves a greedy mixed-length stream —
  including multi-turn sessions whose later prompts extend earlier
  ones — **bitwise identical** to a ``"both"`` fleet over the same
  engines: the handoff travels as an ordinary CRC'd swapped prefix
  through the shared host arena and the decode side resumes chunk
  prefill at the exact committed offset, so the first sampled token
  comes from byte-exact K/V through the same compiled programs;
- the ``handoff_corruption`` chaos kind degrades per the
  hierarchical-KV contract: the decode side re-prefills COLD (counted
  ``serving.disagg.reprefills`` + ``serving.swap.verify_failed``),
  tokens stay bitwise, ZERO retries are charged and every request
  still reaches the typed ``COMPLETED`` terminal — never a wrong
  token, never a fault charged to the request;
- zero leaked pages AND zero leaked arena bytes at drain on both
  sides: per-engine pool audits reconcile, the fleet-level union of
  every cache's swapped keys equals the shared arena's key set, and a
  clearing reset leaves the arena at zero bytes;
- role validation raises loudly: an all-prefill fleet, an all-decode
  fleet, a mixed fleet without ONE shared ``HostTier(shared=True)``,
  a roles/engines length mismatch, and a direct ``submit`` to a
  ``role="decode"`` scheduler are all configuration errors;
- program-count pins per role: a prefill-role engine compiles exactly
  {chunk prefill, swap-out} and a decode-role engine exactly
  {chunk prefill, decode, swap-in} — the existing swap pair split
  across the roles, zero new executables;
- dispatch-ahead chunk prefill (the satellite): ``pipeline_depth=0``
  stays the bitwise oracle for the dispatch-then-reconcile split, on
  a bare scheduler and on the split fleet;
- quarantine requeues on a mixed fleet flow back through the router
  (``on_requeue``): the retry re-probes LIVE replicas at re-route
  time instead of being pinned to the replica that faulted.

Everything runs on CPU with a tiny model at policy O0 (exact fp32).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, FaultPlan, FaultSpec, HostTier,
                              PoolAuditor, Request, RequestStatus,
                              Router, Scheduler)

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

VOCAB = 64
CHUNK = 8


@pytest.fixture(scope="module")
def lm_and_params():
    m = TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                      num_heads=4, max_seq_len=64)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, tier=None, slots=2, pool=4, seed=5,
               **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=64, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=pool, paged=True,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  host_tier=tier, **kw)


@pytest.fixture(scope="module")
def fleet(lm_and_params):
    """Three identically-built paged engines co-owning ONE shared host
    arena: every test resets them (clear_prefixes=True — on a shared
    arena each engine discards only its own records), so bitwise
    comparisons across role layouts stay within the same compiled
    executables per engine."""
    tier = HostTier(1 << 24, shared=True)
    engines = [_mk_engine(lm_and_params, tier=tier) for _ in range(3)]
    return tier, engines


def _reset(fleet):
    tier, engines = fleet
    for e in engines:
        e.reset(clear_prefixes=True)
        e.set_registry(None)
    assert tier.bytes_used == 0, \
        "shared arena holds bytes after every co-owner reset"


def _stream(seed=42):
    """Mixed-length prompts below / at / straddling the chunk boundary
    (short prompts exercise the key-less handoff: no full chunk means
    nothing to hand over, the decode side cold-prefills)."""
    rng = np.random.default_rng(seed)
    return [Request(prompt=list(rng.integers(1, VOCAB, size=n)),
                    max_new_tokens=b)
            for n, b in [(5, 10), (8, 4), (13, 6), (21, 4), (3, 9),
                         (16, 5), (7, 1), (24, 6), (17, 5), (11, 7)]]


def _session_waves(turns=2, sessions=3):
    """Multi-turn sessions: turn t+1's prompt EXTENDS turn t's, served
    wave after wave — the affinity + handoff-interaction workload (a
    later turn may match a locally registered session prefix INSTEAD
    of its own handoff record; the unused record must be released, not
    leaked)."""
    rng = np.random.default_rng(7)
    base = rng.integers(1, VOCAB, size=CHUNK).tolist()
    prompts = []
    for s in range(sessions):
        srng = np.random.default_rng(100 + s)
        p = base + srng.integers(1, VOCAB, size=CHUNK).tolist()
        turns_s = [list(p)]
        for _ in range(turns - 1):
            p = p + srng.integers(1, VOCAB, size=4).tolist()
            turns_s.append(list(p))
        prompts.append(turns_s)
    return [[Request(prompt=prompts[s][t], max_new_tokens=4)
             for s in range(sessions)] for t in range(turns)]


def _tokens(reqs):
    return [list(r.output_tokens) for r in reqs]


def _audit_fleet(fleet):
    """The zero-leak pin, both tiers: every engine's pool reconciles,
    and the fleet-level cross-arena walk closes — the union of every
    cache's swapped keys IS the shared arena's key set (no dangling
    swapped entry anywhere, no orphaned arena record)."""
    tier, engines = fleet
    aud = PoolAuditor()
    swapped = set()
    for e in engines:
        aud.audit(e)                # raises PoolInvariantError on leaks
        swapped |= set(e.prefix_cache.swapped_keys())
    assert swapped == set(tier.keys()), (
        f"fleet swapped keys {sorted(swapped)} != arena keys "
        f"{sorted(tier.keys())}")


def _serve(fleet, roles, requests, *, registry=None, replica_plans=None,
           **kw):
    tier, engines = fleet
    router = Router(engines, registry=registry, roles=roles,
                    retain_prefixes=True, max_queue=16,
                    replica_plans=replica_plans, **kw)
    if isinstance(requests[0], list):            # session waves
        for wave in requests:
            router.run(wave)
        served = [r for wave in requests for r in wave]
    else:
        router.run(requests)
        served = requests
    return served


# ------------------------------------------------------------- validation
def test_roles_validation_raises_loudly(lm_and_params):
    tier = HostTier(1 << 20, shared=True)
    engines = [_mk_engine(lm_and_params, tier=tier) for _ in range(2)]
    with pytest.raises(ValueError, match="no decode-capable"):
        Router(engines, roles=["prefill", "prefill"],
               retain_prefixes=True)
    with pytest.raises(ValueError, match="no prefill-capable"):
        Router(engines, roles=["decode", "decode"],
               retain_prefixes=True)
    with pytest.raises(ValueError, match="roles has 1 entries"):
        Router(engines, roles=["both"], retain_prefixes=True)
    with pytest.raises(ValueError, match="fleet policy"):
        Router(engines, roles=["prefill", "decode"],
               retain_prefixes=True, role="decode")
    # the arena must be ONE instance, marked shared
    unshared = HostTier(1 << 20)
    pair = [_mk_engine(lm_and_params, tier=unshared) for _ in range(2)]
    with pytest.raises(ValueError, match="shared=True"):
        Router(pair, roles=["prefill", "decode"], retain_prefixes=True)
    split_tiers = [_mk_engine(lm_and_params,
                              tier=HostTier(1 << 20, shared=True))
                   for _ in range(2)]
    with pytest.raises(ValueError, match="same"):
        Router(split_tiers, roles=["prefill", "decode"],
               retain_prefixes=True)
    # roles ride on the prefix/handoff machinery: both seams required
    with pytest.raises(ValueError, match="retain_prefixes"):
        Scheduler(engines[0], role="prefill")
    with pytest.raises(ValueError, match="host_tier"):
        Scheduler(_mk_engine(lm_and_params), role="decode",
                  retain_prefixes=True)
    with pytest.raises(ValueError, match="role must be"):
        Scheduler(engines[0], role="draft", retain_prefixes=True)


def test_decode_role_rejects_direct_submit(lm_and_params):
    """A decode-role replica serves router hand-overs only — a raw
    prompt submitted straight at it is a configuration error, not a
    silent cold prefill on the wrong tier."""
    tier = HostTier(1 << 20, shared=True)
    sched = Scheduler(_mk_engine(lm_and_params, tier=tier),
                      role="decode", retain_prefixes=True)
    with pytest.raises(ValueError, match="hand-overs only"):
        sched.submit(Request(prompt=[1, 2, 3], max_new_tokens=2))
    sched.close()


# ------------------------------------------------------ bitwise + leak-free
def test_split_fleet_bitwise_identical_to_both_fleet(fleet):
    """The tentpole pin: 1 prefill + 2 decode serves the identical
    greedy mixed-length + session stream BITWISE as an all-"both"
    fleet over the SAME engines, with zero re-prefills charged on the
    happy path beyond the key-less short prompts, zero retries, and
    both tiers draining leak-free."""
    _reset(fleet)
    baseline = _serve(fleet, ["both"] * 3, _stream())
    base_waves = _serve(fleet, ["both"] * 3, _session_waves())
    base = _tokens(baseline) + _tokens(base_waves)
    _audit_fleet(fleet)

    _reset(fleet)
    reg = telemetry.MetricsRegistry()
    split = _serve(fleet, ["prefill", "decode", "decode"], _stream(),
                   registry=reg)
    split_waves = _serve(fleet, ["prefill", "decode", "decode"],
                         _session_waves(), registry=reg)
    got = _tokens(split) + _tokens(split_waves)
    assert got == base, "split fleet diverged from the 'both' fleet"
    served = split + split_waves
    assert all(r.status is RequestStatus.FINISHED for r in served)
    assert all(r.retries == 0 for r in served), \
        "a handoff charged a retry"
    counters = dict(reg.counters)
    assert counters.get("serving.disagg.handoffs", 0) == len(served), \
        "every ingested prompt must hand over exactly once"
    assert counters.get("serving.disagg.reprefills", 0) == 0, \
        "happy-path handoffs must not re-prefill"
    assert counters.get("serving.disagg.handoff_bytes", 0) > 0
    _audit_fleet(fleet)
    _reset(fleet)


def test_decode_isolation_gauge_and_heartbeat_split(fleet):
    """Decode-role replicas must not spend their beats on prompt
    ingestion: the decode_isolation gauge (fraction of decode-role
    beats that ran NO chunk prefill) stays high on the split fleet —
    only verified-miss re-prefills and the resumed final chunk may
    dent it — while a 'both' fleet pays prefill beats everywhere."""
    _reset(fleet)
    reg = telemetry.MetricsRegistry()
    _serve(fleet, ["prefill", "decode", "decode"], _stream(),
           registry=reg)
    iso = dict(reg.gauges).get("serving.disagg.decode_isolation")
    assert iso is not None, "split fleet emitted no isolation gauge"
    assert 0.0 < iso <= 1.0
    # only the resumed final chunk may touch a decode beat here (no
    # chaos in this test): well over half the decode beats are pure
    assert iso > 0.5, f"decode replicas spent {1 - iso:.0%} of beats " \
        "prefilling — the role split is not isolating ingestion"
    reg2 = telemetry.MetricsRegistry()
    _serve(fleet, ["both"] * 3, _stream(), registry=reg2)
    assert "serving.disagg.decode_isolation" not in dict(reg2.gauges), \
        "a 'both' fleet has no decode-role beats to measure"
    _reset(fleet)


# ------------------------------------------------------------------ chaos
def test_handoff_corruption_reprefills_never_wrong_token(fleet):
    """Seeded ``handoff_corruption`` chaos: the record's CRC fails at
    the importer's swap-in, the request re-prefills COLD on the decode
    side (typed COMPLETED terminal, zero retries charged), tokens stay
    bitwise vs the clean run, and both tiers drain leak-free."""
    _reset(fleet)
    clean = _tokens(_serve(fleet, ["prefill", "decode", "decode"],
                           _stream()))
    _reset(fleet)
    reg = telemetry.MetricsRegistry()
    plan = FaultPlan([FaultSpec(kind="handoff_corruption", tick=3),
                      FaultSpec(kind="handoff_corruption", tick=5)])
    chaos = _serve(fleet, ["prefill", "decode", "decode"], _stream(),
                   registry=reg, replica_plans=[plan, None, None])
    assert _tokens(chaos) == clean, \
        "handoff corruption changed a token — the CRC verify leaked " \
        "rotten bytes into decode"
    assert all(r.status is RequestStatus.FINISHED for r in chaos)
    assert all(r.retries == 0 for r in chaos), \
        "arena rot is not the request's fault — no retry may be charged"
    counters = dict(reg.counters)
    assert counters.get("serving.disagg.reprefills", 0) >= 1, \
        "corruption injected but nothing re-prefilled"
    assert counters.get("serving.swap.verify_failed", 0) >= 1
    assert plan.injected_handoff_corruptions >= 1
    assert plan.stats()["injected_handoff_corruptions"] \
        == plan.injected_handoff_corruptions
    _audit_fleet(fleet)
    _reset(fleet)


def test_faultplan_handoff_corruption_replay_compatible():
    """``handoff_corruption_rate=0.0`` must not perturb the RNG draw
    sequence (seed-N replays from before the kind existed stay
    identical), and a positive rate emits the kind."""
    kw = dict(slots=4, nonfinite_rate=0.3, exception_rate=0.2)
    assert FaultPlan.random(3, 40, **kw).specs \
        == FaultPlan.random(3, 40, handoff_corruption_rate=0.0,
                            **kw).specs
    plan = FaultPlan.random(3, 60, slots=4, handoff_corruption_rate=0.5)
    assert any(s.kind == "handoff_corruption" for s in plan.specs)
    # no uid-keyed records in the arena: armed but nothing to corrupt
    empty = FaultPlan([FaultSpec(kind="handoff_corruption", tick=0)])
    assert not empty.maybe_corrupt_handoff(0, HostTier(1 << 10))


# ----------------------------------------------- dispatch-ahead prefill
def test_dispatch_ahead_prefill_depth0_is_bitwise_oracle(fleet):
    """The satellite's oracle: chunk prefill split into dispatch +
    reconcile halves (``pipeline_depth>=1``) emits bitwise the tokens
    of the synchronous ``depth=0`` beat — on a bare scheduler and on
    the split fleet."""
    _reset(fleet)
    tier, engines = fleet

    def run_sched(depth):
        sched = Scheduler(engines[0], retain_prefixes=True,
                          pipeline_depth=depth, max_queue=16)
        reqs = _stream()
        for r in reqs:
            sched.submit(r)
        steps = 0
        while sched.pending and steps < 5000:
            sched.step()
            steps += 1
        sched.close()
        return _tokens(reqs)

    sync = run_sched(0)
    assert run_sched(1) == sync
    _reset(fleet)
    split = _serve(fleet, ["prefill", "decode", "decode"], _stream(),
                   pipeline_depth=1)
    assert all(r.status is RequestStatus.FINISHED for r in split)
    assert _tokens(split) == sync
    _audit_fleet(fleet)
    _reset(fleet)


# ------------------------------------------------------ requeue re-probe
def test_quarantine_requeue_reroutes_through_router(fleet):
    """Satellite: on a mixed fleet a quarantined request goes back to
    the ROUTER (which re-probes live replicas and the arena at
    re-route time), not the faulted replica's private queue — and
    still completes bitwise with exactly the one charged retry."""
    _reset(fleet)
    clean = _tokens(_serve(fleet, ["prefill", "decode", "decode"],
                           _stream()))
    _reset(fleet)
    reg = telemetry.MetricsRegistry()
    plan = FaultPlan([FaultSpec(kind="exception", tick=2,
                                site="decode")])
    chaos = _serve(fleet, ["prefill", "decode", "decode"], _stream(),
                   registry=reg, replica_plans=[None, plan, None])
    assert _tokens(chaos) == clean
    assert all(r.status is RequestStatus.FINISHED for r in chaos)
    assert sum(r.retries for r in chaos) >= 1, "fault never fired"
    assert dict(reg.counters).get("serving.router.requeued", 0) >= 1, \
        "quarantine requeue bypassed the router"
    _audit_fleet(fleet)
    _reset(fleet)


# ------------------------------------------------------- program pins
def test_program_counts_pin_exact_per_role(lm_and_params):
    """Zero new executables: the role split re-uses the existing swap
    pair, one direction per side. Fresh engines so the census is
    exact: prefill-role = {chunk prefill, swap-out}; decode-role =
    {chunk prefill, decode, swap-in}."""
    tier = HostTier(1 << 24, shared=True)
    pe = _mk_engine(lm_and_params, tier=tier)
    de = _mk_engine(lm_and_params, tier=tier)
    router = Router([pe, de], roles=["prefill", "decode"],
                    retain_prefixes=True, max_queue=16)
    router.run(_stream())
    assert (pe.chunk_traces, pe.swap_out_traces) == (1, 1)
    assert (pe.decode_traces, pe.swap_in_traces, pe.copy_traces,
            pe.verify_traces, pe.prefill_traces) == (0, 0, 0, 0, 0), \
        "a prefill-role engine traced a decode-side program"
    assert (de.chunk_traces, de.decode_traces,
            de.swap_in_traces) == (1, 1, 1)
    assert (de.swap_out_traces, de.copy_traces, de.verify_traces,
            de.prefill_traces) == (0, 0, 0, 0), \
        "a decode-role engine traced an ingest-side program"
    assert pe.compiled_programs == 2 and de.compiled_programs == 3
    router.close()
