"""Flash-attention kernel vs fp32 jnp reference (SURVEY §5.1: oracle
reference impls, not golden files; §5.4: interpret=True so correctness never
depends on the TPU emulator). Mirrors the reference's
apex/contrib/test/multihead_attn + fmha tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels.flash_attention import flash_attention, mha_reference

B, H, S, D = 2, 2, 256, 64


def _qkv(key, s=S, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(key), 3)
    q = jax.random.normal(kq, (B, H, s, D), dtype)
    k = jax.random.normal(kk, (B, H, s, D), dtype)
    v = jax.random.normal(kv, (B, H, s, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q, k, v = _qkv(0)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = mha_reference(q, k, v, causal=causal, scale=1.0 / D ** 0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    q, k, v = _qkv(1)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       interpret=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal,
                                     scale=1.0 / D ** 0.5) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


def test_segment_ids_varlen():
    """fmhalib parity: packed sequences don't attend across boundaries."""
    q, k, v = _qkv(2)
    seg = jnp.concatenate([jnp.zeros((B, S // 2), jnp.int32),
                           jnp.ones((B, S // 2), jnp.int32)], axis=1)
    out = flash_attention(q, k, v, segment_ids=seg, interpret=True)
    ref = mha_reference(q, k, v, scale=1.0 / D ** 0.5, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    # cross-check: first half independently attended
    out_half = flash_attention(q[:, :, :S // 2], k[:, :, :S // 2],
                               v[:, :, :S // 2], interpret=True)
    np.testing.assert_allclose(np.asarray(out[:, :, :S // 2]),
                               np.asarray(out_half), rtol=1e-4, atol=1e-4)


def test_bf16_io():
    q, k, v = _qkv(3, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = mha_reference(q, k, v, causal=True, scale=1.0 / D ** 0.5)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=3e-2, atol=3e-2)


def test_unaligned_falls_back():
    q, k, v = _qkv(4, s=100)  # 100 % 128 != 0 → reference path
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True, scale=1.0 / D ** 0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_cross_attention_shapes():
    q, _, _ = _qkv(5)
    _, k, v = _qkv(6, s=128)
    out = flash_attention(q, k, v, interpret=True)
    assert out.shape == (B, H, S, D)
    ref = mha_reference(q, k, v, scale=1.0 / D ** 0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("bias_shape", [(1, 1), (1, H), (B, 1), (B, H)])
@pytest.mark.parametrize("causal", [False, True])
def test_bias_forward_and_grads(causal, bias_shape):
    """Additive logits bias (apex additive-mask variants / evoformer pair
    bias): forward and ALL grads — including dbias with broadcast
    reduction — must match the unfused fp32 reference."""
    q, k, v = _qkv(3)
    bb, bh = bias_shape
    bias = jax.random.normal(jax.random.PRNGKey(7), (bb, bh, S, S),
                             jnp.float32) * 0.5
    scale = 1.0 / D ** 0.5

    out = flash_attention(q, k, v, causal=causal, bias=bias, interpret=True)
    ref = mha_reference(q, k, v, causal=causal, scale=scale, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)

    def f_flash(q, k, v, bias):
        return (flash_attention(q, k, v, causal=causal, bias=bias,
                                interpret=True)
                .astype(jnp.float32) * _qkv(4)[0].astype(jnp.float32)).sum()

    def f_ref(q, k, v, bias):
        return (mha_reference(q, k, v, causal=causal, scale=scale, bias=bias)
                .astype(jnp.float32) * _qkv(4)[0].astype(jnp.float32)).sum()

    g1 = jax.grad(f_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for a, b, name in zip(g1, g2, "q k v bias".split()):
        assert a.shape == b.shape, name
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


def test_bias_bad_shape_raises():
    q, k, v = _qkv(5)
    with pytest.raises(ValueError, match="bias"):
        flash_attention(q, k, v, bias=jnp.zeros((1, 1, 1, S)),
                        interpret=True)


# ---------------------------------------------------------------- dropout
def test_dropout_zero_rate_is_identity():
    q, k, v = _qkv(6)
    base = flash_attention(q, k, v, causal=True, interpret=True)
    same = flash_attention(q, k, v, causal=True, dropout_rate=0.0,
                           dropout_seed=7, interpret=True)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(same))


def test_dropout_requires_seed_and_valid_rate():
    q, k, v = _qkv(6)
    with pytest.raises(ValueError, match="dropout_seed"):
        flash_attention(q, k, v, dropout_rate=0.1, interpret=True)
    with pytest.raises(ValueError, match="dropout_rate"):
        flash_attention(q, k, v, dropout_rate=1.0, dropout_seed=0,
                        interpret=True)


def test_dropout_fallback_semantics():
    """CPU/interpret path (jax.random mask): deterministic under a fixed
    seed, different under another, unbiased in expectation (inverted
    scaling), and differentiable with the same mask in fwd and bwd."""
    q, k, v = _qkv(7)
    r = 0.3
    d1 = flash_attention(q, k, v, dropout_rate=r, dropout_seed=1,
                         interpret=True)
    d1b = flash_attention(q, k, v, dropout_rate=r, dropout_seed=1,
                          interpret=True)
    d2 = flash_attention(q, k, v, dropout_rate=r, dropout_seed=2,
                         interpret=True)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d1b))
    assert not np.allclose(np.asarray(d1), np.asarray(d2))

    # unbiased: average over many seeds approaches the deterministic output
    base = np.asarray(flash_attention(q, k, v, interpret=True))
    acc = np.zeros_like(base)
    n = 24
    for s in range(n):
        acc += np.asarray(flash_attention(q, k, v, dropout_rate=r,
                                          dropout_seed=100 + s,
                                          interpret=True))
    np.testing.assert_allclose(acc / n, base, atol=0.25)

    # grads: deterministic given the seed, finite, and consistent with the
    # autodiff of the (deterministic) dropped forward
    def loss(v_):
        return (flash_attention(q, k, v_, dropout_rate=r, dropout_seed=3,
                                interpret=True)
                .astype(jnp.float32) ** 2).sum()

    g1 = jax.grad(loss)(v)
    g2 = jax.grad(loss)(v)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
    assert np.isfinite(np.asarray(g1)).all()
    # finite-difference check on one coordinate (same seed → same mask)
    eps = 1e-3
    probe = jnp.zeros_like(v).at[0, 0, 0, 0].set(eps)
    fd = (loss(v + probe) - loss(v - probe)) / (2 * eps)
    np.testing.assert_allclose(float(fd), float(np.asarray(g1)[0, 0, 0, 0]),
                               rtol=2e-2, atol=2e-2)
