"""Request-level distributed tracing: the observability tentpole's
acceptance pins.

- **Off is free, bitwise**: ``tracer=None`` (the default) allocates not
  a single :class:`~apex_tpu.telemetry.Span` (pinned by a poisoned
  ``Span.__init__``), and a traced run's greedy tokens are bitwise
  identical to the untraced run on the SAME engine with ZERO new
  compiled programs — observation never perturbs the observed.
- **Lifecycle coverage**: every served request's trace carries the
  full span ladder (``submit`` → ``queue_wait`` → ``admit`` →
  ``prefill_chunk``+ → ``heartbeat``+ → terminal ``finish``), with the
  annotations the docs table promises (slot, pages, prompt/output
  token counts) and causally ordered timestamps.
- **Chrome export structure**: a 2-replica router run exports
  Perfetto-loadable trace-event JSON — one named process per replica,
  one named track per thread, ``args.trace_id`` on every span event,
  timestamps sorted within each lane — and every span of a routed
  request lands under its placement's pid.
- **Chaos composes** (the satellite pin): under a seeded
  :class:`~apex_tpu.serving.FaultPlan`, every trace ends in EXACTLY
  one terminal span, ``quarantine`` spans carry the typed
  :func:`~apex_tpu.serving.fault_kind`, un-faulted requests stay
  bitwise vs the fault-free untraced run, and tracing+chaos together
  still add zero compiled programs.
- **Router probe short-circuit** (the hash-skip satellite): with
  retention off there is nothing to probe — ``Router.submit`` must
  never touch ``PrefixCache.block_keys`` (the ``affinity_enabled``
  gate); with retention ON, a sub-block prompt (which can never match
  an entry) skips the hash walk and the N probes too.
- **JSONL export + CLI**: ``export_jsonl`` records join the
  ``serving.request`` completion stream on ``trace_id`` through
  ``python -m apex_tpu.telemetry trace``.

Hermetic on CPU with the tiny LM; rides the ``serving`` + ``chaos``
markers like the rest of the fault tier.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, FaultPlan, FaultPolicy, FaultSpec,
                              Request, RequestStatus, Router, Scheduler,
                              fault_kind)
from apex_tpu.serving.prefix_cache import PrefixCache
from apex_tpu.telemetry import JsonlSink, MetricsRegistry, Tracer
from apex_tpu.telemetry import tracing
from apex_tpu.telemetry.summarize import load_records

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

VOCAB = 101
CHUNK = 8

#: the docs table's three terminal names — exactly one per trace
TERMINALS = {"finish", "expired", "failed"}


@pytest.fixture(scope="module")
def lm_and_params():
    m = TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                      num_heads=4, max_seq_len=64)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, pool=4, slots=2, seed=5, **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=64, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=pool, paged=True,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  **kw)


@pytest.fixture(scope="module")
def engine(lm_and_params):
    """One shared paged engine: traced and untraced runs compare
    bitwise within the same compiled executables."""
    return _mk_engine(lm_and_params)


@pytest.fixture(scope="module")
def engines(lm_and_params):
    return [_mk_engine(lm_and_params), _mk_engine(lm_and_params)]


def _fast_policy(**kw):
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("audit_every_n", 1)
    return FaultPolicy(**kw)


def _stream(seed=1):
    rng = np.random.default_rng(seed)
    return [Request(prompt=list(rng.integers(1, VOCAB, size=n)),
                    max_new_tokens=b)
            for n, b in [(5, 8), (13, 6), (9, 5), (17, 4)]]


def _tokens(reqs):
    return [list(r.output_tokens) for r in reqs]


# --------------------------------------------------------- tracer unit
def test_tracer_spans_seal_and_late_attribution():
    clk = iter(float(i) for i in range(100))
    tr = Tracer(clock=lambda: next(clk))
    tr.begin("r1")
    tr.event("r1", "submit", prompt_tokens=5)
    tr.event("r1", "admit", t0=10.0, dur=0.5, slot=1)
    assert [t.trace_id for t in tr.live_traces()] == ["r1"]
    tr.end_trace("r1", "finish", reason="eos")
    t = tr.find("r1")
    assert t.terminal == "finish"
    assert [s.name for s in t.spans] == ["submit", "admit", "finish"]
    assert t.by_name("admit")[0].args == {"slot": 1}
    assert t.by_name("admit")[0].t0 == 10.0
    assert tr.live_traces() == [] and len(tr.traces()) == 1
    # a second terminal is a no-op: first terminal wins
    tr.end_trace("r1", "failed", reason="late")
    assert tr.find("r1").terminal == "finish"
    assert len(tr.find("r1").by_name("failed")) == 0
    # a LATE span (worker thread finishing after the seal) still lands
    tr.event("r1", "swap_out_store", pages=2)
    assert len(tr.find("r1").by_name("swap_out_store")) == 1


def test_tracer_bounded_rings():
    tr = Tracer(max_traces=2)
    for i in range(5):
        tr.event(f"live{i}", "submit")
    assert len(tr.live_traces()) == 2          # oldest evicted
    for i in range(5):
        tr.end_trace(f"done{i}", "finish")
    assert len(tr.traces()) == 2
    assert tr.find("done0") is None            # aged out of the ring
    assert tr.find("done4").terminal == "finish"


def test_tracer_bind_event_current_and_replica_views():
    tr = Tracer()
    tr.event_current("swap_in")                # unbound: silent no-op
    assert tr._all_spans() == []
    assert tr.current() is None
    with tr.bind("req", pid=3):
        assert tr.current() == "req"
        tr.event_current("swap_out", pages=1)
        with tr.bind("inner", pid=4):          # re-entrant stack
            tr.event_current("swap_out_store")
        tr.event_current("swap_in")
    assert tr.current() is None
    assert [s.pid for s in tr.find("req").spans] == [3, 3]
    assert tr.find("inner").spans[0].pid == 4
    # the replica view bakes its pid into events AND terminals
    v = tr.for_replica(7)
    v.event("req2", "admit")
    v.end_trace("req2", "finish")
    assert [s.pid for s in tr.find("req2").spans] == [7, 7]


# ------------------------------------------------------ off is free
def test_tracer_none_is_bitwise_invisible(engine, monkeypatch):
    """The zero-cost contract, both halves: an untraced run constructs
    ZERO Span objects (Span.__init__ is poisoned for its duration),
    and a traced run of the same stream on the same engine produces
    bitwise-identical greedy tokens with zero new compiled programs —
    attaching observability cannot perturb the serve."""
    engine.reset(clear_prefixes=True)

    def _boom(*a, **kw):
        raise AssertionError(
            "Span allocated with tracer=None — the off switch leaks")

    monkeypatch.setattr(tracing.Span, "__init__", _boom)
    plain = _stream()
    Scheduler(engine, retain_prefixes=True,
              fault_policy=_fast_policy()).run(plain)
    monkeypatch.undo()
    programs0 = engine.compiled_programs

    engine.reset(clear_prefixes=True)
    tr = Tracer()
    traced = _stream()
    Scheduler(engine, retain_prefixes=True, fault_policy=_fast_policy(),
              tracer=tr).run(traced)
    assert _tokens(traced) == _tokens(plain), \
        "attaching a tracer changed greedy tokens"
    assert engine.compiled_programs == programs0, \
        "tracing traced new programs"
    assert len(tr.traces()) == len(traced)


# ------------------------------------------------------ lifecycle
def test_lifecycle_spans_cover_every_request(engine):
    engine.reset(clear_prefixes=True)
    tr = Tracer()
    reqs = _stream()
    Scheduler(engine, retain_prefixes=True, fault_policy=_fast_policy(),
              tracer=tr).run(reqs)
    for r in reqs:
        t = tr.find(r.uid)
        assert t is not None and t.terminal == "finish"
        (submit,) = t.by_name("submit")
        assert submit.args["prompt_tokens"] == len(r.prompt)
        (qw,) = t.by_name("queue_wait")
        assert qw.dur >= 0.0
        (admit,) = t.by_name("admit")
        assert admit.args["slot"] in (0, 1)
        assert admit.args["pages"] > 0         # paged engine reserves
        chunks = t.by_name("prefill_chunk")
        assert len(chunks) == r.chunks and chunks[-1].args["final"]
        assert chunks[0].args["lo"] == 0
        beats = t.by_name("heartbeat")
        assert beats and all(b.dur >= 0.0 for b in beats)
        assert {"tick", "host_s", "device_wait_s"} <= set(
            beats[0].args)
        (fin,) = t.by_name("finish")
        assert fin.args["output_tokens"] == len(r.output_tokens)
        # causal order: submitted before admitted before finished
        assert submit.t0 <= admit.t0 <= fin.t0
        # every span on the bare scheduler carries replica 0
        assert {s.pid for s in t.spans} == {0}


# --------------------------------------------- router + chrome export
def test_router_tracing_and_chrome_export_structure(engines, tmp_path):
    for e in engines:
        e.reset(clear_prefixes=True)
    tr = Tracer()
    router = Router(engines, retain_prefixes=True, tracer=tr)
    reqs = _stream(seed=42) + _stream(seed=43)
    router.run(reqs)
    placements = dict(router.placements)
    router.close()
    used = set()
    for r in reqs:
        home = placements[r.uid]
        used.add(home)
        t = tr.find(r.uid)
        (route,) = t.by_name("route")
        assert route.args["replica"] == home
        assert route.args["policy"] == "affinity"
        assert route.dur >= 0.0 and "spills" in route.args
        # EVERY span of the request (route included) sits under its
        # placement's Chrome process — the for_replica(pid) contract
        assert {s.pid for s in t.spans} == {home}, \
            f"request {r.uid} spans leaked across replica pids"

    path = tmp_path / "trace.json"
    n = tr.export_chrome_trace(str(path))
    doc = json.loads(path.read_text())
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    spans = [e for e in events if e["ph"] == "X"]
    assert len(spans) == n > 0
    # one named process per replica pid that emitted anything
    procs = {e["pid"]: e["args"]["name"] for e in meta
             if e["name"] == "process_name"}
    assert procs == {i: f"replica{i}" for i in used}
    # every thread lane is named, spans reference only named lanes
    lanes = {(e["pid"], e["tid"]) for e in meta
             if e["name"] == "thread_name"}
    assert {(e["pid"], e["tid"]) for e in spans} <= lanes
    for e in spans:
        assert e["cat"] == "serving"
        assert "trace_id" in e["args"]
        assert e["ts"] >= 0 and e["dur"] >= 0
    # timestamps sorted within each (pid, tid) lane — what keeps the
    # Perfetto tracks readable
    for lane in {(e["pid"], e["tid"]) for e in spans}:
        ts = [e["ts"] for e in spans
              if (e["pid"], e["tid"]) == lane]
        assert ts == sorted(ts)


# ------------------------------------------------- chaos composition
def test_chaos_and_tracing_compose(engine):
    """The composition pin: tracing a chaotic serve keeps every
    guarantee of both features — exactly ONE terminal span per trace,
    quarantine spans typed by fault_kind, un-faulted requests bitwise
    vs the fault-free UNTRACED run, zero compiled programs added by
    the combination."""
    engine.reset(clear_prefixes=True)
    sched0 = Scheduler(engine, fault_policy=_fast_policy())
    clean_reqs = _stream()
    sched0.run(clean_reqs)
    clean = _tokens(clean_reqs)
    programs0 = engine.compiled_programs

    engine.reset(clear_prefixes=True)
    plan = FaultPlan([
        FaultSpec(kind="stall", tick=1, stall_s=0.03),
        FaultSpec(kind="exception", tick=2, site="chunk"),
        FaultSpec(kind="nonfinite", tick=3, slot=0),
        FaultSpec(kind="exception", tick=6, site="decode", slot=1),
    ])
    stalls = []
    tr = Tracer()
    reqs = _stream()
    Scheduler(engine,
              fault_policy=_fast_policy(max_retries=1,
                                        watchdog_budget_s=0.02,
                                        on_stall=stalls.append),
              fault_plan=plan, tracer=tr).run(reqs)
    assert plan.stats()["injected_nonfinite"] == 1
    assert plan.stats()["injected_exceptions"] == 2
    assert plan.stats()["injected_stalls"] == 1 and len(stalls) >= 1
    faulted = [r for r in reqs if r.retries > 0]
    assert faulted, "the plan must actually fault requests"
    for i, r in enumerate(reqs):
        t = tr.find(r.uid)
        assert t is not None
        # EXACTLY one terminal span, agreeing with the sealed name and
        # the request's typed terminal status
        terms = [s for s in t.spans if s.name in TERMINALS]
        assert len(terms) == 1, \
            f"request {r.uid}: {len(terms)} terminal spans"
        assert t.terminal == terms[0].name
        assert r.status.terminal
        expected = {RequestStatus.FINISHED: "finish",
                    RequestStatus.EXPIRED: "expired",
                    RequestStatus.FAILED: "failed"}[r.status]
        assert t.terminal == expected
        # quarantines are typed: one span per retry, kind from the
        # same classifier the docs table names
        qs = t.by_name("quarantine")
        assert len(qs) == r.retries
        for q in qs:
            assert q.args["kind"] in ("nonfinite", "exception",
                                      "swap", "injected")
            assert q.args["kind"] == fault_kind(q.args["error"])
        # un-faulted and retried-to-completion requests both bitwise
        # reproduce the fault-free untraced tokens
        if r.status is RequestStatus.FINISHED:
            assert list(r.output_tokens) == clean[i], \
                f"request {i} diverged under chaos+tracing"
    kinds = {q.args["kind"] for r in faulted
             for q in tr.find(r.uid).by_name("quarantine")}
    assert "nonfinite" in kinds and "injected" in kinds
    assert engine.compiled_programs == programs0, \
        "chaos+tracing traced new programs"


def test_swap_tracing_and_corruption_compose(lm_and_params):
    """The hierarchical-KV half of the composition pin: the swap-out
    span pair lands in the trace bound at dispatch (admission-side
    ``swap_out`` + store-side ``swap_out_store``), and a chaos
    ``swap_corruption`` racing the restore shows up as a ``swap_in``
    span with ``outcome=verify_failed`` / ``crc_ok=False`` while the
    request still finishes bitwise-cold with exactly one terminal span
    and zero retries (a verified miss is degradation, not a fault)."""
    from apex_tpu.serving import HostTier

    eng = _mk_engine(lm_and_params, host_tier=1 << 24, sync_swap=True)
    cold = _mk_engine(lm_and_params, pool=0)
    rng = np.random.default_rng(17)
    pre = list(rng.integers(1, VOCAB, size=16))
    p2 = pre + list(rng.integers(1, VOCAB, size=3))
    (oracle,) = Scheduler(cold).run(
        [Request(prompt=list(p2), max_new_tokens=5)])

    tr = Tracer()
    sched = Scheduler(eng, retain_prefixes=True,
                      fault_policy=_fast_policy(), tracer=tr)
    sched.run([Request(prompt=pre + [7, 8, 9], max_new_tokens=5)])
    # evict under an explicit binding: both swap-out halves attribute
    # to it (the engine never sees a request — context is the binding)
    with tr.bind("evict-ctx"):
        assert eng.prefix_cache.evict_lru()
    ev = tr.find("evict-ctx")
    (so,) = ev.by_name("swap_out")
    assert so.args["pages"] > 0 and so.args["bytes"] > 0
    (st,) = ev.by_name("swap_out_store")
    assert st.args["stored"] and st.args["inline"]   # sync_swap engine
    assert st.args["bytes"] > 0

    sched.fault_plan = FaultPlan(
        [FaultSpec(kind="swap_corruption", tick=sched._tick)])
    r2 = Request(prompt=list(p2), max_new_tokens=5)
    sched.run([r2])
    assert list(r2.output_tokens) == list(oracle.output_tokens)
    assert r2.retries == 0
    t = tr.find(r2.uid)
    (si,) = t.by_name("swap_in")
    assert si.args["outcome"] == "verify_failed"
    assert si.args["crc_ok"] is False
    assert not t.by_name("quarantine")
    assert [s.name for s in t.spans if s.name in TERMINALS] == ["finish"]
    assert isinstance(eng.host_tier, HostTier) and eng.host_tier.size == 0
    sched.close()
    eng.close()


def test_replica_death_tracing_composes(engines):
    """The router half of the composition pin: a replica killed
    mid-stream drains its requests onto the survivor — every trace
    still ends in exactly ONE terminal span, and that terminal carries
    the SURVIVOR's pid (the trace follows the request across the
    fleet, it doesn't die with the replica)."""
    for e in engines:
        e.reset(clear_prefixes=True)
    tr = Tracer()
    plan = FaultPlan([FaultSpec(kind="replica_death", tick=3,
                                replica=0)])
    router = Router(engines, retain_prefixes=True,
                    route_policy="least_loaded", fault_plan=plan,
                    tracer=tr)
    reqs = _stream(seed=9)
    router.run(reqs)
    assert plan.stats()["injected_replica_deaths"] == 1
    assert router.alive == [False, True]
    for r in reqs:
        assert r.status == "finished"
        t = tr.find(r.uid)
        terms = [s for s in t.spans if s.name in TERMINALS]
        assert len(terms) == 1 and t.terminal == "finish"
        assert terms[0].pid == router.placements[r.uid] != 0
        assert t.by_name("route")                 # routed at least once
    router.close()


# ------------------------------------------- router probe short-circuit
def test_router_submit_never_probes_without_retention(engines,
                                                      monkeypatch):
    """The hash-skip satellite, pinned by counting: with
    retain_prefixes=False (the default) affinity degrades to
    least-loaded and Router.submit must never call
    PrefixCache.block_keys — there are no entries to match, so hashing
    every prompt would be pure routing-path overhead."""
    for e in engines:
        e.reset(clear_prefixes=True)
    calls = []
    real = PrefixCache.block_keys
    monkeypatch.setattr(
        PrefixCache, "block_keys",
        lambda self, tokens, n: (calls.append(len(tokens)),
                                 real(self, tokens, n))[1])
    router = Router(engines)                   # retention off
    assert not router.affinity_enabled
    for r in _stream():
        router.submit(r)
    assert calls == [], \
        "Router.submit hashed prompts with retention off"
    router.close()


def test_router_submit_skips_probe_for_sub_block_prompts(engines,
                                                         monkeypatch):
    """With retention ON, a prompt shorter than one prefix block can
    never match a cache entry: submit must skip the hash walk AND the
    per-replica probes, while a full-block prompt still probes."""
    for e in engines:
        e.reset(clear_prefixes=True)
    block = engines[0].prefix_cache.block_len
    calls = []
    real = PrefixCache.block_keys
    monkeypatch.setattr(
        PrefixCache, "block_keys",
        lambda self, tokens, n: (calls.append(len(tokens)),
                                 real(self, tokens, n))[1])
    router = Router(engines, retain_prefixes=True)
    assert router.affinity_enabled
    router.submit(Request(prompt=list(range(1, block)),
                          max_new_tokens=2))
    assert calls == [], "a sub-block prompt was hashed on submit"
    router.submit(Request(prompt=list(range(1, block + 2)),
                          max_new_tokens=2))
    assert len(calls) == 1, \
        "a full-block prompt must hash exactly once (shared probe key)"
    router.close()


# --------------------------------------------------- jsonl export + CLI
def test_jsonl_export_joins_completion_records_via_cli(engine, tmp_path,
                                                       capsys):
    engine.reset(clear_prefixes=True)
    path = tmp_path / "run.jsonl"
    reg = MetricsRegistry(sinks=[JsonlSink(str(path))])
    tr = Tracer()
    reqs = _stream()
    Scheduler(engine, registry=reg, retain_prefixes=True,
              fault_policy=_fast_policy(), tracer=tr).run(reqs)
    reg.close()
    n = tr.export_jsonl(str(path))             # appends to the same file
    records = load_records(str(path))
    spans = [r for r in records if r.get("tag") == tracing.TRACE_TAG]
    assert len(spans) == n > 0
    for r in spans:
        assert {"trace_id", "span", "ts_s", "dur_s", "replica",
                "thread"} <= set(r)
    # completion records carry the join key and the placement
    comps = [r for r in records if r.get("tag") == "serving.request"]
    assert len(comps) == len(reqs)
    assert all(r["trace_id"] == r["uid"] and r["replica"] == 0
               for r in comps)

    from apex_tpu.telemetry.__main__ import main
    assert main(["trace", str(path), "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["traces"] == len(reqs)
    assert summary["spans"]["finish"]["count"] == len(reqs)
    assert summary["requests"]["matched"] == len(reqs)
    assert summary["requests"]["unmatched_traces"] == 0
    assert summary["requests"]["statuses"] == {"finished": len(reqs)}
    assert "prefill_chunk" in summary["critical_path"]
    # the human rendering names the stages and the join
    assert main(["trace", str(path)]) == 0
    out = capsys.readouterr().out
    for token in ("prefill_chunk", "finish", "p95", "matched"):
        assert token in out
