"""Hierarchical KV — the host-DRAM prefix tier, hermetic.

The acceptance bar from the host-tier issue, as tests:

- a hit-after-swap greedy stream is **bitwise identical** to a
  never-swapped one, across prefix lengths below / at / straddling the
  block boundary (the swap round-trips exact bytes through the same
  compiled programs — storage moved, nothing recomputed);
- the tier adds AT MOST one compiled program (the fixed-shape
  ``swap_in`` page-block scatter — one dispatch per swap-in; the
  chunk/decode/prefill/verify set is untouched);
- zero leaked pages at drain across swap churn: the
  :class:`~apex_tpu.serving.PoolAuditor`'s device walk reconciles, and
  its new cross-tier walk reconciles host-arena entries against the
  prefix cache's swapped state (and is SENSITIVE: fabricated dangling /
  orphaned / drifted states raise);
- the host arena is capacity-bounded with its own LRU: an insert that
  does not fit evicts least-recently-put entries (whose index entries
  are dropped — never left dangling), and an entry bigger than the
  whole arena is declined (destroy fallback, the pre-tier behaviour);
- composition pins: ``kv_quant`` int8 pages swap out and restore
  byte-exact (half the transfer bytes for free), and the
  :class:`~apex_tpu.serving.Router`'s affinity probe still sees
  swapped prefixes (a swapped entry is warm state, not a cold miss);
- chaos: the ``swap_corruption`` fault kind (seeded,
  replay-compatible — rate 0 skips the draw) corrupts arena bytes and
  the next swap-in degrades to a VERIFIED MISS (re-prefill, counted as
  ``serving.swap.verify_failed``, hit/miss accounting reversed) —
  never a wrong token.

Everything runs on CPU with a tiny model at policy O0 (exact fp32).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, FaultPlan, FaultSpec, HostTier,
                              PoolAuditor, PoolInvariantError,
                              PrefixCache, Request, Router, Scheduler)

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

VOCAB = 101
CHUNK = 8          # chunk_len == page_len: every chunk is one page
# tiny-model page bytes: layers(2) * heads(4) * page_len(8) * head_dim(8)
# * fp32(4) * K-and-V(2) — the arena-capacity arithmetic below
PAGE_BYTES = 2 * 4 * 8 * 8 * 4 * 2


def _tiny_lm(max_seq_len=64, **kw):
    return TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                         num_heads=4, max_seq_len=max_seq_len, **kw)


@pytest.fixture(scope="module")
def lm_and_params():
    m = _tiny_lm()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, pool=2, slots=3, seed=5, paged=True,
               **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=64, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=pool, paged=paged,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  **kw)


@pytest.fixture(scope="module")
def engine_pair(lm_and_params):
    """One hierarchical engine (host tier on) + one plain engine —
    identical geometry, so a hit-after-swap stream and a never-swapped
    stream compare bitwise (jit caches warm across the module)."""
    return (_mk_engine(lm_and_params, host_tier=1 << 24),
            _mk_engine(lm_and_params))


# -------------------------------------------------------- arena (pure host)
def _fake_pages(rng, m=2, dtype=np.float32):
    shape = (2, m, 4, 8, 8)         # [layers, m, heads, page_len, d]
    return (rng.normal(size=shape).astype(dtype),
            rng.normal(size=shape).astype(dtype))


def test_host_tier_put_take_contains_and_lru_capacity():
    rng = np.random.default_rng(0)
    k, v = _fake_pages(rng)
    nbytes = k.nbytes + v.nbytes
    evicted = []
    tier = HostTier(2 * nbytes + 1, on_evict=evicted.append)
    assert tier.put(-1, k, v) and tier.put(-2, *_fake_pages(rng))
    assert tier.size == 2 and tier.bytes_used == 2 * nbytes
    assert tier.contains(-1) and not tier.contains(-9)
    assert tier.nbytes_of(-1) == nbytes and tier.nbytes_of(-9) == 0
    # a third insert exceeds the bound: the least-recently-put entry
    # (-1) is evicted and its owner notified
    assert tier.put(-3, *_fake_pages(rng))
    assert evicted == [-1] and not tier.contains(-1)
    assert tier.bytes_used == 2 * nbytes <= tier.capacity_bytes
    assert tier.evictions == 1
    # an entry alone bigger than the arena is DECLINED, nothing evicted
    big = HostTier(nbytes - 1)
    assert not big.put(-7, k, v)
    assert big.declined == 1 and big.size == 0
    # take pops and verifies
    rec = tier.take(-2)
    assert rec is not None and rec.valid and not tier.contains(-2)
    assert tier.take(-2) is None
    with pytest.raises(ValueError, match="capacity_bytes"):
        HostTier(0)
    tier.clear()
    assert tier.size == 0 and tier.bytes_used == 0


def test_host_tier_checksum_detects_corruption():
    rng = np.random.default_rng(1)
    tier = HostTier(1 << 20)
    tier.put(-1, *_fake_pages(rng))
    tier.put(-2, *_fake_pages(rng))
    tier.corrupt_entry(-1)
    bad, good = tier.take(-1), tier.take(-2)
    assert bad is not None and not bad.valid
    assert good is not None and good.valid
    assert tier.corruptions_detected == 1
    with pytest.raises(KeyError):
        tier.corrupt_entry(-99)


def test_prefix_cache_swap_state_and_pressure_valve():
    """Cache↔tier interplay without an engine: eviction under a wired
    tier is a swap (entry stays matchable/probeable), swapped entries
    are never pressure-valve victims (they hold no device pages — the
    pool loop must not spin on them), and a drop reverses cleanly."""
    released, store = [], {}
    pc = PrefixCache(block_len=4, on_evict=released.extend)
    pc.set_swap_hooks(swap_out=lambda key, pages: store.setdefault(
        key, tuple(pages)) is not None, contains=lambda key: key in store)
    prompt = list(range(10, 22))                     # 3 blocks of 4
    assert pc.register(prompt, pages=(3, 7, 9)) == "registered"
    (key,) = [e.row for e in pc._entries.values()]
    assert pc.evict_lru()                            # swap, not destroy
    assert released == [(3, 7, 9)][0:1] or released == [3, 7, 9]
    assert pc.swapped_keys() == [key] and pc.swap_outs == 1
    # still matchable (swapped=True) and probeable, read-only
    m = pc.match(prompt + [1])
    assert m is not None and m.swapped and m.pages is None \
        and m.length == 12
    assert pc.probe(prompt + [1]) == 12
    # no resident victims left: the valve reports nothing evictable
    # instead of spinning on the page-less swapped entry
    assert not pc.evict_lru()
    # the backing disappearing (tier capacity eviction) makes the next
    # match a miss, not a crash
    store.clear()
    assert pc.match(prompt + [1]) is None
    assert pc.drop(key) and not pc.drop(key)
    assert pc.swapped_keys() == [] and pc.size == 0


# ------------------------------------------------- hit-after-swap, bitwise
def _boundary_cases():
    """(prompt_a, prompt_b, expected_reuse) with shared-prefix lengths
    below / at / straddling the block boundary (block == page == 8) —
    the same sweep the paged-pool tests run, now across a swap."""
    rng = np.random.default_rng(42)
    out = []
    for pre_len, want in [(5, 0), (8, 8), (13, 8), (16, 16)]:
        pre = list(rng.integers(1, VOCAB, size=pre_len))
        out.append((pre + list(rng.integers(1, VOCAB, size=3)),
                    pre + list(rng.integers(1, VOCAB, size=3)), want))
    return out


def test_hit_after_swap_bitwise_vs_never_swapped(engine_pair):
    """THE acceptance pin: register a prefix, force it through a full
    device→host→device round trip, and the hit-after-swap stream must
    be bitwise identical to the never-swapped stream on the plain
    engine — same reuse accounting included."""
    et, ec = engine_pair
    for prompt_a, prompt_b, want_reuse in _boundary_cases():
        et.reset(clear_prefixes=True)
        ec.reset(clear_prefixes=True)
        st = Scheduler(et, retain_prefixes=True)
        sc = Scheduler(ec, retain_prefixes=True)
        (ra_t,) = st.run([Request(prompt=list(prompt_a),
                                  max_new_tokens=5)])
        (ra_c,) = sc.run([Request(prompt=list(prompt_a),
                                  max_new_tokens=5)])
        # every prompt here spans >= 1 block, so prompt_a always
        # registered an entry — eviction must SWAP it, not destroy it
        assert et.prefix_cache.evict_lru()
        assert et.prefix_cache.swapped_keys()
        assert et.host_tier.size == 1
        # the affinity probe still sees the swapped prefix (0 when
        # prompt_b's first block genuinely differs — the 5-token case)
        assert et.prefix_cache.probe(prompt_b) == want_reuse
        (rb_t,) = st.run([Request(prompt=list(prompt_b),
                                  max_new_tokens=5)])
        (rb_c,) = sc.run([Request(prompt=list(prompt_b),
                                  max_new_tokens=5)])
        assert ra_t.output_tokens == ra_c.output_tokens
        assert rb_t.output_tokens == rb_c.output_tokens, \
            f"hit-after-swap diverged (prefix {want_reuse})"
        assert rb_t.reused_tokens == rb_c.reused_tokens == want_reuse
        if want_reuse:
            # restored and re-resident: entry back on fresh pages,
            # arena drained of the migrated record
            assert not et.prefix_cache.swapped_keys()
            assert et.host_tier.size == 0


def test_at_most_one_new_program_and_zero_leaks(engine_pair):
    """Program-count pin + leak pin, over all the swap churn the
    module has driven so far: the hierarchical engine compiled exactly
    chunk + decode + swap_in (one more than the plain engine's two),
    and both pools audit clean — then drain to zero pages."""
    et, ec = engine_pair
    assert et.chunk_traces == 1 and et.decode_traces == 1
    assert et.swap_in_traces == 1          # every page shares ONE program
    assert et.copy_traces == et.verify_traces == et.prefill_traces == 0
    assert et.compiled_programs == 3
    assert ec.compiled_programs == 2 and ec.swap_in_traces == 0
    for eng in engine_pair:
        PoolAuditor().audit(eng)
        eng.reset(clear_prefixes=True)
        assert eng.pool.pages_in_use == 0
        PoolAuditor().audit(eng)
    assert et.host_tier.size == 0 and et.host_tier.bytes_used == 0


def test_engine_host_tier_validation(lm_and_params):
    with pytest.raises(ValueError, match="paged=True"):
        _mk_engine(lm_and_params, host_tier=1 << 20, paged=False)
    with pytest.raises(ValueError, match="prefix_pool"):
        _mk_engine(lm_and_params, host_tier=1 << 20, pool=0)
    # a pre-built arena is accepted as-is (capacity honoured)
    eng = _mk_engine(lm_and_params, host_tier=HostTier(1 << 20))
    assert isinstance(eng.host_tier, HostTier)
    assert eng.host_tier.capacity_bytes == 1 << 20


# -------------------------------------------------- capacity + composition
def test_capacity_bounded_arena_evicts_and_drops_entries(lm_and_params):
    """Engine-level capacity bound: an arena sized for ONE two-page
    prefix holds the latest swap-out; swapping a second entry out
    evicts the first's bytes AND drops its index entry (no dangling
    swapped state), with the auditor's cross-tier walk green
    throughout."""
    eng = _mk_engine(lm_and_params, pool=3,
                     host_tier=2 * PAGE_BYTES + 1)
    sched = Scheduler(eng, retain_prefixes=True)
    rng = np.random.default_rng(7)
    pres = [list(rng.integers(1, VOCAB, size=16)) for _ in range(2)]
    for pre in pres:
        sched.run([Request(prompt=pre + [1, 2], max_new_tokens=3)])
    auditor = PoolAuditor()
    assert eng.prefix_cache.evict_lru()        # swap entry 0 out
    auditor.audit(eng)
    assert eng.prefix_cache.evict_lru()        # swap entry 1: evicts 0
    auditor.audit(eng)
    tier = eng.host_tier
    assert tier.size == 1 and tier.evictions == 1
    assert tier.bytes_used <= tier.capacity_bytes
    # entry 0 is GONE from the index (dropped with its bytes): its
    # prefix probes 0, entry 1's still probes through the tier
    assert eng.prefix_cache.probe(pres[0] + [9]) == 0
    assert eng.prefix_cache.probe(pres[1] + [9]) == 16
    assert len(eng.prefix_cache.swapped_keys()) == 1


def test_int8_pages_swap_and_restore_byte_exact(lm_and_params):
    """kv_quant composition: int8 pages ride the tier at half the
    transfer bytes, and the restored device bytes are EXACTLY the
    evicted ones (the whole bitwise argument, at the byte level)."""
    from apex_tpu.serving import KVQuantConfig

    eng = _mk_engine(lm_and_params, host_tier=1 << 24,
                     kv_quant=KVQuantConfig())
    sched = Scheduler(eng, retain_prefixes=True)
    rng = np.random.default_rng(11)
    pre = list(rng.integers(1, VOCAB, size=16))
    sched.run([Request(prompt=pre + [7, 8], max_new_tokens=3)])
    (key,) = list(eng.prefix_cache._entries)
    pages0 = list(eng.prefix_cache._entries[key].pages)
    before_k = np.asarray(eng.cache.k[:, pages0]).copy()
    before_v = np.asarray(eng.cache.v[:, pages0]).copy()
    assert before_k.dtype == np.int8       # half the swap bytes, free
    assert eng.prefix_cache.evict_lru()
    assert eng.host_tier.bytes_used == 2 * PAGE_BYTES // 4   # int8 vs fp32
    (r,) = sched.run([Request(prompt=pre + [9, 10],
                              max_new_tokens=3)])
    assert r.reused_tokens == 16
    pages1 = list(eng.prefix_cache._entries[key].pages)
    np.testing.assert_array_equal(before_k,
                                  np.asarray(eng.cache.k[:, pages1]))
    np.testing.assert_array_equal(before_v,
                                  np.asarray(eng.cache.v[:, pages1]))
    PoolAuditor().audit(eng)


def test_router_affinity_probe_sees_swapped_prefixes(engine_pair):
    """Router composition: a replica whose prefix was swapped to host
    still wins the affinity probe — swap-out moves bytes, not
    routing signal."""
    et, ec = engine_pair
    for eng in engine_pair:
        eng.reset(clear_prefixes=True)
    reg = telemetry.MetricsRegistry()
    router = Router([et, ec], registry=reg, retain_prefixes=True)
    try:
        rng = np.random.default_rng(13)
        pre = list(rng.integers(1, VOCAB, size=16))
        (r1,) = router.run([Request(prompt=pre + [1, 2],
                                    max_new_tokens=3)])
        # find the replica that served turn 1 and swap its prefix out
        (home,) = [i for i, e in enumerate((et, ec))
                   if e.prefix_cache is not None and e.prefix_cache.size]
        owner = (et, ec)[home]
        if owner.host_tier is not None:
            assert owner.prefix_cache.evict_lru()
            assert owner.prefix_cache.swapped_keys()
        hits0 = reg.snapshot()["counters"].get(
            "serving.router.affinity_hits", 0)
        (r2,) = router.run([Request(prompt=pre + [3, 4],
                                    max_new_tokens=3)])
        hits1 = reg.snapshot()["counters"].get(
            "serving.router.affinity_hits", 0)
        assert hits1 == hits0 + 1          # the probe saw the prefix
        assert r2.reused_tokens == 16
    finally:
        router.close()


# ----------------------------------------------------------------- chaos
def test_swap_corruption_degrades_to_verified_miss(engine_pair):
    """The chaos pin: corrupt arena bytes make the next swap-in fail
    its checksum and the request re-prefills COLD — bitwise identical
    to a cold run, `serving.swap.verify_failed` counted, hit/miss
    accounting reversed, request FINISHED (never failed, never a wrong
    token)."""
    et, ec = engine_pair
    for eng in engine_pair:
        eng.reset(clear_prefixes=True)
    rng = np.random.default_rng(17)
    pre = list(rng.integers(1, VOCAB, size=16))
    p2 = pre + list(rng.integers(1, VOCAB, size=3))
    # cold oracle on the plain engine (no retention: fully cold)
    (oracle,) = Scheduler(ec).run([Request(prompt=list(p2),
                                           max_new_tokens=5)])
    reg = telemetry.MetricsRegistry()
    et.set_registry(reg)
    try:
        sched = Scheduler(et, registry=reg, retain_prefixes=True)
        sched.run([Request(prompt=pre + [7, 8, 9], max_new_tokens=5)])
        assert et.prefix_cache.evict_lru()
        base = dict(et.prefix_cache.stats())
        sched.fault_plan = FaultPlan(
            [FaultSpec(kind="swap_corruption", tick=sched._tick)])
        (r,) = sched.run([Request(prompt=list(p2), max_new_tokens=5)])
        assert r.output_tokens == oracle.output_tokens
        assert r.status == "finished" and r.reused_tokens == 0
        assert sched.fault_plan.injected_swap_corruptions == 1
        assert sched.fault_plan.stats()["injected_swap_corruptions"] == 1
        counters = reg.snapshot()["counters"]
        assert counters.get("serving.swap.verify_failed") == 1
        delta = et.prefix_cache.stats_since(base)
        assert delta["hits"] == 0 and delta["misses"] == 1   # reversed
        # the corrupt entry is gone everywhere; the pool stays clean
        assert not et.prefix_cache.swapped_keys()
        assert et.host_tier.size == 0
        PoolAuditor().audit(et)
    finally:
        et.set_registry(None)


def test_faultplan_swap_corruption_replay_compatible():
    """Rate 0 skips the draw entirely (the PR 12 replica-death
    pattern), so every pre-host-tier seed replays bit-for-bit; a
    positive rate draws the new kind."""
    kw = dict(slots=4, nonfinite_rate=0.3, exception_rate=0.2,
              stall_rate=0.1)
    assert FaultPlan.random(3, 40, **kw).specs \
        == FaultPlan.random(3, 40, swap_corruption_rate=0.0, **kw).specs
    plan = FaultPlan.random(3, 60, slots=4, swap_corruption_rate=0.5)
    assert any(s.kind == "swap_corruption" for s in plan.specs)
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(kind="swap_rot", tick=0)
    # an empty arena makes the injection a consumed no-op
    empty = FaultPlan([FaultSpec(kind="swap_corruption", tick=0)])
    assert not empty.maybe_corrupt_swap(0, HostTier(1 << 10))
    assert empty.injected_swap_corruptions == 0


# --------------------------------------------------------------- auditor
def test_auditor_cross_tier_walk_is_sensitive(engine_pair):
    """The extended conservation audit detects every cross-tier rot it
    claims to: dangling swapped entries, orphaned arena bytes, drifted
    byte accounting, and an over-capacity arena."""
    et, _ = engine_pair
    et.reset(clear_prefixes=True)
    sched = Scheduler(et, retain_prefixes=True)
    rng = np.random.default_rng(23)
    pre = list(rng.integers(1, VOCAB, size=16))
    sched.run([Request(prompt=pre + [1, 2], max_new_tokens=3)])
    assert et.prefix_cache.evict_lru()
    auditor = PoolAuditor()
    auditor.audit(et)                      # consistent: green
    tier = et.host_tier
    (key,) = tier.keys()
    # (1) dangling: swapped entry with no arena backing
    rec = tier._entries.pop(key)
    tier._bytes_used -= rec.nbytes
    with pytest.raises(PoolInvariantError, match="no host-tier backing"):
        auditor.audit(et)
    tier._entries[key] = rec
    tier._bytes_used += rec.nbytes
    auditor.audit(et)
    # (2) orphan: arena bytes backing no swapped entry
    tier._entries[-777] = rec
    tier._bytes_used += rec.nbytes
    with pytest.raises(PoolInvariantError, match="host-side leak"):
        auditor.audit(et)
    del tier._entries[-777]
    tier._bytes_used -= rec.nbytes
    # (3) byte-accounting drift
    tier._bytes_used += 1
    with pytest.raises(PoolInvariantError, match="drifted"):
        auditor.audit(et)
    tier._bytes_used -= 1
    # (4) over-capacity arena
    saved = tier.capacity_bytes
    tier.capacity_bytes = 1
    with pytest.raises(PoolInvariantError, match="over capacity"):
        auditor.audit(et)
    tier.capacity_bytes = saved
    auditor.audit(et)
    et.reset(clear_prefixes=True)
