"""Hierarchical KV — the host-DRAM prefix tier, hermetic.

The acceptance bar from the host-tier issue (+ the async/mesh
migration issue), as tests:

- a hit-after-swap greedy stream is **bitwise identical** to a
  never-swapped one, across prefix lengths below / at / straddling the
  block boundary (the swap round-trips exact bytes through the same
  compiled programs — storage moved, nothing recomputed);
- swap-out is ASYNC by default (dispatch on the admission path, the
  force/CRC/store on a ``SwapWorker`` thread) and bitwise identical
  to the ``sync_swap=True`` escape hatch — including a hit that lands
  while the bytes are still in flight (the *swapping* state: the hit
  JOINS the copy, never reads partial bytes) and a chaos
  ``swap_corruption`` racing the in-flight swap (verified miss,
  never a wrong token); a kill with a non-empty swap queue drains
  leak-free and no worker threads leak across construct/serve/close;
- the mesh restriction is LIFTED: a tp=1 mesh host-tier engine is
  bitwise vs ``mesh=None``, tp=2 (slow) is token-exact with
  per-shard arena records (one CRC per shard), and compiled HLO of
  BOTH swap programs carries ZERO collectives (swap is pure data
  movement — each shard moves its own heads slice);
- the tier adds AT MOST one compiled program PER DIRECTION (the
  fixed-shape ``swap_out`` page-block gather and ``swap_in`` scatter
  — one dispatch each, shape-padded to max_pages so no entry size
  can trace a second copy; the chunk/decode/prefill/verify set is
  untouched);
- zero leaked pages at drain across swap churn: the
  :class:`~apex_tpu.serving.PoolAuditor`'s device walk reconciles, and
  its new cross-tier walk reconciles host-arena entries against the
  prefix cache's swapped state (and is SENSITIVE: fabricated dangling /
  orphaned / drifted states raise);
- the host arena is capacity-bounded with its own LRU: an insert that
  does not fit evicts least-recently-put entries (whose index entries
  are dropped — never left dangling), and an entry bigger than the
  whole arena is declined (destroy fallback, the pre-tier behaviour);
- composition pins: ``kv_quant`` int8 pages swap out and restore
  byte-exact (half the transfer bytes for free), and the
  :class:`~apex_tpu.serving.Router`'s affinity probe still sees
  swapped prefixes (a swapped entry is warm state, not a cold miss);
- chaos: the ``swap_corruption`` fault kind (seeded,
  replay-compatible — rate 0 skips the draw) corrupts arena bytes and
  the next swap-in degrades to a VERIFIED MISS (re-prefill, counted as
  ``serving.swap.verify_failed``, hit/miss accounting reversed) —
  never a wrong token.

Everything runs on CPU with a tiny model at policy O0 (exact fp32).
"""

import threading
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, FaultPlan, FaultSpec, HostTier,
                              PoolAuditor, PoolInvariantError,
                              PrefixCache, Request, Router, Scheduler)

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

VOCAB = 101
CHUNK = 8          # chunk_len == page_len: every chunk is one page
# tiny-model page bytes: layers(2) * heads(4) * page_len(8) * head_dim(8)
# * fp32(4) * K-and-V(2) — the arena-capacity arithmetic below
PAGE_BYTES = 2 * 4 * 8 * 8 * 4 * 2


def _tiny_lm(max_seq_len=64, **kw):
    return TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                         num_heads=4, max_seq_len=max_seq_len, **kw)


@pytest.fixture(scope="module")
def lm_and_params():
    m = _tiny_lm()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, pool=2, slots=3, seed=5, paged=True,
               **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=64, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=pool, paged=paged,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  **kw)


@pytest.fixture(scope="module")
def engine_pair(lm_and_params):
    """One hierarchical engine (host tier on) + one plain engine —
    identical geometry, so a hit-after-swap stream and a never-swapped
    stream compare bitwise (jit caches warm across the module)."""
    return (_mk_engine(lm_and_params, host_tier=1 << 24),
            _mk_engine(lm_and_params))


# -------------------------------------------------------- arena (pure host)
def _fake_pages(rng, m=2, dtype=np.float32):
    shape = (2, m, 4, 8, 8)         # [layers, m, heads, page_len, d]
    return (rng.normal(size=shape).astype(dtype),
            rng.normal(size=shape).astype(dtype))


def test_host_tier_put_take_contains_and_lru_capacity():
    rng = np.random.default_rng(0)
    k, v = _fake_pages(rng)
    nbytes = k.nbytes + v.nbytes
    evicted = []
    tier = HostTier(2 * nbytes + 1, on_evict=evicted.append)
    assert tier.put(-1, k, v) and tier.put(-2, *_fake_pages(rng))
    assert tier.size == 2 and tier.bytes_used == 2 * nbytes
    assert tier.contains(-1) and not tier.contains(-9)
    assert tier.nbytes_of(-1) == nbytes and tier.nbytes_of(-9) == 0
    # a third insert exceeds the bound: the least-recently-put entry
    # (-1) is evicted and its owner notified
    assert tier.put(-3, *_fake_pages(rng))
    assert evicted == [-1] and not tier.contains(-1)
    assert tier.bytes_used == 2 * nbytes <= tier.capacity_bytes
    assert tier.evictions == 1
    # an entry alone bigger than the arena is DECLINED, nothing evicted
    big = HostTier(nbytes - 1)
    assert not big.put(-7, k, v)
    assert big.declined == 1 and big.size == 0
    # take pops and verifies
    rec = tier.take(-2)
    assert rec is not None and rec.valid and not tier.contains(-2)
    assert tier.take(-2) is None
    with pytest.raises(ValueError, match="capacity_bytes"):
        HostTier(0)
    tier.clear()
    assert tier.size == 0 and tier.bytes_used == 0


def test_host_tier_checksum_detects_corruption():
    rng = np.random.default_rng(1)
    tier = HostTier(1 << 20)
    tier.put(-1, *_fake_pages(rng))
    tier.put(-2, *_fake_pages(rng))
    tier.corrupt_entry(-1)
    bad, good = tier.take(-1), tier.take(-2)
    assert bad is not None and not bad.valid
    assert good is not None and good.valid
    assert tier.corruptions_detected == 1
    with pytest.raises(KeyError):
        tier.corrupt_entry(-99)


def test_prefix_cache_swap_state_and_pressure_valve():
    """Cache↔tier interplay without an engine: eviction under a wired
    tier is a swap (entry stays matchable/probeable), swapped entries
    are never pressure-valve victims (they hold no device pages — the
    pool loop must not spin on them), and a drop reverses cleanly."""
    released, store = [], {}
    pc = PrefixCache(block_len=4, on_evict=released.extend)
    pc.set_swap_hooks(swap_out=lambda key, pages: store.setdefault(
        key, tuple(pages)) is not None, contains=lambda key: key in store)
    prompt = list(range(10, 22))                     # 3 blocks of 4
    assert pc.register(prompt, pages=(3, 7, 9)) == "registered"
    (key,) = [e.row for e in pc._entries.values()]
    assert pc.evict_lru()                            # swap, not destroy
    assert released == [(3, 7, 9)][0:1] or released == [3, 7, 9]
    assert pc.swapped_keys() == [key] and pc.swap_outs == 1
    # still matchable (swapped=True) and probeable, read-only
    m = pc.match(prompt + [1])
    assert m is not None and m.swapped and m.pages is None \
        and m.length == 12
    assert pc.probe(prompt + [1]) == 12
    # no resident victims left: the valve reports nothing evictable
    # instead of spinning on the page-less swapped entry
    assert not pc.evict_lru()
    # the backing disappearing (tier capacity eviction) makes the next
    # match a miss, not a crash
    store.clear()
    assert pc.match(prompt + [1]) is None
    assert pc.drop(key) and not pc.drop(key)
    assert pc.swapped_keys() == [] and pc.size == 0


# ------------------------------------------------- hit-after-swap, bitwise
def _boundary_cases():
    """(prompt_a, prompt_b, expected_reuse) with shared-prefix lengths
    below / at / straddling the block boundary (block == page == 8) —
    the same sweep the paged-pool tests run, now across a swap."""
    rng = np.random.default_rng(42)
    out = []
    for pre_len, want in [(5, 0), (8, 8), (13, 8), (16, 16)]:
        pre = list(rng.integers(1, VOCAB, size=pre_len))
        out.append((pre + list(rng.integers(1, VOCAB, size=3)),
                    pre + list(rng.integers(1, VOCAB, size=3)), want))
    return out


def test_hit_after_swap_bitwise_vs_never_swapped(engine_pair):
    """THE acceptance pin: register a prefix, force it through a full
    device→host→device round trip, and the hit-after-swap stream must
    be bitwise identical to the never-swapped stream on the plain
    engine — same reuse accounting included."""
    et, ec = engine_pair
    for prompt_a, prompt_b, want_reuse in _boundary_cases():
        et.reset(clear_prefixes=True)
        ec.reset(clear_prefixes=True)
        st = Scheduler(et, retain_prefixes=True)
        sc = Scheduler(ec, retain_prefixes=True)
        (ra_t,) = st.run([Request(prompt=list(prompt_a),
                                  max_new_tokens=5)])
        (ra_c,) = sc.run([Request(prompt=list(prompt_a),
                                  max_new_tokens=5)])
        # every prompt here spans >= 1 block, so prompt_a always
        # registered an entry — eviction must SWAP it, not destroy it
        assert et.prefix_cache.evict_lru()
        assert et.prefix_cache.swapped_keys()
        assert et.host_tier.size == 1
        # the affinity probe still sees the swapped prefix (0 when
        # prompt_b's first block genuinely differs — the 5-token case)
        assert et.prefix_cache.probe(prompt_b) == want_reuse
        (rb_t,) = st.run([Request(prompt=list(prompt_b),
                                  max_new_tokens=5)])
        (rb_c,) = sc.run([Request(prompt=list(prompt_b),
                                  max_new_tokens=5)])
        assert ra_t.output_tokens == ra_c.output_tokens
        assert rb_t.output_tokens == rb_c.output_tokens, \
            f"hit-after-swap diverged (prefix {want_reuse})"
        assert rb_t.reused_tokens == rb_c.reused_tokens == want_reuse
        if want_reuse:
            # restored and re-resident: entry back on fresh pages,
            # arena drained of the migrated record
            assert not et.prefix_cache.swapped_keys()
            assert et.host_tier.size == 0


def test_at_most_one_new_program_per_direction_and_zero_leaks(
        engine_pair):
    """Program-count pin + leak pin, over all the swap churn the
    module has driven so far: the hierarchical engine compiled exactly
    chunk + decode + swap_out + swap_in (TWO more than the plain
    engine's two — one per swap direction, each shape-padded so every
    entry size shares it), and both pools audit clean — then drain to
    zero pages."""
    et, ec = engine_pair
    assert et.chunk_traces == 1 and et.decode_traces == 1
    assert et.swap_in_traces == 1          # every page shares ONE program
    assert et.swap_out_traces == 1         # ... in each direction
    assert et.copy_traces == et.verify_traces == et.prefill_traces == 0
    assert et.compiled_programs == 4
    assert ec.compiled_programs == 2
    assert ec.swap_in_traces == ec.swap_out_traces == 0
    for eng in engine_pair:
        PoolAuditor().audit(eng)
        eng.reset(clear_prefixes=True)
        assert eng.pool.pages_in_use == 0
        PoolAuditor().audit(eng)
    assert et.host_tier.size == 0 and et.host_tier.bytes_used == 0


def test_engine_host_tier_validation(lm_and_params):
    with pytest.raises(ValueError, match="paged=True"):
        _mk_engine(lm_and_params, host_tier=1 << 20, paged=False)
    with pytest.raises(ValueError, match="prefix_pool"):
        _mk_engine(lm_and_params, host_tier=1 << 20, pool=0)
    # a pre-built arena is accepted as-is (capacity honoured)
    eng = _mk_engine(lm_and_params, host_tier=HostTier(1 << 20))
    assert isinstance(eng.host_tier, HostTier)
    assert eng.host_tier.capacity_bytes == 1 << 20


# -------------------------------------------------- capacity + composition
def test_capacity_bounded_arena_evicts_and_drops_entries(lm_and_params):
    """Engine-level capacity bound: an arena sized for ONE two-page
    prefix holds the latest swap-out; swapping a second entry out
    evicts the first's bytes AND drops its index entry (no dangling
    swapped state), with the auditor's cross-tier walk green
    throughout."""
    eng = _mk_engine(lm_and_params, pool=3,
                     host_tier=2 * PAGE_BYTES + 1)
    sched = Scheduler(eng, retain_prefixes=True)
    rng = np.random.default_rng(7)
    pres = [list(rng.integers(1, VOCAB, size=16)) for _ in range(2)]
    for pre in pres:
        sched.run([Request(prompt=pre + [1, 2], max_new_tokens=3)])
    auditor = PoolAuditor()
    assert eng.prefix_cache.evict_lru()        # swap entry 0 out
    auditor.audit(eng)
    assert eng.prefix_cache.evict_lru()        # swap entry 1: evicts 0
    auditor.audit(eng)
    tier = eng.host_tier
    assert tier.size == 1 and tier.evictions == 1
    assert tier.bytes_used <= tier.capacity_bytes
    # entry 0 is GONE from the index (dropped with its bytes): its
    # prefix probes 0, entry 1's still probes through the tier
    assert eng.prefix_cache.probe(pres[0] + [9]) == 0
    assert eng.prefix_cache.probe(pres[1] + [9]) == 16
    assert len(eng.prefix_cache.swapped_keys()) == 1


def test_int8_pages_swap_and_restore_byte_exact(lm_and_params):
    """kv_quant composition: int8 pages ride the tier at half the
    transfer bytes, and the restored device bytes are EXACTLY the
    evicted ones (the whole bitwise argument, at the byte level)."""
    from apex_tpu.serving import KVQuantConfig

    eng = _mk_engine(lm_and_params, host_tier=1 << 24,
                     kv_quant=KVQuantConfig())
    sched = Scheduler(eng, retain_prefixes=True)
    rng = np.random.default_rng(11)
    pre = list(rng.integers(1, VOCAB, size=16))
    sched.run([Request(prompt=pre + [7, 8], max_new_tokens=3)])
    (key,) = list(eng.prefix_cache._entries)
    pages0 = list(eng.prefix_cache._entries[key].pages)
    before_k = np.asarray(eng.cache.k[:, pages0]).copy()
    before_v = np.asarray(eng.cache.v[:, pages0]).copy()
    assert before_k.dtype == np.int8       # half the swap bytes, free
    assert eng.prefix_cache.evict_lru()
    assert eng.host_tier.bytes_used == 2 * PAGE_BYTES // 4   # int8 vs fp32
    (r,) = sched.run([Request(prompt=pre + [9, 10],
                              max_new_tokens=3)])
    assert r.reused_tokens == 16
    pages1 = list(eng.prefix_cache._entries[key].pages)
    np.testing.assert_array_equal(before_k,
                                  np.asarray(eng.cache.k[:, pages1]))
    np.testing.assert_array_equal(before_v,
                                  np.asarray(eng.cache.v[:, pages1]))
    PoolAuditor().audit(eng)


def test_router_affinity_probe_sees_swapped_prefixes(engine_pair):
    """Router composition: a replica whose prefix was swapped to host
    still wins the affinity probe — swap-out moves bytes, not
    routing signal."""
    et, ec = engine_pair
    for eng in engine_pair:
        eng.reset(clear_prefixes=True)
    reg = telemetry.MetricsRegistry()
    router = Router([et, ec], registry=reg, retain_prefixes=True)
    try:
        rng = np.random.default_rng(13)
        pre = list(rng.integers(1, VOCAB, size=16))
        (r1,) = router.run([Request(prompt=pre + [1, 2],
                                    max_new_tokens=3)])
        # find the replica that served turn 1 and swap its prefix out
        (home,) = [i for i, e in enumerate((et, ec))
                   if e.prefix_cache is not None and e.prefix_cache.size]
        owner = (et, ec)[home]
        if owner.host_tier is not None:
            assert owner.prefix_cache.evict_lru()
            assert owner.prefix_cache.swapped_keys()
        hits0 = reg.snapshot()["counters"].get(
            "serving.router.affinity_hits", 0)
        (r2,) = router.run([Request(prompt=pre + [3, 4],
                                    max_new_tokens=3)])
        hits1 = reg.snapshot()["counters"].get(
            "serving.router.affinity_hits", 0)
        assert hits1 == hits0 + 1          # the probe saw the prefix
        assert r2.reused_tokens == 16
    finally:
        router.close()


# ----------------------------------------------------------------- chaos
def test_swap_corruption_degrades_to_verified_miss(engine_pair):
    """The chaos pin: corrupt arena bytes make the next swap-in fail
    its checksum and the request re-prefills COLD — bitwise identical
    to a cold run, `serving.swap.verify_failed` counted, hit/miss
    accounting reversed, request FINISHED (never failed, never a wrong
    token)."""
    et, ec = engine_pair
    for eng in engine_pair:
        eng.reset(clear_prefixes=True)
    rng = np.random.default_rng(17)
    pre = list(rng.integers(1, VOCAB, size=16))
    p2 = pre + list(rng.integers(1, VOCAB, size=3))
    # cold oracle on the plain engine (no retention: fully cold)
    (oracle,) = Scheduler(ec).run([Request(prompt=list(p2),
                                           max_new_tokens=5)])
    reg = telemetry.MetricsRegistry()
    et.set_registry(reg)
    try:
        sched = Scheduler(et, registry=reg, retain_prefixes=True)
        sched.run([Request(prompt=pre + [7, 8, 9], max_new_tokens=5)])
        assert et.prefix_cache.evict_lru()
        base = dict(et.prefix_cache.stats())
        sched.fault_plan = FaultPlan(
            [FaultSpec(kind="swap_corruption", tick=sched._tick)])
        (r,) = sched.run([Request(prompt=list(p2), max_new_tokens=5)])
        assert r.output_tokens == oracle.output_tokens
        assert r.status == "finished" and r.reused_tokens == 0
        assert sched.fault_plan.injected_swap_corruptions == 1
        assert sched.fault_plan.stats()["injected_swap_corruptions"] == 1
        counters = reg.snapshot()["counters"]
        assert counters.get("serving.swap.verify_failed") == 1
        delta = et.prefix_cache.stats_since(base)
        assert delta["hits"] == 0 and delta["misses"] == 1   # reversed
        # the corrupt entry is gone everywhere; the pool stays clean
        assert not et.prefix_cache.swapped_keys()
        assert et.host_tier.size == 0
        PoolAuditor().audit(et)
    finally:
        et.set_registry(None)


def test_faultplan_swap_corruption_replay_compatible():
    """Rate 0 skips the draw entirely (the PR 12 replica-death
    pattern), so every pre-host-tier seed replays bit-for-bit; a
    positive rate draws the new kind."""
    kw = dict(slots=4, nonfinite_rate=0.3, exception_rate=0.2,
              stall_rate=0.1)
    assert FaultPlan.random(3, 40, **kw).specs \
        == FaultPlan.random(3, 40, swap_corruption_rate=0.0, **kw).specs
    plan = FaultPlan.random(3, 60, slots=4, swap_corruption_rate=0.5)
    assert any(s.kind == "swap_corruption" for s in plan.specs)
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(kind="swap_rot", tick=0)
    # an empty arena makes the injection a consumed no-op
    empty = FaultPlan([FaultSpec(kind="swap_corruption", tick=0)])
    assert not empty.maybe_corrupt_swap(0, HostTier(1 << 10))
    assert empty.injected_swap_corruptions == 0


# --------------------------------------------------------------- auditor
def test_auditor_cross_tier_walk_is_sensitive(engine_pair):
    """The extended conservation audit detects every cross-tier rot it
    claims to: dangling swapped entries, orphaned arena bytes, drifted
    byte accounting, and an over-capacity arena."""
    et, _ = engine_pair
    et.reset(clear_prefixes=True)
    sched = Scheduler(et, retain_prefixes=True)
    rng = np.random.default_rng(23)
    pre = list(rng.integers(1, VOCAB, size=16))
    sched.run([Request(prompt=pre + [1, 2], max_new_tokens=3)])
    assert et.prefix_cache.evict_lru()
    auditor = PoolAuditor()
    auditor.audit(et)                      # consistent: green
    tier = et.host_tier
    (key,) = tier.keys()
    # (1) dangling: swapped entry with no arena backing
    rec = tier._entries.pop(key)
    tier._bytes_used -= rec.nbytes
    with pytest.raises(PoolInvariantError, match="no host-tier backing"):
        auditor.audit(et)
    tier._entries[key] = rec
    tier._bytes_used += rec.nbytes
    auditor.audit(et)
    # (2) orphan: arena bytes backing no swapped entry
    tier._entries[-777] = rec
    tier._bytes_used += rec.nbytes
    with pytest.raises(PoolInvariantError, match="host-side leak"):
        auditor.audit(et)
    del tier._entries[-777]
    tier._bytes_used -= rec.nbytes
    # (3) byte-accounting drift
    tier._bytes_used += 1
    with pytest.raises(PoolInvariantError, match="drifted"):
        auditor.audit(et)
    tier._bytes_used -= 1
    # (4) over-capacity arena
    saved = tier.capacity_bytes
    tier.capacity_bytes = 1
    with pytest.raises(PoolInvariantError, match="over capacity"):
        auditor.audit(et)
    tier.capacity_bytes = saved
    auditor.audit(et)
    et.reset(clear_prefixes=True)


# ----------------------------------------------- async swap-out (tentpole)
def _gate_worker(eng):
    """Block ``eng``'s SwapWorker behind an Event so the NEXT
    eviction's bytes deterministically sit in flight (the *swapping*
    state) until the gate opens."""
    gate = threading.Event()
    eng._swap_worker.submit(("gate", id(gate)), gate.wait)
    return gate


def test_async_default_vs_sync_escape_hatch_bitwise(lm_and_params):
    """THE async acceptance pin: the default (worker-threaded)
    swap-out and the ``sync_swap=True`` escape hatch serve identical
    greedy streams token-for-token — including a hit forced to land
    while its entry's swap-out bytes are STILL IN FLIGHT, which must
    JOIN the copy (counted as ``serving.swap.swap_join_waits``),
    never read partial bytes. Zero leaks, clean cross-tier audits."""
    from apex_tpu import telemetry

    ea = _mk_engine(lm_and_params, host_tier=1 << 24)
    es = _mk_engine(lm_and_params, host_tier=1 << 24, sync_swap=True)
    assert ea._swap_worker is not None and es._swap_worker is None
    reg = telemetry.MetricsRegistry()
    ea.set_registry(reg)
    try:
        rng = np.random.default_rng(31)
        pre = list(rng.integers(1, VOCAB, size=16))
        p1, p2 = pre + [1, 2], pre + [3, 4]
        outs = {}
        for name, eng in (("async", ea), ("sync", es)):
            sched = Scheduler(eng, retain_prefixes=True)
            (r1,) = sched.run([Request(prompt=list(p1),
                                       max_new_tokens=5)])
            gate = _gate_worker(eng) if eng._swap_worker is not None \
                else None
            assert eng.prefix_cache.evict_lru()
            if gate is not None:
                # the swap is dispatched but NOT complete: the entry
                # is in the swapping state — reserved in the arena,
                # still matchable and probeable
                assert eng.host_tier.pending_keys()
                assert eng.host_tier.stats()["swapping"] == 1
                assert eng.prefix_cache.probe(p2) == 16
                threading.Timer(0.1, gate.set).start()
            (r2,) = sched.run([Request(prompt=list(p2),
                                       max_new_tokens=5)])
            outs[name] = (list(r1.output_tokens),
                          list(r2.output_tokens), r2.reused_tokens)
            PoolAuditor().audit(eng)
            assert eng.host_tier.size == 0      # restored + drained
        assert outs["async"] == outs["sync"], \
            "async swap-out diverged from the sync escape hatch"
        assert outs["async"][2] == 16
        counters = reg.snapshot()["counters"]
        assert counters.get("serving.swap.swap_join_waits", 0) >= 1, \
            "the in-flight hit never joined the worker copy"
        assert counters.get("serving.swap.verify_failed", 0) == 0
    finally:
        ea.set_registry(None)
        ea.close()
        es.close()


def test_swap_corruption_racing_inflight_swap(lm_and_params):
    """Chaos × async: a ``swap_corruption`` landing while the victim's
    swap-out bytes are still in flight arms the corruption (it rots
    the bytes the moment the worker stores them), and the racing hit
    degrades to a VERIFIED MISS — bitwise identical to a cold run,
    never a wrong token, pool and arena reconciled."""
    from apex_tpu import telemetry

    eng = _mk_engine(lm_and_params, host_tier=1 << 24)
    cold = _mk_engine(lm_and_params)
    try:
        rng = np.random.default_rng(37)
        pre = list(rng.integers(1, VOCAB, size=16))
        p2 = pre + [5, 6, 7]
        (oracle,) = Scheduler(cold).run([Request(prompt=list(p2),
                                                 max_new_tokens=5)])
        reg = telemetry.MetricsRegistry()
        eng.set_registry(reg)
        sched = Scheduler(eng, registry=reg, retain_prefixes=True)
        sched.run([Request(prompt=pre + [1, 2], max_new_tokens=5)])
        gate = _gate_worker(eng)
        assert eng.prefix_cache.evict_lru()
        assert eng.host_tier.pending_keys()
        # the injection races the in-flight swap: consumed NOW, lands
        # at completion time
        plan = FaultPlan([FaultSpec(kind="swap_corruption", tick=0)])
        assert plan.maybe_corrupt_swap(0, eng.host_tier)
        threading.Timer(0.05, gate.set).start()
        (r,) = sched.run([Request(prompt=list(p2), max_new_tokens=5)])
        assert r.output_tokens == oracle.output_tokens
        assert r.status == "finished" and r.reused_tokens == 0
        counters = reg.snapshot()["counters"]
        assert counters.get("serving.swap.verify_failed") == 1
        assert not eng.prefix_cache.swapped_keys()
        assert eng.host_tier.size == 0
        PoolAuditor().audit(eng)
    finally:
        eng.set_registry(None)
        eng.close()


def test_close_with_nonempty_swap_queue_drains_leak_free(lm_and_params):
    """The kill contract: an engine closed while its swap queue is
    non-empty DRAINS — every queued swap-out completes its arena put
    (the bytes were snapshotted at dispatch), so the cross-tier audit
    walks clean with nothing dangling; the engine stays usable after
    close (swap-outs degrade to inline/sync)."""
    eng = _mk_engine(lm_and_params, pool=3, host_tier=1 << 24)
    sched = Scheduler(eng, retain_prefixes=True)
    rng = np.random.default_rng(41)
    pres = [list(rng.integers(1, VOCAB, size=16)) for _ in range(2)]
    for pre in pres:
        sched.run([Request(prompt=pre + [1, 2], max_new_tokens=3)])
    # host_bytes_free load gauge: full arena headroom before any swap
    snap = sched.load_snapshot()
    assert snap["host_bytes_free"] == eng.host_tier.capacity_bytes
    gate = _gate_worker(eng)
    assert eng.prefix_cache.evict_lru()
    assert eng.prefix_cache.evict_lru()
    assert len(eng.host_tier.pending_keys()) == 2   # both in flight
    assert len(eng._swap_worker.pending_keys()) >= 2
    assert sched.load_snapshot()["host_bytes_free"] \
        < eng.host_tier.capacity_bytes      # reservations count NOW
    threading.Timer(0.05, gate.set).start()
    eng.close()                              # drains, then stops
    assert not eng.host_tier.pending_keys()
    assert eng.host_tier.size == 2
    assert len(eng.prefix_cache.swapped_keys()) == 2
    PoolAuditor().audit(eng)
    # post-close swap-outs run inline (sync degradation, never dropped)
    sched.run([Request(prompt=pres[0] + [9], max_new_tokens=3)])
    PoolAuditor().audit(eng)


def test_no_swap_worker_thread_leaks(lm_and_params):
    """No worker-thread leaks across construct/serve/close; close is
    idempotent; sync_swap engines never start a thread; a plain
    scheduler's load snapshot reads host_bytes_free=None."""
    def workers():
        return sum(t.name == "serving-swap-worker"
                   for t in threading.enumerate())

    base = workers()
    eng = _mk_engine(lm_and_params, host_tier=1 << 22)
    assert workers() == base + 1
    sched = Scheduler(eng, retain_prefixes=True)
    sched.run([Request(prompt=list(range(1, 18)), max_new_tokens=3)])
    eng.close()
    eng.close()                              # idempotent
    assert workers() == base
    es = _mk_engine(lm_and_params, host_tier=1 << 22, sync_swap=True)
    assert workers() == base and es._swap_worker is None
    plain = _mk_engine(lm_and_params)
    assert Scheduler(plain).load_snapshot()["host_bytes_free"] is None
    es.close()


# ------------------------------------------------------- mesh composition
def _mesh(n: int) -> Mesh:
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} virtual devices")
    return Mesh(np.array(devs[:n]), ("tp",))


VOCAB_TP = 96       # divisible by the tp sizes under test (1, 2)


@pytest.fixture(scope="module")
def tp_lm_and_params():
    """A tp-divisible tiny model (vocab 96) for the tp>1 mesh tests —
    the module default's 101-token vocab cannot split over 2 shards."""
    m = TransformerLM(vocab_size=VOCAB_TP, hidden=32, num_layers=2,
                      num_heads=4, max_seq_len=64)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _serve_swap_stream(eng, seed=42, vocab=VOCAB):
    """One register → evict(=swap) → hit-after-swap stream; returns
    every request's tokens + the hit's reuse accounting."""
    rng = np.random.default_rng(seed)
    pre = list(rng.integers(1, vocab, size=16))
    p1, p2 = pre + [1, 2, 3], pre + [4, 5, 6]
    sched = Scheduler(eng, retain_prefixes=True)
    (r1,) = sched.run([Request(prompt=list(p1), max_new_tokens=5)])
    assert eng.prefix_cache.evict_lru()
    (r2,) = sched.run([Request(prompt=list(p2), max_new_tokens=5)])
    PoolAuditor().audit(eng)
    return (list(r1.output_tokens), list(r2.output_tokens),
            r2.reused_tokens)


def test_mesh_tp1_host_tier_bitwise_vs_unsharded(lm_and_params):
    """The mesh-lift pin, fast half: a tp=1 mesh host-tier engine
    (shard_map-wrapped swap programs over one device) serves the
    register → swap → hit-after-swap stream BITWISE identical to the
    unsharded ``mesh=None`` host-tier engine, one compiled program per
    swap direction on both."""
    em = _mk_engine(lm_and_params, mesh=_mesh(1), host_tier=1 << 24)
    e0 = _mk_engine(lm_and_params, host_tier=1 << 24)
    try:
        om, o0 = _serve_swap_stream(em), _serve_swap_stream(e0)
        assert om == o0, "tp=1 mesh host tier diverged from mesh=None"
        assert om[2] == 16
        for eng in (em, e0):
            assert eng.swap_out_traces == 1
            assert eng.swap_in_traces == 1
    finally:
        em.close()
        e0.close()


@pytest.mark.slow
def test_mesh_tp2_host_tier_token_exact_with_per_shard_records(
        tp_lm_and_params):
    """The mesh-lift pin, tp=2 half (CPU device emulation): the same
    swap stream is token-exact vs mesh=None, and the arena records are
    PER-SHARD — ``shards == tp`` with one CRC per shard, each
    independently verifying exactly its shard's heads slice of the
    stored bytes."""
    em = _mk_engine(tp_lm_and_params, mesh=_mesh(2), host_tier=1 << 24)
    e0 = _mk_engine(tp_lm_and_params, host_tier=1 << 24)
    try:
        assert _serve_swap_stream(em, vocab=VOCAB_TP) \
            == _serve_swap_stream(e0, vocab=VOCAB_TP)
        # force a fresh swap-out and inspect the resident record
        rng = np.random.default_rng(7)
        pre = list(rng.integers(1, VOCAB_TP, size=16))
        Scheduler(em, retain_prefixes=True).run(
            [Request(prompt=pre + [9], max_new_tokens=3)])
        assert em.prefix_cache.evict_lru()
        em._swap_worker.drain()
        (key,) = em.host_tier.keys()
        rec = em.host_tier._entries[key]
        assert rec.shards == 2 and len(rec.crc) == 2
        # each CRC covers exactly its shard's heads slice (K then V)
        heads = rec.k.shape[2]
        for t in range(2):
            sl = slice(t * heads // 2, (t + 1) * heads // 2)
            want = zlib.crc32(
                np.ascontiguousarray(rec.v[:, :, sl]).tobytes(),
                zlib.crc32(
                    np.ascontiguousarray(rec.k[:, :, sl]).tobytes()))
            assert rec.crc[t] == want, f"shard {t} CRC drifted"
        # and per-shard verification is SENSITIVE: rot one shard's
        # bytes and the take must flag the record invalid
        em.host_tier.corrupt_entry(key)
        bad = em.host_tier.take(key)
        assert bad is not None and not bad.valid
        em.prefix_cache.drop(key)
        PoolAuditor().audit(em)
    finally:
        em.close()
        e0.close()


@pytest.mark.slow
def test_swap_programs_compile_zero_collectives(tp_lm_and_params):
    """The collective pin: compiled HLO of BOTH sharded swap programs
    (tp=2) contains ZERO collectives — swap is pure data movement,
    each shard gathers/scatters its own heads/tp slice of the pool.
    A dedicated engine (``.lower()`` re-traces, which must not touch
    the shared fixtures' trace pins)."""
    import re as _re

    eng = _mk_engine(tp_lm_and_params, mesh=_mesh(2),
                     host_tier=1 << 24)
    try:
        ids = jnp.zeros(eng.max_pages, jnp.int32)
        c = eng.cache
        blk = jnp.zeros((c.layers, eng.max_pages, c.heads, c.page_len,
                         c.head_dim), c.dtype)

        def ncoll(txt):
            return len(_re.findall(
                r"= \S+ (all-reduce|all-gather|collective-permute|"
                r"all-to-all)\(", txt))

        out_hlo = eng._jit_swap_out.lower(
            eng.cache, ids).compile().as_text()
        in_hlo = eng._jit_swap_in.lower(
            eng.cache, blk, blk, ids).compile().as_text()
        assert ncoll(out_hlo) == 0, "swap-out grew a collective"
        assert ncoll(in_hlo) == 0, "swap-in grew a collective"
    finally:
        eng.close()


def test_router_probe_hits_swapping_entry_on_mesh_replica(
        lm_and_params):
    """Router × host-tier × mesh (the composition the mesh=None
    restriction made untestable): an affinity probe landing on a
    *swapping*-state entry — swap-out bytes still in flight — of a
    MESH-SHARDED replica routes the request home, the hit joins the
    copy, and the stream is bitwise identical to a never-swapped hit
    on an identically-built bare scheduler."""
    from apex_tpu import telemetry

    em = _mk_engine(lm_and_params, mesh=_mesh(1), host_tier=1 << 24)
    ep = _mk_engine(lm_and_params)
    eo = _mk_engine(lm_and_params, mesh=_mesh(1), host_tier=1 << 24)
    reg = telemetry.MetricsRegistry()
    router = Router([em, ep], registry=reg, retain_prefixes=True)
    try:
        rng = np.random.default_rng(53)
        pre = list(rng.integers(1, VOCAB, size=16))
        p1, p2 = pre + [1, 2], pre + [3, 4]
        # the never-swapped oracle: same stream, plain hit
        so = Scheduler(eo, retain_prefixes=True)
        (o1,) = so.run([Request(prompt=list(p1), max_new_tokens=5)])
        (o2,) = so.run([Request(prompt=list(p2), max_new_tokens=5)])
        # turn 1 routes to replica 0 (cold caches: least-loaded tie →
        # lowest index) and registers its prefix there
        (r1,) = router.run([Request(prompt=list(p1), max_new_tokens=5)])
        assert router.placements[r1.uid] == 0
        assert em.prefix_cache.size == 1
        # squeeze the home replica: the entry enters the swapping
        # state (swap dispatched, bytes gated in flight)
        gate = _gate_worker(em)
        assert em.prefix_cache.evict_lru()
        assert em.host_tier.pending_keys()
        hits0 = reg.snapshot()["counters"].get(
            "serving.router.affinity_hits", 0)
        threading.Timer(0.1, gate.set).start()
        (r2,) = router.run([Request(prompt=list(p2), max_new_tokens=5)])
        hits1 = reg.snapshot()["counters"].get(
            "serving.router.affinity_hits", 0)
        assert hits1 == hits0 + 1, "probe missed the swapping entry"
        assert router.placements[r2.uid] == 0, "request routed away " \
            "from its swapping prefix"
        assert r2.reused_tokens == 16
        assert r1.output_tokens == o1.output_tokens
        assert r2.output_tokens == o2.output_tokens, \
            "hit-through-swapping-state diverged"
        # the tie-break input is dashboard-visible per replica
        assert "serving.router.replica0.host_bytes_free" \
            in reg.snapshot()["gauges"]
        PoolAuditor().audit(em)
    finally:
        router.close()
        eo.close()
