"""Loss-scaler schedule tests — the semantics apex tests observe via
``loss_scaler.loss_scale()`` (apex/amp/scaler.py — update_scale)."""

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.amp import (LossScaler, init_scaler, unscale,
                          unscale_with_stashed, update_scale)
from apex_tpu.amp.scaler import scale_loss


def test_dynamic_init_scale():
    s = LossScaler("dynamic")
    assert s.loss_scale() == 2.0 ** 16
    assert s.dynamic


def test_static_scale_never_moves():
    s = LossScaler(128.0)
    for _ in range(5):
        s._has_overflow = False
        s.update_scale()
    assert s.loss_scale() == 128.0
    s._has_overflow = True
    s.update_scale()
    assert s.loss_scale() == 128.0


def test_overflow_halves_and_resets():
    s = LossScaler("dynamic")
    s._has_overflow = True
    s.update_scale()
    assert s.loss_scale() == 2.0 ** 15
    assert int(s._state.unskipped) == 0


def test_growth_after_scale_window():
    state = init_scaler("dynamic", init_scale=2.0 ** 8, scale_window=10)
    clean = jnp.bool_(False)
    for _ in range(9):
        state = update_scale(state, clean)
        assert float(state.loss_scale) == 2.0 ** 8
    state = update_scale(state, clean)
    assert float(state.loss_scale) == 2.0 ** 9
    assert int(state.unskipped) == 0


def test_overflow_resets_growth_counter():
    state = init_scaler("dynamic", init_scale=2.0 ** 8, scale_window=4)
    for _ in range(3):
        state = update_scale(state, jnp.bool_(False))
    state = update_scale(state, jnp.bool_(True))   # overflow at step 4
    assert float(state.loss_scale) == 2.0 ** 7
    for _ in range(3):
        state = update_scale(state, jnp.bool_(False))
    assert float(state.loss_scale) == 2.0 ** 7     # window not yet re-filled
    state = update_scale(state, jnp.bool_(False))
    assert float(state.loss_scale) == 2.0 ** 8


def test_max_loss_scale_clamp():
    state = init_scaler("dynamic", init_scale=2.0 ** 24, scale_window=1,
                        max_loss_scale=2.0 ** 24)
    state = update_scale(state, jnp.bool_(False))
    assert float(state.loss_scale) == 2.0 ** 24


def test_min_loss_scale_clamp():
    state = init_scaler("dynamic", init_scale=4.0, min_loss_scale=2.0)
    state = update_scale(state, jnp.bool_(True))
    assert float(state.loss_scale) == 2.0
    state = update_scale(state, jnp.bool_(True))
    assert float(state.loss_scale) == 2.0


def test_unscale_and_found_inf():
    state = init_scaler(8.0)
    grads = {"w": jnp.asarray([8.0, 16.0], jnp.float16)}
    out, found = unscale(grads, state)
    assert not bool(found)
    assert out["w"].dtype == jnp.float32
    assert jnp.allclose(out["w"], jnp.asarray([1.0, 2.0]))

    bad = {"w": jnp.asarray([jnp.inf, 1.0], jnp.float16)}
    _, found = unscale(bad, state)
    assert bool(found)
    nan = {"w": jnp.asarray([jnp.nan, 1.0], jnp.float32)}
    _, found = unscale(nan, state)
    assert bool(found)


def test_unscale_with_stashed_accumulates():
    state = init_scaler(4.0)
    new = {"w": jnp.asarray([4.0], jnp.float16)}
    stash = {"w": jnp.asarray([10.0], jnp.float32)}
    out, found = unscale_with_stashed(new, stash, state)
    assert not bool(found)
    assert jnp.allclose(out["w"], jnp.asarray([11.0]))


def test_unscale_with_stashed_flat_buffer_routes_fused_axpby():
    """The multi_tensor superbuffer layout: flat 1-D operand pairs take
    the ported amp_C.multi_tensor_axpby kernel (fused_axpby, a=1/scale,
    b=1) — same math as the per-leaf path, overflow flag included."""
    state = init_scaler(4.0)
    new = jnp.asarray([4.0, 8.0, -2.0], jnp.float32)
    stash = jnp.asarray([10.0, 0.0, 1.0], jnp.float32)
    out, found = unscale_with_stashed(new, stash, state)
    assert not bool(found)
    assert jnp.allclose(out, jnp.asarray([11.0, 2.0, 0.5]))
    # overflow in either operand raises the flag (axpby checks both)
    _, found = unscale_with_stashed(
        jnp.asarray([jnp.inf, 1.0], jnp.float32),
        jnp.zeros((2,), jnp.float32), state)
    assert bool(found)
    _, found = unscale_with_stashed(
        jnp.ones((2,), jnp.float32),
        jnp.asarray([jnp.nan, 1.0], jnp.float32), state)
    assert bool(found)


def test_facade_overflow_or_accumulates_across_delay_window():
    """delay_unscale window parity (apex's _overflow_buf accumulating
    across multi_tensor launches): an overflow in ANY unscale of the
    window must back the scale off at the single closing update_scale —
    a later clean unscale_with_stashed cannot overwrite the flag."""
    s = LossScaler("dynamic", init_scale=256.0)
    stash = s.unscale({"w": jnp.asarray([jnp.inf], jnp.float16)})  # mb 0: inf
    s.unscale_with_stashed({"w": jnp.asarray([1.0], jnp.float16)},
                           stash)                                  # mb 1: clean
    assert s.update_scale() is True          # window skipped as a whole
    assert s.loss_scale() == 128.0

    # clean window afterwards: flag was reset by update_scale
    stash = s.unscale({"w": jnp.asarray([1.0], jnp.float16)})
    s.unscale_with_stashed({"w": jnp.asarray([1.0], jnp.float16)}, stash)
    assert s.update_scale() is False
    assert s.loss_scale() == 128.0


def test_scale_loss_delay_unscale_keeps_schedule_frozen():
    """amp.scale_loss(delay_unscale=True) must not advance the scaler
    schedule on exit — only the window-closing (delay_unscale=False)
    iteration calls update_scale (apex handle.py's delayed path)."""
    from apex_tpu import amp as amp_mod

    amp_mod._amp_state.loss_scalers = [LossScaler(128.0)]
    scaler = amp_mod._amp_state.loss_scalers[0]
    before = int(scaler._state.steps)
    with amp_mod.scale_loss(jnp.float32(1.0), delay_unscale=True) as sl:
        assert float(sl) == 128.0
    assert int(scaler._state.steps) == before            # frozen
    with amp_mod.scale_loss(jnp.float32(1.0)) as sl:
        pass
    assert int(scaler._state.steps) == before + 1        # window closed
    amp_mod._amp_state.loss_scalers = []


def test_scale_loss_dtype_preserved():
    state = init_scaler(1024.0)
    loss16 = jnp.float16(2.0)
    out = scale_loss(loss16, state)
    assert out.dtype == jnp.float16
    loss32 = jnp.float32(2.0)
    assert scale_loss(loss32, state) == 2048.0


def test_update_scale_is_jittable():
    state = init_scaler("dynamic", scale_window=2)
    step = jax.jit(update_scale)
    state = step(state, jnp.bool_(False))
    state = step(state, jnp.bool_(False))
    assert float(state.loss_scale) == 2.0 ** 17


def test_state_dict_roundtrip():
    s = LossScaler("dynamic")
    s._has_overflow = True
    s.update_scale()
    sd = s.state_dict()
    s2 = LossScaler("dynamic")
    s2.load_state_dict(sd)
    assert s2.loss_scale() == s.loss_scale()
    assert s2.state_dict() == sd


def test_module_state_dict():
    import apex_tpu.amp as amp

    amp.initialize((None, None), opt_level="O2", num_losses=2, verbose=False,
                   verbosity=0)
    sd = amp.state_dict()
    assert set(sd) == {"loss_scaler0", "loss_scaler1"}
    sd["loss_scaler0"]["loss_scale"] = 42.0
    amp.load_state_dict(sd)
    assert amp._amp_state.loss_scalers[0].loss_scale() == 42.0


def test_hysteresis_delays_backoff():
    """Megatron DynamicGradScaler.update schedule (the mechanism of
    csrc/update_scale_hysteresis.cu): with hysteresis=2 the first overflow
    since the last growth is tolerated; every further overflow backs off
    (the tolerance stays exhausted — no refill on backoff or clean steps);
    growth refills it."""
    from apex_tpu.amp.scaler import init_scaler, update_scale

    s = init_scaler("dynamic", init_scale=2.0 ** 10, hysteresis=2)
    s1 = update_scale(s, True)                # first overflow: tolerated
    assert float(s1.loss_scale) == 2.0 ** 10
    assert int(s1.hysteresis_left) == 1
    s2 = update_scale(s1, True)               # exhausted: backoff
    assert float(s2.loss_scale) == 2.0 ** 9
    assert int(s2.hysteresis_left) == 0
    s3 = update_scale(s2, True)               # still exhausted: backoff again
    assert float(s3.loss_scale) == 2.0 ** 8
    assert int(s3.hysteresis_left) == 0
    s4 = update_scale(s3, False)              # clean step: NO refill
    assert int(s4.hysteresis_left) == 0
    s5 = update_scale(s4, True)               # overflow while exhausted
    assert float(s5.loss_scale) == 2.0 ** 7

    # growth refills the tolerance
    s6 = init_scaler("dynamic", init_scale=4.0, scale_window=1, hysteresis=2)
    s6 = update_scale(s6, True)               # hl 2 -> 1
    assert int(s6.hysteresis_left) == 1
    s6 = update_scale(s6, False)              # clean step hits window: grow
    assert float(s6.loss_scale) == 8.0
    assert int(s6.hysteresis_left) == 2


def test_hysteresis_default_is_apex_immediate_backoff():
    """hysteresis=1 (default) must reproduce the classic apex schedule
    bit-for-bit: every overflow halves immediately."""
    from apex_tpu.amp.scaler import init_scaler, update_scale

    s = init_scaler("dynamic", init_scale=2.0 ** 16)
    s = update_scale(s, True)
    assert float(s.loss_scale) == 2.0 ** 15
    s = update_scale(s, True)
    assert float(s.loss_scale) == 2.0 ** 14


def test_hysteresis_state_dict_roundtrip():
    from apex_tpu.amp.scaler import LossScaler

    sc = LossScaler("dynamic", hysteresis=3)
    sc._has_overflow = True
    sc.update_scale()
    sd = sc.state_dict()
    assert sd["hysteresis_left"] == 2
    sc2 = LossScaler("dynamic", hysteresis=3)
    sc2.load_state_dict(sd)
    assert int(sc2._state.hysteresis_left) == 2
