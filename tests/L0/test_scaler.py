"""Loss-scaler schedule tests — the semantics apex tests observe via
``loss_scaler.loss_scale()`` (apex/amp/scaler.py — update_scale)."""

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.amp import (LossScaler, init_scaler, unscale,
                          unscale_with_stashed, update_scale)
from apex_tpu.amp.scaler import scale_loss


def test_dynamic_init_scale():
    s = LossScaler("dynamic")
    assert s.loss_scale() == 2.0 ** 16
    assert s.dynamic


def test_static_scale_never_moves():
    s = LossScaler(128.0)
    for _ in range(5):
        s._has_overflow = False
        s.update_scale()
    assert s.loss_scale() == 128.0
    s._has_overflow = True
    s.update_scale()
    assert s.loss_scale() == 128.0


def test_overflow_halves_and_resets():
    s = LossScaler("dynamic")
    s._has_overflow = True
    s.update_scale()
    assert s.loss_scale() == 2.0 ** 15
    assert int(s._state.unskipped) == 0


def test_growth_after_scale_window():
    state = init_scaler("dynamic", init_scale=2.0 ** 8, scale_window=10)
    clean = jnp.bool_(False)
    for _ in range(9):
        state = update_scale(state, clean)
        assert float(state.loss_scale) == 2.0 ** 8
    state = update_scale(state, clean)
    assert float(state.loss_scale) == 2.0 ** 9
    assert int(state.unskipped) == 0


def test_overflow_resets_growth_counter():
    state = init_scaler("dynamic", init_scale=2.0 ** 8, scale_window=4)
    for _ in range(3):
        state = update_scale(state, jnp.bool_(False))
    state = update_scale(state, jnp.bool_(True))   # overflow at step 4
    assert float(state.loss_scale) == 2.0 ** 7
    for _ in range(3):
        state = update_scale(state, jnp.bool_(False))
    assert float(state.loss_scale) == 2.0 ** 7     # window not yet re-filled
    state = update_scale(state, jnp.bool_(False))
    assert float(state.loss_scale) == 2.0 ** 8


def test_max_loss_scale_clamp():
    state = init_scaler("dynamic", init_scale=2.0 ** 24, scale_window=1,
                        max_loss_scale=2.0 ** 24)
    state = update_scale(state, jnp.bool_(False))
    assert float(state.loss_scale) == 2.0 ** 24


def test_min_loss_scale_clamp():
    state = init_scaler("dynamic", init_scale=4.0, min_loss_scale=2.0)
    state = update_scale(state, jnp.bool_(True))
    assert float(state.loss_scale) == 2.0
    state = update_scale(state, jnp.bool_(True))
    assert float(state.loss_scale) == 2.0


def test_unscale_and_found_inf():
    state = init_scaler(8.0)
    grads = {"w": jnp.asarray([8.0, 16.0], jnp.float16)}
    out, found = unscale(grads, state)
    assert not bool(found)
    assert out["w"].dtype == jnp.float32
    assert jnp.allclose(out["w"], jnp.asarray([1.0, 2.0]))

    bad = {"w": jnp.asarray([jnp.inf, 1.0], jnp.float16)}
    _, found = unscale(bad, state)
    assert bool(found)
    nan = {"w": jnp.asarray([jnp.nan, 1.0], jnp.float32)}
    _, found = unscale(nan, state)
    assert bool(found)


def test_unscale_with_stashed_accumulates():
    state = init_scaler(4.0)
    new = {"w": jnp.asarray([4.0], jnp.float16)}
    stash = {"w": jnp.asarray([10.0], jnp.float32)}
    out, found = unscale_with_stashed(new, stash, state)
    assert not bool(found)
    assert jnp.allclose(out["w"], jnp.asarray([11.0]))


def test_scale_loss_dtype_preserved():
    state = init_scaler(1024.0)
    loss16 = jnp.float16(2.0)
    out = scale_loss(loss16, state)
    assert out.dtype == jnp.float16
    loss32 = jnp.float32(2.0)
    assert scale_loss(loss32, state) == 2048.0


def test_update_scale_is_jittable():
    state = init_scaler("dynamic", scale_window=2)
    step = jax.jit(update_scale)
    state = step(state, jnp.bool_(False))
    state = step(state, jnp.bool_(False))
    assert float(state.loss_scale) == 2.0 ** 17


def test_state_dict_roundtrip():
    s = LossScaler("dynamic")
    s._has_overflow = True
    s.update_scale()
    sd = s.state_dict()
    s2 = LossScaler("dynamic")
    s2.load_state_dict(sd)
    assert s2.loss_scale() == s.loss_scale()
    assert s2.state_dict() == sd


def test_module_state_dict():
    import apex_tpu.amp as amp

    amp.initialize((None, None), opt_level="O2", num_losses=2, verbose=False,
                   verbosity=0)
    sd = amp.state_dict()
    assert set(sd) == {"loss_scaler0", "loss_scaler1"}
    sd["loss_scaler0"]["loss_scale"] = 42.0
    amp.load_state_dict(sd)
    assert amp._amp_state.loss_scalers[0].loss_scale() == 42.0
