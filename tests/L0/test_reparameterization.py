"""Weight-norm reparameterization tests (reference: apex/reparameterization/).

Oracle: direct computation of g * v / ||v|| in fp64 numpy.
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.reparameterization import (
    WeightNorm,
    WeightNormDense,
    apply_weight_norm,
    compute_weight,
    remove_weight_norm,
)


class TestComputeWeight:
    def test_matches_numpy_oracle(self):
        v = np.random.RandomState(0).randn(6, 4).astype(np.float32)
        g = np.random.RandomState(1).rand(6).astype(np.float32) + 0.5
        w = compute_weight(jnp.asarray(v), jnp.asarray(g), dim=0)
        norms = np.linalg.norm(v.reshape(6, -1), axis=1, keepdims=True)
        expected = g[:, None] * v / norms
        np.testing.assert_allclose(np.asarray(w), expected, rtol=1e-5)

    def test_fp16_safe(self):
        """The reason apex forked weight_norm: norm computed in fp32 even for
        half weights (weight_norm.py — compute_weight)."""
        v = (np.random.RandomState(0).randn(8, 8) * 100).astype(np.float16)
        w = compute_weight(jnp.asarray(v), jnp.ones((8,), jnp.float16), dim=0)
        assert w.dtype == jnp.float16
        assert bool(jnp.all(jnp.isfinite(w)))

    def test_reparameterize_roundtrip(self):
        wn = WeightNorm(dim=0)
        weight = jnp.asarray(
            np.random.RandomState(2).randn(5, 3).astype(np.float32))
        v, g = wn.reparameterize(weight)
        back = wn.compute_weight(v, g)
        np.testing.assert_allclose(np.asarray(back), np.asarray(weight),
                                   rtol=1e-5)


class TestTreeTransforms:
    def test_apply_remove_roundtrip(self):
        params = {"layer": {"kernel": jnp.asarray(
            np.random.RandomState(3).randn(4, 2).astype(np.float32)),
            "bias": jnp.zeros((2,))}}
        rep = apply_weight_norm(params)
        assert "kernel_v" in rep["layer"] and "kernel_g" in rep["layer"]
        assert "kernel" not in rep["layer"]
        back = remove_weight_norm(rep)
        np.testing.assert_allclose(np.asarray(back["layer"]["kernel"]),
                                   np.asarray(params["layer"]["kernel"]),
                                   rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(back["layer"]["bias"]),
                                      np.asarray(params["layer"]["bias"]))


class TestWeightNormDense:
    def test_forward_matches_dense(self):
        import flax.linen as nn

        x = jnp.asarray(np.random.RandomState(4).randn(3, 5).astype(np.float32))
        m = WeightNormDense(features=2)
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        # oracle: materialize the kernel and run a plain dense
        kernel = compute_weight(params["params"]["kernel_v"],
                                params["params"]["kernel_g"], dim=1)
        expected = x @ kernel + params["params"]["bias"]
        np.testing.assert_allclose(np.asarray(y), np.asarray(expected),
                                   rtol=1e-5)

    def test_grad_flows(self):
        x = jnp.ones((2, 3))
        m = WeightNormDense(features=2)
        params = m.init(jax.random.PRNGKey(0), x)

        def loss(p):
            return jnp.sum(m.apply(p, x) ** 2)

        grads = jax.grad(loss)(params)
        gleaves = jax.tree_util.tree_leaves(grads)
        assert any(bool(jnp.any(g != 0)) for g in gleaves)
