"""Transformer LM + BERT model tests (BASELINE configs 3 & 4 workloads).

Strategy mirrors the reference's L0 tier: composed fp32 references for
numerics (causality probed directly), short training runs for integration
(loss decreases under amp O2 + fused optimizers — the L1 bar in miniature).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
from apex_tpu.models.bert import BertForPreTraining, BertModel, create_bert
from apex_tpu.models.transformer_lm import TransformerLM, create_lm
from apex_tpu.optimizers import fused_adam, fused_lamb

VOCAB = 101


def _tiny_lm(**kw):
    return TransformerLM(vocab_size=VOCAB, hidden=64, num_layers=2,
                         num_heads=4, max_seq_len=32, **kw)


def test_lm_forward_shape_and_dtype():
    m = _tiny_lm(dtype=jnp.bfloat16)
    toks = jnp.zeros((2, 16), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    logits = m.apply({"params": params}, toks, train=False)
    assert logits.shape == (2, 16, VOCAB)
    assert logits.dtype == jnp.float32  # loss math never in half


def test_lm_is_causal():
    """Changing a future token must not change past logits."""
    m = _tiny_lm()
    rng = jax.random.PRNGKey(1)
    toks = jax.random.randint(rng, (1, 16), 0, VOCAB)
    params = m.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    base = m.apply({"params": params}, toks, train=False)
    toks2 = toks.at[0, 10].set((toks[0, 10] + 1) % VOCAB)
    pert = m.apply({"params": params}, toks2, train=False)
    np.testing.assert_allclose(np.asarray(base[0, :10]),
                               np.asarray(pert[0, :10]), rtol=1e-5,
                               atol=1e-5)
    assert not np.allclose(np.asarray(base[0, 10:]), np.asarray(pert[0, 10:]))


def test_lm_tied_embeddings():
    m = _tiny_lm()
    toks = jnp.zeros((1, 8), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    # no separate lm_head weight: the wte table is the only vocab-sized param
    vocab_params = [k for k, v in jax.tree_util.tree_leaves_with_path(params)
                    if v.shape and VOCAB in v.shape]
    assert len(vocab_params) == 1


def test_lm_trains_amp_o2():
    m = _tiny_lm(dtype=jnp.bfloat16)
    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic")
    toks = jnp.zeros((4, 17), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), toks[:, :-1],
                    train=False)["params"]

    def loss_fn(p, batch):
        logits = m.apply({"params": p}, batch[:, :-1], train=True)
        return softmax_cross_entropy_loss(logits, batch[:, 1:]).mean()

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_adam(1e-3), policy)
    state = init_fn(params)
    jit_step = jax.jit(step_fn)
    rng = jax.random.PRNGKey(2)
    batch = jax.random.randint(rng, (4, 17), 0, VOCAB)
    losses = []
    for _ in range(8):
        state, metrics = jit_step(state, batch)  # same batch: must overfit
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.1, losses
    assert np.isfinite(losses).all()


def test_create_lm_sizes():
    m = create_lm("tiny", vocab_size=50, max_seq_len=16)
    assert m.hidden == 128 and m.num_layers == 2
    with pytest.raises(ValueError):
        create_lm("huge")


@pytest.fixture(scope="module")
def bert_setup():
    cfg = create_bert("tiny", vocab_size=97, max_position_embeddings=32,
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0)
    model = BertForPreTraining(cfg)
    rng = jax.random.PRNGKey(0)
    B, S, P = 2, 16, 4
    input_ids = jax.random.randint(rng, (B, S), 0, cfg.vocab_size)
    token_type_ids = jnp.zeros((B, S), jnp.int32)
    attention_mask = jnp.ones((B, S), jnp.int32).at[1, 10:].set(0)
    mlm_pos = jnp.array([[1, 3, 5, 7], [0, 2, 4, 6]], jnp.int32)
    params = model.init(rng, input_ids, token_type_ids, attention_mask,
                        mlm_pos, train=False)["params"]
    return cfg, model, params, (input_ids, token_type_ids, attention_mask,
                                mlm_pos)


def test_bert_pretraining_shapes(bert_setup):
    cfg, model, params, batch = bert_setup
    mlm_logits, nsp_logits = model.apply({"params": params}, *batch,
                                         train=False)
    assert mlm_logits.shape == (2, 4, cfg.vocab_size)
    assert nsp_logits.shape == (2, 2)
    assert mlm_logits.dtype == jnp.float32


def test_bert_mlm_decoder_is_tied(bert_setup):
    cfg, model, params, batch = bert_setup
    # exactly one vocab×hidden table (tied decoder), plus the mlm bias vector
    big = [v for v in jax.tree_util.tree_leaves(params)
           if v.ndim == 2 and cfg.vocab_size in v.shape]
    assert len(big) == 1


def test_bert_padding_is_ignored(bert_setup):
    """Content of padded positions must not affect unmasked outputs."""
    cfg, model, params, batch = bert_setup
    input_ids, tt, mask, mlm_pos = batch
    out1, _ = model.apply({"params": params}, input_ids, tt, mask, mlm_pos,
                          train=False)
    poked = input_ids.at[1, 12].set((input_ids[1, 12] + 3) % cfg.vocab_size)
    out2, _ = model.apply({"params": params}, poked, tt, mask, mlm_pos,
                          train=False)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               rtol=1e-5, atol=1e-5)


def test_bert_trains_with_lamb(bert_setup):
    cfg, model, params, batch = bert_setup
    input_ids, tt, mask, mlm_pos = batch
    mlm_ids = jax.random.randint(jax.random.PRNGKey(3), mlm_pos.shape, 1,
                                 cfg.vocab_size)
    nsp = jnp.array([0, 1], jnp.int32)
    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic")

    def loss_fn(p, b):
        ids, ttb, mb, pos, tgt, nspb = b
        mlm_logits, nsp_logits = model.apply({"params": p}, ids, ttb, mb,
                                             pos, train=False)
        return (softmax_cross_entropy_loss(mlm_logits, tgt).mean()
                + softmax_cross_entropy_loss(nsp_logits, nspb).mean())

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_lamb(5e-3), policy)
    state = init_fn(params)
    jit_step = jax.jit(step_fn)
    full = (input_ids, tt, mask, mlm_pos, mlm_ids, nsp)
    losses = []
    for _ in range(6):
        state, metrics = jit_step(state, full)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_bert_model_standalone():
    cfg = create_bert("tiny", vocab_size=31, max_position_embeddings=16)
    m = BertModel(cfg)
    ids = jnp.zeros((2, 8), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), ids, train=False)["params"]
    seq, pooled = m.apply({"params": params}, ids, train=False)
    assert seq.shape == (2, 8, cfg.hidden_size)
    assert pooled.shape == (2, cfg.hidden_size)


def test_lm_remat_matches_plain():
    """remat=True is a memory/recompute trade, not a numerics change: fwd
    and grads must match the plain model exactly (SURVEY §6 — activation
    checkpointing maps to jax.checkpoint)."""
    toks = jax.random.randint(jax.random.PRNGKey(7), (2, 12), 0, VOCAB)
    plain = _tiny_lm()
    remat = _tiny_lm(remat=True)
    params = plain.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    out_p = plain.apply({"params": params}, toks, train=False)
    out_r = remat.apply({"params": params}, toks, train=False)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_r),
                               rtol=1e-6, atol=1e-6)

    def loss(m):
        def f(p):
            lg = m.apply({"params": p}, toks, train=True)
            return jnp.sum(lg ** 2) * 1e-4
        return f

    g_p = jax.grad(loss(plain))(params)
    g_r = jax.grad(loss(remat))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), g_p, g_r)


def test_bert_remat_matches_plain():
    cfg = create_bert("tiny", vocab_size=53, max_position_embeddings=16,
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0)
    ids = jax.random.randint(jax.random.PRNGKey(0), (2, 8), 0, 53)
    plain = BertModel(cfg)
    remat = BertModel(cfg, remat=True)
    params = plain.init(jax.random.PRNGKey(1), ids, train=False)["params"]
    s_p, p_p = plain.apply({"params": params}, ids, train=False)
    s_r, p_r = remat.apply({"params": params}, ids, train=False)
    np.testing.assert_allclose(np.asarray(s_p), np.asarray(s_r),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(p_p), np.asarray(p_r),
                               rtol=1e-6, atol=1e-6)
