"""Multi-tensor harness tests — mirrors tests/L0/run_optimizers/
test_fused_optimizer.py's oracle pattern: fused whole-model update vs
torch.optim reference, per-step allclose over many iterations."""

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels.multi_tensor import (fused_adam_step, fused_axpby,
                                           fused_l2norm, fused_scale,
                                           fused_sgd_step)
from apex_tpu.multi_tensor_apply import (multi_tensor_adam,
                                         multi_tensor_applier,
                                         multi_tensor_l2norm,
                                         multi_tensor_scale,
                                         MultiTensorApply)


def _flat(n, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(n), dtype)


@pytest.mark.parametrize("n", [5, 128, 1000, 4096])
def test_scale(n):
    x = _flat(n)
    out, found = fused_scale(x, 0.5, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 0.5,
                               rtol=1e-6)
    assert not bool(found)


def test_scale_found_inf():
    x = _flat(300).at[123].set(jnp.inf)
    _, found = fused_scale(x, 1.0, interpret=True)
    assert bool(found)
    x = _flat(300).at[0].set(jnp.nan)
    _, found = fused_scale(x, 1.0, interpret=True)
    assert bool(found)


def test_axpby():
    x, y = _flat(500, 0), _flat(500, 1)
    out, found = fused_axpby(x, y, 2.0, -1.0, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               2 * np.asarray(x) - np.asarray(y), rtol=1e-6)
    assert not bool(found)
    _, found = fused_axpby(x.at[7].set(jnp.inf), y, 1.0, 1.0, interpret=True)
    assert bool(found)


@pytest.mark.parametrize("n", [7, 1024, 5000])
def test_l2norm(n):
    x = _flat(n)
    out = fused_l2norm(x, interpret=True)
    np.testing.assert_allclose(float(out), float(np.linalg.norm(np.asarray(x))),
                               rtol=1e-5)


@pytest.mark.parametrize("adam_w", [False, True])
def test_adam_vs_torch(adam_w):
    import torch

    n = 1000
    rng = np.random.RandomState(3)
    p0 = rng.randn(n).astype(np.float32)
    lr, b1, b2, eps, wd = 1e-2, 0.9, 0.999, 1e-8, 0.01

    tp = torch.nn.Parameter(torch.tensor(p0.copy()))
    topt = (torch.optim.AdamW([tp], lr=lr, betas=(b1, b2), eps=eps,
                              weight_decay=wd)
            if adam_w else
            torch.optim.Adam([tp], lr=lr, betas=(b1, b2), eps=eps,
                             weight_decay=wd))

    p = jnp.asarray(p0)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)

    for step in range(1, 6):
        g = rng.randn(n).astype(np.float32)
        tp.grad = torch.tensor(g.copy())
        topt.step()
        p, m, v = fused_adam_step(p, m, v, jnp.asarray(g), lr=lr, beta1=b1,
                                  beta2=b2, eps=eps, weight_decay=wd,
                                  step=step, adam_w_mode=adam_w,
                                  interpret=True)
        # fp32 op-ordering noise vs torch (apex allows the same class of
        # tolerance in run_optimizers/test_fused_optimizer.py)
        np.testing.assert_allclose(np.asarray(p), tp.detach().numpy(),
                                   atol=1e-5, rtol=1e-3)


@pytest.mark.parametrize("momentum,nesterov,wd", [(0.0, False, 0.0),
                                                  (0.9, False, 1e-4),
                                                  (0.9, True, 1e-4)])
def test_sgd_vs_torch(momentum, nesterov, wd):
    import torch

    n = 512
    rng = np.random.RandomState(5)
    p0 = rng.randn(n).astype(np.float32)
    lr = 0.1

    tp = torch.nn.Parameter(torch.tensor(p0.copy()))
    topt = torch.optim.SGD([tp], lr=lr, momentum=momentum, nesterov=nesterov,
                           weight_decay=wd)
    p = jnp.asarray(p0)
    buf = jnp.zeros((n,), jnp.float32)
    for _ in range(5):
        g = rng.randn(n).astype(np.float32)
        tp.grad = torch.tensor(g.copy())
        topt.step()
        p, buf = fused_sgd_step(p, buf, jnp.asarray(g), lr=lr,
                                momentum=momentum, weight_decay=wd,
                                nesterov=nesterov, interpret=True)
        np.testing.assert_allclose(np.asarray(p), tp.detach().numpy(),
                                   atol=1e-6, rtol=1e-5)


def test_tensor_list_frontend():
    ts = [_flat(10, 0), _flat(300, 1).reshape(20, 15), _flat(7, 2)]
    out, found = multi_tensor_scale(ts, 2.0, interpret=True)
    assert not bool(found)
    for o, t in zip(out, ts):
        assert o.shape == t.shape
        np.testing.assert_allclose(np.asarray(o), 2 * np.asarray(t),
                                   rtol=1e-6)
    total = multi_tensor_l2norm(ts, interpret=True)
    expect = np.linalg.norm(np.concatenate([np.asarray(t).ravel()
                                            for t in ts]))
    np.testing.assert_allclose(float(total), float(expect), rtol=1e-5)
    total2, per = multi_tensor_l2norm(ts, per_tensor=True, interpret=True)
    np.testing.assert_allclose(float(total2), float(expect), rtol=1e-5)
    assert len(per) == 3


def test_applier_shim_signature():
    # apex calling convention: applier(op, noop_buf, tensor_lists, *args)
    applier = MultiTensorApply(2048)

    def op(noop, lists, scale):
        return multi_tensor_scale(lists[0], scale, interpret=True)

    out, found = applier(op, None, [[_flat(16)]], 3.0)
    assert not bool(found)
    assert multi_tensor_applier.available


def test_adam_list_frontend():
    ps = [_flat(33, 0), _flat(65, 1)]
    ms = [jnp.zeros_like(p) for p in ps]
    vs = [jnp.zeros_like(p) for p in ps]
    gs = [_flat(33, 2), _flat(65, 3)]
    newp, newm, newv = multi_tensor_adam(
        ps, ms, vs, gs, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, step=1,
        interpret=True)
    assert [p.shape for p in newp] == [p.shape for p in ps]
    # single-step oracle: p - lr * g/(|g| + eps) after bias correction
    g = np.asarray(gs[0])
    mhat = g  # m/(1-b1) with m=(1-b1)g
    vhat = g * g
    expect = np.asarray(ps[0]) - 1e-3 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(np.asarray(newp[0]), expect, atol=1e-6)
