"""Fused LM-head + CE (kernels/lm_head_loss.py) vs the unfused oracle.

The op's claim is purely structural (logits never hit HBM), so the test
bar is numerical identity with the composed path at matching compute
dtype — loss AND both cotangents (dx, dkernel), smoothing included.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels.lm_head_loss import (lm_head_xent_reference,
                                           lm_head_xentropy)

N, H, V = 24, 64, 512


def _setup(seed=0, dtype=jnp.float32):
    rng = jax.random.PRNGKey(seed)
    x = jax.random.normal(rng, (N, H), dtype)
    w = jax.random.normal(jax.random.fold_in(rng, 1), (V, H), dtype) * 0.1
    y = jax.random.randint(jax.random.fold_in(rng, 2), (N,), 0, V)
    return x, w, y


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
@pytest.mark.parametrize("chunk", [128, 256, 512, 8192])
def test_fwd_matches_reference(smoothing, chunk):
    x, w, y = _setup()
    got = lm_head_xentropy(x, w, y, smoothing=smoothing, chunk=chunk)
    want = lm_head_xent_reference(x, w, y, smoothing)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_grads_match_reference(smoothing):
    x, w, y = _setup()

    def fused(x, w):
        return lm_head_xentropy(x, w, y, smoothing=smoothing,
                                chunk=128).mean()

    def composed(x, w):
        return lm_head_xent_reference(x, w, y, smoothing).mean()

    gx_f, gw_f = jax.grad(fused, argnums=(0, 1))(x, w)
    gx_c, gw_c = jax.grad(composed, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_c),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_c),
                               rtol=2e-4, atol=2e-5)


def test_half_compute_dtype_close_to_fp32():
    """bf16 GEMM inputs with fp32 accumulation: loss within bf16-level
    tolerance of the fp32 path, grads carried in the primal dtypes."""
    x, w, y = _setup()
    lo = lm_head_xentropy(x, w, y, chunk=128, compute_dtype=jnp.bfloat16)
    hi = lm_head_xentropy(x, w, y, chunk=128)
    np.testing.assert_allclose(np.asarray(lo), np.asarray(hi),
                               rtol=0.05, atol=0.05)
    gx, gw = jax.grad(
        lambda x, w: lm_head_xentropy(
            x, w, y, chunk=128, compute_dtype=jnp.bfloat16).mean(),
        argnums=(0, 1))(x, w)
    assert gx.dtype == x.dtype and gw.dtype == w.dtype
    assert np.all(np.isfinite(np.asarray(gx)))


def test_batched_leading_dims():
    x, w, y = _setup()
    xb = x.reshape(4, 6, H)
    yb = y.reshape(4, 6)
    got = lm_head_xentropy(xb, w, yb, chunk=128)
    assert got.shape == (4, 6)
    flat = lm_head_xentropy(x, w, y, chunk=128)
    np.testing.assert_allclose(np.asarray(got).reshape(-1),
                               np.asarray(flat), rtol=1e-6)


@pytest.mark.parametrize("v", [130, 1000, 257])
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_unaligned_vocab_pads_and_masks(v, smoothing):
    """Vocabs that don't divide the chunk (GPT-2's 50257 is prime) stay
    FUSED: the weight pads to a chunk multiple and the pad columns are
    masked out of the logsumexp, the smoothing floor, and dW. Loss and
    both cotangents must match the unpadded reference exactly."""
    rng = jax.random.PRNGKey(3)
    x = jax.random.normal(rng, (8, H))
    w = jax.random.normal(jax.random.fold_in(rng, 1), (v, H)) * 0.1
    y = jax.random.randint(jax.random.fold_in(rng, 2), (8,), 0, v)
    got = lm_head_xentropy(x, w, y, smoothing=smoothing, chunk=128)
    want = lm_head_xent_reference(x, w, y, smoothing)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    gx_f, gw_f = jax.grad(
        lambda x, w: lm_head_xentropy(x, w, y, smoothing=smoothing,
                                      chunk=128).mean(),
        argnums=(0, 1))(x, w)
    gx_c, gw_c = jax.grad(
        lambda x, w: lm_head_xent_reference(x, w, y, smoothing).mean(),
        argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_c),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_c),
                               rtol=2e-4, atol=2e-5)


def test_validation_errors():
    x, w, y = _setup()
    with pytest.raises(ValueError, match="smoothing"):
        lm_head_xentropy(x, w, y, smoothing=1.0)
    with pytest.raises(ValueError, match="vocab-major"):
        lm_head_xentropy(x, w.T, y)
    with pytest.raises(ValueError, match="labels"):
        lm_head_xentropy(x, w, y[:-1])


def test_matches_onchip_xentropy_composition():
    """Cross-check against the repo's own Pallas xentropy path composed
    with an explicit head GEMM — the exact pair of ops the fused version
    replaces in the LM recipe."""
    from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
    x, w, y = _setup()
    logits = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    want = softmax_cross_entropy_loss(logits, y)
    got = lm_head_xentropy(x, w, y, chunk=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
