"""Fused LM-head + CE (kernels/lm_head_loss.py) vs the unfused oracle.

The op's claim is purely structural (logits never hit HBM), so the test
bar is numerical identity with the composed path at matching compute
dtype — loss AND both cotangents (dx, dkernel), smoothing included.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels.lm_head_loss import (lm_head_xent_reference,
                                           lm_head_xentropy)

N, H, V = 24, 64, 512


def _setup(seed=0, dtype=jnp.float32):
    rng = jax.random.PRNGKey(seed)
    x = jax.random.normal(rng, (N, H), dtype)
    w = jax.random.normal(jax.random.fold_in(rng, 1), (V, H), dtype) * 0.1
    y = jax.random.randint(jax.random.fold_in(rng, 2), (N,), 0, V)
    return x, w, y


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
@pytest.mark.parametrize("chunk", [128, 256, 512, 8192])
def test_fwd_matches_reference(smoothing, chunk):
    x, w, y = _setup()
    got = lm_head_xentropy(x, w, y, smoothing=smoothing, chunk=chunk)
    want = lm_head_xent_reference(x, w, y, smoothing)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_grads_match_reference(smoothing):
    x, w, y = _setup()

    def fused(x, w):
        return lm_head_xentropy(x, w, y, smoothing=smoothing,
                                chunk=128).mean()

    def composed(x, w):
        return lm_head_xent_reference(x, w, y, smoothing).mean()

    gx_f, gw_f = jax.grad(fused, argnums=(0, 1))(x, w)
    gx_c, gw_c = jax.grad(composed, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_c),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_c),
                               rtol=2e-4, atol=2e-5)


def test_half_compute_dtype_close_to_fp32():
    """bf16 GEMM inputs with fp32 accumulation: loss within bf16-level
    tolerance of the fp32 path, grads carried in the primal dtypes."""
    x, w, y = _setup()
    lo = lm_head_xentropy(x, w, y, chunk=128, compute_dtype=jnp.bfloat16)
    hi = lm_head_xentropy(x, w, y, chunk=128)
    np.testing.assert_allclose(np.asarray(lo), np.asarray(hi),
                               rtol=0.05, atol=0.05)
    gx, gw = jax.grad(
        lambda x, w: lm_head_xentropy(
            x, w, y, chunk=128, compute_dtype=jnp.bfloat16).mean(),
        argnums=(0, 1))(x, w)
    assert gx.dtype == x.dtype and gw.dtype == w.dtype
    assert np.all(np.isfinite(np.asarray(gx)))


def test_batched_leading_dims():
    x, w, y = _setup()
    xb = x.reshape(4, 6, H)
    yb = y.reshape(4, 6)
    got = lm_head_xentropy(xb, w, yb, chunk=128)
    assert got.shape == (4, 6)
    flat = lm_head_xentropy(x, w, y, chunk=128)
    np.testing.assert_allclose(np.asarray(got).reshape(-1),
                               np.asarray(flat), rtol=1e-6)


@pytest.mark.parametrize("v", [130, 1000, 257])
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_unaligned_vocab_pads_and_masks(v, smoothing):
    """Vocabs that don't divide the chunk (GPT-2's 50257 is prime) stay
    FUSED: the weight pads to a chunk multiple and the pad columns are
    masked out of the logsumexp, the smoothing floor, and dW. Loss and
    both cotangents must match the unpadded reference exactly."""
    rng = jax.random.PRNGKey(3)
    x = jax.random.normal(rng, (8, H))
    w = jax.random.normal(jax.random.fold_in(rng, 1), (v, H)) * 0.1
    y = jax.random.randint(jax.random.fold_in(rng, 2), (8,), 0, v)
    got = lm_head_xentropy(x, w, y, smoothing=smoothing, chunk=128)
    want = lm_head_xent_reference(x, w, y, smoothing)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    gx_f, gw_f = jax.grad(
        lambda x, w: lm_head_xentropy(x, w, y, smoothing=smoothing,
                                      chunk=128).mean(),
        argnums=(0, 1))(x, w)
    gx_c, gw_c = jax.grad(
        lambda x, w: lm_head_xent_reference(x, w, y, smoothing).mean(),
        argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_c),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_c),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_out_of_range_labels_match_reference_nan(smoothing):
    """ADVICE r5 #1: ignore-index −100 (and ids >= V) must NOT silently
    read as a finite loss on a wrong column — both paths return NaN at
    exactly the invalid positions and stay correct everywhere else."""
    x, w, y = _setup()
    y = y.at[0].set(-100).at[3].set(-1).at[5].set(V).at[7].set(V + 9)
    got = np.asarray(lm_head_xentropy(x, w, y, smoothing=smoothing,
                                      chunk=128))
    want = np.asarray(lm_head_xent_reference(x, w, y, smoothing))
    invalid = np.asarray((y < 0) | (y >= V))
    assert np.isnan(got[invalid]).all() and np.isnan(want[invalid]).all()
    np.testing.assert_allclose(got[~invalid], want[~invalid],
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_out_of_range_labels_grads_match_reference(smoothing):
    """Backward parity on bad labels: masking the returned losses (the
    documented ignore-index recipe) zeroes invalid rows' cotangents, and
    both paths must produce IDENTICAL finite grads — the onehot term
    drops while at smoothing>0 the mean-logp term still flows for rows
    that keep a nonzero cotangent."""
    x, w, y = _setup()
    y = y.at[0].set(-100).at[5].set(V + 1)
    valid = (y >= 0) & (y < V)

    def masked(losses):
        return jnp.sum(jnp.where(valid, losses, 0.0)) / jnp.sum(valid)

    gx_f, gw_f = jax.grad(
        lambda x, w: masked(lm_head_xentropy(x, w, y, smoothing=smoothing,
                                             chunk=128)),
        argnums=(0, 1))(x, w)
    gx_c, gw_c = jax.grad(
        lambda x, w: masked(lm_head_xent_reference(x, w, y, smoothing)),
        argnums=(0, 1))(x, w)
    assert np.isfinite(np.asarray(gx_f)).all()
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_c),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_c),
                               rtol=2e-4, atol=2e-5)
    # invalid rows' dx vanishes: their loss was masked out of the sum
    np.testing.assert_allclose(np.asarray(gx_f)[~np.asarray(valid)], 0.0,
                               atol=1e-7)


def test_pick_chunk_clamps_unrolled_count_and_warns(caplog):
    """ADVICE r5 #2: a small chunk at large vocab must not unroll
    hundreds of straight-line GEMM iterations — the chunk is widened
    (with a warning) so the count stays <= _MAX_UNROLLED_CHUNKS."""
    import logging

    from apex_tpu.kernels.lm_head_loss import (_MAX_UNROLLED_CHUNKS,
                                               _pick_chunk)

    # the package root logger is propagate=False (log_util installs its
    # own stderr handler) — re-enable propagation so caplog sees records
    apex_root = logging.getLogger("apex_tpu")
    old_propagate = apex_root.propagate
    apex_root.propagate = True
    try:
        with caplog.at_level(logging.WARNING,
                             logger="apex_tpu.kernels.lm_head_loss"):
            c = _pick_chunk(50304, 128)    # 393 iterations unclamped
        n_chunks = -(-50304 // c)
        assert n_chunks <= _MAX_UNROLLED_CHUNKS
        assert c % 128 == 0
        assert any("unroll" in r.message for r in caplog.records)

        # sane requests pass through untouched, silently
        caplog.clear()
        with caplog.at_level(logging.WARNING,
                             logger="apex_tpu.kernels.lm_head_loss"):
            assert _pick_chunk(512, 128) == 128
            assert _pick_chunk(50304, 8192) == 8192
        assert not caplog.records

        # extreme vocab (10M retrieval head): the widening honors the
        # caller's memory intent — capped at max(chunk, 8192), warned,
        # never silently blown up to a 156k-wide logits block
        caplog.clear()
        with caplog.at_level(logging.WARNING,
                             logger="apex_tpu.kernels.lm_head_loss"):
            assert _pick_chunk(10_000_000, 8192) == 8192
            assert _pick_chunk(10_000_000, 16384) == 16384
        assert all("vocab-parallel" in r.message for r in caplog.records)
    finally:
        apex_root.propagate = old_propagate


def test_clamped_chunk_still_matches_reference():
    """The widened chunk is a perf guard, not a semantics change."""
    v = 16384                              # 128 chunks at chunk=128 → clamped
    rng = jax.random.PRNGKey(7)
    x = jax.random.normal(rng, (4, H))
    w = jax.random.normal(jax.random.fold_in(rng, 1), (v, H)) * 0.05
    y = jax.random.randint(jax.random.fold_in(rng, 2), (4,), 0, v)
    got = lm_head_xentropy(x, w, y, chunk=128)
    want = lm_head_xent_reference(x, w, y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_validation_errors():
    x, w, y = _setup()
    with pytest.raises(ValueError, match="smoothing"):
        lm_head_xentropy(x, w, y, smoothing=1.0)
    with pytest.raises(ValueError, match="vocab-major"):
        lm_head_xentropy(x, w.T, y)
    with pytest.raises(ValueError, match="labels"):
        lm_head_xentropy(x, w, y[:-1])


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_pallas_xentropy_out_of_range_labels_match_reference(smoothing):
    """The PALLAS dispatch path of softmax_cross_entropy_loss (aligned
    vocab → the in-kernel masked-reduction gather, interpret-mode on
    CPU) must agree with xent_reference on out-of-range labels too: NaN
    loss, onehot cotangent dropped — not the silently-finite lse the
    unmasked kernel used to return."""
    from apex_tpu.kernels.xentropy import (softmax_cross_entropy_loss,
                                           xent_reference)

    x, w, y = _setup()
    logits = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    y = y.at[0].set(-100).at[3].set(V).at[5].set(-1)
    assert logits.shape[-1] % 128 == 0          # Pallas path, not fallback
    got = np.asarray(softmax_cross_entropy_loss(logits, y, smoothing))
    want = np.asarray(xent_reference(logits, y, smoothing))
    invalid = np.asarray((y < 0) | (y >= V))
    assert np.isnan(got[invalid]).all() and np.isnan(want[invalid]).all()
    np.testing.assert_allclose(got[~invalid], want[~invalid],
                               rtol=1e-5, atol=1e-5)

    valid = jnp.asarray(~invalid)

    def masked(fn):
        def run(lg):
            losses = fn(lg, y, smoothing)
            return jnp.sum(jnp.where(valid, losses, 0.0)) / jnp.sum(valid)
        return run

    g_f = jax.grad(masked(softmax_cross_entropy_loss))(logits)
    g_c = jax.grad(masked(xent_reference))(logits)
    assert np.isfinite(np.asarray(g_f)).all()
    np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_c),
                               rtol=2e-4, atol=2e-5)


def test_matches_onchip_xentropy_composition():
    """Cross-check against the repo's own Pallas xentropy path composed
    with an explicit head GEMM — the exact pair of ops the fused version
    replaces in the LM recipe."""
    from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
    x, w, y = _setup()
    logits = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    want = softmax_cross_entropy_loss(logits, y)
    got = lm_head_xentropy(x, w, y, chunk=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
