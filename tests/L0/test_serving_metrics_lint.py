"""Serving-telemetry lint: every ``serving.faults.*`` /
``serving.watchdog.*`` / ``serving.spec.*`` / ``serving.tp.*`` metric
the serving code emits must be documented in ``docs/serving.md``, and
every documented one must be emitted.

Same failure mode as the tuned-keys lint, one layer up: metric names
are stringly typed, so a renamed counter silently orphans its dashboard
row (and a doc'd metric nobody emits is an alert that can never fire).
The fault-isolation layer is exactly where that rot is most expensive —
``serving.faults.nonfinite`` going dark looks identical to "no faults"
— and the speculative layer is next in line: an orphaned
``serving.spec.acceptance_rate`` reads as "speculation off" while the
verify program burns real FLOPs. The tensor-parallel family joined with
the mesh tentpole: ``serving.tp.shards`` / the per-program collective
gauges going dark would make a sharded fleet indistinguishable from a
single-chip one on every dashboard. The ``serving.kv.*`` family joined
with the quantized-cache tentpole: ``serving.kv.bytes_per_token`` is
the capacity claim's basis, and ``serving.kv.quant_scale_absmax`` going
dark would hide that a drifted workload is CLIPPING against its
calibration. The loop is closed by lint: the set of
fault/watchdog/spec/tp/kv metric literals in ``apex_tpu/serving/``
source must EQUAL the set named in the docs' tables.
"""

import glob
import os
import re

import pytest

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.abspath(os.path.join(HERE, os.pardir, os.pardir))
SRC_DIR = os.path.join(ROOT, "apex_tpu", "serving")
DOC = os.path.join(ROOT, "docs", "serving.md")

# metric families the fault-isolation + speculative + tensor-parallel
# + quantized-KV layers own
_PAT = re.compile(
    r"serving\.(?:faults|watchdog|spec|tp|kv)\.[a-z0-9_]+")


def _emitted():
    refs = {}
    for path in glob.glob(os.path.join(SRC_DIR, "**", "*.py"),
                          recursive=True):
        with open(path) as f:
            for name in _PAT.findall(f.read()):
                refs.setdefault(name, []).append(
                    os.path.relpath(path, ROOT))
    return refs


def _documented():
    with open(DOC) as f:
        return set(_PAT.findall(f.read()))


def test_scan_surface_is_alive():
    """The lint must be looking at real code and real docs — an empty
    scan means the regex or paths broke, not that the code is clean."""
    emitted = _emitted()
    assert emitted, "no serving.faults.*/watchdog.*/spec.* literals " \
        "found under apex_tpu/serving — scan broken?"
    # the metrics the issues headline must exist and come from the
    # layers that own them (engine guard / scheduler watchdog + spec)
    assert os.path.join("apex_tpu", "serving", "engine.py") \
        in emitted.get("serving.faults.nonfinite", [])
    assert os.path.join("apex_tpu", "serving", "scheduler.py") \
        in emitted.get("serving.watchdog.stall", [])
    # the speculative-decoding layer (watchdog warm-start satellite
    # rides the same scan): acceptance + warm-up accounting are live
    sched = os.path.join("apex_tpu", "serving", "scheduler.py")
    for name in ("serving.spec.drafted", "serving.spec.accepted",
                 "serving.spec.acceptance_rate",
                 "serving.spec.tokens_per_step",
                 "serving.watchdog.warmup_s"):
        assert sched in emitted.get(name, []), \
            f"{name} not emitted by the scheduler — spec/watchdog " \
            "telemetry went dark"
    assert os.path.join("apex_tpu", "serving", "engine.py") \
        in emitted.get("serving.spec.verify_s", [])
    # the batched-verify slot-step counter (bench arithmetic's basis)
    # and the tensor-parallel gauge family are engine-emitted
    engine_py = os.path.join("apex_tpu", "serving", "engine.py")
    for name in ("serving.spec.verify_slots", "serving.tp.shards",
                 "serving.tp.psums_per_program",
                 "serving.tp.all_gathers_per_program",
                 "serving.tp.hbm_bytes_per_shard",
                 "serving.tp.pool_pages_per_shard",
                 "serving.kv.bytes_per_token",
                 "serving.kv.quant_scale_absmax"):
        assert engine_py in emitted.get(name, []), \
            f"{name} not emitted by the engine — batched-verify/tp/" \
            "quantized-kv telemetry went dark"
    assert _documented(), "docs/serving.md names no fault/watchdog/" \
        "spec metrics — doc section missing?"


def test_every_emitted_fault_metric_is_documented():
    emitted = _emitted()
    documented = _documented()
    missing = {k: v for k, v in emitted.items() if k not in documented}
    assert not missing, (
        f"fault/watchdog metrics emitted in code but absent from "
        f"docs/serving.md (document them in the fault-tolerance "
        f"section): {missing}")


def test_every_documented_fault_metric_is_emitted():
    emitted = set(_emitted())
    stale = _documented() - emitted
    assert not stale, (
        f"docs/serving.md documents fault/watchdog metrics no serving "
        f"code emits (stale doc rows — delete them or wire the "
        f"emitter): {stale}")
