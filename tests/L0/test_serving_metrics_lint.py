"""Serving-telemetry lint: every ``serving.faults.*`` /
``serving.watchdog.*`` / ``serving.spec.*`` / ``serving.tp.*`` metric
the serving code emits must be documented in ``docs/serving.md``, and
every documented one must be emitted.

Same failure mode as the tuned-keys lint, one layer up: metric names
are stringly typed, so a renamed counter silently orphans its dashboard
row (and a doc'd metric nobody emits is an alert that can never fire).
The fault-isolation layer is exactly where that rot is most expensive —
``serving.faults.nonfinite`` going dark looks identical to "no faults"
— and the speculative layer is next in line: an orphaned
``serving.spec.acceptance_rate`` reads as "speculation off" while the
verify program burns real FLOPs. The tensor-parallel family joined with
the mesh tentpole: ``serving.tp.shards`` / the per-program collective
gauges going dark would make a sharded fleet indistinguishable from a
single-chip one on every dashboard. The ``serving.kv.*`` family joined
with the quantized-cache tentpole: ``serving.kv.bytes_per_token`` is
the capacity claim's basis, and ``serving.kv.quant_scale_absmax`` going
dark would hide that a drifted workload is CLIPPING against its
calibration. The ``serving.heartbeat.*`` family joined with the async
pipelined heartbeat: ``host_s`` / ``device_wait_s`` / ``duty_cycle``
are the duty-cycle claim's basis (the whole point of dispatch-ahead
execution), and ``discarded`` going dark would hide speculated-finality
rollbacks entirely. The ``serving.router.*`` family joined with the
replica-parallel tentpole: ``affinity_hits`` going dark reads as "no
multi-turn reuse" while requests silently re-prefill on cold replicas,
``replica_deaths`` / ``requeued`` going dark makes a dying fleet look
healthy, and the per-replica gauge namespace
(``serving.router.replica<i>.*``) is what keeps N replicas sharing one
registry from clobbering each other's pool gauges. The ``serving.swap.*``
family joined with the hierarchical-KV tentpole: ``hit_after_swap``
going dark reads as "the host tier never pays off" while swap-ins
silently skip real prefill chunks, ``verify_failed`` going dark would
hide that swapped prefixes are rotting (every one a full re-prefill),
and ``host_bytes`` is the tier's capacity claim. The loop is closed
by lint: the set of fault/watchdog/spec/tp/kv/heartbeat/router/swap
metric literals in ``apex_tpu/serving/`` source must EQUAL the set
named in the docs' tables.

The ``serving.wq.*`` family joined with the quantized-weights
tentpole: ``bytes_per_param`` is the weight-capacity claim's basis and
the family's absence on an engine is the signal the tier is OFF — both
gauges going dark would make a quantized fleet indistinguishable from
a bf16 one on every dashboard.

The ``serving.lora.*`` family joined with the multi-tenant LoRA
tentpole: ``loads`` vs ``hits`` is the adapter-affinity routing
claim's measurement basis (a dark ``hits`` reads as "every request
pays a host→device swap-in"), ``evictions`` going dark hides arena
thrash under adapter churn, and ``arena_bytes`` /
``active_adapters`` are the host store's capacity claim.

This file also owns the **eager-gather shape lint** (the PR 13 gotcha,
generalized): an eager ``pool[:, idx_list]`` fancy-index gather over
the device KV pool compiles ONE executable PER INDEX-COUNT — a serving
path whose index list length is data-dependent (per-prefix page
counts) silently recompiles ~165 ms mid-serve the first time an unseen
length appears, wrecking latency percentiles while every parity test
stays green (the bytes are right, only the wall-clock rots). The fix
is always the same: pad the index list to a fixed bound (the page-0
sentinel absorbs padding) so one shape serves all sizes. The lint
AST-scans ``apex_tpu/serving/`` for fancy-index gathers over the pool
arrays and pins the site set to exactly the allowlisted PADDED ones
(both host_tier swap directions), so every new gather must either pad
and join the allowlist deliberately or take a compiled fixed-shape
path.

This file also owns the **force-early lint**: the dispatch-ahead
regions of the serving stack must never force a device value to host
— no ``int()`` / ``float()`` / ``np.asarray()`` / ``np.array()`` /
``jax.device_get`` calls inside :func:`Scheduler._dispatch_decode`,
:func:`Scheduler._pipeline_last_tokens` (the pipelined heartbeat:
everything between a decode dispatch and its reconcile), or
:func:`Engine._dispatch_swap_out` (the async hierarchical-KV
swap-out's admission-side half: it snapshots pool bytes for the
:class:`SwapWorker` by DISPATCHING a compiled gather — a forced read
there silently reverts the tier to the synchronous admission stall).
A single forced read in any of these serializes the host against the
device with ZERO token-level symptom — the exact foot-gun the async
refactors exist to remove, invisible to every parity test because
forcing changes no tokens. Functions are checked BY NAME per file, so
a rename breaks the lint loudly instead of silently un-scoping it.

This file also owns the **span-name lint** (the tracing tentpole's
version of the metric-name loop): span names are stringly typed at
their emit sites (``tracer.event(uid, "admit", ...)``), so a renamed
span silently orphans its row in the ``### Span taxonomy`` table in
``docs/serving.md`` — and a documented span nobody emits is a Perfetto
lane a reader will wait for forever. The lint AST-scans
``apex_tpu/serving/`` for calls to the three tracer recording methods
(``.event`` / ``.event_current`` / ``.end_trace``) and extracts each
call's first string-literal positional argument (the span name —
trace ids are never literals), then pins that set EQUAL to the
backticked first column of the taxonomy table. And the **tracer
force-lint**: the tracer's recording methods run inside the
dispatch-ahead regions' dynamic extent (the heartbeat/swap hooks call
them between dispatch and reconcile), so they get the same
force-early treatment as the regions themselves — no ``int()`` /
``np.asarray`` / ``jax.device_get`` in any hot recording method (the
exporters force freely; they run offline).
"""

import ast
import glob
import os
import re

import pytest

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.abspath(os.path.join(HERE, os.pardir, os.pardir))
SRC_DIR = os.path.join(ROOT, "apex_tpu", "serving")
DOC = os.path.join(ROOT, "docs", "serving.md")

# metric families the fault-isolation + speculative + tensor-parallel
# + quantized-KV + async-heartbeat + replica-router layers own.
# NOTE the per-replica namespace: the router emits gauges as
# f"serving.router.replica{i}.<gauge>" — the literal this regex
# extracts from that f-string (source AND docs) is
# "serving.router.replica", which is exactly the namespacing contract
# the docs must name.
_PAT = re.compile(
    r"serving\.(?:faults|watchdog|spec|tp|kv|wq|heartbeat|router|swap"
    r"|disagg|fleet|slo|preempt|lora)"
    r"\.[a-z0-9_]+")


def _emitted():
    refs = {}
    for path in glob.glob(os.path.join(SRC_DIR, "**", "*.py"),
                          recursive=True):
        with open(path) as f:
            for name in _PAT.findall(f.read()):
                refs.setdefault(name, []).append(
                    os.path.relpath(path, ROOT))
    return refs


def _documented():
    with open(DOC) as f:
        return set(_PAT.findall(f.read()))


def test_scan_surface_is_alive():
    """The lint must be looking at real code and real docs — an empty
    scan means the regex or paths broke, not that the code is clean."""
    emitted = _emitted()
    assert emitted, "no serving.faults.*/watchdog.*/spec.* literals " \
        "found under apex_tpu/serving — scan broken?"
    # the metrics the issues headline must exist and come from the
    # layers that own them (engine guard / scheduler watchdog + spec)
    assert os.path.join("apex_tpu", "serving", "engine.py") \
        in emitted.get("serving.faults.nonfinite", [])
    assert os.path.join("apex_tpu", "serving", "scheduler.py") \
        in emitted.get("serving.watchdog.stall", [])
    # the speculative-decoding layer (watchdog warm-start satellite
    # rides the same scan): acceptance + warm-up accounting are live
    sched = os.path.join("apex_tpu", "serving", "scheduler.py")
    for name in ("serving.spec.drafted", "serving.spec.accepted",
                 "serving.spec.acceptance_rate",
                 "serving.spec.tokens_per_step",
                 "serving.watchdog.warmup_s"):
        assert sched in emitted.get(name, []), \
            f"{name} not emitted by the scheduler — spec/watchdog " \
            "telemetry went dark"
    assert os.path.join("apex_tpu", "serving", "engine.py") \
        in emitted.get("serving.spec.verify_s", [])
    # the batched-verify slot-step counter (bench arithmetic's basis)
    # and the tensor-parallel gauge family are engine-emitted
    engine_py = os.path.join("apex_tpu", "serving", "engine.py")
    for name in ("serving.spec.verify_slots", "serving.tp.shards",
                 "serving.tp.psums_per_program",
                 "serving.tp.all_gathers_per_program",
                 "serving.tp.hbm_bytes_per_shard",
                 "serving.tp.pool_pages_per_shard",
                 "serving.kv.bytes_per_token",
                 "serving.kv.quant_scale_absmax"):
        assert engine_py in emitted.get(name, []), \
            f"{name} not emitted by the engine — batched-verify/tp/" \
            "quantized-kv telemetry went dark"
    # the quantized-weights family: the bytes-per-param capacity gauge
    # and the scale-provenance gauge are engine-emitted (and double as
    # the tier's liveness signal — unquantized engines emit neither)
    for name in ("serving.wq.bytes_per_param",
                 "serving.wq.quant_scale_absmax"):
        assert engine_py in emitted.get(name, []), \
            f"{name} not emitted by the engine — quantized-weights " \
            "telemetry went dark"
    # the async-heartbeat family: the host-think/device-wait split and
    # the speculated-finality rollback counter are scheduler-emitted
    for name in ("serving.heartbeat.host_s",
                 "serving.heartbeat.device_wait_s",
                 "serving.heartbeat.duty_cycle",
                 "serving.heartbeat.discarded"):
        assert sched in emitted.get(name, []), \
            f"{name} not emitted by the scheduler — async-heartbeat " \
            "telemetry went dark"
    # the hierarchical-KV family: swap traffic, the host-arena
    # capacity gauge, the hit-after-swap payoff counter and the
    # verified-miss degradation counter are all engine-emitted
    for name in ("serving.swap.swapped_out_pages",
                 "serving.swap.swapped_in_pages",
                 "serving.swap.host_bytes",
                 "serving.swap.hit_after_swap",
                 "serving.swap.verify_failed",
                 "serving.swap.host_evictions",
                 "serving.swap.out_s", "serving.swap.in_s",
                 # the async swap-out's own family: the admission-path
                 # stall histogram (the bench's sync-vs-async claim),
                 # the in-flight-hit join counter and the worker-queue
                 # depth gauge — any of these going dark hides whether
                 # the async tier is actually off the hot path
                 "serving.swap.admit_stall_s",
                 "serving.swap.swap_join_waits",
                 "serving.swap.swap_out_queue_depth"):
        assert engine_py in emitted.get(name, []), \
            f"{name} not emitted by the engine — hierarchical-KV " \
            "telemetry went dark"
    # the replica-router family: routing outcomes, death containment
    # and the per-replica gauge namespace are router-emitted
    router_py = os.path.join("apex_tpu", "serving", "router.py")
    for name in ("serving.router.routed", "serving.router.affinity_hits",
                 "serving.router.spills",
                 "serving.router.replica_deaths",
                 "serving.router.requeued",
                 "serving.router.replicas_alive",
                 "serving.router.replica"):
        assert router_py in emitted.get(name, []), \
            f"{name} not emitted by the router — replica-routing " \
            "telemetry went dark"
    # the disaggregated-serving family: each metric from the layer
    # that owns it — export count + verified-miss re-prefills
    # (scheduler), export bytes (engine), decode-beat isolation
    # (router)
    for name, owner in (("serving.disagg.handoffs", sched),
                        ("serving.disagg.handoff_bytes", engine_py),
                        ("serving.disagg.reprefills", sched),
                        ("serving.disagg.decode_isolation", router_py)):
        assert owner in emitted.get(name, []), \
            f"{name} not emitted by {os.path.basename(owner)} — " \
            "disaggregated-serving telemetry went dark"
    # the process-fleet family: routing outcomes mirror the router's
    # (same dashboard shape, one process per replica), plus the
    # health-detector and restart instrumentation that only exist
    # out-of-process — heartbeat latency, missed-beat hang
    # declarations, and the rolling-restart duration histogram
    fleet_py = os.path.join("apex_tpu", "serving", "fleet.py")
    for name in ("serving.fleet.routed", "serving.fleet.affinity_hits",
                 "serving.fleet.spills", "serving.fleet.worker_deaths",
                 "serving.fleet.requeued", "serving.fleet.restarts",
                 "serving.fleet.hangs_detected",
                 "serving.fleet.workers_alive",
                 "serving.fleet.heartbeat_s",
                 "serving.fleet.restart_s"):
        assert fleet_py in emitted.get(name, []), \
            f"{name} not emitted by the fleet controller — " \
            "process-fleet telemetry went dark"
    # the SLO/preemption family: preempt/resume churn counters and the
    # per-class namespaces (f-string families — the literal the regex
    # extracts from f"serving.slo.class.{cls}.ttft_s" is
    # "serving.slo.class", the namespacing contract the docs name) are
    # all scheduler-emitted — any going dark hides overload shaping
    for name in ("serving.preempt.preemptions",
                 "serving.preempt.resumes",
                 "serving.preempt.resume_reprefills",
                 "serving.slo.deadline_missed",
                 "serving.slo.deadline_rejected",
                 "serving.slo.class", "serving.slo.tenant"):
        assert sched in emitted.get(name, []), \
            f"{name} not emitted by the scheduler — SLO/preemption " \
            "telemetry went dark"
    # the multi-tenant LoRA family: arena churn counters (load-from-
    # host, warm-row hits, LRU evictions) and the residency gauges —
    # all emitted by the host-store/arena layer itself; any going dark
    # makes a thousand-adapter fleet indistinguishable from a base-only
    # one, and ``loads`` vs ``hits`` is the affinity routing claim's
    # entire measurement basis
    lora_py = os.path.join("apex_tpu", "serving", "lora.py")
    for name in ("serving.lora.loads", "serving.lora.hits",
                 "serving.lora.evictions",
                 "serving.lora.arena_bytes",
                 "serving.lora.active_adapters"):
        assert lora_py in emitted.get(name, []), \
            f"{name} not emitted by the LoRA tier — multi-tenant " \
            "adapter telemetry went dark"
    assert _documented(), "docs/serving.md names no fault/watchdog/" \
        "spec metrics — doc section missing?"


def test_every_emitted_fault_metric_is_documented():
    emitted = _emitted()
    documented = _documented()
    missing = {k: v for k, v in emitted.items() if k not in documented}
    assert not missing, (
        f"fault/watchdog metrics emitted in code but absent from "
        f"docs/serving.md (document them in the fault-tolerance "
        f"section): {missing}")


def test_every_documented_fault_metric_is_emitted():
    emitted = set(_emitted())
    stale = _documented() - emitted
    assert not stale, (
        f"docs/serving.md documents fault/watchdog metrics no serving "
        f"code emits (stale doc rows — delete them or wire the "
        f"emitter): {stale}")


# ------------------------------------------------- the force-early lint
# Functions that make up the dispatch-ahead regions, per file: between
# issuing a decode step and reconciling it (scheduler), and between
# dispatching a swap-out gather and the worker's deferred force
# (engine), the host must never block on a device value. These are
# checked by NAME so a rename breaks the lint loudly instead of
# silently un-scoping it.
_DISPATCH_REGION = {
    "scheduler.py": ("_dispatch_decode", "_pipeline_last_tokens",
                     "_dispatch_prefill"),
    "engine.py": ("_dispatch_swap_out", "prefill_chunk_dispatch"),
}

# Call shapes that force a device array to host. ``jnp.*`` stays legal
# (device-side ops); ``np.zeros``/``np.flatnonzero`` over host state
# stay legal (no device operand can reach them in these functions,
# which hold only host bookkeeping + PendingDecode handles).
_FORCING_NAMES = {"int", "float", "bool"}
_FORCING_ATTRS = {("np", "asarray"), ("np", "array"),
                  ("numpy", "asarray"), ("numpy", "array"),
                  ("jax", "device_get"), ("jax", "block_until_ready")}


def _forcing_calls(fn_node):
    bad = []
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in _FORCING_NAMES:
            bad.append((f.id, node.lineno))
        elif (isinstance(f, ast.Attribute)
              and isinstance(f.value, ast.Name)
              and (f.value.id, f.attr) in _FORCING_ATTRS):
            bad.append((f"{f.value.id}.{f.attr}", node.lineno))
    return bad


def test_dispatch_ahead_region_never_forces_to_host():
    """No code path between a dispatch and its reconcile/completion
    may call ``int()`` / ``float()`` / ``np.asarray`` /
    ``jax.device_get`` on anything: a forced read there stalls the
    host on in-flight device work and silently degrades the async
    path to its synchronous shape (pipeline_depth>=1 to the sync
    beat; the async swap-out to the admission stall) — tokens
    identical, overlap gone, no parity test can catch it."""
    for fname, region in _DISPATCH_REGION.items():
        path = os.path.join(SRC_DIR, fname)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        found = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and node.name in region:
                found[node.name] = _forcing_calls(node)
        missing = set(region) - set(found)
        assert not missing, (
            f"dispatch-ahead functions {sorted(missing)} not found in "
            f"{fname} — renamed? update _DISPATCH_REGION so the "
            "force-early lint keeps covering the region")
        offenders = {name: calls for name, calls in found.items()
                     if calls}
        assert not offenders, (
            f"host-forcing calls inside {fname}'s dispatch-ahead "
            f"region (function -> [(call, line)]): {offenders} — "
            "these block the host on in-flight device work, the exact "
            "stall the async refactors exist to remove. Move the read "
            "to the reconcile/complete half (_reconcile_oldest / "
            "_complete_swap_out — the batched readback sites).")


# ---------------------------------------------- the eager-gather shape lint
# Fancy-index gathers over the device KV pool arrays that are ALLOWED
# because their index operand is padded to a fixed bound (max_pages,
# page-0 sentinel absorbing the padding) so one compiled shape serves
# every entry size: the compiled swap-out gather's two pool reads
# (its page_ids operand is always a padded [max_pages] array — see
# Engine._dispatch_swap_out). Keyed (file, function, gathered-array)
# so a refactor that moves or renames a site re-reviews its padding
# deliberately.
_PADDED_GATHERS_ALLOWED = {
    ("engine.py", "_swap_out_impl", "cache.k"),
    ("engine.py", "_swap_out_impl", "cache.v"),
}


def _attr_chain(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        parts.append("<expr>")
    return ".".join(reversed(parts))


def _is_fancy_index(idx):
    """True when any element of the subscript is a VARIABLE index
    (Name/List/expression) rather than a slice or constant — the shape
    of a gather whose compiled shape follows the index length. Slices
    with variable bounds stay legal (their shapes are per-engine
    constants like ``[:slots]``, not per-call data)."""
    elts = idx.elts if isinstance(idx, ast.Tuple) else [idx]
    for e in elts:
        if isinstance(e, (ast.Slice, ast.Constant)):
            continue
        if isinstance(e, ast.UnaryOp) \
                and isinstance(e.operand, ast.Constant):
            continue
        return True
    return False


def _pool_gather_sites():
    """Every fancy-index READ of a pool array (attribute chain ending
    in ``.k`` / ``.v`` — the device K/V pools; ``.at[...]`` functional
    updates are excluded, they live inside compiled bodies with
    fixed-shape operands) under apex_tpu/serving/, attributed to its
    INNERMOST enclosing function."""
    sites = set()
    for path in glob.glob(os.path.join(SRC_DIR, "**", "*.py"),
                          recursive=True):
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        funcs = [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))]
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)):
                continue
            chain = _attr_chain(node.value)
            if not chain.split(".")[-1] in ("k", "v"):
                continue
            if not _is_fancy_index(node.slice):
                continue
            enclosing = [fn for fn in funcs
                         if fn.lineno <= node.lineno
                         <= (fn.end_lineno or fn.lineno)]
            fname = max(enclosing, key=lambda fn: fn.lineno).name \
                if enclosing else "<module>"
            sites.add((os.path.basename(path), fname, chain))
    return sites


def test_pool_gathers_are_exactly_the_padded_allowlist():
    """Every fancy-index gather over the device pool arrays must be an
    allowlisted PADDED site: an unpadded one compiles a new executable
    per index length — the ~165 ms per-shape mid-serve recompile trap
    (PR 13) that no parity test can see. Set EQUALITY both directions:
    a new gather fails until it pads its index to a fixed bound and
    joins the allowlist deliberately, and a removed/renamed allowlist
    entry fails so the lint never rots into scanning nothing."""
    sites = _pool_gather_sites()
    new = sites - _PADDED_GATHERS_ALLOWED
    assert not new, (
        f"unreviewed fancy-index gathers over the device KV pool: "
        f"{sorted(new)} — an index list whose length is data-dependent "
        "recompiles a fresh executable per length mid-serve (~165 ms "
        "each, PR 13). Pad the index to a fixed bound (page-0 sentinel "
        "absorbs padding) and add the site to "
        "_PADDED_GATHERS_ALLOWED with the padding in place.")
    stale = _PADDED_GATHERS_ALLOWED - sites
    assert not stale, (
        f"allowlisted pool-gather sites no longer found (moved or "
        f"renamed — re-review their padding and update the "
        f"allowlist): {sorted(stale)}")


# ---------------------------------------------------- the span-name lint
# The tracer's three recording methods. Any call of the shape
# ``<anything>.event(...)`` / ``.event_current(...)`` / ``.end_trace(...)``
# under apex_tpu/serving/ is a span emit site; the span name is the
# call's first string-literal positional argument (``event`` and
# ``end_trace`` take the trace id first, but a trace id is never a
# string literal — it's ``request.uid`` — so "first str literal" is
# position-agnostic across all three signatures).
_SPAN_METHODS = {"event", "event_current", "end_trace"}
TRACING_PY = os.path.join(ROOT, "apex_tpu", "telemetry", "tracing.py")


def _spans_emitted():
    """Every span-name literal passed to a tracer recording method
    under apex_tpu/serving/, mapped to the files that emit it."""
    refs = {}
    for path in glob.glob(os.path.join(SRC_DIR, "**", "*.py"),
                          recursive=True):
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _SPAN_METHODS):
                continue
            lits = [a.value for a in node.args
                    if isinstance(a, ast.Constant)
                    and isinstance(a.value, str)]
            if lits:
                refs.setdefault(lits[0], []).append(
                    os.path.relpath(path, ROOT))
    return refs


def _spans_documented():
    """The backticked first column of every row of the
    ``### Span taxonomy`` table in docs/serving.md."""
    names = set()
    in_section = False
    with open(DOC) as f:
        for line in f:
            if line.startswith("#"):
                in_section = line.strip() == "### Span taxonomy"
                continue
            if in_section and line.startswith("| `"):
                names.add(line.split("`")[1])
    return names


def test_span_scan_surface_is_alive():
    """The span lint must be looking at real emit sites and a real doc
    table — and the tentpole's headline spans must come from the
    layers that own them (terminal trio + quarantine from the
    scheduler, routing from the router, the swap pair from the engine,
    the draft span from the scheduler's worker closure)."""
    emitted = _spans_emitted()
    assert emitted, "no tracer recording calls found under " \
        "apex_tpu/serving — span scan broken?"
    sched = os.path.join("apex_tpu", "serving", "scheduler.py")
    for name in ("submit", "queue_wait", "admit", "prefill_chunk",
                 "heartbeat", "draft", "verify", "quarantine",
                 "finish", "expired", "failed",
                 # the disaggregated handoff pair: export at prompt-
                 # ingestion completion, import resolution at admission
                 "handoff_export", "handoff_import",
                 # the SLO pair: committed-state export at preemption,
                 # warm (or verified-cold) re-attach at re-admission
                 "preempt", "resume"):
        assert sched in emitted.get(name, []), \
            f"span {name!r} not emitted by the scheduler — request " \
            "lifecycle tracing went dark"
    assert os.path.join("apex_tpu", "serving", "router.py") \
        in emitted.get("route", [])
    engine_py = os.path.join("apex_tpu", "serving", "engine.py")
    for name in ("swap_out", "swap_out_store", "swap_in"):
        assert engine_py in emitted.get(name, []), \
            f"span {name!r} not emitted by the engine — migration " \
            "tracing went dark"
    assert _spans_documented(), "docs/serving.md has no " \
        "'### Span taxonomy' table — doc section missing/renamed?"


def test_every_emitted_span_is_documented():
    emitted = _spans_emitted()
    documented = _spans_documented()
    missing = {k: v for k, v in emitted.items() if k not in documented}
    assert not missing, (
        f"spans emitted in code but absent from docs/serving.md's "
        f"span-taxonomy table (add a row): {missing}")


def test_every_documented_span_is_emitted():
    emitted = set(_spans_emitted())
    stale = _spans_documented() - emitted
    assert not stale, (
        f"docs/serving.md's span-taxonomy table names spans no "
        f"serving code emits (stale rows — delete them or wire the "
        f"emitter): {stale}")


# ------------------------------------------------ the tracer force-lint
# The tracer's hot recording methods execute inside the serving hooks —
# including the dispatch-ahead regions' dynamic extent (the heartbeat
# span lands between a decode dispatch and its reconcile; the swap_out
# span inside _dispatch_swap_out itself) — so they inherit the regions'
# contract: never force a device value to host. Annotation values are
# stored as passed (Python floats/ints from host bookkeeping); the
# exporters (export_chrome_trace / export_jsonl) normalize with int()
# at export time, offline, and are deliberately NOT in this list.
_TRACER_HOT = ("now", "begin", "event", "event_current", "end_trace",
               "current")


def test_tracer_recording_methods_never_force_to_host():
    """Every definition of a hot tracer recording method (Tracer AND
    its _BoundTracer replica view both define them) must be free of
    host-forcing calls — a single ``int()``/``np.asarray`` there would
    stall every traced heartbeat on in-flight device work, silently
    un-asyncing the PR 11/15 paths for traced runs only (the exact
    divergence-under-observation a tracer must never introduce)."""
    with open(TRACING_PY) as f:
        tree = ast.parse(f.read(), filename=TRACING_PY)
    found = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in _TRACER_HOT:
            found.setdefault(node.name, []).extend(_forcing_calls(node))
    missing = set(_TRACER_HOT) - set(found)
    assert not missing, (
        f"hot tracer methods {sorted(missing)} not found in "
        "apex_tpu/telemetry/tracing.py — renamed? update _TRACER_HOT "
        "so the force lint keeps covering the recording path")
    offenders = {name: calls for name, calls in found.items() if calls}
    assert not offenders, (
        f"host-forcing calls inside hot tracer recording methods "
        f"(method -> [(call, line)]): {offenders} — these run inside "
        "the dispatch-ahead regions' dynamic extent; move any "
        "normalization to the exporters (offline).")
