"""apex_C flatten/unflatten parity (csrc/flatten_unflatten.cpp)."""

import jax.numpy as jnp
import numpy as np

from apex_tpu.utils import flatten, unflatten, flatten_tree, unflatten_tree


def test_flatten_roundtrip():
    ts = [jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
          jnp.ones((4,), jnp.float32) * 7,
          jnp.zeros((1, 1, 2), jnp.float32)]
    flat = flatten(ts)
    assert flat.shape == (12,)
    back = unflatten(flat, ts)
    for a, b in zip(ts, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_flatten_empty():
    assert flatten([]).shape == (0,)


def test_tree_roundtrip_mixed_dtypes():
    tree = {"a": jnp.ones((2, 2), jnp.bfloat16),
            "b": {"c": jnp.arange(3, dtype=jnp.float32)}}
    flat, spec = flatten_tree(tree)
    back = unflatten_tree(flat, spec)
    assert back["a"].dtype == jnp.bfloat16
    assert back["b"]["c"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(back["b"]["c"]),
                               np.asarray(tree["b"]["c"]))
