"""fp16_utils tier tests — mirrors apex tests/L0 coverage of the legacy API.

Oracle strategy per SURVEY §5.1: fused/converted paths compared against
composed fp32 references (optax on fp32 params), dtype-dependent tolerances.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.fp16_utils import (
    BN_convert_float,
    DynamicLossScaler,
    FP16_Optimizer,
    LossScaler,
    clip_grad_norm,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    to_python_float,
)


def _params():
    return {
        "dense": {"kernel": jnp.ones((4, 3), jnp.float32),
                  "bias": jnp.zeros((3,), jnp.float32)},
        "bn": {"scale": jnp.ones((3,), jnp.float32),
               "bias": jnp.zeros((3,), jnp.float32)},
    }


class TestConversion:
    def test_network_to_half_keeps_bn_fp32(self):
        half = network_to_half(_params())
        assert half["dense"]["kernel"].dtype == jnp.bfloat16
        assert half["bn"]["scale"].dtype == jnp.float32

    def test_network_to_half_fp16(self):
        half = network_to_half(_params(), dtype=jnp.float16)
        assert half["dense"]["kernel"].dtype == jnp.float16

    def test_bn_convert_float(self):
        all_half = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), _params())
        fixed = BN_convert_float(all_half)
        assert fixed["bn"]["scale"].dtype == jnp.float32
        assert fixed["dense"]["kernel"].dtype == jnp.bfloat16

    def test_prep_param_lists(self):
        model, master = prep_param_lists(network_to_half(_params()))
        assert master["dense"]["kernel"].dtype == jnp.float32
        np.testing.assert_allclose(
            np.asarray(master["dense"]["kernel"]),
            np.asarray(model["dense"]["kernel"], np.float32))

    def test_prep_param_lists_flat_master(self):
        model, (flat, spec) = prep_param_lists(_params(), flat_master=True)
        assert flat.ndim == 1 and flat.dtype == jnp.float32
        assert flat.size == sum(p.size for p in
                                jax.tree_util.tree_leaves(_params()))

    def test_grad_copies_roundtrip(self):
        model = network_to_half(_params())
        grads = jax.tree_util.tree_map(
            lambda p: jnp.full_like(p, 0.5), model)
        master_g = model_grads_to_master_grads(grads)
        assert master_g["dense"]["kernel"].dtype == jnp.float32
        back = master_params_to_model_params(master_g, model)
        assert back["dense"]["kernel"].dtype == jnp.bfloat16

    def test_to_python_float(self):
        assert to_python_float(jnp.float32(3.5)) == 3.5

    def test_clip_grad_norm(self):
        grads = {"a": jnp.full((10,), 3.0), "b": jnp.full((10,), 4.0)}
        clipped, total = clip_grad_norm(grads, max_norm=1.0)
        np.testing.assert_allclose(float(total), np.sqrt(90 + 160), rtol=1e-6)
        new_total = float(jnp.sqrt(sum(
            jnp.sum(g ** 2) for g in jax.tree_util.tree_leaves(clipped))))
        np.testing.assert_allclose(new_total, 1.0, rtol=1e-4)


class TestLegacyScalers:
    def test_static_never_overflows(self):
        s = LossScaler(128.0)
        assert s.loss_scale == 128.0
        assert not s.has_overflow({"g": jnp.array([jnp.inf])})
        s.update_scale(True)
        assert s.loss_scale == 128.0

    def test_dynamic_halves_on_overflow(self):
        s = DynamicLossScaler(init_scale=2.0 ** 15)
        assert s.has_overflow({"g": jnp.array([jnp.nan, 1.0])})
        s.update_scale(True)
        assert s.loss_scale == 2.0 ** 14

    def test_dynamic_grows_after_window(self):
        s = DynamicLossScaler(init_scale=4.0, scale_window=10)
        s.update_scale(True)  # → 2.0, iter 0 overflowed
        for _ in range(10):
            s.update_scale(False)
        assert s.loss_scale == 4.0


class TestFP16Optimizer:
    def _loss_fn(self, params, x):
        y = x @ params["w"] + params["b"]
        return jnp.sum(y ** 2)

    def test_matches_fp32_sgd(self):
        """FP16_Optimizer on bf16 params tracks plain fp32 SGD (the apex L1
        convergence-parity bar, scaled down)."""
        key = jax.random.PRNGKey(0)
        w = jax.random.normal(key, (8, 4), jnp.float32) * 0.1
        ref = {"w": w, "b": jnp.zeros((4,))}
        model = network_to_half(ref, keep_fp32=None)
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 8))

        opt = FP16_Optimizer(optax.sgd(1e-2), model,
                             static_loss_scale=128.0)
        ref_opt = optax.sgd(1e-2)
        ref_state = ref_opt.init(ref)

        for _ in range(5):
            grads = jax.grad(
                lambda p: opt.scale_loss(
                    self._loss_fn(jax.tree_util.tree_map(
                        lambda t: t.astype(jnp.float32), p), x)))(model)
            model = opt.step(grads, model)

            ref_grads = jax.grad(lambda p: self._loss_fn(p, x))(ref)
            updates, ref_state = ref_opt.update(ref_grads, ref_state, ref)
            ref = optax.apply_updates(ref, updates)

        np.testing.assert_allclose(
            np.asarray(opt.fp32_params["w"]), np.asarray(ref["w"]),
            atol=2e-2)  # bf16 grad quantization

    def test_overflow_skips_step(self):
        params = {"w": jnp.ones((2, 2), jnp.float16)}
        opt = FP16_Optimizer(optax.sgd(0.1), params,
                             dynamic_loss_scale=True,
                             dynamic_loss_args={"init_scale": 2.0 ** 10})
        before = np.asarray(opt.fp32_params["w"]).copy()
        bad = {"w": jnp.full((2, 2), jnp.inf, jnp.float16)}
        out = opt.step(bad, params)
        assert opt.overflow
        assert opt.loss_scale == 2.0 ** 9
        np.testing.assert_array_equal(np.asarray(opt.fp32_params["w"]), before)
        np.testing.assert_array_equal(np.asarray(out["w"]),
                                      np.asarray(params["w"]))

    def test_state_dict_roundtrip(self):
        params = {"w": jnp.ones((2,), jnp.bfloat16)}
        opt = FP16_Optimizer(optax.sgd(0.1), params, dynamic_loss_scale=True)
        opt.step({"w": jnp.full((2,), jnp.inf, jnp.bfloat16)}, params)
        sd = opt.state_dict()
        opt2 = FP16_Optimizer(optax.sgd(0.1), params, dynamic_loss_scale=True)
        opt2.load_state_dict(sd)
        assert opt2.loss_scale == opt.loss_scale
        assert opt2.overflow == opt.overflow
