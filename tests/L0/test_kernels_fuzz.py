"""Property-based fuzzing of the Pallas kernel tier (interpret/CPU paths).

The reference's L0 tests fix a handful of shapes; these close the gap on
odd shapes, extreme values, and dtype combos. Oracles are pure jnp fp32
compositions (SURVEY §5.1: reference-implementation oracles, never golden
files)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("hypothesis")   # optional dev dep: skip, don't error
from hypothesis import given, settings, strategies as st  # noqa: E402

from apex_tpu.kernels.layer_norm import (layer_norm, layer_norm_reference,
                                         rms_norm, rms_norm_reference)
from apex_tpu.kernels.multi_tensor import (fused_axpby, fused_l2norm,
                                           fused_scale)

_SETTINGS = dict(max_examples=25, deadline=None)


@st.composite
def flat_arrays(draw, max_len=4096):
    n = draw(st.integers(1, max_len))
    seed = draw(st.integers(0, 2**31 - 1))
    scale = draw(st.sampled_from([1e-3, 1.0, 1e3]))
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(n) * scale, jnp.float32)


@given(flat_arrays(), st.floats(-4.0, 4.0))
@settings(**_SETTINGS)
def test_fused_scale_matches_numpy(x, s):
    out, flag = fused_scale(x, jnp.asarray(s, jnp.float32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * np.float32(s),
                               rtol=1e-6, atol=1e-6)
    assert int(flag) == 0


@given(flat_arrays(max_len=2048), st.floats(-2.0, 2.0), st.floats(-2.0, 2.0))
@settings(**_SETTINGS)
def test_fused_axpby_matches_numpy(x, a, b):
    y = x[::-1].copy()
    out, flag = fused_axpby(x, y, jnp.asarray(a, jnp.float32),
                            jnp.asarray(b, jnp.float32))
    ref = np.float32(a) * np.asarray(x) + np.float32(b) * np.asarray(y)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)
    assert int(flag) == 0


@given(flat_arrays(max_len=2048))
@settings(**_SETTINGS)
def test_fused_l2norm_matches_numpy(x):
    out = fused_l2norm(x)
    ref = np.linalg.norm(np.asarray(x, np.float64))
    np.testing.assert_allclose(float(out), ref, rtol=1e-4, atol=1e-6)


@given(flat_arrays(max_len=512))
@settings(**_SETTINGS)
def test_fused_scale_flags_nonfinite(x):
    """Any inf/nan anywhere in the buffer must raise the found_inf flag
    (amp_C overflow-check semantics)."""
    bad = x.at[len(x) // 2].set(jnp.inf)
    _, flag = fused_scale(bad, jnp.asarray(1.0, jnp.float32))
    assert int(flag) == 1
    bad = x.at[0].set(jnp.nan)
    _, flag = fused_scale(bad, jnp.asarray(1.0, jnp.float32))
    assert int(flag) == 1


@st.composite
def ln_inputs(draw):
    rows = draw(st.integers(1, 12))
    hidden = draw(st.sampled_from([1, 7, 64, 128, 513, 1024]))
    seed = draw(st.integers(0, 2**31 - 1))
    rng = np.random.RandomState(seed)
    x = rng.randn(rows, hidden).astype(np.float32)
    w = (1.0 + 0.1 * rng.randn(hidden)).astype(np.float32)
    b = (0.1 * rng.randn(hidden)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)


@given(ln_inputs())
@settings(**_SETTINGS)
def test_layer_norm_fuzz(args):
    x, w, b = args
    out = layer_norm(x, w, b)
    ref = layer_norm_reference(x, w, b)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # grads stay finite and match the autodiff of the reference
    g1 = jax.grad(lambda x: layer_norm(x, w, b).sum())(x)
    g2 = jax.grad(lambda x: layer_norm_reference(x, w, b).sum())(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-3, atol=1e-4)


@given(ln_inputs())
@settings(**_SETTINGS)
def test_rms_norm_fuzz(args):
    x, w, _ = args
    out = rms_norm(x, w)
    ref = rms_norm_reference(x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ------------------------------------------------------------- xentropy
from apex_tpu.kernels.xentropy import (softmax_cross_entropy_loss,
                                       xent_reference)


@st.composite
def xent_inputs(draw):
    n = draw(st.sampled_from([1, 3, 8, 16, 128]))
    v = draw(st.sampled_from([2, 10, 128, 513, 1024]))
    seed = draw(st.integers(0, 2**31 - 1))
    smoothing = draw(st.sampled_from([0.0, 0.1]))
    scale = draw(st.sampled_from([1.0, 10.0]))
    rng = np.random.RandomState(seed)
    logits = jnp.asarray(rng.randn(n, v) * scale, jnp.float32)
    labels = jnp.asarray(rng.randint(0, v, size=n), jnp.int32)
    return logits, labels, smoothing


@given(xent_inputs())
@settings(**_SETTINGS)
def test_xentropy_fuzz(args):
    logits, labels, smoothing = args
    loss = softmax_cross_entropy_loss(logits, labels, smoothing)
    ref = xent_reference(logits, labels, smoothing)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # grads vs autodiff of the reference
    g1 = jax.grad(lambda lg: softmax_cross_entropy_loss(
        lg, labels, smoothing).sum())(logits)
    g2 = jax.grad(lambda lg: xent_reference(
        lg, labels, smoothing).sum())(logits)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-5)


@st.composite
def causal_shapes(draw):
    n = draw(st.integers(1, 3))
    sq = draw(st.sampled_from([8, 16, 24, 128]))
    sk = draw(st.sampled_from([128, 256]))
    seed = draw(st.integers(0, 2**31 - 1))
    scale = draw(st.sampled_from([0.1, 1.0, 4.0]))
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(n, sq, sk) * scale, jnp.float32), \
        draw(st.sampled_from([0.125, 1.0]))


@given(causal_shapes())
@settings(**_SETTINGS)
def test_causal_softmax_fuzz(args):
    from apex_tpu.kernels.causal_softmax import (causal_softmax,
                                                 causal_softmax_reference)

    x, scale = args
    out = causal_softmax(x, scale, interpret=True)
    ref = causal_softmax_reference(x, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    gk = jax.grad(lambda x: jnp.sum(jnp.sin(
        causal_softmax(x, scale, interpret=True) * 2.0)))(x)
    gr = jax.grad(lambda x: jnp.sum(jnp.sin(
        causal_softmax_reference(x, scale) * 2.0)))(x)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                               rtol=1e-4, atol=1e-5)


@st.composite
def masked_shapes(draw):
    sq = draw(st.sampled_from([8, 16, 24, 128]))
    sk = draw(st.sampled_from([128, 256]))
    # broadcast patterns the kernel folds into its index map: full lead
    # dims, a [b, 1] head broadcast, and no lead dims at all
    layout = draw(st.sampled_from(["full", "head_bcast", "bare"]))
    b = draw(st.integers(1, 3))
    h = draw(st.integers(1, 3))
    seed = draw(st.integers(0, 2**31 - 1))
    rng = np.random.RandomState(seed)
    if layout == "bare":
        xshape, mshape = (sq, sk), (sq, sk)
    elif layout == "full":
        xshape, mshape = (b, h, sq, sk), (b, h, sq, sk)
    else:
        xshape, mshape = (b, h, sq, sk), (b, 1, sq, sk)
    x = jnp.asarray(rng.randn(*xshape) * 2.0, jnp.float32)
    m = rng.rand(*mshape) < draw(st.sampled_from([0.0, 0.3, 0.7]))
    m[..., 0] = False       # never a fully-masked row (reference padding)
    return x, jnp.asarray(m), draw(st.sampled_from([0.125, 1.0]))


@given(masked_shapes())
@settings(**_SETTINGS)
def test_masked_softmax_fuzz(args):
    from apex_tpu.kernels.masked_softmax import (masked_softmax,
                                                 masked_softmax_reference)

    x, m, scale = args
    out = masked_softmax(x, m, scale, interpret=True)
    ref = masked_softmax_reference(x, m, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    gk = jax.grad(lambda x: jnp.sum(jnp.sin(
        masked_softmax(x, m, scale, interpret=True) * 2.0)))(x)
    gr = jax.grad(lambda x: jnp.sum(jnp.sin(
        masked_softmax_reference(x, m, scale) * 2.0)))(x)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                               rtol=1e-4, atol=1e-5)


@st.composite
def gn_inputs(draw):
    n = draw(st.integers(1, 2))
    s = draw(st.sampled_from([7, 16, 33]))
    c = draw(st.sampled_from([128, 256]))
    groups = draw(st.sampled_from([1, 8, c]))
    shift = draw(st.sampled_from([0.0, 100.0]))
    seed = draw(st.integers(0, 2**31 - 1))
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(n, s, c) + shift, jnp.float32)
    g = jnp.asarray(rng.randn(c) + 1.0, jnp.float32)
    b = jnp.asarray(rng.randn(c), jnp.float32)
    return x, groups, g, b


@given(gn_inputs(), st.sampled_from([None, "silu"]))
@settings(**_SETTINGS)
def test_group_norm_fuzz(args, act):
    from apex_tpu.kernels.group_norm import (group_norm_nhwc,
                                             group_norm_reference)

    x, groups, g, b = args
    out = group_norm_nhwc(x, groups, g, b, act=act, interpret=True)
    ref = group_norm_reference(x, groups, g, b, act=act)
    # same large-mean (shift=100) fp32 cancellation note as the grads
    # below: xhat loses ~mean/std of precision in BOTH paths, so the
    # forward needs the same cancellation headroom (a hypothesis draw
    # found 2.4e-4 on one element); structural errors are O(1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=5e-4)
    gk = jax.grad(lambda x, g, b: jnp.sum(jnp.sin(
        group_norm_nhwc(x, groups, g, b, act=act, interpret=True))),
        argnums=(0, 1, 2))(x, g, b)
    gr = jax.grad(lambda x, g, b: jnp.sum(jnp.sin(
        group_norm_reference(x, groups, g, b, act=act))),
        argnums=(0, 1, 2))(x, g, b)
    # large-mean draws (shift=100) amplify fp32 cancellation in BOTH
    # paths' xhat by ~mean/std; tolerance covers that while still
    # catching structural (wrong-slot) errors, which are O(1)
    for a, r in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-3, atol=3e-3)
