"""LARC tests (reference: apex/parallel/LARC.py — class LARC.step).

Oracle: a literal numpy transcription of apex's step loop — per tensor,
adaptive_lr = trust * ||p|| / (||g|| + wd*||p|| + eps); clip mode scales the
grad by min(adaptive_lr/lr, 1); grads get wd*p folded in; zero-norm params
are skipped."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.parallel.LARC import LARC, larc, larc_transform


def _oracle_scaled_grads(params, grads, lr, trust, clip, eps, wd):
    out = {}
    for k in params:
        p, g = np.asarray(params[k], np.float64), np.asarray(grads[k],
                                                             np.float64)
        pn, gn = np.linalg.norm(p), np.linalg.norm(g)
        if pn != 0 and gn != 0:
            adaptive = trust * pn / (gn + wd * pn + eps)
            scale = min(adaptive / lr, 1.0) if clip else adaptive
            out[k] = (g + wd * p) * scale
        else:
            out[k] = g + wd * p
    return out


@pytest.mark.parametrize("clip", [True, False])
@pytest.mark.parametrize("wd", [0.0, 1e-2])
def test_larc_transform_matches_apex_formula(clip, wd):
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(16, 8), jnp.float32),
              "b": jnp.asarray(rng.randn(8) * 1e-3, jnp.float32),
              "z": jnp.zeros((4,), jnp.float32)}          # zero-norm: skipped
    grads = {"w": jnp.asarray(rng.randn(16, 8), jnp.float32),
             "b": jnp.asarray(rng.randn(8), jnp.float32),
             "z": jnp.zeros((4,), jnp.float32)}
    lr, trust, eps = 0.1, 0.02, 1e-8

    tx = larc_transform(lr, trust, clip, eps, wd)
    scaled, _ = tx.update(grads, tx.init(params), params)
    ref = _oracle_scaled_grads(params, grads, lr, trust, clip, eps, wd)
    for k in params:
        np.testing.assert_allclose(np.asarray(scaled[k]), ref[k],
                                   rtol=1e-5, atol=1e-7)


def test_larc_clip_caps_effective_lr():
    """clip=True: effective lr never exceeds the base lr — a huge gradient
    must be scaled DOWN, a tiny gradient must pass through (scale==1)."""
    params = {"w": jnp.ones((8,), jnp.float32)}
    tiny = {"w": jnp.full((8,), 1e-6, jnp.float32)}
    huge = {"w": jnp.full((8,), 1e3, jnp.float32)}
    tx = larc_transform(0.1, 0.02, True, 1e-8, 0.0)
    out_tiny, _ = tx.update(tiny, tx.init(params), params)
    out_huge, _ = tx.update(huge, tx.init(params), params)
    np.testing.assert_allclose(np.asarray(out_tiny["w"]),
                               np.asarray(tiny["w"]), rtol=1e-6)
    assert np.linalg.norm(np.asarray(out_huge["w"])) \
        < np.linalg.norm(np.asarray(huge["w"]))


def test_larc_wrapped_sgd_trains():
    """larc(sgd) must reduce loss on a small quadratic and stay finite."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(64, 4), jnp.float32)
    y = jnp.asarray(rng.randn(64, 2), jnp.float32)
    params = {"w": jnp.zeros((4, 2), jnp.float32)}
    opt = larc(optax.sgd(0.1, momentum=0.9), 0.1, trust_coefficient=0.02)
    state = opt.init(params)

    def loss_fn(p):
        return jnp.mean((x @ p["w"] - y) ** 2)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(loss_fn)(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    losses = []
    for _ in range(80):
        params, state, l = step(params, state)
        losses.append(float(l))
    # LARC's trust coefficient (0.02) throttles the effective lr once the
    # weights grow, so convergence is slower than plain SGD — require steady
    # monotone-ish progress, not a fixed factor
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])
    assert np.isfinite(losses).all()


def test_larc_class_facade():
    """Apex-shaped usage: LARC(FusedSGD(...)) with .step(grads, params)."""
    from apex_tpu.optimizers import FusedSGD
    rng = np.random.RandomState(2)
    params = {"w": jnp.asarray(rng.randn(8, 4), jnp.float32)}
    grads = {"w": jnp.asarray(rng.randn(8, 4), jnp.float32)}
    inner = FusedSGD(params, lr=0.1, momentum=0.9)
    wrapped = LARC(inner, trust_coefficient=0.02)
    new_params = wrapped.step(grads, params)
    assert not np.allclose(np.asarray(new_params["w"]),
                           np.asarray(params["w"]))
    # attribute passthrough (apex: LARC proxies the inner optimizer)
    assert wrapped.lr == 0.1


def test_larc_facade_applies_weight_decay_once():
    """Apex zeroes the inner group's weight_decay around step (the decay is
    folded into the trust-scaled grad): the wrapped step must equal a plain
    wd=0 SGD step on the LARC-scaled gradient, and the inner optimizer's wd
    must be restored afterwards."""
    from apex_tpu.optimizers import FusedSGD
    rng = np.random.RandomState(3)
    lr, wd, trust = 0.1, 0.1, 0.02
    params = {"w": jnp.asarray(rng.randn(8, 4), jnp.float32)}
    grads = {"w": jnp.asarray(rng.randn(8, 4), jnp.float32)}

    inner = FusedSGD(params, lr=lr, weight_decay=wd)
    out = LARC(inner, trust_coefficient=trust).step(grads, params)
    assert inner.weight_decay == wd        # restored

    ref_scaled = _oracle_scaled_grads(params, grads, lr, trust, True, 1e-8,
                                      wd)
    expected = np.asarray(params["w"], np.float64) - lr * ref_scaled["w"]
    np.testing.assert_allclose(np.asarray(out["w"]), expected,
                               rtol=1e-5, atol=1e-6)


def test_param_groups_lr_write_takes_effect():
    """torch idiom: for g in opt.param_groups: g['lr'] = ... must change the
    next step (the facade rebuilds its transform from the live groups)."""
    from apex_tpu.optimizers import FusedSGD
    params = {"w": jnp.ones((4,), jnp.float32)}
    grads = {"w": jnp.ones((4,), jnp.float32)}

    opt = FusedSGD(params, lr=0.1)
    stepped = opt.step(grads, params)
    np.testing.assert_allclose(np.asarray(stepped["w"]), 0.9, rtol=1e-6)

    opt2 = FusedSGD(params, lr=0.1)
    for g in opt2.param_groups:
        g["lr"] = 0.5
    assert opt2.lr == 0.5                  # property reads the live group
    stepped2 = opt2.step(grads, params)
    np.testing.assert_allclose(np.asarray(stepped2["w"]), 0.5, rtol=1e-6)
