"""apex_tpu.serving — KV-cache engine + continuous batching, hermetic.

The acceptance bar from the subsystem's issues (PR 3 + PR 4's chunked
prefill), as tests:

- greedy KV-cache decode is token-exact against the full-recompute
  forward's argmax for >= 64 generated tokens (teacher-forcing form:
  ONE full forward over [prompt + generated] re-derives every step's
  argmax, so both paths are compared through identical programs — the
  shared-program discipline of test_amp_train_step.py, avoiding 64
  separately-fused eager forwards);
- chunked prefill is token-exact (bitwise argmax) against BOTH the
  monolithic-prefill path and full recompute, for prompt lengths
  shorter than / equal to / straddling a chunk boundary;
- a variable-length request stream exercising chunked serving plus the
  monolithic baseline is served by exactly 3 compiled programs (chunk
  prefill + decode step + legacy monolithic prefill), pinned by trace
  counters;
- chunk-prefill steps interleave with the decode heartbeat: an
  in-flight decode gains a token on EVERY tick of a long admit (the
  head-of-line-blocking fix);
- telemetry records tokens/sec, the TTFT decomposition (queue wait +
  prefill chunks), chunks-per-prompt, and slot occupancy.

Everything runs on CPU with a tiny model; the engine's Pallas decode
and chunk-prefill kernels take their interpret/reference paths here
(the Mosaic lowering is the tests/tpu tier's job).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import serving, telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import Engine, KVCache, QueueFull, Request, Scheduler

pytestmark = pytest.mark.serving

VOCAB = 101


def _tiny_lm(max_seq_len=128, **kw):
    return TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                         num_heads=4, max_seq_len=max_seq_len, **kw)


@pytest.fixture(scope="module")
def lm_and_params():
    m = _tiny_lm()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


@pytest.fixture(scope="module")
def fp32_engine(lm_and_params):
    """Exact-fp32 engine (policy O0) shared by the parity/trace tests."""
    m, params = lm_and_params
    return Engine(m, params, slots=3, max_len=128, prefill_len=16,
                  policy=resolve_policy("O0", verbose=False), seed=7)


# ------------------------------------------------------------------ kv cache
def test_kv_cache_create_and_geometry():
    c = KVCache.create(layers=2, slots=4, heads=3, max_len=32, head_dim=8,
                       dtype=jnp.bfloat16)
    assert (c.layers, c.slots, c.heads, c.max_len, c.head_dim) \
        == (2, 4, 3, 32, 8)
    assert c.dtype == jnp.bfloat16
    assert c.nbytes() == 2 * 4 * 3 * 32 * 8 * 2 * 2
    assert c.occupancy() == 0.0 and c.padding_waste() == 1.0


def test_kv_cache_insert_and_advance():
    c = KVCache.create(layers=2, slots=2, heads=1, max_len=8, head_dim=4,
                       dtype=jnp.float32)
    k_new = jnp.ones((2, 1, 1, 4, 4))
    c = c.insert(1, k_new, 2 * k_new, 3)
    assert int(c.lengths[1]) == 3 and int(c.lengths[0]) == 0
    np.testing.assert_array_equal(np.asarray(c.k[:, 1, :, :4]),
                                  np.ones((2, 1, 4, 4)))
    # advance grows only active slots, clamped at max_len
    c = c.advance(c.k, c.v, jnp.asarray([False, True]))
    assert int(c.lengths[1]) == 4 and int(c.lengths[0]) == 0
    assert c.occupancy(active=[False, True]) == 0.5


def test_kv_cache_insert_validates():
    c = KVCache.create(layers=1, slots=1, heads=1, max_len=4, head_dim=4)
    with pytest.raises(ValueError, match="exceeds cache max_len"):
        c.insert(0, jnp.zeros((1, 1, 1, 8, 4)), jnp.zeros((1, 1, 1, 8, 4)),
                 8)
    with pytest.raises(ValueError, match="prefill K/V"):
        c.insert(0, jnp.zeros((1, 2, 1, 4, 4)), jnp.zeros((1, 2, 1, 4, 4)),
                 4)


# ------------------------------------------------------------------ sampling
def test_sample_tokens_greedy_vs_temperature():
    logits = jnp.asarray([[0.0, 5.0, 1.0], [9.0, 0.0, 0.0]])
    key = jax.random.PRNGKey(0)
    greedy = serving.sample_tokens(logits, jnp.zeros(2), key)
    np.testing.assert_array_equal(np.asarray(greedy), [1, 0])
    # temperature sampling is deterministic per key and stays in-vocab
    hot = serving.sample_tokens(logits, jnp.full(2, 2.0), key)
    hot2 = serving.sample_tokens(logits, jnp.full(2, 2.0), key)
    np.testing.assert_array_equal(np.asarray(hot), np.asarray(hot2))
    assert np.all((np.asarray(hot) >= 0) & (np.asarray(hot) < 3))


def test_sample_tokens_top_k_restricts_support():
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0, -1.0]])
    keys = jax.random.split(jax.random.PRNGKey(1), 32)
    got = {int(serving.sample_tokens(logits, jnp.full(1, 5.0), k,
                                     top_k=2)[0]) for k in keys}
    assert got <= {2, 3}            # only the top-2 ids are reachable


# ----------------------------------------------------------- decode parity
def test_greedy_decode_token_exact_vs_full_recompute(fp32_engine,
                                                     lm_and_params):
    """>= 64 greedy tokens from the KV-cache engine == the argmax chain
    of one full-recompute forward over the final sequence (causality
    makes teacher-forcing re-derivation exact for greedy decode).

    The default scheduler now admits through CHUNKED prefill, so this is
    also the PR 4 acceptance pin: the chunked path is token-exact for
    >= 64 generated tokens against full-recompute argmax (the
    chunk-boundary sweep lives in
    test_chunked_prefill_token_exact_vs_monolithic_and_recompute)."""
    m, params = lm_and_params
    eng = fp32_engine
    sched = Scheduler(eng)
    prompt = [3, 17, 91, 42, 8]
    n_gen = 65
    (req,) = sched.run([Request(prompt=prompt, max_new_tokens=n_gen)])
    assert req.finish_reason == "max_new_tokens"
    assert len(req.output_tokens) == n_gen
    seq = jnp.asarray([list(prompt) + req.output_tokens], jnp.int32)
    full = m.apply({"params": params}, seq, train=False)   # [1, S, V]
    want = np.asarray(jnp.argmax(full[0], axis=-1))
    for i, tok in enumerate(req.output_tokens):
        # token i was sampled from the logits at position prompt+i-1
        assert tok == int(want[len(prompt) - 1 + i]), \
            f"divergence at generated token {i}"


# --------------------------------------------------------- chunked prefill
@pytest.fixture(scope="module")
def chunk_engines(lm_and_params):
    """Two identical O0 engines (chunk_len=8) — one serves the chunked
    path, one the monolithic baseline, for output comparisons."""
    m, params = lm_and_params
    mk = lambda: Engine(m, params, slots=3, max_len=128, prefill_len=24,
                        chunk_len=8,
                        policy=resolve_policy("O0", verbose=False),
                        seed=5)
    return mk(), mk()


def _greedy_reqs():
    rng = np.random.default_rng(42)
    # shorter than (5), equal to (8), straddling one (13) and two (21)
    # chunk boundaries at chunk_len=8 (the >= 64-token stream lives in
    # test_greedy_decode_token_exact_vs_full_recompute — same chunked
    # admission path — keeping this sweep fast)
    return [Request(prompt=list(rng.integers(1, VOCAB, size=n)),
                    max_new_tokens=b)
            for n, b in [(5, 12), (8, 4), (13, 4), (21, 4)]]


def test_exactly_three_compiled_programs(chunk_engines):
    """Variable-length, variable-budget, variable-chunk-count request
    stream through the chunked scheduler PLUS the monolithic-baseline
    prefill → exactly one chunk-prefill trace, one decode-step trace and
    one monolithic-prefill trace (the fixed-shape contract: no
    per-token, per-request, per-offset or per-chunk-count recompiles).
    Runs first on the module's shared engine, so the pin covers every
    later test on it too."""
    eng, _ = chunk_engines
    sched = Scheduler(eng)
    rng = np.random.default_rng(0)
    # prompt lengths span 1-3 chunks, including exact chunk multiples
    reqs = [Request(prompt=list(rng.integers(1, VOCAB, size=n)),
                    max_new_tokens=mnt, temperature=t)
            for n, mnt, t in [(1, 3, 0.0), (8, 9, 0.0), (17, 5, 0.7),
                              (24, 12, 0.0), (11, 2, 1.3), (5, 4, 0.0)]]
    done = sched.run(reqs)
    assert len(done) == 6
    assert [r.chunks for r in reqs] == [eng.chunks_for(len(r.prompt))
                                        for r in reqs]
    # the monolithic baseline path still compiles (and only once)
    eng.reset()
    eng.prefill(0, [5, 9, 2])
    eng.prefill(1, list(range(1, 20)))
    assert (eng.chunk_traces, eng.decode_traces, eng.prefill_traces) \
        == (1, 1, 1)
    assert eng.compiled_programs == 3


def test_chunked_prefill_token_exact_vs_monolithic_and_recompute(
        chunk_engines, lm_and_params):
    """The PR 4 acceptance bar: greedy decode after chunked prefill is
    bitwise-argmax identical to the monolithic-prefill path AND to one
    teacher-forcing full recompute, across chunk-boundary prompt
    lengths."""
    m, params = lm_and_params
    eng_c, eng_m = chunk_engines
    eng_c.reset()
    eng_m.reset()
    reqs_c, reqs_m = _greedy_reqs(), _greedy_reqs()
    Scheduler(eng_c, chunked=True).run(reqs_c)
    Scheduler(eng_m, chunked=False).run(reqs_m)
    for rc, rm in zip(reqs_c, reqs_m):
        assert rc.output_tokens == rm.output_tokens, \
            f"chunked vs monolithic diverged (prompt len {len(rc.prompt)})"
        assert rc.chunks == eng_c.chunks_for(len(rc.prompt))
        assert rm.chunks == 1
        # teacher-forcing: one full forward re-derives every greedy step
        seq = jnp.asarray([list(rc.prompt) + rc.output_tokens], jnp.int32)
        full = m.apply({"params": params}, seq, train=False)
        want = np.asarray(jnp.argmax(full[0], axis=-1))
        for i, tok in enumerate(rc.output_tokens):
            assert tok == int(want[len(rc.prompt) - 1 + i]), \
                f"prompt len {len(rc.prompt)}: divergence at token {i}"


def test_chunked_prefill_interleaves_with_decode(chunk_engines):
    """The head-of-line fix, observed at token granularity: while a
    3-chunk prompt ingests (one chunk per heartbeat), the in-flight
    decode gains a token on EVERY tick — the monolithic path would
    stall it for the whole prefill."""
    eng, _ = chunk_engines
    eng.reset()
    sched = Scheduler(eng)
    a = Request(prompt=[3, 1, 4], max_new_tokens=50)
    sched.submit(a)
    sched.step()                      # admit + single final chunk + decode
    assert a.status == "running" and len(a.output_tokens) == 2
    b = Request(prompt=list(range(1, 25)), max_new_tokens=4)  # 3 chunks
    sched.submit(b)
    for tick in range(1, 4):
        n_before = len(a.output_tokens)
        sched.step()
        assert len(a.output_tokens) == n_before + 1, \
            f"decode stalled at tick {tick} during b's prefill"
        assert b.chunks == tick
    # b's final-chunk tick yields its first token AND a decode token —
    # the fresh slot joins the same heartbeat it finished prefilling in
    assert b.status == "running" and len(b.output_tokens) == 2
    assert b.ttft_s is not None and b.chunks == 3
    # the budget caps chunk work per heartbeat at one chunk
    assert eng.chunks_for(len(b.prompt)) == 3


def test_chunked_ttft_decomposition_and_request_records(chunk_engines):
    """serving.queue_wait_s and serving.prefill_chunk_s land as separate
    histograms from serving.ttft_s, and every completion emits a
    serving.request record carrying chunks_per_prompt."""
    reg = telemetry.MetricsRegistry()
    eng, _ = chunk_engines
    eng.reset()
    eng.set_registry(reg)
    sched = Scheduler(eng, registry=reg)
    reqs = [Request(prompt=[1, 2, 3], max_new_tokens=4),
            Request(prompt=list(range(1, 20)), max_new_tokens=3)]
    try:
        sched.run(reqs)
    finally:
        eng.set_registry(None)
    snap = reg.snapshot()
    h = snap["histograms"]
    assert h["serving.queue_wait_s"]["count"] == 2
    assert h["serving.prefill_chunk_s"]["count"] == 1 + 3   # 1 + 3 chunks
    assert h["serving.ttft_s"]["count"] == 2
    assert snap["counters"]["serving.prefill.chunks"] == 4
    for r in reqs:
        assert r.queue_wait_s is not None and r.prefill_s > 0
        assert r.ttft_s >= r.queue_wait_s
    # event-shaped records stay OUT of the histogram layer: no junk
    # per-request reservoirs for uid / duplicated latencies
    assert not any(k.startswith("serving.request.") for k in h)
    recs = [rec for rec in reg.records
            if rec.get("tag") == "serving.request"]
    assert len(recs) == 2
    by_uid = {rec["uid"]: rec for rec in recs}
    assert by_uid[reqs[0].uid]["chunks_per_prompt"] == 1
    assert by_uid[reqs[1].uid]["chunks_per_prompt"] == 3
    for rec in recs:
        assert rec["finish_reason"] == "max_new_tokens"
        assert rec["queue_wait_s"] is not None
        assert rec["ttft_s"] is not None


def test_prefill_chunk_validation(lm_and_params, chunk_engines):
    m, params = lm_and_params
    with pytest.raises(ValueError, match="chunk_len"):
        Engine(m, params, slots=1, max_len=32, prefill_len=8,
               chunk_len=16)
    eng, _ = chunk_engines                     # chunk_len=8, prefill 24
    with pytest.raises(ValueError, match="chunk length"):
        eng.prefill_chunk(0, list(range(1, 10)), 0)
    with pytest.raises(ValueError, match="slot"):
        eng.prefill_chunk(5, [1], 0)
    with pytest.raises(ValueError, match="exceeds prefill_len"):
        eng.prefill_chunk(0, [1, 2, 3, 4], 21)
    with pytest.raises(ValueError, match="prompt length"):
        eng.prefill_chunked(0, list(range(25)))
    with pytest.raises(ValueError, match="chunk_budget"):
        Scheduler(eng, chunk_budget=0)
    # the final PADDED chunk window must fit max_len: a geometry whose
    # last chunk would spill past the cache (and be silently relocated
    # by the model's position clip, corrupting earlier prompt K/V) is
    # rejected at construction, not discovered as wrong tokens
    with pytest.raises(ValueError, match="final chunk window"):
        Engine(m, params, slots=1, max_len=20, prefill_len=20,
               chunk_len=8)
    # ... and direct prefill_chunk callers at arbitrary offsets hit the
    # same wall per call
    eng24 = Engine(m, params, slots=1, max_len=24, prefill_len=24,
                   chunk_len=8)
    with pytest.raises(ValueError, match="exceeds max_len"):
        eng24.prefill_chunk(0, [1, 2], 18)


def test_chunk_budget_caps_ingestion_only_while_decoding(chunk_engines):
    """The budget bounds the stall imposed ON in-flight decodes: with a
    decode active, at most chunk_budget chunks run per tick; with
    nothing decoding there is nothing to stall, so a cold queue bursts
    straight to full ingestion instead of idling between heartbeats."""
    eng, _ = chunk_engines
    eng.reset()
    sched = Scheduler(eng, chunk_budget=2)
    c = Request(prompt=[1, 2], max_new_tokens=50)
    sched.submit(c)
    sched.step()                               # c: 1 chunk → decoding
    assert c.status == "running"
    a = Request(prompt=list(range(1, 17)), max_new_tokens=3)   # 2 chunks
    b = Request(prompt=list(range(2, 18)), max_new_tokens=3)   # 2 chunks
    sched.submit(a)
    sched.submit(b)
    sched.step()
    assert a.chunks == 1 and b.chunks == 1     # one chunk EACH this tick
    sched.step()
    assert a.chunks == 2 and b.chunks == 2
    assert a.status == "running" and b.status == "running"


def test_cold_queue_bursts_to_full_ingestion(chunk_engines):
    eng, _ = chunk_engines
    eng.reset()
    sched = Scheduler(eng)                     # chunk_budget=1
    a = Request(prompt=list(range(1, 24)), max_new_tokens=4)   # 3 chunks
    sched.submit(a)
    sched.step()
    # nothing was decoding, so one tick burst through ALL 3 chunks
    # (instead of idling two heartbeats) and ran the first decode; the
    # burst stops the moment a slot flips to decoding, so the budget
    # bound on in-flight stalls is never violated
    assert a.chunks == 3
    assert a.status == "running" and len(a.output_tokens) == 2


# ----------------------------------------------------------------- engine
def test_engine_default_policy_is_pure_half(lm_and_params):
    """Default O3 policy: weights AND cache in bf16 — no fp32 masters."""
    m, params = lm_and_params
    eng = Engine(m, params, slots=2, max_len=32, prefill_len=8)
    assert eng.cache.dtype == jnp.bfloat16
    for leaf in jax.tree_util.tree_leaves(eng.params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16
    tok = eng.prefill(0, [5, 9, 2])
    assert 0 <= tok < VOCAB
    out = eng.decode_step([tok, 0], [True, False], [0.0, 0.0])
    assert out.shape == (2,) and 0 <= int(out[0]) < VOCAB
    assert eng.lengths().tolist() == [4, 0]


def test_engine_validation(lm_and_params):
    m, params = lm_and_params
    with pytest.raises(ValueError, match="max_seq_len"):
        Engine(m, params, slots=1, max_len=4096)
    with pytest.raises(ValueError, match="prefill_len"):
        Engine(m, params, slots=1, max_len=32, prefill_len=64)
    eng = Engine(m, params, slots=1, max_len=16, prefill_len=8)
    with pytest.raises(ValueError, match="prompt length"):
        eng.prefill(0, list(range(9)))
    with pytest.raises(ValueError, match="slot"):
        eng.prefill(3, [1, 2])


# -------------------------------------------------------------- scheduler
def test_scheduler_backpressure_bounded_queue(fp32_engine):
    sched = Scheduler(fp32_engine, max_queue=2)
    sched.submit(Request(prompt=[1], max_new_tokens=2))
    sched.submit(Request(prompt=[2], max_new_tokens=2))
    with pytest.raises(QueueFull):
        sched.submit(Request(prompt=[3], max_new_tokens=2))
    # a step drains the queue into slots; capacity frees up
    sched.step()
    sched.submit(Request(prompt=[3], max_new_tokens=2))
    while sched.pending:
        sched.step()
    assert len(sched.completed) == 3


def test_scheduler_rejects_unservable_prompts(fp32_engine):
    sched = Scheduler(fp32_engine)
    with pytest.raises(ValueError, match="prefill"):
        sched.submit(Request(prompt=list(range(17))))   # > prefill_len 16
    with pytest.raises(ValueError, match="max_new_tokens"):
        sched.submit(Request(prompt=[1], max_new_tokens=0))


def test_scheduler_timeout(fp32_engine):
    sched = Scheduler(fp32_engine, default_timeout_s=0.0)
    r = sched.submit(Request(prompt=[1, 2], max_new_tokens=500))
    time.sleep(0.01)
    sched.step()
    assert r.status == "expired" and r.finish_reason == "timeout"
    assert sched.pending == 0


def test_scheduler_eos_and_max_len_eviction(lm_and_params):
    m, params = lm_and_params
    eng = Engine(m, params, slots=1, max_len=12, prefill_len=8,
                 policy=resolve_policy("O0", verbose=False))
    # find the greedy first token, then declare it EOS: request must
    # finish at prefill without ever occupying a slot
    probe = eng.prefill(0, [7, 7, 7])
    eng.reset()
    sched = Scheduler(eng, eos_id=probe)
    (r,) = sched.run([Request(prompt=[7, 7, 7], max_new_tokens=50)])
    assert r.finish_reason == "eos" and len(r.output_tokens) == 1
    # cache exhaustion: prompt 8 + budget 50 >> max_len 12
    eng.reset()
    sched = Scheduler(eng)
    (r2,) = sched.run([Request(prompt=list(range(1, 9)),
                               max_new_tokens=50)])
    assert r2.finish_reason == "max_len"
    # prompt(8) fills to 8; decode may write positions 8..11
    assert len(r2.output_tokens) <= 12 - 8 + 1


def test_serving_telemetry_records_the_issue_metrics(lm_and_params):
    """tokens/sec, time-to-first-token, per-step decode latency and
    slot occupancy all land in the MetricsRegistry."""
    m, params = lm_and_params
    reg = telemetry.MetricsRegistry()
    eng = Engine(m, params, slots=2, max_len=32, prefill_len=8,
                 policy=resolve_policy("O0", verbose=False), registry=reg)
    sched = Scheduler(eng, registry=reg)
    sched.run([Request(prompt=[1, 2, 3], max_new_tokens=4),
               Request(prompt=[9], max_new_tokens=6)])
    snap = reg.snapshot()
    assert snap["gauges"]["serving.tokens_per_s"] > 0
    assert snap["histograms"]["serving.ttft_s"]["count"] == 2
    assert snap["histograms"]["serving.decode.step_s"]["count"] >= 5
    assert 0.0 < snap["histograms"]["serving.slot_occupancy"]["mean"] <= 1.0
    assert snap["counters"]["serving.requests.completed"] == 2
    assert snap["counters"]["serving.tokens_generated"] >= 8
    # padding waste is the occupancy complement
    occ = snap["histograms"]["serving.slot_occupancy"]["mean"]
    waste = snap["histograms"]["serving.padding_waste"]["mean"]
    assert abs((occ + waste) - 1.0) < 1e-9


def test_full_prompt_finishes_at_prefill_without_cache_corruption(
        lm_and_params):
    """A prompt that already fills the cache (n == max_len) must finish
    at prefill: a decode step would clamp its write to max_len-1,
    destroying the last prompt position's K/V and emitting a corrupted
    token as real output."""
    m, params = lm_and_params
    eng = Engine(m, params, slots=1, max_len=8, prefill_len=8,
                 policy=resolve_policy("O0", verbose=False))
    sched = Scheduler(eng)
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    (r,) = sched.run([Request(prompt=prompt, max_new_tokens=4)])
    assert r.finish_reason == "max_len"
    assert len(r.output_tokens) == 1          # prefill's token is valid
    full = m.apply({"params": params}, jnp.asarray([prompt], jnp.int32),
                   train=False)
    assert r.output_tokens[0] == int(jnp.argmax(full[0, -1]))


def test_prefill_block_overrides_are_applied_and_restored(lm_and_params):
    """decode.prefill_block_q/_k bite the prefill trace (numerics
    unchanged) and the training flash.* geometry is restored after."""
    from apex_tpu.kernels import vmem

    m, params = lm_and_params
    pol = resolve_policy("O0", verbose=False)
    base = Engine(m, params, slots=1, max_len=32, prefill_len=16,
                  policy=pol, seed=3).prefill(0, [7, 8, 9])
    vmem.set_override("decode.prefill_block_q", 8)
    vmem.set_override("decode.prefill_block_k", 128)
    vmem.set_override("flash.block_q", 64)      # training-time value
    try:
        eng = Engine(m, params, slots=1, max_len=32, prefill_len=16,
                     policy=pol, seed=3)
        tok = eng.prefill(0, [7, 8, 9])
        assert tok == base                      # geometry never changes math
        assert vmem.overrides().get("flash.block_q") == 64  # restored
        assert "flash.block_k" not in vmem.overrides()
    finally:
        for k in ("decode.prefill_block_q", "decode.prefill_block_k",
                  "flash.block_q"):
            vmem.remove_override(k)


def test_prefill_and_decode_agree_on_tokens_generated_counter(
        lm_and_params):
    """The serving.tokens_generated counter must match the engine's own
    tokens_generated tally (the tokens/s numerator) — prefill's first
    token counts in both."""
    m, params = lm_and_params
    reg = telemetry.MetricsRegistry()
    eng = Engine(m, params, slots=2, max_len=32, prefill_len=8,
                 policy=resolve_policy("O0", verbose=False), registry=reg)
    Scheduler(eng, registry=reg).run(
        [Request(prompt=[1, 2], max_new_tokens=3),
         Request(prompt=[4], max_new_tokens=5)])
    assert reg.snapshot()["counters"]["serving.tokens_generated"] \
        == eng.tokens_generated == 8


def test_temperature_decode_stays_in_vocab_and_finishes(fp32_engine):
    sched = Scheduler(fp32_engine)
    (r,) = sched.run([Request(prompt=[5, 6], max_new_tokens=10,
                              temperature=1.5)])
    assert len(r.output_tokens) == 10
    assert all(0 <= t < VOCAB for t in r.output_tokens)
