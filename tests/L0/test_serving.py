"""apex_tpu.serving — KV-cache engine + continuous batching, hermetic.

The acceptance bar from the subsystem's issue, as tests:

- greedy KV-cache decode is token-exact against the full-recompute
  forward's argmax for >= 64 generated tokens (teacher-forcing form:
  ONE full forward over [prompt + generated] re-derives every step's
  argmax, so both paths are compared through identical programs — the
  shared-program discipline of test_amp_train_step.py, avoiding 64
  separately-fused eager forwards);
- a stream of variable-length requests is served by exactly 2 compiled
  programs (prefill + decode step), pinned by trace counters;
- telemetry records tokens/sec, time-to-first-token and slot occupancy.

Everything runs on CPU with a tiny model; the engine's Pallas decode
kernel takes its interpret/reference path here (the Mosaic lowering is
the tests/tpu tier's job).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import serving, telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import Engine, KVCache, QueueFull, Request, Scheduler

pytestmark = pytest.mark.serving

VOCAB = 101


def _tiny_lm(max_seq_len=128, **kw):
    return TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                         num_heads=4, max_seq_len=max_seq_len, **kw)


@pytest.fixture(scope="module")
def lm_and_params():
    m = _tiny_lm()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


@pytest.fixture(scope="module")
def fp32_engine(lm_and_params):
    """Exact-fp32 engine (policy O0) shared by the parity/trace tests."""
    m, params = lm_and_params
    return Engine(m, params, slots=3, max_len=128, prefill_len=16,
                  policy=resolve_policy("O0", verbose=False), seed=7)


# ------------------------------------------------------------------ kv cache
def test_kv_cache_create_and_geometry():
    c = KVCache.create(layers=2, slots=4, heads=3, max_len=32, head_dim=8,
                       dtype=jnp.bfloat16)
    assert (c.layers, c.slots, c.heads, c.max_len, c.head_dim) \
        == (2, 4, 3, 32, 8)
    assert c.dtype == jnp.bfloat16
    assert c.nbytes() == 2 * 4 * 3 * 32 * 8 * 2 * 2
    assert c.occupancy() == 0.0 and c.padding_waste() == 1.0


def test_kv_cache_insert_and_advance():
    c = KVCache.create(layers=2, slots=2, heads=1, max_len=8, head_dim=4,
                       dtype=jnp.float32)
    k_new = jnp.ones((2, 1, 1, 4, 4))
    c = c.insert(1, k_new, 2 * k_new, 3)
    assert int(c.lengths[1]) == 3 and int(c.lengths[0]) == 0
    np.testing.assert_array_equal(np.asarray(c.k[:, 1, :, :4]),
                                  np.ones((2, 1, 4, 4)))
    # advance grows only active slots, clamped at max_len
    c = c.advance(c.k, c.v, jnp.asarray([False, True]))
    assert int(c.lengths[1]) == 4 and int(c.lengths[0]) == 0
    assert c.occupancy(active=[False, True]) == 0.5


def test_kv_cache_insert_validates():
    c = KVCache.create(layers=1, slots=1, heads=1, max_len=4, head_dim=4)
    with pytest.raises(ValueError, match="exceeds cache max_len"):
        c.insert(0, jnp.zeros((1, 1, 1, 8, 4)), jnp.zeros((1, 1, 1, 8, 4)),
                 8)
    with pytest.raises(ValueError, match="prefill K/V"):
        c.insert(0, jnp.zeros((1, 2, 1, 4, 4)), jnp.zeros((1, 2, 1, 4, 4)),
                 4)


# ------------------------------------------------------------------ sampling
def test_sample_tokens_greedy_vs_temperature():
    logits = jnp.asarray([[0.0, 5.0, 1.0], [9.0, 0.0, 0.0]])
    key = jax.random.PRNGKey(0)
    greedy = serving.sample_tokens(logits, jnp.zeros(2), key)
    np.testing.assert_array_equal(np.asarray(greedy), [1, 0])
    # temperature sampling is deterministic per key and stays in-vocab
    hot = serving.sample_tokens(logits, jnp.full(2, 2.0), key)
    hot2 = serving.sample_tokens(logits, jnp.full(2, 2.0), key)
    np.testing.assert_array_equal(np.asarray(hot), np.asarray(hot2))
    assert np.all((np.asarray(hot) >= 0) & (np.asarray(hot) < 3))


def test_sample_tokens_top_k_restricts_support():
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0, -1.0]])
    keys = jax.random.split(jax.random.PRNGKey(1), 32)
    got = {int(serving.sample_tokens(logits, jnp.full(1, 5.0), k,
                                     top_k=2)[0]) for k in keys}
    assert got <= {2, 3}            # only the top-2 ids are reachable


# ----------------------------------------------------------- decode parity
def test_greedy_decode_token_exact_vs_full_recompute(fp32_engine,
                                                     lm_and_params):
    """>= 64 greedy tokens from the KV-cache engine == the argmax chain
    of one full-recompute forward over the final sequence (causality
    makes teacher-forcing re-derivation exact for greedy decode)."""
    m, params = lm_and_params
    eng = fp32_engine
    sched = Scheduler(eng)
    prompt = [3, 17, 91, 42, 8]
    n_gen = 65
    (req,) = sched.run([Request(prompt=prompt, max_new_tokens=n_gen)])
    assert req.finish_reason == "max_new_tokens"
    assert len(req.output_tokens) == n_gen
    seq = jnp.asarray([list(prompt) + req.output_tokens], jnp.int32)
    full = m.apply({"params": params}, seq, train=False)   # [1, S, V]
    want = np.asarray(jnp.argmax(full[0], axis=-1))
    for i, tok in enumerate(req.output_tokens):
        # token i was sampled from the logits at position prompt+i-1
        assert tok == int(want[len(prompt) - 1 + i]), \
            f"divergence at generated token {i}"


def test_exactly_two_compiled_programs(fp32_engine):
    """Variable-length, variable-budget request stream → exactly one
    prefill trace and one decode-step trace (the fixed-shape contract:
    no per-token or per-request recompiles)."""
    eng = fp32_engine
    base_p, base_d = eng.prefill_traces, eng.decode_traces
    sched = Scheduler(eng)
    rng = np.random.default_rng(0)
    reqs = [Request(prompt=list(rng.integers(1, VOCAB, size=n)),
                    max_new_tokens=mnt, temperature=t)
            for n, mnt, t in [(1, 3, 0.0), (7, 9, 0.0), (16, 5, 0.7),
                              (4, 12, 0.0), (11, 2, 1.3)]]
    done = sched.run(reqs)
    assert len(done) == 5
    assert eng.prefill_traces - base_p <= 1
    assert eng.decode_traces - base_d <= 1
    # the fixture's earlier users already compiled both programs once
    assert eng.prefill_traces == 1 and eng.decode_traces == 1


# ----------------------------------------------------------------- engine
def test_engine_default_policy_is_pure_half(lm_and_params):
    """Default O3 policy: weights AND cache in bf16 — no fp32 masters."""
    m, params = lm_and_params
    eng = Engine(m, params, slots=2, max_len=32, prefill_len=8)
    assert eng.cache.dtype == jnp.bfloat16
    for leaf in jax.tree_util.tree_leaves(eng.params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16
    tok = eng.prefill(0, [5, 9, 2])
    assert 0 <= tok < VOCAB
    out = eng.decode_step([tok, 0], [True, False], [0.0, 0.0])
    assert out.shape == (2,) and 0 <= int(out[0]) < VOCAB
    assert eng.lengths().tolist() == [4, 0]


def test_engine_validation(lm_and_params):
    m, params = lm_and_params
    with pytest.raises(ValueError, match="max_seq_len"):
        Engine(m, params, slots=1, max_len=4096)
    with pytest.raises(ValueError, match="prefill_len"):
        Engine(m, params, slots=1, max_len=32, prefill_len=64)
    eng = Engine(m, params, slots=1, max_len=16, prefill_len=8)
    with pytest.raises(ValueError, match="prompt length"):
        eng.prefill(0, list(range(9)))
    with pytest.raises(ValueError, match="slot"):
        eng.prefill(3, [1, 2])


# -------------------------------------------------------------- scheduler
def test_scheduler_backpressure_bounded_queue(fp32_engine):
    sched = Scheduler(fp32_engine, max_queue=2)
    sched.submit(Request(prompt=[1], max_new_tokens=2))
    sched.submit(Request(prompt=[2], max_new_tokens=2))
    with pytest.raises(QueueFull):
        sched.submit(Request(prompt=[3], max_new_tokens=2))
    # a step drains the queue into slots; capacity frees up
    sched.step()
    sched.submit(Request(prompt=[3], max_new_tokens=2))
    while sched.pending:
        sched.step()
    assert len(sched.completed) == 3


def test_scheduler_rejects_unservable_prompts(fp32_engine):
    sched = Scheduler(fp32_engine)
    with pytest.raises(ValueError, match="prefill"):
        sched.submit(Request(prompt=list(range(17))))   # > prefill_len 16
    with pytest.raises(ValueError, match="max_new_tokens"):
        sched.submit(Request(prompt=[1], max_new_tokens=0))


def test_scheduler_timeout(fp32_engine):
    sched = Scheduler(fp32_engine, default_timeout_s=0.0)
    r = sched.submit(Request(prompt=[1, 2], max_new_tokens=500))
    time.sleep(0.01)
    sched.step()
    assert r.status == "timeout" and r.finish_reason == "timeout"
    assert sched.pending == 0


def test_scheduler_eos_and_max_len_eviction(lm_and_params):
    m, params = lm_and_params
    eng = Engine(m, params, slots=1, max_len=12, prefill_len=8,
                 policy=resolve_policy("O0", verbose=False))
    # find the greedy first token, then declare it EOS: request must
    # finish at prefill without ever occupying a slot
    probe = eng.prefill(0, [7, 7, 7])
    eng.reset()
    sched = Scheduler(eng, eos_id=probe)
    (r,) = sched.run([Request(prompt=[7, 7, 7], max_new_tokens=50)])
    assert r.finish_reason == "eos" and len(r.output_tokens) == 1
    # cache exhaustion: prompt 8 + budget 50 >> max_len 12
    eng.reset()
    sched = Scheduler(eng)
    (r2,) = sched.run([Request(prompt=list(range(1, 9)),
                               max_new_tokens=50)])
    assert r2.finish_reason == "max_len"
    # prompt(8) fills to 8; decode may write positions 8..11
    assert len(r2.output_tokens) <= 12 - 8 + 1


def test_serving_telemetry_records_the_issue_metrics(lm_and_params):
    """tokens/sec, time-to-first-token, per-step decode latency and
    slot occupancy all land in the MetricsRegistry."""
    m, params = lm_and_params
    reg = telemetry.MetricsRegistry()
    eng = Engine(m, params, slots=2, max_len=32, prefill_len=8,
                 policy=resolve_policy("O0", verbose=False), registry=reg)
    sched = Scheduler(eng, registry=reg)
    sched.run([Request(prompt=[1, 2, 3], max_new_tokens=4),
               Request(prompt=[9], max_new_tokens=6)])
    snap = reg.snapshot()
    assert snap["gauges"]["serving.tokens_per_s"] > 0
    assert snap["histograms"]["serving.ttft_s"]["count"] == 2
    assert snap["histograms"]["serving.decode.step_s"]["count"] >= 5
    assert 0.0 < snap["histograms"]["serving.slot_occupancy"]["mean"] <= 1.0
    assert snap["counters"]["serving.requests.completed"] == 2
    assert snap["counters"]["serving.tokens_generated"] >= 8
    # padding waste is the occupancy complement
    occ = snap["histograms"]["serving.slot_occupancy"]["mean"]
    waste = snap["histograms"]["serving.padding_waste"]["mean"]
    assert abs((occ + waste) - 1.0) < 1e-9


def test_full_prompt_finishes_at_prefill_without_cache_corruption(
        lm_and_params):
    """A prompt that already fills the cache (n == max_len) must finish
    at prefill: a decode step would clamp its write to max_len-1,
    destroying the last prompt position's K/V and emitting a corrupted
    token as real output."""
    m, params = lm_and_params
    eng = Engine(m, params, slots=1, max_len=8, prefill_len=8,
                 policy=resolve_policy("O0", verbose=False))
    sched = Scheduler(eng)
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    (r,) = sched.run([Request(prompt=prompt, max_new_tokens=4)])
    assert r.finish_reason == "max_len"
    assert len(r.output_tokens) == 1          # prefill's token is valid
    full = m.apply({"params": params}, jnp.asarray([prompt], jnp.int32),
                   train=False)
    assert r.output_tokens[0] == int(jnp.argmax(full[0, -1]))


def test_prefill_block_overrides_are_applied_and_restored(lm_and_params):
    """decode.prefill_block_q/_k bite the prefill trace (numerics
    unchanged) and the training flash.* geometry is restored after."""
    from apex_tpu.kernels import vmem

    m, params = lm_and_params
    pol = resolve_policy("O0", verbose=False)
    base = Engine(m, params, slots=1, max_len=32, prefill_len=16,
                  policy=pol, seed=3).prefill(0, [7, 8, 9])
    vmem.set_override("decode.prefill_block_q", 8)
    vmem.set_override("decode.prefill_block_k", 128)
    vmem.set_override("flash.block_q", 64)      # training-time value
    try:
        eng = Engine(m, params, slots=1, max_len=32, prefill_len=16,
                     policy=pol, seed=3)
        tok = eng.prefill(0, [7, 8, 9])
        assert tok == base                      # geometry never changes math
        assert vmem.overrides().get("flash.block_q") == 64  # restored
        assert "flash.block_k" not in vmem.overrides()
    finally:
        for k in ("decode.prefill_block_q", "decode.prefill_block_k",
                  "flash.block_q"):
            vmem.remove_override(k)


def test_prefill_and_decode_agree_on_tokens_generated_counter(
        lm_and_params):
    """The serving.tokens_generated counter must match the engine's own
    tokens_generated tally (the tokens/s numerator) — prefill's first
    token counts in both."""
    m, params = lm_and_params
    reg = telemetry.MetricsRegistry()
    eng = Engine(m, params, slots=2, max_len=32, prefill_len=8,
                 policy=resolve_policy("O0", verbose=False), registry=reg)
    Scheduler(eng, registry=reg).run(
        [Request(prompt=[1, 2], max_new_tokens=3),
         Request(prompt=[4], max_new_tokens=5)])
    assert reg.snapshot()["counters"]["serving.tokens_generated"] \
        == eng.tokens_generated == 8


def test_temperature_decode_stays_in_vocab_and_finishes(fp32_engine):
    sched = Scheduler(fp32_engine)
    (r,) = sched.run([Request(prompt=[5, 6], max_new_tokens=10,
                              temperature=1.5)])
    assert len(r.output_tokens) == 10
    assert all(0 <= t < VOCAB for t in r.output_tokens)
