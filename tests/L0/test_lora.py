"""Multi-tenant LoRA serving: the adapter-arena acceptance pins.

The perf claim (one base engine serving heterogeneous fine-tunes in
one batch) is only honest with these bars, per ISSUE 20:

- **adapter=None bitwise**: a LoRA-enabled engine with no adapter
  bound serves the EXACT base-engine stream on the same executables —
  the zero arena row's epilogue term is ``+0.0`` everywhere, and the
  program-count pins do not move;
- **one invocation**: a mixed-adapter batch decodes in ONE compiled
  invocation — the compiled-program count is independent of how many
  adapters are registered, resident or bound (adapter id is data, not
  a trace key);
- **per-slot isolation**: slot A's adapter provably never perturbs
  slot B's tokens — a mixed-adapter batch is bitwise identical to
  per-adapter sequential runs at the same geometry;
- **graceful degradation + loud failure**: a full arena holds the
  request queued (FIFO preserved); an unknown or checksum-corrupt
  adapter fails the request LOUDLY, never a silent base-model
  fallback, never wrong tokens;
- **churn is leak-free**: hot-load/evict under faulted traffic drains
  with zero leaked pages (PoolAuditor) and a clean arena refcount
  audit;
- **routing**: ``Request.adapter`` crosses the wire (v3) and both
  routing fronts rank a resident-adapter hit right after the prefix
  match;
- **composition**: kv_quant + weight_quant + speculative verify ride
  along; tp=1 mesh is bitwise (the tp=2 parity run carries the
  ``slow`` marker like every multi-device test).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, FaultPlan, FaultSpec, KVQuantConfig,
                              LoRAConfig, LoRAManager, PoolAuditor,
                              Request, RequestStatus, Router, Scheduler,
                              SpecConfig, WeightQuantConfig,
                              request_from_wire, request_to_wire)
from apex_tpu.serving.lora import SITES, lora_spec_tree
from apex_tpu.serving.routing_policy import rank_replicas

pytestmark = pytest.mark.serving

VOCAB, H, LAYERS, HEADS = 64, 32, 2, 4
CHUNK = 8
RANK = 4


@pytest.fixture(scope="module")
def lm_and_params():
    m = TransformerLM(vocab_size=VOCAB, hidden=H, num_layers=LAYERS,
                      num_heads=HEADS, max_seq_len=64)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_adapter(seed, scale=0.5, rank=RANK):
    rng = np.random.default_rng(seed)
    dims = {"qkv": (H, 3 * H), "proj": (H, H),
            "mlp_in": (H, 4 * H), "mlp_out": (4 * H, H)}
    return {s: (rng.normal(size=(LAYERS, di, rank))
                .astype(np.float32) * scale,
                rng.normal(size=(LAYERS, rank, do))
                .astype(np.float32) * scale)
            for s, (di, do) in dims.items()}


_CFG = LoRAConfig(rank=RANK, arena_slots=2, host_bytes=1 << 22)

#: name -> deterministic generator seed, shared by every engine build
#: so any two engines hold bitwise-identical adapters
_ADAPTERS = {"a1": 1, "a2": 2, "a3": 3}


def _mk_engine(lm_and_params, *, lora=_CFG, slots=3, mesh=None,
               register=("a1", "a2"), **kw):
    m, params = lm_and_params
    eng = Engine(m, params, slots=slots, max_len=64, prefill_len=24,
                 chunk_len=CHUNK, prefix_pool=0, seed=5, paged=True,
                 page_len=CHUNK, num_pages=64, lora=lora, mesh=mesh,
                 **kw)
    if lora is not None:
        for name in register:
            eng.lora_register(name, _mk_adapter(_ADAPTERS[name]),
                              alpha=0.7)
    return eng


def _prompts(n=6, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, VOCAB, size=8 + i).tolist()
            for i in range(n)]


def _run_jobs(eng, jobs, *, sched_kw=None, budget=5):
    """Serve ``[(prompt, adapter), ...]`` and return each job's token
    stream in submission order (plus the requests themselves)."""
    sched = Scheduler(eng, **(sched_kw or {}))
    reqs = [Request(prompt=list(p), max_new_tokens=budget, adapter=ad)
            for p, ad in jobs]
    sched.run(reqs)
    return [list(r.output_tokens) for r in reqs], reqs


# ------------------------------------------------------------- config/units
def test_lora_config_validation():
    with pytest.raises(ValueError, match="rank"):
        LoRAConfig(rank=0)
    with pytest.raises(ValueError, match="arena_slots"):
        LoRAConfig(arena_slots=0)
    with pytest.raises(ValueError, match="host_bytes"):
        LoRAConfig(host_bytes=0)


def test_spec_tree_rides_the_pr9_axes():
    """A column-split, B row-split, restated for the stacked arena:
    column-parallel sites split B's OUTPUT axis, row-parallel sites
    split A's INPUT axis, everything else is replicated — the existing
    post-proj/post-mlp psums restore the row-parallel partial sums, so
    the tier adds zero collectives."""
    tree = lora_spec_tree("tp")
    assert tree["qkv_b"] == P(None, None, None, "tp")
    assert tree["mlp_in_b"] == P(None, None, None, "tp")
    assert tree["proj_a"] == P(None, None, "tp", None)
    assert tree["mlp_out_a"] == P(None, None, "tp", None)
    for k in ("qkv_a", "mlp_in_a", "proj_b", "mlp_out_b", "alpha"):
        assert tree[k] == P(), k


def _bare_manager(host_bytes=1 << 22, arena_slots=2):
    return LoRAManager(
        LoRAConfig(rank=RANK, arena_slots=arena_slots,
                   host_bytes=host_bytes),
        hidden=H, num_heads=HEADS, num_layers=LAYERS)


def test_manager_register_validation():
    mgr = _bare_manager()
    sites = _mk_adapter(1)
    bad = dict(sites)
    del bad["proj"]
    with pytest.raises(ValueError, match="missing site"):
        mgr.register("x", bad)
    bad = dict(sites)
    a, b = bad["qkv"]
    bad["qkv"] = (a[:, :, :-1], b)          # wrong rank
    with pytest.raises(ValueError, match="shapes"):
        mgr.register("x", bad)
    # an adapter alone larger than the store is loud, not an LRU spin
    one = sum(a.nbytes + b.nbytes for a, b in sites.values())
    small = _bare_manager(host_bytes=one - 1)
    with pytest.raises(ValueError, match="exceeds the host store"):
        small.register("x", sites)


def test_manager_lru_refcount_and_residency():
    sites = _mk_adapter(1)
    one = sum(a.nbytes + b.nbytes for a, b in sites.values())
    mgr = _bare_manager(host_bytes=2 * one)
    mgr.register("a1", _mk_adapter(1))
    mgr.register("a2", _mk_adapter(2))
    row = mgr.acquire("a1")                 # a1 pinned (refcount 1)
    assert row and mgr.resident_names() == ["a1"]
    # byte pressure evicts the LRU UNPINNED record (a2), never a1
    mgr.register("a3", _mk_adapter(3))
    assert not mgr.contains("a2") and mgr.contains("a1")
    assert mgr.evictions == 1
    # a pinned record refuses re-register (live math must not change)
    with pytest.raises(ValueError, match="pinned"):
        mgr.register("a1", _mk_adapter(9))
    # with every byte pinned, registration fails loudly
    mgr.acquire("a3")
    with pytest.raises(ValueError, match="pinned"):
        mgr.register("a4", _mk_adapter(4))
    # release keeps residency: the next acquire is a HIT, not a load
    mgr.release(row)
    loads = mgr.loads
    assert mgr.acquire("a1") == row
    assert mgr.loads == loads and mgr.hits == 1
    mgr.release(row)
    with pytest.raises(ValueError, match="below zero"):
        mgr.release(row)
        mgr.release(row)
    mgr.audit()


def test_manager_crc_corrupt_is_a_loud_reload():
    mgr = _bare_manager()
    mgr.register("a1", _mk_adapter(1))
    mgr.corrupt_entry("a1")
    with pytest.raises(KeyError, match="checksum"):
        mgr.acquire("a1")
    # the record is DROPPED — a retry cannot silently serve the
    # corrupt bytes — and a re-register reloads cleanly
    assert not mgr.contains("a1")
    assert mgr.corruptions_detected == 1
    mgr.register("a1", _mk_adapter(1))
    assert mgr.acquire("a1") == 1
    mgr.audit({1: 1})


# ------------------------------------------------- bitwise + program pins
def test_adapter_none_bitwise_with_program_pins(lm_and_params):
    base = _mk_engine(lm_and_params, lora=None)
    lled = _mk_engine(lm_and_params)        # LoRA on, nothing bound
    jobs = [(p, None) for p in _prompts(4)]
    b_toks, _ = _run_jobs(base, jobs)
    l_toks, _ = _run_jobs(lled, jobs)
    assert l_toks == b_toks, \
        "a LoRA engine with no adapter bound must be BITWISE the base"
    assert lled.compiled_programs == base.compiled_programs, \
        "the LoRA tier moved the program-count pin"


def test_heterogeneous_batch_one_invocation_per_slot_isolated(
        lm_and_params):
    """The tentpole pin: a mixed-adapter batch (base + a1 + a2 across
    the slots) decodes through the SAME compiled programs as the
    adapter-less engine — and each request's stream is bitwise what a
    per-adapter sequential run produces at identical geometry."""
    prompts = _prompts(6)
    jobs = [(prompts[0], None), (prompts[1], "a1"), (prompts[2], "a2"),
            (prompts[3], "a1"), (prompts[4], None), (prompts[5], "a2")]
    eng = _mk_engine(lm_and_params)
    mixed, reqs = _run_jobs(eng, jobs)
    assert all(r.status is RequestStatus.FINISHED for r in reqs)
    base = _mk_engine(lm_and_params, lora=None)
    _run_jobs(base, [(p, None) for p, _ in jobs])
    assert eng.compiled_programs == base.compiled_programs, \
        "adapter count leaked into the trace key set"
    # the adapters actually do something: a1 jobs differ from base
    b_toks, _ = _run_jobs(_mk_engine(lm_and_params, lora=None),
                          [(prompts[1], None)])
    assert mixed[1] != b_toks[0], "bound adapter had no effect"
    # per-adapter sequential runs, identical geometry: bitwise
    for group in (None, "a1", "a2"):
        gjobs = [(p, ad) for p, ad in jobs if ad == group]
        gtoks, _ = _run_jobs(_mk_engine(lm_and_params), gjobs)
        want = [mixed[k] for k, (_, ad) in enumerate(jobs)
                if ad == group]
        assert gtoks == want, f"adapter group {group!r} not isolated"
    eng.lora_audit()                        # zero bindings at drain
    assert PoolAuditor().audit(eng)["pages_in_use"] == 0


def test_arena_full_holds_fifo_and_degrades_gracefully(lm_and_params):
    """Three adapters through a one-row arena: binds beyond capacity
    return False (never an exception), the scheduler holds the queue
    FIFO, and everything finishes as rows free up."""
    cfg = LoRAConfig(rank=RANK, arena_slots=1, host_bytes=1 << 22)
    eng = _mk_engine(lm_and_params, lora=cfg,
                     register=("a1", "a2", "a3"))
    prompts = _prompts(4)
    jobs = [(prompts[0], "a1"), (prompts[1], "a2"),
            (prompts[2], "a3"), (prompts[3], "a1")]
    toks, reqs = _run_jobs(eng, jobs)
    assert all(r.status is RequestStatus.FINISHED for r in reqs)
    assert eng.lora.evictions >= 2          # real churn happened
    eng.lora_audit()
    assert PoolAuditor().audit(eng)["pages_in_use"] == 0


def test_unknown_adapter_fails_loudly(lm_and_params):
    eng = _mk_engine(lm_and_params)
    toks, reqs = _run_jobs(eng, [(_prompts(1)[0], "nope")])
    assert reqs[0].status is RequestStatus.FAILED
    assert "nope" in reqs[0].error and toks[0] == [], \
        "an unknown adapter must never decode (no base-model fallback)"


def test_adapter_on_loraless_engine_rejected_at_submit(lm_and_params):
    eng = _mk_engine(lm_and_params, lora=None)
    with pytest.raises(ValueError, match="without lora"):
        Scheduler(eng).submit(Request(prompt=[1, 2, 3],
                                      max_new_tokens=2, adapter="a1"))


def test_corrupt_record_fails_request_then_reloads(lm_and_params):
    """The swap_corruption contract for adapter records: a corrupt
    host record fails the NEXT cold bind loudly (request FAILED, the
    record dropped) — never wrong tokens — and a re-register serves
    the stream bitwise clean."""
    prompt = _prompts(1)[0]
    oracle, _ = _run_jobs(_mk_engine(lm_and_params), [(prompt, "a1")])
    eng = _mk_engine(lm_and_params)
    eng.lora.corrupt_entry("a1")
    toks, reqs = _run_jobs(eng, [(prompt, "a1")])
    assert reqs[0].status is RequestStatus.FAILED
    assert "checksum" in reqs[0].error and toks[0] == []
    assert eng.lora.corruptions_detected == 1
    # loud reload: re-register, serve again, bitwise the clean run
    eng.lora_register("a1", _mk_adapter(_ADAPTERS["a1"]), alpha=0.7)
    toks, reqs = _run_jobs(eng, [(prompt, "a1")])
    assert reqs[0].status is RequestStatus.FINISHED
    assert toks[0] == oracle[0]
    eng.lora_audit()


def test_adapter_churn_chaos_drains_leak_free(lm_and_params):
    """Seeded fault stream over adapter churn (3 adapters, 2 arena
    rows, transient chunk/decode exceptions + a non-finite injection):
    every request reaches a terminal state, retried requests re-serve
    bitwise (greedy is deterministic), and the drain leaves zero
    leaked pages AND a clean arena refcount audit."""
    prompts = _prompts(6, seed=11)
    jobs = [(prompts[0], "a1"), (prompts[1], "a2"), (prompts[2], None),
            (prompts[3], "a3"), (prompts[4], "a1"), (prompts[5], "a3")]
    oracle, _ = _run_jobs(
        _mk_engine(lm_and_params, register=("a1", "a2", "a3")), jobs)
    plan = FaultPlan([
        FaultSpec(kind="exception", tick=2, site="chunk"),
        FaultSpec(kind="nonfinite", tick=3, slot=1),
        FaultSpec(kind="exception", tick=5, site="decode", slot=0),
    ])
    eng = _mk_engine(lm_and_params, register=("a1", "a2", "a3"))
    toks, reqs = _run_jobs(eng, jobs,
                           sched_kw={"fault_plan": plan})
    assert all(r.status.terminal for r in reqs)
    for k, r in enumerate(reqs):
        if r.status is RequestStatus.FINISHED:
            assert toks[k] == oracle[k], \
                f"request {k} (adapter={jobs[k][1]!r}) drifted " \
                "under faulted churn"
    assert PoolAuditor().audit(eng)["pages_in_use"] == 0, \
        "the churn leaked pages"
    stats = eng.lora_audit()                # raises on refcount drift
    assert stats["bytes_used"] == sum(
        a.nbytes + b.nbytes for nm in ("a1", "a2", "a3")
        for a, b in _mk_adapter(_ADAPTERS[nm]).values()), \
        "the churn leaked arena bytes"


# --------------------------------------------------------------- routing
def test_request_wire_carries_adapter():
    r = Request(prompt=[1, 2], max_new_tokens=2, adapter="tenant-7")
    back = request_from_wire(request_to_wire(r))
    assert back.adapter == "tenant-7"
    assert request_from_wire(
        request_to_wire(Request(prompt=[1], max_new_tokens=1))
    ).adapter is None


def test_rank_replicas_adapter_affinity():
    """A resident-adapter hit ranks right after the prefix match:
    it beats free slots, and a longer prefix match still beats it.
    ``adapter_hits=None`` preserves the pre-LoRA ordering exactly."""
    snaps = {i: {"slots_free": s, "queue_depth": 0, "pages_free": None,
                 "host_bytes_free": None}
             for i, s in ((0, 4), (1, 1))}
    lens = {0: 0, 1: 0}
    assert rank_replicas([0, 1], lens, snaps) == [0, 1]
    assert rank_replicas([0, 1], lens, snaps,
                         adapter_hits={0: 0, 1: 1}) == [1, 0]
    # prefix affinity still dominates
    assert rank_replicas([0, 1], {0: 2, 1: 0}, snaps,
                         adapter_hits={0: 0, 1: 1}) == [0, 1]


def test_router_routes_to_the_resident_adapter(lm_and_params):
    """Adapter affinity on the in-process front: with equal load and
    no prefix signal, a request lands on the replica whose arena
    already holds its adapter (replica 1 here — index order would
    pick 0)."""
    engines = [_mk_engine(lm_and_params, slots=2) for _ in range(2)]
    # warm replica 1's arena: bind+release leaves a1 RESIDENT there
    assert engines[1].lora_bind(0, "a1")
    engines[1].lora_unbind(0)
    assert engines[1].resident_adapters() == ["a1"]
    router = Router(engines)
    r = Request(prompt=_prompts(1)[0], max_new_tokens=3, adapter="a1")
    router.submit(r)
    assert router.placements[r.uid] == 1
    while router.pending:
        router.step()
    assert r.status is RequestStatus.FINISHED
    # base-model requests rank exactly as before (index tie-break)
    r2 = Request(prompt=_prompts(1)[0], max_new_tokens=3)
    router.submit(r2)
    assert router.placements[r2.uid] == 0
    while router.pending:
        router.step()
    router.close()


def test_snapshot_reports_resident_adapters(lm_and_params):
    eng = _mk_engine(lm_and_params)
    sched = Scheduler(eng)
    assert sched.load_snapshot()["resident_adapters"] == []
    assert eng.lora_bind(0, "a2")
    assert sched.load_snapshot()["resident_adapters"] == ["a2"]
    eng.lora_unbind(0)
    base = _mk_engine(lm_and_params, lora=None)
    assert Scheduler(base).load_snapshot()["resident_adapters"] is None


# ----------------------------------------------------------- composition
def test_composes_with_quant_and_speculative(lm_and_params):
    """kv_quant + weight_quant + speculative verify, LoRA on: the
    no-adapter stream matches the same-config LoRA-less engine
    bitwise (the int8 tiers quantize identically — the zero row adds
    +0.0 AFTER the dequant epilogue), and bound adapters still
    isolate per slot."""
    kw = dict(kv_quant=KVQuantConfig(), weight_quant=WeightQuantConfig(),
              spec=SpecConfig(draft_len=3, ngram=2))
    prompts = _prompts(4, seed=3)
    jobs = [(prompts[0], None), (prompts[1], "a1"),
            (prompts[2], "a2"), (prompts[3], "a1")]
    base = _mk_engine(lm_and_params, lora=None, **kw)
    b_toks, _ = _run_jobs(base, [(p, None) for p, _ in jobs],
                          sched_kw={"speculative": True}, budget=8)
    eng = _mk_engine(lm_and_params, **kw)
    toks, reqs = _run_jobs(eng, jobs, sched_kw={"speculative": True},
                           budget=8)
    assert all(r.status is RequestStatus.FINISHED for r in reqs)
    assert toks[0] == b_toks[0], \
        "adapter=None drifted under kv_quant+weight_quant+spec"
    assert toks[1] != b_toks[1], "adapter inert under the quant tiers"
    assert eng.compiled_programs == base.compiled_programs
    solo, _ = _run_jobs(_mk_engine(lm_and_params, **kw),
                        [(prompts[1], "a1")],
                        sched_kw={"speculative": True}, budget=8)
    assert solo[0] == toks[1], "mixed vs sequential drifted under spec"


def _mesh(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} virtual devices")
    return Mesh(np.array(devs[:n]), ("tp",))


def test_tp1_mesh_bitwise(lm_and_params):
    """A 1-device mesh LoRA engine is the same serving engine: the
    no-adapter stream AND a bound-adapter stream are bitwise the
    mesh=None LoRA engine's."""
    prompts = _prompts(3, seed=7)
    jobs = [(prompts[0], None), (prompts[1], "a1"), (prompts[2], "a2")]
    plain, _ = _run_jobs(_mk_engine(lm_and_params), jobs)
    meshed, reqs = _run_jobs(_mk_engine(lm_and_params, mesh=_mesh(1)),
                             jobs)
    assert all(r.status is RequestStatus.FINISHED for r in reqs)
    assert meshed == plain


@pytest.mark.slow
def test_tp2_mesh_token_exact(lm_and_params):
    """The sharded arena (A column-split, B row-split, qkv B
    head-group-permuted) over 2 shards: token-exact vs the single-chip
    LoRA engine on a mixed-adapter stream — the existing post-proj /
    post-mlp psums restore the row-parallel partial sums."""
    prompts = _prompts(4, seed=9)
    jobs = [(prompts[0], None), (prompts[1], "a1"),
            (prompts[2], "a2"), (prompts[3], "a1")]
    plain, _ = _run_jobs(_mk_engine(lm_and_params), jobs)
    sharded, reqs = _run_jobs(_mk_engine(lm_and_params, mesh=_mesh(2)),
                              jobs)
    assert all(r.status is RequestStatus.FINISHED for r in reqs)
    assert sharded == plain
