"""Native host-side extension tests (csrc/flatten_unflatten.c).

Mirror of the reference's graceful-degradation contract: every test of the
native path skips when the extension isn't built (apex/contrib tests
SkipTest on ImportError), and the Python fallback is tested unconditionally
against the same assertions.
"""

import numpy as np
import pytest

from apex_tpu.utils import pytree

try:
    from apex_tpu import _C
except ImportError:
    _C = None

needs_ext = pytest.mark.skipif(_C is None, reason="apex_tpu._C not built "
                               "(python setup.py build_ext --inplace "
                               "--cpp_ext)")


def _arrays():
    rs = np.random.RandomState(0)
    return [rs.randn(7).astype(np.float32),
            rs.randn(3, 5).astype(np.float32),
            rs.randn(1).astype(np.float32)]


@needs_ext
def test_native_flatten_roundtrip():
    arrays = _arrays()
    flat = np.frombuffer(_C.flatten(arrays), np.float32)
    ref = np.concatenate([a.ravel() for a in arrays])
    np.testing.assert_array_equal(flat, ref)
    outs = [np.zeros_like(a) for a in arrays]
    _C.unflatten_into(flat, outs)
    for o, a in zip(outs, arrays):
        np.testing.assert_array_equal(o, a)


@needs_ext
def test_native_mixed_dtype_bytes():
    # the C layer is dtype-agnostic (byte-level), like flatten_dense_tensors
    # per dtype-group callers
    a = np.arange(4, dtype=np.float32)
    b = np.arange(4, dtype=np.int64)
    flat = bytes(_C.flatten([a, b]))
    assert flat == a.tobytes() + b.tobytes()


@needs_ext
def test_native_unflatten_overrun_rejected():
    flat = np.zeros(4, np.float32)
    with pytest.raises(ValueError, match="bytes"):
        _C.unflatten_into(flat, [np.zeros(8, np.float32)])


@needs_ext
def test_native_rejects_non_buffer():
    with pytest.raises(TypeError):
        _C.flatten([object()])


@pytest.mark.parametrize("force_fallback", [False, True])
def test_host_flatten_parity(monkeypatch, force_fallback):
    if force_fallback:
        monkeypatch.setattr(pytree, "_native", None)
    elif _C is None:
        pytest.skip("ext not built")
    arrays = _arrays()
    flat = pytree.host_flatten(arrays)
    np.testing.assert_array_equal(
        flat, np.concatenate([a.ravel() for a in arrays]))
    outs = [np.zeros_like(a) for a in arrays]
    pytree.host_unflatten_into(flat, outs)
    for o, a in zip(outs, arrays):
        np.testing.assert_array_equal(o, a)


def test_host_flatten_mixed_dtype_rejected():
    with pytest.raises(ValueError, match="mixed"):
        pytree.host_flatten([np.zeros(2, np.float32),
                             np.zeros(2, np.float64)])


def test_host_unflatten_requires_writable():
    flat = np.arange(4, dtype=np.float32)
    out = np.zeros(4, np.float32)
    out.flags.writeable = False
    with pytest.raises(ValueError, match="writable"):
        pytree.host_unflatten_into(flat, [out])


def test_host_flatten_empty():
    assert pytree.host_flatten([]).size == 0
