"""Quantized KV cache — int8 per-head-scale storage, hermetic.

The acceptance bar from the quantized-cache issue, as tests:

- **calibration guard**: an absmax of 0 or a non-finite absmax raises
  LOUDLY at engine construction (degenerate scales must never surface
  later as NaN output), and the quantize/dequant round-trip error is
  bounded by ``scale / 2`` at representative absmax ranges;
- **dequant-in-kernel**: the four attention kernels' int8 paths match
  the jnp gather-dequant oracles (the PR 6 oracle pattern, lifted to
  the quantized tier);
- **composition** is the point: greedy token-match-rate >= threshold
  vs the bf16 oracle across a prefix hit/miss/evict stream, the paged
  and contiguous quantized engines token-exact against EACH OTHER
  (same quantization, indirected storage), COW prefix sharing over
  quantized pages with no scale copies, speculative verify token-exact
  plain-vs-spec ON the quantized engine (accept-longest-prefix emits
  the program's own greedy targets — quantization moves both sides
  identically), and a tp=1 mesh bitwise vs the unsharded quantized
  engine (tp=2 slow-marked, per the PR 5 pattern);
- **the bf16 default stays the bitwise baseline**: ``kv_quant=None``
  builds a scale-less cache, compiles the same pinned program set, and
  none of the quant code is on its trace path (two default engines
  serve a greedy stream token-identically);
- **capacity accounting**: int8 halves ``cache.nbytes()`` and the
  ``serving.kv.bytes_per_token`` gauge at identical geometry.

Everything runs on CPU with a tiny model at policy O0 (exact fp32
compute — the match-rate tolerance isolates QUANTIZATION error, not
bf16 rounding); the kernels take their interpret/reference paths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.kernels.decode_attention import (
    decode_attention, decode_attention_reference, paged_decode_attention,
    paged_decode_attention_reference)
from apex_tpu.kernels.prefill_attention import (
    paged_prefill_attention, paged_prefill_attention_reference,
    prefill_attention, prefill_attention_reference)
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, KVQuantConfig, Request, Scheduler,
                              SpecConfig)
from apex_tpu.serving.kv_quant import QMAX, dequantize, quantize

pytestmark = pytest.mark.serving

VOCAB = 96          # divisible by the tp sizes under test (1, 2)
CHUNK = 8
# the tolerance of the issue's token-match contract at tiny-model
# scale: a single early argmax flip diverges a request's whole greedy
# tail, so the bound is deliberately below the bench-scale 0.99 claim
MATCH_THRESHOLD = 0.95


def _tiny_lm(**kw):
    return TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                         num_heads=4, max_seq_len=64, **kw)


@pytest.fixture(scope="module")
def lm_and_params():
    m = _tiny_lm()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, kv_quant=None, paged=True, pool=2,
               slots=3, seed=5, **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=64, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=pool, paged=paged,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  kv_quant=kv_quant, **kw)


@pytest.fixture(scope="module")
def engine_trio(lm_and_params):
    """bf16(O0) oracle + paged-int8 + contiguous-int8, identical
    geometry — the match-rate triple (jit caches warm across the
    module)."""
    return (_mk_engine(lm_and_params),
            _mk_engine(lm_and_params, kv_quant=KVQuantConfig()),
            _mk_engine(lm_and_params, kv_quant=KVQuantConfig(),
                       paged=False))


def _shared_prefix_stream(seed, n=8, new_tokens=8):
    """Prefix hit/miss/evict shape: every prompt opens with one shared
    16-token (2-page) prefix plus a short unique tail."""
    rng = np.random.default_rng(seed)
    pre = list(rng.integers(1, VOCAB, size=16))
    reqs = []
    for _ in range(n):
        tail = list(rng.integers(1, VOCAB,
                                 size=int(rng.integers(1, 7))))
        reqs.append(Request(prompt=pre + tail,
                            max_new_tokens=new_tokens))
    return reqs


def _serve(engine, seed, **sched_kw):
    engine.reset(clear_prefixes=True)
    sched = Scheduler(engine, retain_prefixes=True, **sched_kw)
    reqs = _shared_prefix_stream(seed)
    sched.run(reqs)
    return [list(r.output_tokens) for r in reqs]


def _match_rate(a_lists, b_lists):
    tot = hit = 0
    for a, b in zip(a_lists, b_lists):
        assert len(a) == len(b)
        tot += len(a)
        hit += sum(int(x == y) for x, y in zip(a, b))
    return hit / tot if tot else 1.0


# ------------------------------------------------------ config + round-trip
def test_config_validation():
    with pytest.raises(ValueError, match="int8"):
        KVQuantConfig(dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="granularity"):
        KVQuantConfig(scale_granularity="page")
    with pytest.raises(ValueError, match="margin"):
        KVQuantConfig(margin=0.0)
    with pytest.raises(ValueError, match="margin"):
        KVQuantConfig(margin=float("nan"))
    with pytest.raises(ValueError, match="calibration_len"):
        KVQuantConfig(calibration_len=0)


@pytest.mark.parametrize("absmax", [1e-3, 0.25, 1.0, 100.0])
def test_quantize_roundtrip_error_bound(absmax):
    """The int8 tier's accuracy floor, pinned per absmax range: for
    in-range inputs the round-trip error is <= scale / 2 per element
    (symmetric round-to-nearest on a uniform grid), and out-of-range
    inputs clip to the representable absmax."""
    rng = np.random.default_rng(3)
    h = 4
    scale = np.full(h, absmax / QMAX, np.float32)
    x = jnp.asarray(rng.uniform(-absmax, absmax, size=(2, h, 16)),
                    jnp.float32)
    q = quantize(x, scale, axis=1)
    assert q.dtype == jnp.int8
    back = dequantize(q, scale, axis=1)
    bound = absmax / QMAX / 2
    assert float(jnp.max(jnp.abs(back - x))) <= bound * (1 + 1e-6)
    # clipping: 2x the range lands exactly at the grid edge
    over = jnp.full((1, h, 1), 2 * absmax, jnp.float32)
    qo = quantize(over, scale, axis=1)
    assert int(jnp.max(qo)) == QMAX
    np.testing.assert_allclose(np.asarray(dequantize(qo, scale, axis=1)),
                               absmax, rtol=1e-5)


def test_degenerate_calibration_raises_at_construction(lm_and_params):
    """The calibration guard satellite: absmax 0 / NaN / negative must
    be a LOUD engine-construction error, never NaN output later."""
    for bad in (0.0, float("nan"), float("inf"), -1.0):
        with pytest.raises(ValueError, match="degenerate"):
            _mk_engine(lm_and_params,
                       kv_quant=KVQuantConfig(calibration_absmax=bad))
    # one bad head inside an otherwise-fine array is still loud
    absmax = np.ones((2, 4), np.float32)
    absmax[1, 2] = 0.0
    with pytest.raises(ValueError, match=r"layer=1, head=2"):
        _mk_engine(lm_and_params,
                   kv_quant=KVQuantConfig(calibration_absmax=absmax))
    # an explicit positive absmax (scalar or (k, v) pair) constructs
    eng = _mk_engine(lm_and_params,
                     kv_quant=KVQuantConfig(calibration_absmax=(2.0,
                                                                3.0)))
    assert float(jnp.max(eng.cache.v_scale)) > \
        float(jnp.max(eng.cache.k_scale))


def test_kv_quant_type_and_tokens_validation(lm_and_params):
    with pytest.raises(TypeError, match="KVQuantConfig"):
        _mk_engine(lm_and_params, kv_quant="int8")
    with pytest.raises(ValueError, match="calibration_tokens"):
        _mk_engine(lm_and_params,
                   kv_quant=KVQuantConfig(calibration_tokens=[]))


# ------------------------------------------------- kernels vs dequant oracle
def test_quantized_kernels_match_gather_dequant_oracles():
    """All four attention kernels' int8 dequant-in-kernel paths vs the
    jnp gather-dequant oracles (the PR 6 oracle pattern)."""
    rng = np.random.default_rng(0)
    B, h, L, d, C = 2, 4, 256, 16, 16
    NP_, PL, MAXP = 5, 128, 2
    q1 = jnp.asarray(rng.standard_normal((B, h, d)), jnp.float32)
    qc = jnp.asarray(rng.standard_normal((B, h, C, d)), jnp.float32)
    k8 = jnp.asarray(rng.integers(-QMAX, QMAX + 1, size=(B, h, L, d)),
                     jnp.int8)
    v8 = jnp.asarray(rng.integers(-QMAX, QMAX + 1, size=(B, h, L, d)),
                     jnp.int8)
    kp = jnp.asarray(rng.integers(-QMAX, QMAX + 1, size=(NP_, h, PL, d)),
                     jnp.int8)
    vp = jnp.asarray(rng.integers(-QMAX, QMAX + 1, size=(NP_, h, PL, d)),
                     jnp.int8)
    pt = jnp.asarray(rng.integers(0, NP_, size=(B, MAXP)), jnp.int32)
    ks = jnp.asarray(rng.uniform(0.01, 0.05, size=h), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.05, size=h), jnp.float32)
    lens = jnp.asarray([37, 256], jnp.int32)
    offs = jnp.asarray([0, 200], jnp.int32)
    plens = jnp.asarray([5, 130], jnp.int32)
    poffs = jnp.asarray([0, 100], jnp.int32)
    cases = [
        (decode_attention(q1, k8, v8, lens, k_scale=ks, v_scale=vs),
         decode_attention_reference(q1, k8, v8, lens, scale=1 / d ** 0.5,
                                    k_scale=ks, v_scale=vs)),
        (prefill_attention(qc, k8, v8, offs, k_scale=ks, v_scale=vs),
         prefill_attention_reference(qc, k8, v8, offs,
                                     scale=1 / d ** 0.5, k_scale=ks,
                                     v_scale=vs)),
        (paged_decode_attention(q1, kp, vp, pt, plens, k_scale=ks,
                                v_scale=vs, interpret=True),
         paged_decode_attention_reference(q1, kp, vp, pt, plens,
                                          scale=1 / d ** 0.5,
                                          k_scale=ks, v_scale=vs)),
        (paged_prefill_attention(qc, kp, vp, pt, poffs, k_scale=ks,
                                 v_scale=vs, interpret=True),
         paged_prefill_attention_reference(qc, kp, vp, pt, poffs,
                                           scale=1 / d ** 0.5,
                                           k_scale=ks, v_scale=vs)),
    ]
    for out, ref in cases:
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
    # a lone scale is a caller bug, named loudly
    with pytest.raises(ValueError, match="together"):
        decode_attention(q1, k8, v8, lens, k_scale=ks)
    with pytest.raises(ValueError, match="per head"):
        paged_decode_attention(q1, kp, vp, pt, plens, k_scale=ks[:2],
                               v_scale=vs[:2])


# ------------------------------------------------------------- composition
def test_quantized_token_match_vs_bf16_oracle_over_hit_miss_evict(
        engine_trio):
    """THE composition pin: the quantized engines serve the prefix
    hit/miss/evict stream at greedy token-match-rate >= threshold vs
    the bf16 oracle, and paged-int8 is token-EXACT vs contiguous-int8
    (same quantization, indirected storage — the PR 6 parity argument,
    one tier down)."""
    oracle, quant_paged, quant_contig = engine_trio
    out_o = _serve(oracle, seed=42)
    out_p = _serve(quant_paged, seed=42)
    out_c = _serve(quant_contig, seed=42)
    rate = _match_rate(out_o, out_p)
    assert rate >= MATCH_THRESHOLD, \
        f"quantized token-match-rate {rate:.3f} vs bf16 oracle"
    assert out_p == out_c, \
        "paged and contiguous int8 engines diverged — quantization " \
        "must be a storage property, not a layout property"
    # halved storage at identical geometry
    assert quant_paged.cache.nbytes() * 2 <= oracle.cache.nbytes()


def test_cow_prefix_sharing_shares_quantized_pages(engine_trio):
    """COW composition: a prefix hit on the quantized engine shares
    int8 pages by refcount bump (zero data movement, zero scale
    copies — scales are per-head engine state, not per-page), and the
    hit request's tokens match the cold miss path token-for-token
    (shared bytes are byte-identical to freshly written bytes)."""
    _, eq, _ = engine_trio
    eq.reset(clear_prefixes=True)
    sched = Scheduler(eq, retain_prefixes=True)
    rng = np.random.default_rng(9)
    pre = list(rng.integers(1, VOCAB, size=8))      # exactly one page
    tail = list(rng.integers(1, VOCAB, size=3))
    (miss,) = sched.run([Request(prompt=pre + tail, max_new_tokens=4)])
    assert miss.reused_tokens == 0
    stats = eq.pool_stats()
    assert stats["pages_in_use"] == 1 and stats["cow_shares"] == 0
    (hit,) = sched.run([Request(prompt=pre + tail, max_new_tokens=4)])
    assert hit.reused_tokens == 8
    assert hit.output_tokens == miss.output_tokens
    # the scale arrays are the ENGINE's two [layers, heads] tensors —
    # sharing pages allocated no per-page scale state
    assert eq.cache.k_scale.shape == (2, 4)
    assert eq.cache.v_scale.shape == (2, 4)


def test_speculative_verify_is_token_exact_on_the_quantized_engine(
        lm_and_params):
    """Speculative composition: ON the quantized engine, spec-vs-plain
    stays token-exact (the verify program's emitted tokens ARE its own
    greedy targets, so quantization moves both modes identically) with
    real drafts accepted, and rollback stays length arithmetic — no
    scale state to unwind."""
    eng = _mk_engine(lm_and_params, kv_quant=KVQuantConfig(),
                     spec=SpecConfig(draft_len=3, ngram=2))
    rng = np.random.default_rng(7)
    hist = list(rng.integers(1, VOCAB, size=10))

    def stream(r):
        reqs = []
        for _ in range(4):
            tail = list(r.integers(1, VOCAB, size=3))
            reqs.append(Request(prompt=(hist + tail + tail)[:24],
                                max_new_tokens=10))
        return reqs

    outs, accepted = {}, {}
    for mode, sp in (("plain", False), ("spec", True)):
        eng.reset(clear_prefixes=True)
        sched = Scheduler(eng, speculative=sp)
        reqs = stream(np.random.default_rng(3))
        sched.run(reqs)
        outs[mode] = [list(r.output_tokens) for r in reqs]
        accepted[mode] = sum(r.spec_accepted for r in reqs)
    assert outs["spec"] == outs["plain"]
    assert accepted["spec"] > 0, "drafter never fired — the exactness " \
        "pin proved nothing"
    # quantization adds no program: 3 paged + 1 lazy verify
    assert eng.compiled_programs == eng.chunk_traces \
        + eng.decode_traces + eng.verify_traces
    assert eng.verify_traces == 1


def test_tp1_mesh_is_bitwise_vs_unsharded_quantized_engine(
        lm_and_params):
    """Tensor-parallel composition (tier-1 half): a 1-device mesh over
    the quantized engine — scales sharded along heads next to the pool
    — serves the greedy stream BITWISE identical to the unsharded
    quantized engine, the same pin the bf16 tier carries."""
    if len(jax.devices()) < 1:        # pragma: no cover
        pytest.skip("needs a device")
    from jax.sharding import Mesh

    e0 = _mk_engine(lm_and_params, kv_quant=KVQuantConfig(), seed=11)
    e1 = _mk_engine(lm_and_params, kv_quant=KVQuantConfig(), seed=11,
                    mesh=Mesh(np.array(jax.devices()[:1]), ("tp",)))
    assert _serve(e1, seed=21) == _serve(e0, seed=21)


@pytest.mark.slow
def test_tp2_mesh_is_token_exact_vs_unsharded_quantized_engine(
        lm_and_params):
    """Tensor-parallel composition (slow half, per the PR 5 pattern):
    tp=2 CPU device emulation over the quantized engine is token-exact
    vs the unsharded quantized engine, with the scale arrays sharded
    [layers, heads/tp] per shard."""
    from jax.sharding import Mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    e0 = _mk_engine(lm_and_params, kv_quant=KVQuantConfig(), seed=11)
    e2 = _mk_engine(lm_and_params, kv_quant=KVQuantConfig(), seed=11,
                    mesh=Mesh(np.array(jax.devices()[:2]), ("tp",)))
    assert _serve(e2, seed=23) == _serve(e0, seed=23)
    shard_shapes = {s.data.shape
                    for s in e2.cache.k_scale.addressable_shards}
    assert shard_shapes == {(2, 2)}   # [layers, heads/tp] per shard


def test_monolithic_prefill_attends_the_quant_grid(lm_and_params):
    """Ingest-path consistency: the monolithic (``return_kv``) prefill
    on a quantized engine attends K/V through the SAME storage grid
    chunked prefill writes and reads. Pinned at the model level — with
    ``kv_scales`` the returned K/V are fixed points of quantize∘
    dequantize (so the engine's storage cast is exact code recovery)
    and the logits move off the raw-precision forward — and at the
    engine level: the monolithic scheduler path token-matches the
    chunked path on one quantized engine (different executables, so
    the tolerance contract, not bitwise — same bar as the oracle
    comparison)."""
    m, params = lm_and_params
    eng = _mk_engine(lm_and_params, kv_quant=KVQuantConfig(), seed=13)
    ks, vs = eng.cache.k_scale, eng.cache.v_scale
    toks = jnp.asarray([list(range(1, 13))], jnp.int32)
    logits_q, (k_q, v_q) = m.apply({"params": params}, toks,
                                   train=False, return_kv=True,
                                   kv_scales=(ks, vs))
    sk = ks[:, None, :, None, None]
    sv = vs[:, None, :, None, None]
    for got, scale in ((k_q, sk), (v_q, sv)):
        np.testing.assert_array_equal(
            np.asarray(dequantize(quantize(got, scale), scale)),
            np.asarray(got, np.float32),
            err_msg="return_kv K/V are not on the quantization grid")
    logits_raw = m.apply({"params": params}, toks, train=False)
    assert not np.array_equal(np.asarray(logits_q),
                              np.asarray(logits_raw)), \
        "kv_scales did not engage the grid in the return_kv forward"
    # engine level: chunked vs monolithic ingestion, one quantized
    # engine, chunk-boundary prompt lengths (below/at/straddling)
    rng = np.random.default_rng(17)
    prompts = [list(rng.integers(1, VOCAB, size=n))
               for n in (5, CHUNK, 13, 21)]
    outs = {}
    for label, chunked in (("chunk", True), ("mono", False)):
        eng.reset(clear_prefixes=True)
        reqs = [Request(prompt=p, max_new_tokens=6) for p in prompts]
        Scheduler(eng, chunked=chunked).run(reqs)
        outs[label] = [list(r.output_tokens) for r in reqs]
    rate = _match_rate(outs["chunk"], outs["mono"])
    assert rate >= MATCH_THRESHOLD, \
        f"quantized chunked-vs-monolithic token-match-rate {rate:.3f}"


# ----------------------------------------------------- the bf16 default pin
def test_kv_quant_none_stays_the_bitwise_baseline_with_pinned_programs(
        lm_and_params):
    """The contract the ROADMAP states: kv_quant=None is the DEFAULT
    and the bitwise baseline. Two default engines serve the stream
    token-identically through the pinned paged program set (3 + the
    monolithic baseline = 3 total distinct executables, copy retired),
    their caches carry NO scale state, and the quantized engine
    compiles the same set — zero new programs either way."""
    a = _mk_engine(lm_and_params, seed=11)
    b = _mk_engine(lm_and_params, seed=11)
    assert a.kv_quant is None and a.cache.k_scale is None \
        and a.cache.v_scale is None
    assert _serve(a, seed=31) == _serve(b, seed=31)
    a.prefill(0, [5, 9, 2])           # the monolithic baseline compiles
    assert (a.chunk_traces, a.decode_traces, a.prefill_traces,
            a.copy_traces) == (1, 1, 1, 0)
    assert a.compiled_programs == 3
    q = _mk_engine(lm_and_params, kv_quant=KVQuantConfig(), seed=11)
    _serve(q, seed=31)
    q.prefill(0, [5, 9, 2])
    assert (q.chunk_traces, q.decode_traces, q.prefill_traces,
            q.copy_traces) == (1, 1, 1, 0)
    assert q.compiled_programs == 3


def test_kv_gauges_report_the_capacity_claim(lm_and_params):
    """serving.kv.* telemetry: bytes_per_token halves at identical
    geometry (the measurable capacity claim) and the quantized engine
    reports the representable absmax its scales encode."""
    reg_b, reg_q = telemetry.MetricsRegistry(), telemetry.MetricsRegistry()
    eb = _mk_engine(lm_and_params, registry=reg_b)
    eq = _mk_engine(lm_and_params, kv_quant=KVQuantConfig(),
                    registry=reg_q)
    gb = reg_b.snapshot()["gauges"]
    gq = reg_q.snapshot()["gauges"]
    # O0 oracle stores fp32 (4 bytes); int8 is a 4x cut there, 2x vs
    # the production bf16 default — assert the itemsize ratio exactly
    ratio = np.dtype(eb.cache.dtype).itemsize
    assert gb["serving.kv.bytes_per_token"] \
        == ratio * gq["serving.kv.bytes_per_token"]
    assert "serving.kv.quant_scale_absmax" not in gb
    assert gq["serving.kv.quant_scale_absmax"] > 0
    # swap-in registry path (warmup pattern) re-emits the gauges
    reg2 = telemetry.MetricsRegistry()
    eq.set_registry(reg2)
    assert "serving.kv.bytes_per_token" in reg2.snapshot()["gauges"]
