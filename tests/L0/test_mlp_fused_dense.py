"""apex_tpu.mlp + apex_tpu.fused_dense tests.

Mirror of the reference's tests/L0/run_mlp/test_mlp.py (MLP vs
nn.Sequential(Linear, ReLU, ...) oracle, fwd+bwd allclose) and
run_fused_dense/ (FusedDense vs composed linear+gelu reference).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.fused_dense import (DenseNoBias, FusedDense, FusedDenseGeluDense,
                                  fused_dense_function,
                                  fused_dense_gelu_dense_function)
from apex_tpu.mlp import MLP, mlp_function


def _ref_mlp(x, weights, biases, activation="relu"):
    acts = {"none": lambda v: v, "relu": jax.nn.relu,
            "sigmoid": jax.nn.sigmoid}
    y = jnp.asarray(x, jnp.float32)
    for i, w in enumerate(weights):
        y = y @ jnp.asarray(w, jnp.float32).T
        if biases is not None:
            y = y + jnp.asarray(biases[i], jnp.float32)
        y = acts[activation](y)
    return y


@pytest.mark.parametrize("activation", ["relu", "sigmoid", "none"])
def test_mlp_function_matches_composed_reference(activation):
    k = jax.random.PRNGKey(0)
    sizes = [64, 48, 32]
    ks = jax.random.split(k, 5)
    x = jax.random.normal(ks[0], (8, sizes[0]), jnp.float32)
    ws = [jax.random.normal(ks[1 + i], (sizes[i + 1], sizes[i])) * 0.1
          for i in range(2)]
    bs = [jax.random.normal(ks[3 + i], (sizes[i + 1],)) * 0.1
          for i in range(2)]
    y = mlp_function(x, ws, bs, activation)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(_ref_mlp(x, ws, bs, activation)),
                               rtol=1e-5, atol=1e-5)


def test_mlp_module_fwd_bwd():
    m = MLP(mlp_sizes=[32, 24, 16], bias=True, activation="relu")
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32), jnp.float32)
    variables = m.init(jax.random.PRNGKey(1), x)
    p = variables["params"]

    def loss(params, x):
        return jnp.sum(m.apply({"params": params}, x) ** 2)

    def ref_loss(params, x):
        ws = [params["weight_0"], params["weight_1"]]
        bs = [params["bias_0"], params["bias_1"]]
        return jnp.sum(_ref_mlp(x, ws, bs) ** 2)

    g = jax.grad(loss)(p, x)
    g_ref = jax.grad(ref_loss)(p, x)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-4, atol=1e-4),
        g, g_ref)


def test_mlp_module_no_bias_and_bf16():
    m = MLP(mlp_sizes=[32, 16], bias=False, dtype=jnp.bfloat16)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32), jnp.float32)
    variables = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(variables, x)
    assert y.dtype == jnp.bfloat16
    ref = _ref_mlp(x.astype(jnp.bfloat16).astype(jnp.float32),
                   [variables["params"]["weight_0"]], None)
    np.testing.assert_allclose(np.asarray(y, dtype=np.float32),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_mlp_validates_args():
    with pytest.raises(ValueError):
        mlp_function(jnp.ones((2, 4)), [jnp.ones((4, 4))], None, "tanh")
    m = MLP(mlp_sizes=[8])
    with pytest.raises(ValueError):
        m.init(jax.random.PRNGKey(0), jnp.ones((2, 8)))


def test_fused_dense_matches_linear():
    k = jax.random.PRNGKey(2)
    x = jax.random.normal(k, (6, 20), jnp.float32)
    m = FusedDense(in_features=20, out_features=12)
    variables = m.init(jax.random.PRNGKey(3), x)
    y = m.apply(variables, x)
    w = variables["params"]["weight"]
    b = variables["params"]["bias"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w.T + b),
                               rtol=1e-5, atol=1e-5)


def test_dense_no_bias():
    x = jax.random.normal(jax.random.PRNGKey(4), (3, 10), jnp.float32)
    m = DenseNoBias(in_features=10, out_features=5)
    variables = m.init(jax.random.PRNGKey(5), x)
    assert "bias" not in variables["params"]
    y = m.apply(variables, x)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(x @ variables["params"]["weight"].T),
        rtol=1e-5, atol=1e-5)


def test_fused_dense_gelu_dense_matches_composed():
    k = jax.random.PRNGKey(6)
    x = jax.random.normal(k, (5, 16), jnp.float32)
    m = FusedDenseGeluDense(in_features=16, intermediate_features=32,
                            out_features=8)
    variables = m.init(jax.random.PRNGKey(7), x)
    p = variables["params"]
    y = m.apply(variables, x)
    h = x @ p["weight1"].T + p["bias1"]
    h = jax.nn.gelu(h, approximate=False)
    ref = h @ p["weight2"].T + p["bias2"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    # backward also matches the composed reference
    def loss(params):
        return jnp.sum(m.apply({"params": params}, x) ** 2)

    def ref_loss(params):
        h = x @ params["weight1"].T + params["bias1"]
        h = jax.nn.gelu(h, approximate=False)
        return jnp.sum((h @ params["weight2"].T + params["bias2"]) ** 2)

    g, g_ref = jax.grad(loss)(p), jax.grad(ref_loss)(p)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-4, atol=1e-4),
        g, g_ref)


def test_functional_forms_half_io():
    x = jax.random.normal(jax.random.PRNGKey(8), (4, 8), jnp.bfloat16)
    w1 = jax.random.normal(jax.random.PRNGKey(9), (16, 8), jnp.float32) * 0.2
    b1 = jnp.zeros((16,))
    w2 = jax.random.normal(jax.random.PRNGKey(10), (4, 16), jnp.float32) * 0.2
    b2 = jnp.zeros((4,))
    y = fused_dense_gelu_dense_function(x, w1, b1, w2, b2)
    assert y.dtype == jnp.bfloat16
    y1 = fused_dense_function(x, w1, b1)
    assert y1.dtype == jnp.bfloat16
