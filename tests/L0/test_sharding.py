"""Tensor-parallel serving: the Engine(mesh=...) acceptance pins.

The bars from the tensor-parallel issue, as tests:

- **the tp=1 bitwise pin** (tier-1): ``Engine(mesh=<1-device>)`` serves
  a greedy stream — prefix hit/miss/evict, warm reset, speculative
  verify — BITWISE token-identical to the verbatim ``mesh=None``
  single-chip baseline (the sharded programs over one device must be
  the same serving engine, not a numerically-adjacent cousin);
- **the tp>1 parity pin** (slow — CPU device emulation): the same
  stream over a 2-shard mesh is token-exact vs the baseline, with the
  pool provably heads-sharded and per-shard HBM halved;
- **the collective pin** (slow): compiled HLO of the sharded decode /
  chunk-prefill / verify programs schedules EXACTLY
  ``2 * num_layers`` all-reduces (the two canonical Megatron psums per
  block: post-attention projection, post-MLP down-projection) plus
  ONE all-gather (the sampled logits rows' vocab/tp slices rejoined)
  — attention contributes zero collectives because the pool shards
  along heads (:func:`serving.sharding.expected_collectives`);
- **rule-table units**: ``match_partition_rules`` assigns every
  TransformerLM leaf a spec (column/row/replicated per the Megatron
  split), ``shard_params`` hands each shard head-grouped qkv slices
  and 1/tp-scaled row biases;
- **mesh lifecycle**: heads/vocab/MLP-inner divisibility rejected at
  construction, contiguous+mesh rejected, 2-D meshes rejected; warm
  ``reset()`` keeps retained prefixes valid per shard (hits after the
  reset, tokens bitwise vs the cold pass);
- **compiled-programs + trace discipline**: a sharded engine keeps the
  paged pin (3 programs + 1 lazy verify), shard_map adds no hidden
  retraces.

The whole suite is hermetic on the 8-virtual-device CPU backend
(tests/conftest.py); the multi-device (tp=2) tests carry the ``slow``
marker to hold the tier-1 wall-time budget, exactly like the other
multi-device files.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, Request, Scheduler, SpecConfig,
                              sharding)

pytestmark = pytest.mark.serving

VOCAB = 96          # divisible by the tp sizes under test (1, 2, 4)
CHUNK = 8
K = 3


def _tiny_lm(**kw):
    return TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                         num_heads=4, max_seq_len=128, **kw)


@pytest.fixture(scope="module")
def lm_and_params():
    m = _tiny_lm()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mesh(n: int) -> Mesh:
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} virtual devices")
    return Mesh(np.array(devs[:n]), ("tp",))


def _mk_engine(lm_and_params, *, mesh=None, slots=3, seed=5,
               prefix_pool=2, spec=True, **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=128, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=prefix_pool,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  spec=SpecConfig(draft_len=K, ngram=2) if spec else None,
                  mesh=mesh, **kw)


def _stream_reqs(seed=42):
    """Prompt lengths below/at/straddling chunk boundaries; a shared
    leading block so retention produces real hits on the second pass."""
    rng = np.random.default_rng(seed)
    shared = list(rng.integers(1, VOCAB, size=CHUNK))
    reqs = []
    for n, b in [(5, 16), (CHUNK, 12), (13, 10), (21, 8)]:
        tail = list(rng.integers(1, VOCAB, size=max(1, n - CHUNK)))
        prompt = (shared + tail)[:n] if n > CHUNK else \
            list(rng.integers(1, VOCAB, size=n))
        reqs.append(Request(prompt=prompt, max_new_tokens=b))
    return reqs


def _serve_stream(eng, registry=None):
    """The acceptance stream: two retained-prefix speculative passes
    (pass 1 registers — misses; pass 2 hits), an LRU eviction between
    them, and a warm reset — hit/miss/evict + speculative, exactly the
    greedy stream the tp=1 pin names. Returns every request's tokens in
    order."""
    out = []
    for window in range(2):
        reqs = _stream_reqs()
        Scheduler(eng, registry=registry, retain_prefixes=True,
                  speculative=True).run(reqs)
        out.append([list(r.output_tokens) for r in reqs])
        if window == 0 and eng.prefix_cache is not None:
            # exercise the evict path identically on every engine under
            # comparison, then re-register on the next pass
            eng.prefix_cache.evict_lru()
        eng.reset()     # warm: retained prefixes survive
    return out


# ------------------------------------------------------------- rule table
def test_match_partition_rules_covers_the_tree(lm_and_params):
    m, params = lm_and_params
    specs = sharding.match_partition_rules(
        sharding.partition_rules("tp"), params)
    flat = {"/".join(str(getattr(k, "key", k)) for k in path): spec
            for path, spec in jax.tree_util.tree_flatten_with_path(
                specs)[0]}
    assert flat["block_0/attn/qkv/kernel"] == P(None, "tp")
    assert flat["block_0/attn/qkv/bias"] == P("tp")
    assert flat["block_0/attn/proj/kernel"] == P("tp", None)
    assert flat["block_0/attn/proj/bias"] == P()
    assert flat["block_1/mlp_in/kernel"] == P(None, "tp")
    assert flat["block_1/mlp_out/kernel"] == P("tp", None)
    # replicated tail: embeddings, positional table, every LayerNorm
    assert flat["wte/embedding"] == P()
    assert flat["wpe"] == P()
    assert flat["block_0/ln_attn/scale"] == P()
    assert flat["ln_f/bias"] == P()
    assert jax.tree_util.tree_structure(specs) \
        == jax.tree_util.tree_structure(params)


def test_match_partition_rules_requires_a_match():
    rules = ((r"attn/qkv/kernel$", P(None, "tp")),)   # no catch-all
    with pytest.raises(ValueError, match="no partition rule"):
        sharding.match_partition_rules(
            rules, {"mlp_out": {"kernel": np.zeros((4, 4))}})


def test_shard_params_shapes_and_values(lm_and_params):
    """tp=2 placement: column splits halve output features, row splits
    halve input features, qkv shards are head-grouped (each shard owns
    its heads' Q AND K AND V), row-parallel biases are value-scaled by
    1/tp so the in-program psum restores them exactly once."""
    m, params = lm_and_params
    mesh = _mesh(2)
    sharded = sharding.shard_params(params, mesh, num_heads=4)
    b0 = sharded["block_0"]
    qkv = b0["attn"]["qkv"]["kernel"]
    assert qkv.shape == (32, 96)        # global shape unchanged
    shards = {s.index[1].start or 0: np.asarray(s.data)
              for s in qkv.addressable_shards}
    assert all(x.shape == (32, 48) for x in shards.values())
    # head-grouped: shard 0's slice is the full kernel's (3, heads 0-1,
    # d) block, not its first 48 contiguous columns
    full = np.asarray(params["block_0"]["attn"]["qkv"]["kernel"])
    want0 = full.reshape(32, 3, 4, 8)[:, :, :2, :].reshape(32, 48)
    np.testing.assert_array_equal(shards[0], want0)
    want1 = full.reshape(32, 3, 4, 8)[:, :, 2:, :].reshape(32, 48)
    np.testing.assert_array_equal(shards[48], want1)
    proj = b0["attn"]["proj"]
    assert [s.data.shape for s in
            proj["kernel"].addressable_shards] == [(16, 32)] * 2
    # row-parallel bias: replicated, scaled 1/tp
    np.testing.assert_allclose(
        np.asarray(proj["bias"].addressable_shards[0].data),
        np.asarray(params["block_0"]["attn"]["proj"]["bias"]) / 2)
    mlp_in = b0["mlp_in"]["kernel"]
    assert [s.data.shape for s in mlp_in.addressable_shards] \
        == [(32, 64)] * 2
    # replicated leaves: every shard holds the full value, untouched
    wte = sharded["wte"]["embedding"]
    np.testing.assert_array_equal(
        np.asarray(wte.addressable_shards[0].data),
        np.asarray(params["wte"]["embedding"]))


def test_expected_collectives_inventory():
    assert sharding.expected_collectives(6) \
        == {"all_reduce": 12, "all_gather": 1}


# --------------------------------------------------------- mesh lifecycle
def test_engine_mesh_validation(lm_and_params):
    m, params = lm_and_params
    kw = dict(slots=2, max_len=64, prefill_len=16, chunk_len=8,
              policy=resolve_policy("O0", verbose=False))
    # heads not divisible by tp (4 heads over 8 shards)
    with pytest.raises(ValueError, match="not divisible"):
        Engine(m, params, mesh=_mesh(8), **kw)
    # contiguous layout cannot shard
    with pytest.raises(ValueError, match="paged=True"):
        Engine(m, params, mesh=_mesh(2), paged=False, **kw)
    # 2-D meshes are a configuration error
    devs = jax.devices()
    mesh2d = Mesh(np.array(devs[:4]).reshape(2, 2), ("tp", "dp"))
    with pytest.raises(ValueError, match="1-D"):
        Engine(m, params, mesh=mesh2d, **kw)
    # vocab not divisible by tp
    m_odd = TransformerLM(vocab_size=97, hidden=32, num_layers=1,
                          num_heads=4, max_seq_len=64)
    p_odd = m_odd.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, 4), jnp.int32),
                       train=False)["params"]
    with pytest.raises(ValueError, match="vocab_size"):
        Engine(m_odd, p_odd, mesh=_mesh(2), **kw)


def test_tp_geometry_validation_units():
    sharding.validate_tp_geometry(2, num_heads=4, hidden=32, mlp_ratio=4,
                                  vocab_size=96)
    with pytest.raises(ValueError, match="num_heads"):
        sharding.validate_tp_geometry(3, num_heads=4, hidden=32,
                                      mlp_ratio=4, vocab_size=96)
    with pytest.raises(ValueError, match="vocab"):
        sharding.validate_tp_geometry(4, num_heads=4, hidden=32,
                                      mlp_ratio=4, vocab_size=98)
    with pytest.raises(ValueError, match=">= 1"):
        sharding.validate_tp_geometry(0, num_heads=4, hidden=32,
                                      mlp_ratio=4, vocab_size=96)
    with pytest.raises(ValueError, match="1-D"):
        devs = jax.devices()
        sharding.tp_axis_of(Mesh(np.array(devs[:4]).reshape(2, 2),
                                 ("a", "b")))


# ------------------------------------------------------- the tp=1 pin
def test_tp1_mesh_bitwise_vs_unsharded(lm_and_params):
    """THE tier-1 acceptance pin: a 1-device mesh runs the SHARDED
    programs (shard_map, rule-table param placement, vocab-parallel
    head + gather) and must reproduce the verbatim mesh=None baseline
    BITWISE on a greedy stream exercising prefix hit/miss/evict, warm
    reset and speculative verify."""
    base_eng = _mk_engine(lm_and_params)
    base = _serve_stream(base_eng)
    eng = _mk_engine(lm_and_params, mesh=_mesh(1))
    assert eng.tp == 1 and eng.mesh is not None
    got = _serve_stream(eng)
    assert got == base, "tp=1 mesh diverged from the mesh=None baseline"
    # the sharded engine keeps the paged compiled-programs discipline
    assert eng.chunk_traces == 1
    assert eng.decode_traces == 1
    assert eng.verify_traces == 1
    assert eng.prefill_traces == 0      # scheduler streams never use it
    assert eng.copy_traces == 0


def test_sharded_warm_reset_keeps_prefixes_valid(lm_and_params):
    """Mesh lifecycle satellite: retained prefixes survive a sharded
    warm reset — the second pass HITS (zero-copy page shares into the
    sharded pool) and its tokens are bitwise the first pass's (the
    hit-vs-cold guarantee, per shard)."""
    eng = _mk_engine(lm_and_params, mesh=_mesh(1))
    reg = telemetry.MetricsRegistry()
    # serve, warm-reset, serve the same prompts: pass 2 must hit
    reqs1 = _stream_reqs()
    Scheduler(eng, retain_prefixes=True, speculative=True).run(reqs1)
    eng.reset()                         # warm: prefixes survive
    reqs2 = _stream_reqs()
    Scheduler(eng, registry=reg, retain_prefixes=True,
              speculative=True).run(reqs2)
    snap = reg.snapshot()
    assert snap["counters"].get("serving.prefix.hits", 0) > 0, \
        "warm reset dropped the retained prefixes"
    got1 = [list(r.output_tokens) for r in reqs1]
    got2 = [list(r.output_tokens) for r in reqs2]
    assert got1 == got2, "a prefix hit changed tokens on the sharded " \
        "engine — per-shard K/V reuse is not byte-identical"
    assert sum(r.reused_tokens for r in reqs2) > 0


def test_tp_gauges_emitted(lm_and_params):
    """The serving.tp.* telemetry family: shard count, per-program
    collective inventory (the HLO pin's numbers), per-shard pool
    gauges. Single-chip engines emit none of it."""
    reg = telemetry.MetricsRegistry()
    eng = _mk_engine(lm_and_params, mesh=_mesh(1), registry=reg)
    g = reg.snapshot()["gauges"]
    assert g["serving.tp.shards"] == 1.0
    assert g["serving.tp.psums_per_program"] == 4.0     # 2 blocks x 2
    assert g["serving.tp.all_gathers_per_program"] == 1.0
    assert g["serving.tp.hbm_bytes_per_shard"] \
        == eng.cache.nbytes() / eng.tp
    assert g["serving.tp.pool_pages_per_shard"] == float(eng.num_pages)
    reg2 = telemetry.MetricsRegistry()
    _mk_engine(lm_and_params, registry=reg2, spec=False)
    assert not any(k.startswith("serving.tp.")
                   for k in reg2.snapshot()["gauges"])


def test_model_requires_tp_fields(lm_and_params):
    """A model without the tp_axis/tp_size contract is rejected loudly
    at construction, not with a shape error inside the first trace."""

    class NoTP:
        hidden, num_heads, num_layers, max_seq_len = 32, 4, 2, 128
        vocab_size = VOCAB

        def clone(self, **kw):
            raise TypeError("unexpected fields")

    _, params = lm_and_params
    with pytest.raises(TypeError, match="tp_axis"):
        Engine(NoTP(), params, slots=2, max_len=64, prefill_len=16,
               mesh=_mesh(1))


# ------------------------------------------------ multi-device (slow tier)
@pytest.mark.slow
def test_tp2_token_exact_vs_unsharded(lm_and_params):
    """The tp>1 parity pin (CPU device emulation): the full acceptance
    stream — hit/miss/evict, warm reset, speculative — over a 2-shard
    mesh is token-exact vs the single-chip baseline, the pool is
    provably heads-sharded (each shard holds heads/tp of every page),
    and the trace discipline is unchanged."""
    base = _serve_stream(_mk_engine(lm_and_params))
    mesh = _mesh(2)
    eng = _mk_engine(lm_and_params, mesh=mesh)
    assert eng.tp == 2
    # heads-sharded pool: global shape keeps all 4 heads, each shard
    # holds 2 — per-shard HBM is half the pool
    assert eng.cache.k.shape[2] == 4
    shard_shapes = {s.data.shape for s in eng.cache.k.addressable_shards}
    assert shard_shapes == {(2, eng.num_pages, 2, eng.page_len, 8)}
    got = _serve_stream(eng)
    assert got == base, "tp=2 diverged from the single-chip baseline"
    assert (eng.chunk_traces, eng.decode_traces, eng.verify_traces) \
        == (1, 1, 1)


@pytest.mark.slow
def test_tp2_collective_counts_from_hlo(lm_and_params):
    """The scheduled-HLO certificate: each sharded program compiles
    EXACTLY expected_collectives(num_layers) — 2 psums per block
    (post-attention, post-MLP) + 1 all-gather at the sampled logits.
    Attention adds nothing (heads-sharded pool). A fresh engine is used
    because .lower() re-traces (the shared engines' trace pins must not
    see it)."""
    eng = _mk_engine(lm_and_params, mesh=_mesh(2), prefix_pool=0,
                     seed=0)
    want = sharding.expected_collectives(2)     # 2-layer tiny model

    def counts(txt):
        return {"all_reduce": len(re.findall(r"= \S+ all-reduce\(",
                                             txt)),
                "all_gather": len(re.findall(r"= \S+ all-gather\(",
                                             txt))}

    key = jax.random.PRNGKey(0)
    mp = eng.max_pages
    decode = eng._jit_decode.lower(
        eng.params, eng.cache, jnp.zeros(3, jnp.int32),
        jnp.zeros((3, mp), jnp.int32), jnp.zeros(3, jnp.int32),
        jnp.zeros(3, jnp.float32), jnp.zeros(3, jnp.float32),
        key).compile().as_text()
    assert counts(decode) == want, "decode collectives drifted"
    chunk = eng._jit_chunk.lower(
        eng.params, eng.cache, jnp.zeros((1, CHUNK), jnp.int32),
        jnp.zeros((1, mp), jnp.int32), np.int32(0), np.int32(CHUNK),
        np.float32(0), np.float32(0), key).compile().as_text()
    assert counts(chunk) == want, "chunk-prefill collectives drifted"
    verify = eng._jit_verify.lower(
        eng.params, eng.cache, jnp.zeros((3, K + 1), jnp.int32),
        jnp.zeros((3, mp), jnp.int32), jnp.zeros(3, jnp.int32),
        jnp.zeros(3, jnp.int32),
        jnp.zeros(3, jnp.float32)).compile().as_text()
    assert counts(verify) == want, "verify collectives drifted"
    prefill = eng._jit_prefill.lower(
        eng.params, eng.cache, jnp.zeros((1, 24), jnp.int32),
        jnp.zeros((1, mp), jnp.int32), np.int32(4), np.float32(0),
        key).compile().as_text()
    assert counts(prefill) == want, "monolithic prefill collectives " \
        "drifted"


@pytest.mark.slow
def test_tp2_verify_batch_matches_sequential(lm_and_params):
    """Batched-verify satellite, composed with the mesh: one
    [slots, K+1] call over two verifying slots emits bitwise the same
    tokens as two sequential single-slot verify_step calls through the
    same executable — on a 2-shard engine."""
    eng = _mk_engine(lm_and_params, mesh=_mesh(2), prefix_pool=0)
    prompts = {0: [3, 17, 91, 42, 8], 1: [7, 7, 9, 7, 7, 9, 2]}
    drafts = {0: [5, 9, 1], 1: [7, 9, 2]}

    def prep():
        eng.reset()
        return {s: eng.prefill_chunked(s, p)
                for s, p in prompts.items()}

    first = prep()
    toks_b, acc_b = eng.verify_batch(
        {s: (first[s], drafts[s]) for s in prompts})
    first = prep()
    seq = {s: eng.verify_step(s, first[s], drafts[s], len(prompts[s]))
           for s in prompts}
    for s in prompts:
        assert int(acc_b[s]) == seq[s][1]
        assert toks_b[s].tolist() == seq[s][0].tolist(), \
            f"slot {s}: batched verify diverged from per-slot verify"
