"""Fault-isolated serving: chaos injection, quarantine, auditing.

The acceptance bar from the fault-isolation issue, as tests:

- **the chaos pin**: under a seeded :class:`FaultPlan` mixing
  non-finite logits, transient step exceptions and a watchdog stall,
  every UN-faulted greedy request's token stream is bitwise identical
  to a fault-free run on the same engine (healthy slots in a batch
  with a quarantined slot keep their exact tokens), every faulted
  request reaches a typed terminal status, and the
  :class:`PoolAuditor` reports zero leaked/double-freed pages at
  drain;
- containment adds ZERO compiled programs: the chaos run's trace
  counters match the fault-free run's (the guard is fused into the
  existing programs; injection rides a zero-in-production operand);
- the non-finite guard is per-slot (decode) / per-call (chunk,
  monolithic prefill) and fires on REAL NaN logits (a NaN-poisoned
  engine fails every request typed-``FAILED`` without crashing);
- the fault policy requeues with capped exponential backoff up to
  ``max_retries`` then lands the typed ``FAILED`` terminal status,
  reclaiming every page;
- the auditor detects manufactured corruption (leaked refcounts,
  double-frees, corrupted debug-copy page tables) and passes on
  healthy pools;
- the watchdog flags heartbeats over budget (``serving.watchdog.*``)
  and invokes the policy callback;
- ``QueueFull`` carries a decode-throughput-derived ``retry_after_s``;
- the slow soak: several hundred randomized heartbeats of faults
  interleaved with pool exhaustion and prefix eviction — zero leaks,
  zero clean-request token mismatches.

Everything hermetic on CPU with a tiny model (the kernels take their
reference paths); the ``chaos`` marker selects this tier.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import serving, telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (Engine, FaultPlan, FaultPolicy, FaultSpec,
                              InjectedFault, PoolAuditor,
                              PoolInvariantError, QueueFull, Request,
                              RequestStatus, Scheduler)

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

VOCAB = 101
CHUNK = 8


def _tiny_lm(max_seq_len=64, **kw):
    return TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                         num_heads=4, max_seq_len=max_seq_len, **kw)


@pytest.fixture(scope="module")
def lm_and_params():
    m = _tiny_lm()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, paged=True, pool=0, slots=2, seed=5,
               **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=64, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=pool, paged=paged,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  **kw)


@pytest.fixture(scope="module")
def engine(lm_and_params):
    """One shared paged engine — the pin tests run clean and chaos
    passes on the SAME compiled programs (reset between runs), so
    bitwise comparisons never cross executables."""
    return _mk_engine(lm_and_params)


def _fast_policy(**kw):
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("audit_every_n", 1)
    return FaultPolicy(**kw)


def _stream():
    rng = np.random.default_rng(1)
    return [Request(prompt=list(rng.integers(1, VOCAB, size=n)),
                    max_new_tokens=b)
            for n, b in [(5, 8), (13, 6), (9, 5), (17, 4)]]


# ------------------------------------------------------------ FaultPlan
def test_fault_spec_validation():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(kind="meteor", tick=0)
    with pytest.raises(ValueError, match="victim slot"):
        FaultSpec(kind="nonfinite", tick=0)
    with pytest.raises(ValueError, match="site"):
        FaultSpec(kind="exception", tick=0, site="prefix")
    with pytest.raises(ValueError, match="stall_s"):
        FaultSpec(kind="stall", tick=0)


def test_fault_plan_is_deterministic_and_seeded():
    a = FaultPlan.random(3, 50, slots=4, nonfinite_rate=0.2,
                         exception_rate=0.2, stall_rate=0.1)
    b = FaultPlan.random(3, 50, slots=4, nonfinite_rate=0.2,
                         exception_rate=0.2, stall_rate=0.1)
    assert a.specs == b.specs and len(a.specs) > 0
    c = FaultPlan.random(4, 50, slots=4, nonfinite_rate=0.2,
                         exception_rate=0.2, stall_rate=0.1)
    assert a.specs != c.specs


def test_fault_plan_injection_surface():
    plan = FaultPlan([
        FaultSpec(kind="nonfinite", tick=2, slot=1,
                  value=float("inf")),
        FaultSpec(kind="exception", tick=3, site="decode", slot=0),
        FaultSpec(kind="stall", tick=4, stall_s=0.01),
    ])
    assert plan.decode_bias(0, 3) is None
    bias = plan.decode_bias(2, 3)
    assert bias.shape == (3,) and np.isinf(bias[1])
    assert bias[0] == 0.0 and bias[2] == 0.0
    # victims outside the engine's slot range are ignored, not crashed
    assert plan.decode_bias(2, 1) is None
    plan.maybe_raise("chunk", 3)             # wrong site: no-op
    with pytest.raises(InjectedFault) as ei:
        plan.maybe_raise("decode", 3)
    assert ei.value.slot == 0 and ei.value.transient
    t0 = time.perf_counter()
    assert plan.maybe_stall(4) > 0
    assert time.perf_counter() - t0 >= 0.01
    assert plan.maybe_stall(5) == 0.0
    assert plan.stats()["injected_exceptions"] == 1


def test_corrupt_page_table_refuses_live_views(engine):
    plan = FaultPlan()
    live = engine._page_table
    with pytest.raises(ValueError, match="DEBUG COPIES"):
        plan.corrupt_page_table(live[:, :], engine._n_pages)


# ---------------------------------------------------------- PoolAuditor
def test_auditor_passes_on_healthy_pool_and_samples(engine, lm_and_params):
    engine.reset()
    sched = Scheduler(engine, fault_policy=_fast_policy())
    sched.run(_stream())
    report = sched.auditor.audit(engine)
    assert report["pages_in_use"] == 0       # drained: everything back
    aud = PoolAuditor(every_n=2)
    assert aud.maybe_audit(engine) is None   # event 1: sampled out
    assert aud.maybe_audit(engine) is not None
    assert aud.audits == 1
    off = PoolAuditor(every_n=0)             # disabled
    assert off.maybe_audit(engine) is None
    with pytest.raises(RuntimeError, match="paged engines only"):
        PoolAuditor().audit(_mk_engine(lm_and_params, paged=False))


def test_auditor_detects_leak_and_double_free(engine):
    engine.reset()
    auditor = PoolAuditor()
    page = engine.pool.alloc()
    try:
        # refcount 1 but NO table/prefix entry references it: a leak
        with pytest.raises(PoolInvariantError, match="LEAKED"):
            auditor.audit(engine)
    finally:
        engine.pool.release([page])
    auditor.audit(engine)                    # healthy again
    # a slot's table references a page whose refcount was dropped
    # behind the allocator's back: dangling/double-free
    engine.prefill_chunk(0, [1, 2, 3], 0)
    held = int(engine._page_table[0, 0])
    engine.pool.refcount[held] -= 1
    engine.pool._free.append(held)
    try:
        with pytest.raises(PoolInvariantError, match="dangling|DOUBLE"):
            auditor.audit(engine)
    finally:
        engine.pool._free.remove(held)
        engine.pool.refcount[held] += 1
    engine.release_slot(0)
    auditor.audit(engine)


def test_auditor_detects_corrupted_debug_copy(engine):
    engine.reset()
    engine.prefill_chunk(0, [4, 5, 6], 0)
    table, n_pages = engine.page_table_snapshot()
    FaultPlan().corrupt_page_table(table, n_pages, slot=0, value=-7)
    with pytest.raises(PoolInvariantError, match="outside the"):
        PoolAuditor().audit(engine, page_table=table, n_pages=n_pages)
    # the live tables were untouched: the real audit still passes
    PoolAuditor().audit(engine)
    engine.release_slot(0)


# ------------------------------------------------------ non-finite guard
def test_decode_nonfinite_guard_is_per_slot(lm_and_params):
    """A NaN bias into slot 1's logits flags ONLY slot 1, and slot 0's
    token is bitwise identical to the bias-free step (the +0.0 rows are
    value-identical — healthy batchmates never see the fault). Two
    engines built identically (same params/seed/geometry) run the same
    step, one clean and one injected — the comparison crosses two
    traces of the same program, the discipline the chunked-vs-
    monolithic parity test already relies on."""
    e1 = _mk_engine(lm_and_params)
    e2 = _mk_engine(lm_and_params)
    for e in (e1, e2):
        e.prefill_chunked(0, [3, 1, 4, 1, 5])
        e.prefill_chunked(1, [9, 2, 6, 5])
    clean = e1.decode_step([7, 8], [True, True], [0.0, 0.0])
    assert e1.last_decode_finite.tolist() == [True, True]
    assert e1.nonfinite_events == 0
    bad = e2.decode_step([7, 8], [True, True], [0.0, 0.0],
                         fault_bias=[0.0, float("nan")])
    assert e2.last_decode_finite.tolist() == [True, False]
    assert int(bad[0]) == int(clean[0])
    assert e2.nonfinite_events == 1
    with pytest.raises(ValueError, match="fault_bias"):
        e2.decode_step([7, 8], [True, True], [0.0, 0.0],
                       fault_bias=[0.0, 0.0, 0.0])


def test_nan_params_engine_fails_typed_and_survives(lm_and_params):
    """REAL non-finite logits (a NaN-poisoned weight) exercise the
    in-program guard end-to-end: every request lands in the typed
    FAILED terminal state, nothing crashes, the pool drains clean."""
    m, params = lm_and_params
    poisoned = jax.tree_util.tree_map(
        lambda x: (x.at[(0,) * x.ndim].set(float("nan"))
                   if jnp.issubdtype(x.dtype, jnp.floating) else x),
        params)
    reg = telemetry.MetricsRegistry()
    eng = Engine(m, poisoned, slots=2, max_len=64, prefill_len=24,
                 chunk_len=CHUNK, registry=reg,
                 policy=resolve_policy("O0", verbose=False))
    sched = Scheduler(eng, registry=reg,
                      fault_policy=_fast_policy(max_retries=1))
    reqs = _stream()
    done = sched.run(reqs)
    assert len(done) == len(reqs)
    assert all(r.status is RequestStatus.FAILED for r in reqs)
    assert all(r.status.terminal for r in reqs)
    assert all(r.finish_reason == "fault" for r in reqs)
    assert all(r.retries == 2 for r in reqs)     # max_retries + final
    assert all("non-finite" in r.error for r in reqs)
    snap = reg.snapshot()
    assert snap["counters"]["serving.requests.failed"] == len(reqs)
    assert snap["counters"]["serving.faults.nonfinite"] > 0
    assert snap["counters"]["serving.faults.requeued"] == len(reqs)
    assert sched.auditor.audit(eng)["pages_in_use"] == 0


# ------------------------------------------------------- the chaos pin
def test_chaos_pin_unfaulted_requests_bitwise_and_zero_leaks(engine):
    """THE acceptance pin: a seeded plan mixing non-finite logits,
    transient chunk/decode exceptions and a heartbeat stall — every
    un-faulted request bitwise-matches the fault-free run (same engine,
    same compiled programs), every faulted request reaches a typed
    terminal status, zero new programs trace, zero pages leak."""
    engine.reset()
    sched0 = Scheduler(engine, fault_policy=_fast_policy())
    clean_reqs = _stream()
    sched0.run(clean_reqs)
    clean = [list(r.output_tokens) for r in clean_reqs]
    traces0 = (engine.chunk_traces, engine.decode_traces,
               engine.prefill_traces)

    engine.reset()
    stalls = []
    plan = FaultPlan([
        FaultSpec(kind="stall", tick=1, stall_s=0.03),
        FaultSpec(kind="exception", tick=2, site="chunk"),
        FaultSpec(kind="nonfinite", tick=3, slot=0),
        FaultSpec(kind="exception", tick=6, site="decode", slot=1),
    ])
    policy = _fast_policy(max_retries=1, watchdog_budget_s=0.02,
                          on_stall=stalls.append)
    reg = telemetry.MetricsRegistry()
    engine.set_registry(reg)    # the engine owns the nonfinite counter
    sched = Scheduler(engine, registry=reg, fault_policy=policy,
                      fault_plan=plan)
    reqs = _stream()
    try:
        done = sched.run(reqs)
    finally:
        engine.set_registry(None)
    assert len(done) == len(reqs)
    # every injected fault actually landed on a live request
    assert plan.stats()["injected_nonfinite"] == 1
    assert plan.stats()["injected_exceptions"] == 2
    faulted = [r for r in reqs if r.retries > 0
               or r.status is RequestStatus.FAILED]
    assert len(faulted) >= 2, "the plan must actually fault requests"
    for r in reqs:
        assert r.status.terminal
        assert r.status in (RequestStatus.FINISHED, RequestStatus.FAILED)
    # the headline: un-faulted requests are bitwise identical
    for i, r in enumerate(reqs):
        if r.retries == 0 and r.status is RequestStatus.FINISHED:
            assert list(r.output_tokens) == clean[i], \
                f"clean request {i} diverged under chaos"
    # greedy retried-to-completion requests reproduce the clean tokens
    # too (a retry is a full cold restart through the same programs)
    for i, r in enumerate(reqs):
        if r.retries and r.status is RequestStatus.FINISHED:
            assert list(r.output_tokens) == clean[i]
    # containment added ZERO compiled programs
    assert (engine.chunk_traces, engine.decode_traces,
            engine.prefill_traces) == traces0
    # watchdog saw the injected stall; auditor sees zero leaks at drain
    assert plan.stats()["injected_stalls"] == 1
    assert len(stalls) >= 1
    snap = reg.snapshot()
    assert snap["counters"]["serving.watchdog.stall"] >= 1
    assert snap["histograms"]["serving.watchdog.stall_s"]["count"] >= 1
    assert snap["counters"]["serving.faults.transient"] == 2
    assert snap["counters"]["serving.faults.nonfinite"] >= 1
    assert sched.auditor.audit(engine)["pages_in_use"] == 0
    engine.reset()


def test_contiguous_engine_containment(lm_and_params):
    """The fault policy is layout-agnostic: the contiguous (paged=False)
    engine quarantines and requeues the same way — no auditor (nothing
    paged to audit), same typed terminals."""
    eng = _mk_engine(lm_and_params, paged=False)
    plan = FaultPlan([FaultSpec(kind="exception", tick=2, site="chunk")])
    sched = Scheduler(eng, fault_policy=_fast_policy(max_retries=2),
                      fault_plan=plan)
    assert sched.auditor is None
    reqs = _stream()
    sched.run(reqs)
    assert all(r.status is RequestStatus.FINISHED for r in reqs)
    assert sum(r.retries for r in reqs) == 1


# ------------------------------------------------- policy + scheduler
def test_failed_terminal_after_max_retries_reclaims_pages(engine):
    engine.reset()
    # every chunk call fails: the victim can never prefill
    plan = FaultPlan([FaultSpec(kind="exception", tick=t, site="chunk")
                      for t in range(64)])
    sched = Scheduler(engine, fault_policy=_fast_policy(max_retries=2),
                      fault_plan=plan)
    (r,) = sched.run([Request(prompt=[1, 2, 3], max_new_tokens=4)])
    assert r.status is RequestStatus.FAILED
    assert r.finish_reason == "fault" and r.retries == 3
    assert "InjectedFault" in r.error
    assert sched.auditor.audit(engine)["pages_in_use"] == 0
    # the engine is not poisoned: a clean follow-up run serves fine
    sched2 = Scheduler(engine, fault_policy=_fast_policy())
    (ok,) = sched2.run([Request(prompt=[1, 2, 3], max_new_tokens=4)])
    assert ok.status is RequestStatus.FINISHED
    engine.reset()


def test_backoff_schedule_and_eligibility(engine):
    pol = FaultPolicy(backoff_base_s=0.1, backoff_cap_s=0.3)
    assert pol.backoff_s(1) == pytest.approx(0.1)
    assert pol.backoff_s(2) == pytest.approx(0.2)
    assert pol.backoff_s(3) == pytest.approx(0.3)   # capped
    assert pol.backoff_s(9) == pytest.approx(0.3)
    assert FaultPolicy(backoff_base_s=0.0).backoff_s(5) == 0.0
    # a backing-off request is not admitted before its horizon, and it
    # never blocks an eligible request behind it
    engine.reset()
    sched = Scheduler(engine, fault_policy=_fast_policy())
    blocked = Request(prompt=[1, 2], max_new_tokens=2)
    eligible = Request(prompt=[3, 4], max_new_tokens=2)
    sched.submit(blocked)
    sched.submit(eligible)
    blocked._not_before = time.perf_counter() + 60.0
    sched.step()
    assert blocked.status is RequestStatus.QUEUED
    assert eligible.status.terminal or \
        eligible.status in (RequestStatus.PREFILLING,
                            RequestStatus.RUNNING)
    blocked._not_before = None      # horizon cleared: admits normally
    while sched.pending:
        sched.step()
    assert blocked.status is RequestStatus.FINISHED
    engine.reset()


def test_queue_full_carries_retry_after_hint(engine):
    engine.reset()
    sched = Scheduler(engine, max_queue=1,
                      fault_policy=_fast_policy())
    # before any decode step there is nothing honest to say
    sched.submit(Request(prompt=[1], max_new_tokens=2))
    with pytest.raises(QueueFull) as e0:
        sched.submit(Request(prompt=[2], max_new_tokens=2))
    assert e0.value.retry_after_s is None
    while sched.pending:
        sched.step()
    # after measured decode steps the hint is throughput-derived
    sched.submit(Request(prompt=[1], max_new_tokens=64))
    sched.step()
    sched.submit(Request(prompt=[2], max_new_tokens=2))
    with pytest.raises(QueueFull) as e1:
        sched.submit(Request(prompt=[3], max_new_tokens=2))
    assert e1.value.retry_after_s is not None
    assert e1.value.retry_after_s > 0
    assert "retry_after_s" in str(e1.value)
    while sched.pending:
        sched.step()
    engine.reset()


def test_status_enum_is_consistent_across_records_and_telemetry(engine):
    """The satellite pin: ONE status vocabulary. Request.status is the
    typed enum, the serving.request record carries its value, and the
    terminal counters (completed/timeout/failed) map onto it."""
    engine.reset()
    reg = telemetry.MetricsRegistry()
    sched = Scheduler(engine, registry=reg,
                      fault_policy=_fast_policy(),
                      default_timeout_s=0.0)
    expired = sched.submit(Request(prompt=[1, 2], max_new_tokens=4))
    time.sleep(0.01)
    sched.step()
    assert expired.status is RequestStatus.EXPIRED
    assert expired.status.terminal and expired.status == "expired"
    sched2 = Scheduler(engine, registry=reg,
                       fault_policy=_fast_policy())
    (fin,) = sched2.run([Request(prompt=[1, 2], max_new_tokens=2)])
    assert fin.status is RequestStatus.FINISHED
    for st in (RequestStatus.QUEUED, RequestStatus.PREFILLING,
               RequestStatus.RUNNING):
        assert not st.terminal
    recs = {rec["uid"]: rec for rec in reg.records
            if rec.get("tag") == "serving.request"}
    assert recs[expired.uid]["status"] == "expired"
    assert recs[fin.uid]["status"] == "finished"
    assert recs[fin.uid]["retries"] == 0
    snap = reg.snapshot()
    assert snap["counters"]["serving.requests.timeout"] == 1
    assert snap["counters"]["serving.requests.completed"] == 1
    engine.reset()


def test_watchdog_flags_slow_heartbeats_only_over_budget(engine):
    engine.reset()
    # warm the programs so trace time doesn't trip the tiny budget
    Scheduler(engine, fault_policy=_fast_policy()).run(
        [Request(prompt=[5, 6], max_new_tokens=2)])
    engine.reset()
    stalls = []
    reg = telemetry.MetricsRegistry()
    plan = FaultPlan([FaultSpec(kind="stall", tick=1, stall_s=0.2)])
    sched = Scheduler(
        engine, registry=reg, fault_plan=plan,
        fault_policy=_fast_policy(watchdog_budget_s=0.15,
                                  on_stall=stalls.append))
    sched.run([Request(prompt=[5, 6], max_new_tokens=8)])
    assert len(stalls) == 1 and stalls[0] > 0.15
    snap = reg.snapshot()
    assert snap["counters"]["serving.watchdog.stall"] == 1
    assert snap["histograms"]["serving.watchdog.stall_s"]["count"] == 1
    engine.reset()


def test_watchdog_warm_start_exempts_tracing_ticks(lm_and_params):
    """The warm-start regression (PR 7 NOTE): the first heartbeat on a
    COLD engine traces compiled programs, so a tiny
    ``watchdog_budget_s`` used to false-trip on tick 0 before the
    engine had done anything wrong. Tracing ticks are now exempt and
    separately accounted as ``serving.watchdog.warmup_s``: with a
    budget every tick must breach, stalls + warm-ups partition the run
    exactly, and the ticks that traced never counted as stalls."""
    eng = _mk_engine(lm_and_params, seed=9)     # cold: nothing traced
    assert eng.compiled_programs == 0
    stalls = []
    reg = telemetry.MetricsRegistry()
    sched = Scheduler(
        eng, registry=reg,
        fault_policy=_fast_policy(watchdog_budget_s=1e-9,
                                  on_stall=stalls.append))
    steps = 0
    sched.submit(Request(prompt=[5, 6, 7], max_new_tokens=4))
    while sched.pending:
        sched.step()
        steps += 1
    snap = reg.snapshot()
    warmups = snap["histograms"]["serving.watchdog.warmup_s"]["count"]
    stalls_n = snap["counters"].get("serving.watchdog.stall", 0)
    # tick 0 traced the chunk AND decode programs (the final chunk
    # flips the slot to decoding within the same heartbeat)
    assert warmups >= 1, "tracing ticks were not accounted as warm-up"
    # every tick either warmed or breached the (impossible) budget —
    # and the tracing ticks are exactly the ones that did NOT stall
    assert warmups + stalls_n == steps
    assert len(stalls) == stalls_n
    # a warmed engine stops producing warm-up ticks: one more request,
    # same scheduler — every subsequent tick breaches instead
    sched.submit(Request(prompt=[5, 6, 7], max_new_tokens=2))
    more = 0
    while sched.pending:
        sched.step()
        more += 1
    snap = reg.snapshot()
    assert snap["histograms"]["serving.watchdog.warmup_s"]["count"] \
        == warmups, "a warm engine must not keep claiming warm-up"
    assert snap["counters"]["serving.watchdog.stall"] == stalls_n + more


# ------------------------------------------------------------- the soak
@pytest.mark.slow
def test_chaos_soak_pool_exhaustion_prefix_eviction_zero_leaks(
        lm_and_params):
    """Several hundred randomized heartbeats of a seeded FaultPlan over
    a deliberately small pool with prefix retention on — admissions
    block on exhaustion, prefix entries evict under pressure, faults
    quarantine/requeue/fail throughout — and at every audit point and
    at drain: zero leaked pages, zero double-frees; clean requests'
    tokens bitwise-match the fault-free pass."""
    # a pool sized for ~2.5 in-flight worst cases: exhaustion is the
    # common case, so admission blocking + LRU prefix eviction are
    # exercised constantly
    def mk():
        return _mk_engine(lm_and_params, slots=3, pool=2,
                          num_pages=2 * (64 // CHUNK) + 5)

    rng = np.random.default_rng(11)
    shared = list(rng.integers(1, VOCAB, size=CHUNK * 2))

    def stream():
        out = []
        r2 = np.random.default_rng(12)
        for i in range(24):
            if i % 3:
                prompt = shared + list(r2.integers(1, VOCAB, size=int(
                    r2.integers(1, 8))))
            else:
                prompt = list(r2.integers(1, VOCAB, size=int(
                    r2.integers(1, 20))))
            out.append(Request(prompt=prompt,
                               max_new_tokens=int(r2.integers(1, 10))))
        return out

    def serve(engine, plan):
        policy = _fast_policy(max_retries=2)
        sched = Scheduler(engine, max_queue=64, retain_prefixes=True,
                          fault_policy=policy, fault_plan=plan)
        reqs = stream()
        feed = iter(reqs)
        fed = 0
        for tick in range(600):
            if tick % 2 == 0:
                r = next(feed, None)
                if r is not None:
                    sched.submit(r)
                    fed += 1
            sched.step()
            if fed == len(reqs) and not sched.pending:
                break
        assert not sched.pending, "soak failed to drain in 600 ticks"
        return reqs, sched

    clean_engine = mk()
    clean_reqs, _ = serve(clean_engine, None)
    assert all(r.status is RequestStatus.FINISHED for r in clean_reqs)

    chaos_engine = mk()
    plan = FaultPlan.random(7, 600, slots=3, nonfinite_rate=0.04,
                            exception_rate=0.04, stall_rate=0.01,
                            stall_s=0.001)
    chaos_reqs, sched = serve(chaos_engine, plan)
    injected = plan.stats()
    assert injected["injected_nonfinite"] \
        + injected["injected_exceptions"] > 0, \
        "the soak must actually inject faults"
    mismatches = 0
    for i, r in enumerate(chaos_reqs):
        assert r.status.terminal
        if r.retries == 0 and r.status is RequestStatus.FINISHED:
            if list(r.output_tokens) \
                    != list(clean_reqs[i].output_tokens):
                mismatches += 1
    assert mismatches == 0, \
        f"{mismatches} clean requests diverged under chaos"
    report = sched.auditor.audit(chaos_engine)     # raises on any leak
    # at drain only prefix-entry pages may remain resident
    held = sum(len(p) for p in
               chaos_engine.prefix_cache.page_holds())
    assert report["pages_in_use"] == held
    chaos_engine.reset(clear_prefixes=True)
    assert sched.auditor.audit(chaos_engine)["pages_in_use"] == 0


@pytest.mark.slow
def test_sharded_engine_chaos_quarantine_frees_pages_on_every_shard(
        lm_and_params):
    """The tensor-parallel satellite's containment case: on an
    Engine(mesh=<2 shards>) the same seeded chaos plan — non-finite
    logits and transient chunk/decode exceptions — quarantines only its
    victims, un-faulted requests stay bitwise identical to the sharded
    fault-free run, and every quarantine's page release drains the ONE
    host-side pool whose pages back all shards at heads/tp width: a
    page freed is freed on every shard by construction, and the auditor
    (which reconciles refcounts against the replicated page tables)
    proves zero leaks at drain."""
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs 2 virtual devices")
    mesh = Mesh(np.array(devs[:2]), ("tp",))
    # this module's shared VOCAB (101) is deliberately odd; the sharded
    # head needs vocab % tp == 0, so the case carries its own model
    m = TransformerLM(vocab_size=100, hidden=32, num_layers=2,
                      num_heads=4, max_seq_len=64)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    eng = Engine(m, params, slots=3, max_len=64, prefill_len=24,
                 chunk_len=CHUNK,
                 policy=resolve_policy("O0", verbose=False), seed=5,
                 mesh=mesh)
    assert eng.tp == 2

    def _stream100():
        rng = np.random.default_rng(1)
        return [Request(prompt=list(rng.integers(1, 100, size=n)),
                        max_new_tokens=b)
                for n, b in [(5, 8), (13, 6), (9, 5), (17, 4)]]

    eng.reset()
    clean_reqs = _stream100()
    Scheduler(eng, fault_policy=_fast_policy()).run(clean_reqs)
    clean = [list(r.output_tokens) for r in clean_reqs]
    traces0 = (eng.chunk_traces, eng.decode_traces, eng.prefill_traces)

    eng.reset()
    plan = FaultPlan([
        FaultSpec(kind="exception", tick=2, site="chunk"),
        FaultSpec(kind="nonfinite", tick=3, slot=0),
        FaultSpec(kind="exception", tick=6, site="decode", slot=1),
    ])
    reg = telemetry.MetricsRegistry()
    eng.set_registry(reg)
    sched = Scheduler(eng, registry=reg,
                      fault_policy=_fast_policy(max_retries=1),
                      fault_plan=plan)
    reqs = _stream100()
    try:
        done = sched.run(reqs)
    finally:
        eng.set_registry(None)
    assert len(done) == len(reqs)
    assert plan.stats()["injected_nonfinite"] == 1
    assert plan.stats()["injected_exceptions"] == 2
    faulted = [r for r in reqs if r.retries > 0
               or r.status is RequestStatus.FAILED]
    assert faulted, "the plan must actually fault requests"
    for i, r in enumerate(reqs):
        assert r.status.terminal
        if r.status is RequestStatus.FINISHED:
            assert list(r.output_tokens) == clean[i], \
                f"request {i} diverged under chaos on the sharded engine"
    # containment added ZERO compiled programs on the sharded engine
    assert (eng.chunk_traces, eng.decode_traces,
            eng.prefill_traces) == traces0
    snap = reg.snapshot()
    assert snap["counters"]["serving.faults.nonfinite"] >= 1
    # the tp gauges rode the same registry
    assert snap["gauges"]["serving.tp.shards"] == 2.0
    # zero leaked pages at drain — the heads-sharded pool's host
    # allocator is shard-agnostic, so this IS the every-shard claim
    assert sched.auditor.audit(eng)["pages_in_use"] == 0
    assert eng.pool.reserved_total == 0
