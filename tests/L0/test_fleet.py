"""Process-level fleet: the :class:`~apex_tpu.serving.FleetController`
contract pins.

The headline guarantees, per ISSUE 18's acceptance criteria:

- **Parity**: a greedy session stream served through the process fleet
  (one OS process per replica, stdlib transport) is BITWISE identical
  to the in-process :class:`~apex_tpu.serving.Router` over engines
  built from the same specs — the shared :mod:`routing_policy` core
  plus versioned wire forms change WHERE a request decodes, never what
  it decodes.
- **Wire forms**: requests, load snapshots and disagg arena records
  round-trip through versioned dicts; an unknown version fails LOUDLY
  (a controller and worker from different trees must never
  deserialize garbage), a missing field raises, private clocks never
  cross (perf_counter bases are per-process).
- **Chaos**: a ``replica_death`` at the fleet tier kills a REAL
  process (SIGKILL, no goodbye); every victim request reaches a typed
  terminal state on the survivors with no retry charged, the
  survivor's pool audits with zero leaked pages, and close() leaves
  zero orphan processes and zero leaked threads. The new
  ``worker_hang`` kind makes a worker stop answering its transport —
  only the missed-beat detector can catch that (an alive-but-hung
  process never EOFs).
- **Rolling restart**: drain → kill → respawn → rejoin, one worker at
  a time, under live traffic; drained requests re-route with no retry
  charged and post-restart multi-turn traffic re-registers prefixes
  warm (hit rate > 0 after every process was recycled).
- **Elastic scale**: ``add_replica`` / ``remove_replica`` /
  ``set_role`` under live traffic, including the disaggregated
  fleet's role refit — handoff records travel BY VALUE and re-verify
  by CRC on the importing arena.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from apex_tpu.serving import (FaultPlan, FaultSpec, FleetController,
                              QueueFull, Request, RequestStatus,
                              Router, record_from_wire, record_to_wire,
                              request_from_wire, request_to_wire,
                              snapshot_from_wire, snapshot_to_wire)
from apex_tpu.serving.fleet import (MAX_FRAME_BYTES, recv_frame,
                                    send_frame)
from apex_tpu.serving.fleet_worker import build_engine_from_spec
from apex_tpu.serving.host_tier import HostTierRecord
from apex_tpu.serving.routing_policy import (fleet_retry_hint,
                                             note_placement,
                                             rank_replicas)

pytestmark = [pytest.mark.serving, pytest.mark.chaos,
              pytest.mark.fleet]

VOCAB = 64
CHUNK = 8

#: One spec builds bitwise-identical engines in ANY process on the
#: same backend (params from init_seed via PRNGKey) — the parity
#: test's whole premise.
SPEC = {
    "model": {"vocab_size": VOCAB, "hidden": 32, "num_layers": 2,
              "num_heads": 4, "max_seq_len": 64},
    "init_seed": 0,
    "engine": {"slots": 2, "max_len": 64, "prefill_len": 24,
               "chunk_len": CHUNK, "prefix_pool": 4, "seed": 5,
               "policy": "O0"},
}

#: The disagg variant: a per-worker host arena for by-value handoffs.
SPEC_TIER = {**SPEC, "engine": {**SPEC["engine"],
                                "host_tier_bytes": 1 << 22}}


def _session_waves(turns=2, sessions=3):
    """Multi-turn sessions (turn t+1 extends turn t) — the affinity
    workload, same construction as test_router's."""
    rng = np.random.default_rng(7)
    base = rng.integers(1, VOCAB, size=CHUNK).tolist()
    prompts = []
    for s in range(sessions):
        srng = np.random.default_rng(100 + s)
        p = base + srng.integers(1, VOCAB, size=CHUNK).tolist()
        turns_s = [list(p)]
        for _ in range(turns - 1):
            p = p + srng.integers(1, VOCAB, size=4).tolist()
            turns_s.append(list(p))
        prompts.append(turns_s)
    return [[list(prompts[s][t]) for s in range(sessions)]
            for t in range(turns)]


def _assert_no_orphans(fc):
    """Every process the controller EVER spawned is gone — the
    no-orphan pin (kill(pid, 0) on a reaped pid raises)."""
    for p in fc._procs:
        assert p.poll() is not None, f"worker pid {p.pid} still runs"
        try:
            os.kill(p.pid, 0)
            # poll() reaped it, so a living pid here is a RE-USED pid
            # from some other process — not ours; nothing to assert
        except ProcessLookupError:
            pass


# ----------------------------------------------------------- wire forms
def test_request_wire_roundtrip():
    r = Request(prompt=[1, 2, 3], max_new_tokens=5, temperature=0.5,
                timeout_s=2.0, priority=7, slo_class="interactive",
                deadline_s=1.5, tenant="acme")
    r.output_tokens = [7, 8]
    r.status = RequestStatus.RUNNING
    r.ttft_s = 0.25
    r.chunks = 3
    r.reused_tokens = 16
    r.retries = 1
    r.preemptions = 2
    r.deadline_missed = True
    r._t_submit = 123.0             # private clock: must NOT cross
    wire = request_to_wire(r)
    back = request_from_wire(wire)
    assert back.uid == r.uid
    assert back.prompt == [1, 2, 3]
    assert back.max_new_tokens == 5
    assert back.temperature == 0.5
    assert back.timeout_s == 2.0
    assert back.output_tokens == [7, 8]
    assert back.status is RequestStatus.RUNNING
    assert back.ttft_s == 0.25 and back.chunks == 3
    assert back.reused_tokens == 16 and back.retries == 1
    # the v2 SLO fields: identity in, verdicts out
    assert back.priority == 7 and back.slo_class == "interactive"
    assert back.deadline_s == 1.5 and back.tenant == "acme"
    assert back.preemptions == 2 and back.deadline_missed is True
    assert back._t_submit is None, \
        "per-process perf_counter clocks must never cross the wire"


def test_request_wire_versioned_and_loud():
    wire = request_to_wire(Request(prompt=[1], max_new_tokens=1))
    bad = dict(wire)
    bad["v"] = 999
    with pytest.raises(ValueError, match="version"):
        request_from_wire(bad)
    missing = dict(wire)
    del missing["prompt"]
    with pytest.raises(KeyError):
        request_from_wire(missing)


def test_snapshot_wire_roundtrip_and_version():
    snap = {"queue_depth": 3, "queue_free": 5, "slots": 2,
            "slots_busy": 1, "slots_free": 1, "inflight_steps": 0,
            "pages_free": 40, "host_bytes_free": None,
            "oldest_deadline_s": -0.25, "preemptible_pages": 12,
            "resident_adapters": ["a1", "b2"]}
    wire = snapshot_to_wire(snap)
    assert snapshot_from_wire(wire) == snap
    # the v2 SLO fields (and the v3 adapter column) are part of the
    # fixed key set: an older-shaped snapshot must fail loudly, not
    # rank on garbage
    with pytest.raises(KeyError):
        snapshot_to_wire({k: snap[k] for k in snap
                          if k not in ("oldest_deadline_s",
                                       "preemptible_pages")})
    with pytest.raises(KeyError):
        snapshot_to_wire({k: snap[k] for k in snap
                          if k != "resident_adapters"})
    bad = dict(wire)
    bad["v"] = 999
    with pytest.raises(ValueError, match="version"):
        snapshot_from_wire(bad)


def test_record_wire_roundtrip_and_version():
    k = np.arange(2 * 1 * 4 * 8 * 4, dtype=np.float32) \
        .reshape(2, 1, 4, 8, 4)
    v = k + 1
    rec = HostTierRecord(k=k, v=v, nbytes=k.nbytes + v.nbytes,
                         crc=(123,), shards=1)
    wire = record_to_wire(77, rec)
    key, back = record_from_wire(wire)
    assert key == 77
    np.testing.assert_array_equal(back.k, k)
    np.testing.assert_array_equal(back.v, v)
    assert back.crc == (123,) and back.nbytes == rec.nbytes
    assert back.k.flags.owndata or back.k.base is None or \
        back.k.flags.writeable    # owned copy, not a frombuffer view
    bad = dict(wire)
    bad["v"] = 999
    with pytest.raises(ValueError, match="version"):
        record_from_wire(bad)
    with pytest.raises(ValueError, match="pending"):
        record_to_wire(1, HostTierRecord(k=None, v=None, nbytes=0,
                                         crc=(), pending=True))


# ------------------------------------------------------- routing policy
def test_rank_replicas_order():
    snaps = {
        0: {"slots_free": 1, "queue_depth": 2, "pages_free": 10,
            "host_bytes_free": None},
        1: {"slots_free": 2, "queue_depth": 0, "pages_free": 5,
            "host_bytes_free": None},
        2: {"slots_free": 2, "queue_depth": 0, "pages_free": 5,
            "host_bytes_free": 100},
    }
    # no affinity: free slots first, then queue, pages, host headroom
    assert rank_replicas([0, 1, 2], {0: 0, 1: 0, 2: 0},
                         snaps) == [2, 1, 0]
    # affinity dominates load entirely
    assert rank_replicas([0, 1, 2], {0: 8, 1: 0, 2: 0},
                         snaps) == [0, 2, 1]


def test_fleet_retry_hint_and_placement_cap():
    assert fleet_retry_hint([None, 0.5, 0.2]) == 0.5
    assert fleet_retry_hint([None, None]) is None
    placements = {}
    for uid in range(5):
        note_placement(placements, uid, uid % 2, cap=3)
    assert len(placements) == 3
    assert list(placements) == [2, 3, 4]    # oldest shed first
    note_placement(placements, 2, 1, cap=3)
    assert list(placements) == [3, 4, 2]    # re-place refreshes order


# --------------------------------------------------------- frame codec
def test_frame_codec_roundtrip_and_eof():
    a, b = socket.socketpair()
    try:
        msg = {"op": "x", "blob": b"\x00" * 4096, "n": [1, 2, 3]}
        send_frame(a, msg)
        assert recv_frame(b) == msg
        # peer closing mid-frame is EOFError (the death signal), not
        # a hang and not a half-parsed pickle
        a.sendall(b"\x00\x00\x10\x00partial")
        a.close()
        with pytest.raises(EOFError):
            recv_frame(b)
    finally:
        b.close()
    with pytest.raises(ValueError, match="transport bound"):
        send_frame(None, {"blob": b"\x00" * (MAX_FRAME_BYTES + 1)})


# ------------------------------------------------- worker_hang faults
def test_worker_hang_spec_validation():
    with pytest.raises(ValueError, match="victim replica"):
        FaultSpec(kind="worker_hang", tick=0)
    s = FaultSpec(kind="worker_hang", tick=3, replica=1)
    plan = FaultPlan([s])
    assert plan.take_worker_hangs(3) == [1]
    assert plan.take_worker_hangs(3) == []      # consume-once
    assert plan.stats()["injected_worker_hangs"] == 1


def test_worker_hang_rate_replays_pre_fleet_seeds():
    """The rate-0 draw is SKIPPED, so plans seeded before the fleet
    existed replay bit-for-bit; at rate > 0 the hang draw comes LAST,
    so every pre-existing fault in the schedule is unchanged."""
    kw = dict(slots=2, nonfinite_rate=0.1, replica_death_rate=0.05,
              replicas=2)
    base = FaultPlan.random(11, 40, **kw)
    same = FaultPlan.random(11, 40, worker_hang_rate=0.0, **kw)
    assert [repr(s) for s in base.specs] == \
        [repr(s) for s in same.specs]
    with_hangs = FaultPlan.random(11, 40, worker_hang_rate=0.3, **kw)
    hangs = [s for s in with_hangs.specs if s.kind == "worker_hang"]
    others = [s for s in with_hangs.specs if s.kind != "worker_hang"]
    assert hangs, "rate 0.3 over 40 ticks drew no hang?"
    assert all(0 <= s.replica < 2 for s in hangs)
    # drawn LAST within each tick: everything tick 0 drew BEFORE the
    # first hang draw is bit-identical to the hang-free plan (later
    # ticks legitimately shift — the hang draw consumes the stream)
    t0_base = [repr(s) for s in base.specs if s.tick == 0]
    t0_hang = [repr(s) for s in others if s.tick == 0]
    assert t0_hang == t0_base


# ------------------------------------------- the process fleet, live
def test_fleet_lifecycle_end_to_end():
    """The tentpole pins, chained on ONE fleet (spawning processes is
    the expensive part): bitwise parity vs the in-process Router →
    warm rolling restart → chaos process-kill with terminal-on-
    survivors + zero-leak audit → respawn → idempotent close with
    zero orphan processes and zero leaked threads."""
    waves = _session_waves(turns=2, sessions=3)
    threads_before = threading.active_count()

    # the in-process oracle: engines built from the SAME specs by the
    # same function the workers run
    engines = [build_engine_from_spec(SPEC) for _ in range(2)]
    router = Router(engines, seed=0, retain_prefixes=True,
                    max_queue=32)
    oracle = []
    for wave in waves:
        rs = [Request(prompt=list(p), max_new_tokens=4) for p in wave]
        router.run(rs)
        oracle.append([list(r.output_tokens) for r in rs])
    router.close()
    for e in engines:
        e.reset(clear_prefixes=True)

    fc = FleetController([SPEC, SPEC], seed=0, retain_prefixes=True,
                         max_queue=32)
    try:
        # --- bitwise parity across the process boundary
        fleet_tokens = []
        for wave in waves:
            rs = [Request(prompt=list(p), max_new_tokens=4)
                  for p in wave]
            fc.run(rs)
            assert all(r.status is RequestStatus.FINISHED for r in rs)
            fleet_tokens.append([list(r.output_tokens) for r in rs])
        assert fleet_tokens == oracle, \
            "process fleet diverged bitwise from the in-process Router"

        # --- rolling restart: every process recycled, fleet keeps
        # serving, and follow-up turns re-register prefixes warm
        fc.rolling_restart()
        assert all(w.alive for w in fc.workers)
        last = [Request(prompt=waves[-1][s] + [9, 9, 9, 9],
                        max_new_tokens=4) for s in range(3)]
        fc.run(last)
        # a repeat turn over the same (prefill_len-capped) history:
        # its block-aligned prefix was just re-registered above
        again = [Request(prompt=list(r.prompt), max_new_tokens=4)
                 for r in last]
        fc.run(again)
        hits = sum(fc.prefix_stats(i).get("hits", 0) for i in (0, 1))
        assert hits > 0, \
            "no prefix hits after the rolling restart — the fleet " \
            "rejoined cold and never re-warmed"

        # --- chaos: a replica_death at the fleet tier kills a REAL
        # process; victims re-route and finish on the survivor
        plan = FaultPlan([FaultSpec(kind="replica_death",
                                    tick=fc._tick + 1, replica=0)])
        fc.fault_plan = plan
        rng = np.random.default_rng(5)
        chaos = [Request(prompt=list(rng.integers(1, VOCAB, size=9)),
                         max_new_tokens=5) for _ in range(4)]
        fc.run(chaos)
        assert plan.stats()["injected_replica_deaths"] == 1
        assert not fc.workers[0].alive
        assert fc.workers[0].proc.poll() is not None, \
            "chaos replica_death must kill the actual OS process"
        assert all(r.status is RequestStatus.FINISHED for r in chaos)
        assert all(r.retries == 0 for r in chaos), \
            "a worker death is never the request's fault"
        # the survivor's pool audits leak-free (runs the worker's own
        # PoolAuditor + clearing reset over the RPC)
        assert fc.audit_worker(1)["pages_in_use"] == 0

        # --- revive the dead slot and serve through it again
        fc.respawn_worker(0)
        assert fc.workers[0].alive
        post = [Request(prompt=list(rng.integers(1, VOCAB, size=7)),
                        max_new_tokens=3) for _ in range(2)]
        fc.run(post)
        assert all(r.status is RequestStatus.FINISHED for r in post)
    finally:
        fc.close()
        fc.close()                  # idempotent

    _assert_no_orphans(fc)
    time.sleep(0.1)
    assert threading.active_count() <= threads_before, \
        "fleet close leaked controller-side threads"


@pytest.mark.slow
def test_fleet_parity_mixed_class_stream():
    """The bitwise-parity pin extended to a MIXED-CLASS stream
    (ISSUE 19): both fronts inherit the same SLO-aware rank order
    from the one ``routing_policy`` core — the fold uses the
    request's STATIC base priority plus the v2 snapshot's
    ``preemptible_pages``, no clocks — so the process fleet places
    and serves a priority-laden tenant-tagged stream exactly like
    the in-process Router, and the completion records carry the
    same SLO verdict fields back over the wire."""
    from apex_tpu.serving import SLOConfig

    slo = SLOConfig(classes={"batch": 0, "interactive": 10},
                    tenant_weights={"t0": 1.0, "t1": 2.0})
    waves = _session_waves(turns=2, sessions=4)

    def _requests(wave):
        return [Request(prompt=list(p), max_new_tokens=4,
                        slo_class="interactive" if s % 2 else "batch",
                        tenant=f"t{s % 2}")
                for s, p in enumerate(wave)]

    engines = [build_engine_from_spec(SPEC) for _ in range(2)]
    router = Router(engines, seed=0, retain_prefixes=True,
                    max_queue=32, slo=slo)
    oracle = []
    for wave in waves:
        rs = _requests(wave)
        router.run(rs)
        oracle.append([list(r.output_tokens) for r in rs])
    router.close()
    for e in engines:
        e.reset(clear_prefixes=True)

    fc = FleetController([SPEC, SPEC], seed=0, retain_prefixes=True,
                         max_queue=32, slo=slo)
    try:
        fleet_tokens = []
        done = []
        for wave in waves:
            rs = _requests(wave)
            fc.run(rs)
            assert all(r.status is RequestStatus.FINISHED for r in rs)
            fleet_tokens.append([list(r.output_tokens) for r in rs])
            done.extend(rs)
        assert fleet_tokens == oracle, \
            "mixed-class stream diverged bitwise between the " \
            "process fleet and the in-process Router"
        # the SLO identity survives the wire round-trip on results
        assert all(r.slo_class in ("batch", "interactive")
                   for r in done)
        assert all(r.tenant in ("t0", "t1") for r in done)
        # the v2 snapshot columns actually cross the worker wire:
        # an SLO-configured idle worker reports both (preemptible 0,
        # no live deadline — but present, not dropped by an old form)
        snaps = fc._poll(range(2))
        for snap in snaps.values():
            assert "oldest_deadline_s" in snap
            assert "preemptible_pages" in snap
            assert snap["preemptible_pages"] == 0
    finally:
        fc.close()
    _assert_no_orphans(fc)


@pytest.mark.slow
def test_worker_dies_during_drain():
    """A worker whose process vanishes MID-drain (the rolling
    restart's worst moment) degrades to the hard-death path: its
    requests re-route from the controller's canonical copies, the
    restart completes, the fleet serves on."""
    fc = FleetController([SPEC, SPEC], seed=0, retain_prefixes=True,
                         max_queue=32)
    try:
        rng = np.random.default_rng(2)
        fc.run([Request(prompt=list(rng.integers(1, VOCAB, size=8)),
                        max_new_tokens=3)])
        # murder worker 0 behind the controller's back, then ask for a
        # rolling restart: the drain RPC meets a corpse
        fc.workers[0].proc.kill()
        fc.workers[0].proc.wait(timeout=30)
        fc.rolling_restart()
        assert all(w.alive for w in fc.workers)
        reqs = [Request(prompt=list(rng.integers(1, VOCAB, size=8)),
                        max_new_tokens=3) for _ in range(3)]
        fc.run(reqs)
        assert all(r.status is RequestStatus.FINISHED for r in reqs)
    finally:
        fc.close()
    _assert_no_orphans(fc)


@pytest.mark.slow
def test_hang_detector_and_fleet_queue_full():
    """A hung worker (alive process, silent transport) is caught ONLY
    by the missed-beat detector: suspect after one missed ping, dead
    after ``max_missed_beats``, its requests re-routing onto the
    survivors. Plus the fleet-level backpressure pin: QueueFull
    surfaces only when every live worker is saturated, carrying the
    max-of-hints retry_after_s."""
    from apex_tpu import telemetry
    reg = telemetry.MetricsRegistry()
    fc = FleetController([SPEC, SPEC], seed=0, retain_prefixes=True,
                         max_queue=1, registry=reg,
                         ping_timeout_s=0.5, max_missed_beats=2)
    try:
        rng = np.random.default_rng(3)
        fc.run([Request(prompt=list(rng.integers(1, VOCAB, size=8)),
                        max_new_tokens=3)])
        # saturate: 2 workers x (2 slots + 1 queue) admit 6; the 7th+
        # must raise fleet-level QueueFull
        burst = [Request(prompt=list(rng.integers(1, VOCAB, size=8)),
                         max_new_tokens=4) for _ in range(8)]
        saw_queue_full = False
        for r in burst:
            while True:
                try:
                    fc.submit(r)
                    break
                except QueueFull:
                    saw_queue_full = True
                    if not fc.step():
                        time.sleep(0.002)
        while fc.pending:
            if not fc.step():
                time.sleep(0.002)
        assert saw_queue_full, \
            "8 requests through 6 seats never saw backpressure"
        assert all(r.status is RequestStatus.FINISHED for r in burst)

        # now hang worker 1 via the fault plan and let the missed-beat
        # detector declare it
        fc.fault_plan = FaultPlan([FaultSpec(
            kind="worker_hang", tick=fc._tick + 1, replica=1)])
        reqs = [Request(prompt=list(rng.integers(1, VOCAB, size=8)),
                        max_new_tokens=4) for _ in range(3)]
        fc.run(reqs)
        assert not fc.workers[1].alive
        assert all(r.status is RequestStatus.FINISHED for r in reqs)
        snap = reg.snapshot()
        assert snap["counters"].get(
            "serving.fleet.hangs_detected") == 1.0
        assert snap["counters"].get(
            "serving.fleet.worker_deaths") == 1.0
    finally:
        fc.close()
    _assert_no_orphans(fc)


@pytest.mark.slow
def test_elastic_scale_and_role_refit():
    """Elasticity under live traffic: a disaggregated fleet serves
    through by-value KV handoffs, grows a decode worker, refits it to
    prefill when the mix moves, and shrinks again — every phase
    serving to completion, no orphans at close."""
    fc = FleetController([SPEC_TIER, SPEC_TIER], seed=0,
                         retain_prefixes=True, max_queue=32,
                         roles=["prefill", "decode"])
    try:
        rng = np.random.default_rng(4)

        def _burst(n=3):
            rs = [Request(prompt=list(rng.integers(1, VOCAB, size=16)),
                          max_new_tokens=4) for _ in range(n)]
            fc.run(rs)
            assert all(r.status is RequestStatus.FINISHED for r in rs)
            return rs

        _burst()
        snap = fc.metrics_snapshot()
        assert snap["counters"].get("serving.disagg.handoffs", 0) >= 3
        assert snap["counters"].get(
            "serving.swap.hit_after_swap", 0) >= 1, \
            "no handoff record survived the by-value transfer"

        # grow: a third worker, decode role
        idx = fc.add_replica(SPEC_TIER, role="decode")
        assert idx == 2 and fc.workers[2].alive
        _burst()

        # refit: the new worker becomes prefill-capable
        fc.set_role(2, "prefill")
        assert fc.workers[2].role == "prefill"
        _burst()

        # shrink back down; the remaining mix must still be a fleet
        fc.remove_replica(2)
        assert not fc.workers[2].alive
        _burst()

        # losing a whole role tier is refused loudly
        with pytest.raises((RuntimeError, ValueError),
                           match="last one alive|decode-capable"):
            fc.remove_replica(1)
            fc.remove_replica(0)
    finally:
        fc.close()
    _assert_no_orphans(fc)


@pytest.mark.slow
def test_respawn_while_saturated():
    """A worker killed while the fleet is saturated re-routes its
    load into overflow; respawning it under that pressure drains the
    overflow onto the revived capacity and every request finishes."""
    fc = FleetController([SPEC, SPEC], seed=0, retain_prefixes=True,
                         max_queue=2)
    try:
        rng = np.random.default_rng(6)
        reqs = [Request(prompt=list(rng.integers(1, VOCAB, size=8)),
                        max_new_tokens=6) for _ in range(6)]
        for r in reqs:
            while True:
                try:
                    fc.submit(r)
                    break
                except QueueFull:
                    if not fc.step():
                        time.sleep(0.002)
        fc.kill_worker(0)
        fc.respawn_worker(0)
        while fc.pending:
            if not fc.step():
                time.sleep(0.002)
        assert all(r.status is RequestStatus.FINISHED for r in reqs)
        assert all(r.retries == 0 for r in reqs)
        assert fc.audit_worker(0)["pages_in_use"] == 0
        assert fc.audit_worker(1)["pages_in_use"] == 0
    finally:
        fc.close()
    _assert_no_orphans(fc)


# ----------------------------------------------------------- TCP transport
#: The LoRA fleet spec: every worker builds a per-process adapter
#: arena; adapters are then broadcast by value over the transport.
SPEC_LORA = {**SPEC, "engine": {**SPEC["engine"],
                                "lora": {"rank": 4, "arena_slots": 2,
                                         "host_bytes": 1 << 22}}}


def _mk_adapter_sites(seed, rank=4, scale=0.5):
    """Stacked per-site (A, B) pairs matching SPEC's model geometry
    (hidden=32, layers=2) — deterministic, so any process holds
    bitwise-identical adapters."""
    rng = np.random.default_rng(seed)
    hd, layers = 32, 2
    dims = {"qkv": (hd, 3 * hd), "proj": (hd, hd),
            "mlp_in": (hd, 4 * hd), "mlp_out": (4 * hd, hd)}
    return {s: (rng.normal(size=(layers, di, rank))
                .astype(np.float32) * scale,
                rng.normal(size=(layers, rank, do))
                .astype(np.float32) * scale)
            for s, (di, do) in dims.items()}


def test_fleet_transport_spec_validation():
    with pytest.raises(ValueError, match="transport"):
        FleetController([SPEC], transport=("carrier-pigeon",))


def test_fleet_tcp_loopback_lifecycle():
    """The TCP transport pin: a loopback AF_INET fleet (port 0 — the
    OS picks, getsockname reports) runs the full lifecycle — spawn,
    fleet-wide by-value adapter registration, a mixed base+adapter
    stream BITWISE the in-process Router oracle, resident_adapters
    visible through the snapshot wire, zero-leak worker audits,
    idempotent close, zero orphan processes. The frame codec and RPC
    surface are address-family-agnostic; only the listener and the
    worker's --socket arg change."""
    rng = np.random.default_rng(13)
    jobs = [(rng.integers(1, VOCAB, size=10).tolist(), ad)
            for ad in (None, "a1", "a1", None)]

    engines = [build_engine_from_spec(SPEC_LORA) for _ in range(2)]
    for e in engines:
        e.lora_register("a1", _mk_adapter_sites(1), alpha=0.7)
    router = Router(engines, seed=0, max_queue=32)
    rs = [Request(prompt=list(p), max_new_tokens=4, adapter=ad)
          for p, ad in jobs]
    router.run(rs)
    oracle = [list(r.output_tokens) for r in rs]
    router.close()

    fc = FleetController([SPEC_LORA, SPEC_LORA], seed=0, max_queue=32,
                         transport=("tcp", "127.0.0.1", 0))
    try:
        assert fc._worker_addr.startswith("tcp:127.0.0.1:")
        assert int(fc._worker_addr.rsplit(":", 1)[1]) > 0
        fc.lora_register("a1", _mk_adapter_sites(1), alpha=0.7)
        rs = [Request(prompt=list(p), max_new_tokens=4, adapter=ad)
              for p, ad in jobs]
        fc.run(rs)
        assert all(r.status is RequestStatus.FINISHED for r in rs)
        assert [list(r.output_tokens) for r in rs] == oracle, \
            "TCP fleet diverged bitwise from the in-process Router"
        snaps = fc._poll([0, 1])
        assert any("a1" in (s.get("resident_adapters") or [])
                   for s in snaps.values()), \
            "no worker reports the adapter resident over the wire"
        # an unknown adapter is a LOUD worker-side failure, even
        # across the transport — never a silent base-model decode
        bad = Request(prompt=jobs[0][0], max_new_tokens=2,
                      adapter="nope")
        fc.run([bad])
        assert bad.status is RequestStatus.FAILED
        assert "nope" in (bad.error or "")
        assert fc.audit_worker(0)["pages_in_use"] == 0
        assert fc.audit_worker(1)["pages_in_use"] == 0
    finally:
        fc.close()
    fc.close()                              # idempotent
    _assert_no_orphans(fc)
