"""comm module tests: mesh construction incl. the hybrid ICI/DCN helper
(single-slice degradation path — multi-slice needs a pod)."""

import jax
import numpy as np
import pytest

from apex_tpu import comm


def test_make_mesh_order_and_validation(eight_devices):
    mesh = comm.make_mesh({"data": 2, "model": 4})
    assert mesh.axis_names == ("data", "model")
    assert mesh.shape == {"data": 2, "model": 4}
    with pytest.raises(ValueError, match="needs"):
        comm.make_mesh({"data": 100})


def test_hybrid_mesh_single_slice_degrades_to_plain(eight_devices):
    mesh = comm.make_hybrid_mesh(ici_axes={"model": 4}, dcn_axes={"data": 2})
    # DCN axes outermost, same names/shape as the plain construction
    assert mesh.axis_names == ("data", "model")
    assert mesh.shape == {"data": 2, "model": 4}
    np.testing.assert_array_equal(
        np.array([[d.id for d in row] for row in mesh.devices]),
        np.arange(8).reshape(2, 4))


def test_hybrid_mesh_axis_in_one_fabric_only():
    with pytest.raises(ValueError, match="exactly one fabric"):
        comm.make_hybrid_mesh(ici_axes={"data": 2}, dcn_axes={"data": 2})


def test_hybrid_mesh_size_one_axes(eight_devices):
    mesh = comm.make_hybrid_mesh(ici_axes={"pipe": 2, "model": 2},
                                 dcn_axes={"data": 2})
    assert mesh.axis_names == ("data", "pipe", "model")
    assert mesh.shape == {"data": 2, "pipe": 2, "model": 2}
