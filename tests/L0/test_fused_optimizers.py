"""Fused optimizer tests — mirrors tests/L0/run_optimizers/
test_fused_optimizer.py (FusedAdam vs torch.optim.Adam param-wise allclose
across iterations) and test_lamb.py (vs an in-test reference NVLAMB impl)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.optimizers import (FusedAdam, fused_adagrad, fused_adam,
                                 fused_lamb, fused_novograd, fused_sgd)


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "layer1": {"kernel": jnp.asarray(rng.randn(8, 16), jnp.float32),
                   "bias": jnp.asarray(rng.randn(16), jnp.float32)},
        "layer2": {"kernel": jnp.asarray(rng.randn(16, 4), jnp.float32)},
    }


def _grads_like(params, seed):
    rng = np.random.RandomState(seed)
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.randn(*p.shape), jnp.float32), params)


def _torch_mirror(params):
    import torch

    leaves, _ = jax.tree_util.tree_flatten(params)
    return [torch.nn.Parameter(torch.tensor(np.asarray(l))) for l in leaves]


def _assert_tree_close(params, torch_params, atol=1e-5, rtol=1e-3):
    leaves = jax.tree_util.tree_leaves(params)
    for leaf, tp in zip(leaves, torch_params):
        np.testing.assert_allclose(np.asarray(leaf), tp.detach().numpy(),
                                   atol=atol, rtol=rtol)


@pytest.mark.parametrize("adam_w,wd", [(False, 0.0), (False, 0.01),
                                       (True, 0.01)])
def test_fused_adam_vs_torch(adam_w, wd):
    import torch

    params = _params()
    tparams = _torch_mirror(params)
    lr, betas, eps = 1e-2, (0.9, 0.999), 1e-8
    topt = (torch.optim.AdamW(tparams, lr=lr, betas=betas, eps=eps,
                              weight_decay=wd) if adam_w else
            torch.optim.Adam(tparams, lr=lr, betas=betas, eps=eps,
                             weight_decay=wd))
    opt = fused_adam(lr, betas[0], betas[1], eps, wd, adam_w_mode=adam_w)
    state = opt.init(params)
    update = jax.jit(opt.update)
    for i in range(10):
        grads = _grads_like(params, 100 + i)
        for tp, g in zip(tparams, jax.tree_util.tree_leaves(grads)):
            tp.grad = torch.tensor(np.asarray(g))
        topt.step()
        updates, state = update(grads, state, params)
        params = optax.apply_updates(params, updates)
        _assert_tree_close(params, tparams)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_adam_tree_and_flat_layouts_bitwise(dtype):
    """The default tree layout (per-leaf state, XLA-fused — the
    TPU-native redesign measured 3.6x faster on v5e) must produce the
    EXACT parameter trajectory of the round-1..4 flat superbuffer
    layout, including mixed-precision leaf casting."""
    params = jax.tree_util.tree_map(lambda x: x.astype(dtype), _params())
    tx_t = fused_adam(1e-2, weight_decay=0.01, layout="tree")
    tx_f = fused_adam(1e-2, weight_decay=0.01, layout="flat")
    st_t, st_f = tx_t.init(params), tx_f.init(params)
    # tree layout: per-leaf fp32 state shaped like params
    assert jax.tree_util.tree_structure(st_t.m) == \
        jax.tree_util.tree_structure(params)
    p_t = p_f = params
    for i in range(5):
        g = jax.tree_util.tree_map(lambda x: x.astype(dtype),
                                   _grads_like(params, i))
        u_t, st_t = tx_t.update(g, st_t, p_t)
        u_f, st_f = tx_f.update(g, st_f, p_f)
        p_t = optax.apply_updates(p_t, u_t)
        p_f = optax.apply_updates(p_f, u_f)
    for a, b in zip(jax.tree_util.tree_leaves(p_t),
                    jax.tree_util.tree_leaves(p_f)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    with pytest.raises(ValueError, match="layout"):
        fused_adam(layout="superbuffer")


def test_fused_sgd_vs_torch():
    import torch

    params = _params(1)
    tparams = _torch_mirror(params)
    topt = torch.optim.SGD(tparams, lr=0.05, momentum=0.9, weight_decay=1e-4)
    opt = fused_sgd(0.05, momentum=0.9, weight_decay=1e-4)
    state = opt.init(params)
    update = jax.jit(opt.update)
    for i in range(8):
        grads = _grads_like(params, 200 + i)
        for tp, g in zip(tparams, jax.tree_util.tree_leaves(grads)):
            tp.grad = torch.tensor(np.asarray(g))
        topt.step()
        updates, state = update(grads, state, params)
        params = optax.apply_updates(params, updates)
        _assert_tree_close(params, tparams)


def _reference_lamb_step(p, g, m, v, step, lr, b1, b2, eps, wd,
                         max_grad_norm, global_norm, use_nvlamb=False):
    """In-test NVLAMB reference (the pattern of apex tests/L0/run_optimizers/
    test_lamb.py, which defines RefLAMB in the test file)."""
    clip = global_norm / max_grad_norm if global_norm > max_grad_norm else 1.0
    g = g / clip
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    mhat = m / (1 - b1 ** step)
    vhat = v / (1 - b2 ** step)
    upd = mhat / (np.sqrt(vhat) + eps) + wd * p
    w_norm = np.linalg.norm(p)
    u_norm = np.linalg.norm(upd)
    ratio = w_norm / u_norm if (w_norm > 0 and u_norm > 0) else 1.0
    if wd == 0.0 and not use_nvlamb:
        ratio = 1.0
    return p - lr * ratio * upd, m, v


@pytest.mark.parametrize("momentum,nesterov,wd_after",
                         [(0.9, False, False), (0.9, True, False),
                          (0.0, False, False), (0.9, False, True)])
def test_fused_sgd_tree_and_flat_layouts_bitwise(momentum, nesterov,
                                                 wd_after):
    """Tree (default) and flat SGD layouts must produce the exact same
    trajectory across the momentum/nesterov/wd_after_momentum variants."""
    params = _params()
    kw = dict(momentum=momentum, weight_decay=0.01, nesterov=nesterov,
              wd_after_momentum=wd_after)
    tx_t = fused_sgd(1e-2, layout="tree", **kw)
    tx_f = fused_sgd(1e-2, layout="flat", **kw)
    st_t, st_f = tx_t.init(params), tx_f.init(params)
    p_t = p_f = params
    for i in range(4):
        g = _grads_like(params, i)
        u_t, st_t = tx_t.update(g, st_t, p_t)
        u_f, st_f = tx_f.update(g, st_f, p_f)
        p_t = optax.apply_updates(p_t, u_t)
        p_f = optax.apply_updates(p_f, u_f)
    for a, b in zip(jax.tree_util.tree_leaves(p_t),
                    jax.tree_util.tree_leaves(p_f)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_lamb_vs_reference():
    n = 64
    rng = np.random.RandomState(7)
    p0 = rng.randn(n).astype(np.float32)
    lr, b1, b2, eps, wd, mgn = 1e-2, 0.9, 0.999, 1e-6, 0.01, 1.0

    params = {"w": jnp.asarray(p0)}
    opt = fused_lamb(lr, b1, b2, eps, wd, max_grad_norm=mgn)
    state = opt.init(params)
    update = jax.jit(opt.update)

    ref_p, ref_m, ref_v = p0.copy(), np.zeros(n, np.float32), np.zeros(n, np.float32)
    for step in range(1, 6):
        g = rng.randn(n).astype(np.float32)
        gn = np.linalg.norm(g)
        ref_p, ref_m, ref_v = _reference_lamb_step(
            ref_p, g, ref_m, ref_v, step, lr, b1, b2, eps, wd, mgn, gn)
        updates, state = update({"w": jnp.asarray(g)}, state, params)
        params = optax.apply_updates(params, updates)
        np.testing.assert_allclose(np.asarray(params["w"]), ref_p, atol=1e-5,
                                   rtol=1e-4)


def test_lamb_no_decay_trust_ratio_one():
    # wd=0, use_nvlamb=False → ratio forced to 1 → reduces to clipped Adam
    params = {"w": jnp.ones((16,), jnp.float32)}
    opt = fused_lamb(0.1, weight_decay=0.0, max_grad_norm=1e9)
    state = opt.init(params)
    g = {"w": jnp.full((16,), 0.5, jnp.float32)}
    updates, state = opt.update(g, state, params)
    newp = optax.apply_updates(params, updates)
    # adam first step: mhat = g, vhat = g*g → upd = sign(g)/(1+eps-ish)
    expect = 1.0 - 0.1 * (0.5 / (0.5 + 1e-6))
    np.testing.assert_allclose(np.asarray(newp["w"]),
                               np.full(16, expect, np.float32), rtol=1e-4)


def test_fused_novograd_first_step_norm_init():
    params = {"w": jnp.ones((8,), jnp.float32)}
    opt = fused_novograd(0.1, beta1=0.95, beta2=0.98, weight_decay=0.0,
                         grad_averaging=True)
    state = opt.init(params)
    g = np.full(8, 2.0, np.float32)
    updates, state = opt.update({"w": jnp.asarray(g)}, state, params)
    # first step: v = ||g||^2, m = (1-b1)*g/(||g||+eps), p -= lr*m
    gnorm = np.linalg.norm(g)
    expect_m = 0.05 * g / (gnorm + 1e-8)
    newp = optax.apply_updates(params, updates)
    np.testing.assert_allclose(np.asarray(newp["w"]), 1.0 - 0.1 * expect_m,
                               rtol=1e-5)
    np.testing.assert_allclose(float(state.v["w"]), gnorm ** 2, rtol=1e-5)


def test_fused_adagrad_vs_torch():
    import torch

    params = _params(2)
    tparams = _torch_mirror(params)
    topt = torch.optim.Adagrad(tparams, lr=0.05, eps=1e-10,
                               weight_decay=1e-4)
    opt = fused_adagrad(0.05, eps=1e-10, weight_decay=1e-4)
    state = opt.init(params)
    for i in range(6):
        grads = _grads_like(params, 300 + i)
        for tp, g in zip(tparams, jax.tree_util.tree_leaves(grads)):
            tp.grad = torch.tensor(np.asarray(g))
        topt.step()
        updates, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        _assert_tree_close(params, tparams, atol=1e-5, rtol=1e-3)


def test_fused_adam_class_api():
    params = _params(3)
    opt = FusedAdam(params, lr=1e-3)
    grads = _grads_like(params, 42)
    newp = opt.step(grads)
    assert jax.tree_util.tree_structure(newp) == \
        jax.tree_util.tree_structure(params)
    with pytest.raises(RuntimeError):
        FusedAdam(params, amsgrad=True)
    sd = opt.state_dict()
    opt2 = FusedAdam(params, lr=1e-3)
    opt2.load_state_dict(sd)
    assert int(opt2.state.count) == 1


def test_fused_adam_with_amp_train_step():
    """FusedAdam composes with the amp O2 master-weight step."""
    from apex_tpu.amp import make_train_step, resolve_policy, init_scaler

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"].astype(x.dtype)
        return jnp.mean((pred.astype(jnp.float32) - y) ** 2)

    policy = resolve_policy("O2", half_dtype=jnp.float16, verbose=False)
    init_fn, step_fn = make_train_step(loss_fn, fused_adam(1e-2), policy)
    state = init_fn({"w": jnp.ones((4, 2), jnp.float32)})
    state = state.replace(scaler=init_scaler("dynamic", init_scale=128.0))
    step = jax.jit(step_fn)
    x = jnp.ones((8, 4), jnp.float32)
    y = jnp.zeros((8, 2), jnp.float32)
    prev = float("inf")
    for _ in range(10):
        state, m = step(state, (x, y))
        assert not bool(m["found_inf"])
        cur = float(m["loss"])
        assert cur <= prev + 1e-3
        prev = cur
