"""Mirrors tests/L0/run_transformer/test_parallel_state.py of the reference:
initialize with (tp, pp), check group sizes, destroy."""

import numpy as np
import pytest

from apex_tpu import comm
from apex_tpu.transformer import parallel_state


@pytest.fixture(autouse=True)
def _clean():
    yield
    parallel_state.destroy_model_parallel()


def test_initialize_shapes(eight_devices):
    mesh = parallel_state.initialize_model_parallel(2, 2,
                                                    devices=eight_devices)
    assert parallel_state.model_parallel_is_initialized()
    assert parallel_state.get_tensor_model_parallel_world_size() == 2
    assert parallel_state.get_pipeline_model_parallel_world_size() == 2
    assert parallel_state.get_data_parallel_world_size() == 2
    assert dict(mesh.shape) == {"data": 2, "pipe": 2, "model": 2}
    # model must be the innermost (fastest-varying) axis → ICI neighbours
    assert tuple(mesh.axis_names) == ("data", "pipe", "model")


def test_indivisible_world_raises(eight_devices):
    with pytest.raises(RuntimeError):
        parallel_state.initialize_model_parallel(3, 1,
                                                 devices=eight_devices)


def test_virtual_pipeline_bookkeeping(eight_devices):
    parallel_state.initialize_model_parallel(
        1, 2, virtual_pipeline_model_parallel_size_=2,
        devices=eight_devices)
    assert parallel_state.get_virtual_pipeline_model_parallel_world_size() == 2
    assert parallel_state.get_virtual_pipeline_model_parallel_rank() == 0
    parallel_state.set_virtual_pipeline_model_parallel_rank(1)
    assert parallel_state.get_virtual_pipeline_model_parallel_rank() == 1
    # vpp rank 1 is not the first virtual stage
    assert not parallel_state.is_pipeline_first_stage()


def test_destroy(eight_devices):
    parallel_state.initialize_model_parallel(2, 1, devices=eight_devices)
    parallel_state.destroy_model_parallel()
    assert not parallel_state.model_parallel_is_initialized()
    # after destroy the default data-only mesh comes back
    assert comm.axis_size("model") == 1
