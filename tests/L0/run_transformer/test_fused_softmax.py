"""Mirrors the reference's fused softmax tests (apex/contrib-style kernel vs
torch softmax): our fused path vs jax.nn.softmax with masking."""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.transformer import AttnMaskType
from apex_tpu.transformer.functional import (FusedScaleMaskSoftmax,
                                             scaled_masked_softmax,
                                             scaled_upper_triang_masked_softmax)


def test_scaled_masked_matches_reference():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 8))
    mask = jax.random.bernoulli(jax.random.PRNGKey(1), 0.3, (2, 1, 8, 8))
    y = scaled_masked_softmax(x, mask, scale=0.5)
    ref = jax.nn.softmax(jnp.where(mask, -10000.0, x * 0.5), axis=-1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-6)


def test_causal_masks_upper_triangle():
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 8))
    y = scaled_upper_triang_masked_softmax(x)
    out = np.asarray(y)
    iu = np.triu_indices(8, k=1)
    assert (out[:, iu[0], iu[1]] < 1e-4).all()
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


def test_bf16_io_fp32_math():
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 16, 16),
                          jnp.bfloat16)
    y = scaled_upper_triang_masked_softmax(x)
    assert y.dtype == jnp.bfloat16
    ref = jax.nn.softmax(
        jnp.where(jnp.triu(jnp.ones((16, 16), bool), 1), -1e4,
                  jnp.asarray(x, jnp.float32)), axis=-1)
    np.testing.assert_allclose(np.asarray(y, np.float32), np.asarray(ref),
                               atol=1e-2)


def test_module_dispatch():
    m = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal, scale=2.0)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 8, 8))
    y = m(x)
    ref = scaled_upper_triang_masked_softmax(x, scale=2.0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref))

    import pytest
    with pytest.raises(ValueError):
        FusedScaleMaskSoftmax(input_in_fp16=True, input_in_bf16=True)
    with pytest.raises(ValueError):
        FusedScaleMaskSoftmax(scale=2.0, softmax_in_fp32=False)
