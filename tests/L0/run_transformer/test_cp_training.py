"""Context-parallel TRAINING test: ring attention inside an amp-O2 train
step over the ``context`` axis — the long-context story end-to-end, not just
the attention op.

Grad correctness note (why grad_average_axis="context" is right): params are
replicated per shard; shard r's local backward already accumulates the
k/v-path contributions of every shard (they flow back through the ring's
ppermute transposes), while q-path terms live only on their own shard —
each path term exists on exactly one shard's copy, so the psum-mean over
the axis reconstructs d(mean-over-shards loss)/dθ with no double counting.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.optimizers import fused_adam
from apex_tpu.transformer import ring_attention

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow

B, H_HEADS, S_LOCAL, D, HID = 2, 4, 16, 8, 32


def _attn_model(p, x, axis_name):
    """One pre-LN-ish attention block over seq-sharded activations."""
    qkv = x @ p["w_qkv"]                                # [B, S_l, 3*HID]
    qkv = qkv.reshape(B, S_LOCAL, 3, H_HEADS, D)
    q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
    o = ring_attention(q, k, v, axis_name=axis_name, causal=True)
    o = jnp.moveaxis(o, 1, 2).reshape(B, S_LOCAL, HID)
    return x + o @ p["w_out"]


def test_ring_attention_train_step_decreases_loss(eight_devices):
    mesh = Mesh(np.array(eight_devices), ("context",))
    rs = np.random.RandomState(0)
    params = {
        "w_qkv": jnp.asarray(rs.randn(HID, 3 * HID).astype(np.float32) * 0.1),
        "w_out": jnp.asarray(rs.randn(HID, HID).astype(np.float32) * 0.1),
    }
    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic")

    def loss_fn(p, batch):
        x, t = batch
        y = _attn_model(p, jnp.asarray(x, policy.compute_dtype), "context")
        return jnp.mean((jnp.asarray(y, jnp.float32) - t) ** 2)

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_adam(3e-3), policy,
                                           grad_average_axis="context")

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), (P(None, "context"),
                                       P(None, "context"))),
                       out_specs=(P(), P()), check_vma=False)
    def run(state, batch):
        for _ in range(6):
            state, metrics = step_fn(state, batch)
        first = metrics  # last step's metrics
        return state.master_params, first["loss"]

    # global sequence 8*S_LOCAL = 128 tokens, sharded contiguously
    x = rs.randn(B, 8 * S_LOCAL, HID).astype(np.float32)
    t = np.tanh(x[:, ::-1].copy())  # nontrivial target
    state = init_fn(params)
    masters, final_loss = jax.jit(run)(state, (jnp.asarray(x),
                                              jnp.asarray(t)))

    # baseline: untouched params' loss on the same batch (single-shard ref)
    from apex_tpu.kernels.flash_attention import mha_reference

    def ref_loss(p):
        qkv = (x @ np.asarray(p["w_qkv"])).reshape(B, 8 * S_LOCAL, 3,
                                                   H_HEADS, D)
        q, k, v = (np.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
        o = np.asarray(mha_reference(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), causal=True,
                                     scale=D ** -0.5))
        y = x + np.moveaxis(o, 1, 2).reshape(B, 8 * S_LOCAL, HID) \
            @ np.asarray(p["w_out"])
        return float(np.mean((y - t) ** 2))

    assert np.isfinite(float(final_loss))
    assert float(final_loss) < ref_loss(params), (
        float(final_loss), ref_loss(params))
    # trained masters evaluated on the FULL (unsharded) reference model also
    # improve — proving the sharded training optimized the real objective
    assert ref_loss(jax.tree_util.tree_map(np.asarray, masters)) \
        < ref_loss(params)


# -------------------------------------------------------- ring + dropout
def test_ring_attention_dropout_deterministic_and_unbiased():
    """Ring attention with fused prob-dropout: deterministic per seed,
    varies across seeds, unbiased in expectation vs the no-dropout ring,
    for both layouts."""
    from apex_tpu.transformer.context_parallel import (ring_attention,
                                                       zigzag_order)

    n = 4
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.array(devs[:n]), ("context",))
    B, H, S, D = 1, 2, 64, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, H, S, D), jnp.float32) for kk in ks)
    spec = P(None, None, "context", None)

    for layout in ("contiguous", "zigzag"):
        if layout == "zigzag":
            order = zigzag_order(S, n)
            q_, k_, v_ = (jnp.take(t, order, axis=2) for t in (q, k, v))
        else:
            q_, k_, v_ = q, k, v
        fn = jax.jit(shard_map(
            lambda q, k, v, s: ring_attention(
                q, k, v, causal=True, layout=layout,
                dropout_rate=0.3, dropout_seed=s),
            mesh=mesh, in_specs=(spec,) * 3 + (P(),), out_specs=spec))
        base_fn = jax.jit(shard_map(
            functools.partial(ring_attention, causal=True, layout=layout),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))

        d1 = fn(q_, k_, v_, jnp.int32(1))
        d1b = fn(q_, k_, v_, jnp.int32(1))
        d2 = fn(q_, k_, v_, jnp.int32(2))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d1b))
        assert not np.allclose(np.asarray(d1), np.asarray(d2)), layout

        base = np.asarray(base_fn(q_, k_, v_))
        acc = np.zeros_like(base)
        m = 24
        for s in range(m):
            acc += np.asarray(fn(q_, k_, v_, jnp.int32(50 + s)))
        # Monte-Carlo bound on the MEAN deviation (the early causal rows
        # keep a single softmax entry, so the per-element variance is huge
        # and a max-norm bound would need thousands of samples)
        assert np.abs(acc / m - base).mean() < 0.08, layout


def test_ring_attention_dropout_grads_finite_and_deterministic():
    from apex_tpu.transformer.context_parallel import ring_attention

    n = 4
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.array(devs[:n]), ("context",))
    B, H, S, D = 1, 2, 64, 16
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (B, H, S, D), jnp.float32) for kk in ks)
    spec = P(None, None, "context", None)
    fn = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=True,
                                       dropout_rate=0.2,
                                       dropout_seed=jnp.int32(9)),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))

    def loss(q, k, v):
        return (fn(q, k, v).astype(jnp.float32) ** 2).sum()

    g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.isfinite(np.asarray(a)).all()
    # dropout must actually change the grads vs the clean path
    fn0 = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=True),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))

    def loss0(q, k, v):
        return (fn0(q, k, v).astype(jnp.float32) ** 2).sum()

    g0 = jax.grad(loss0, argnums=(0, 1, 2))(q, k, v)
    assert not np.allclose(np.asarray(g1[0]), np.asarray(g0[0]))


def test_ring_attention_dropout_rate_validation():
    from jax.sharding import Mesh as _M
    n = 4
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs 4 devices")
    mesh = _M(np.array(devs[:n]), ("context",))
    q = jnp.zeros((1, 1, 4 * 8, 8))
    spec = P(None, None, "context", None)
    fn_bad = shard_map(
        lambda q: ring_attention(q, q, q, dropout_rate=1.0,
                                 dropout_seed=jnp.int32(0)),
        mesh=mesh, in_specs=(spec,), out_specs=spec)
    with pytest.raises(ValueError, match="dropout_rate"):
        jax.jit(fn_bad)(q)
    fn_noseed = shard_map(
        lambda q: ring_attention(q, q, q, dropout_rate=0.5),
        mesh=mesh, in_specs=(spec,), out_specs=spec)
    with pytest.raises(ValueError, match="dropout_seed"):
        jax.jit(fn_noseed)(q)
