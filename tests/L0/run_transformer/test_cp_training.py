"""Context-parallel TRAINING test: ring attention inside an amp-O2 train
step over the ``context`` axis — the long-context story end-to-end, not just
the attention op.

Grad correctness note (why grad_average_axis="context" is right): params are
replicated per shard; shard r's local backward already accumulates the
k/v-path contributions of every shard (they flow back through the ring's
ppermute transposes), while q-path terms live only on their own shard —
each path term exists on exactly one shard's copy, so the psum-mean over
the axis reconstructs d(mean-over-shards loss)/dθ with no double counting.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.optimizers import fused_adam
from apex_tpu.transformer import ring_attention

B, H_HEADS, S_LOCAL, D, HID = 2, 4, 16, 8, 32


def _attn_model(p, x, axis_name):
    """One pre-LN-ish attention block over seq-sharded activations."""
    qkv = x @ p["w_qkv"]                                # [B, S_l, 3*HID]
    qkv = qkv.reshape(B, S_LOCAL, 3, H_HEADS, D)
    q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
    o = ring_attention(q, k, v, axis_name=axis_name, causal=True)
    o = jnp.moveaxis(o, 1, 2).reshape(B, S_LOCAL, HID)
    return x + o @ p["w_out"]


def test_ring_attention_train_step_decreases_loss(eight_devices):
    mesh = Mesh(np.array(eight_devices), ("context",))
    rs = np.random.RandomState(0)
    params = {
        "w_qkv": jnp.asarray(rs.randn(HID, 3 * HID).astype(np.float32) * 0.1),
        "w_out": jnp.asarray(rs.randn(HID, HID).astype(np.float32) * 0.1),
    }
    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic")

    def loss_fn(p, batch):
        x, t = batch
        y = _attn_model(p, jnp.asarray(x, policy.compute_dtype), "context")
        return jnp.mean((jnp.asarray(y, jnp.float32) - t) ** 2)

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_adam(3e-3), policy,
                                           grad_average_axis="context")

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), (P(None, "context"),
                                       P(None, "context"))),
                       out_specs=(P(), P()), check_vma=False)
    def run(state, batch):
        for _ in range(6):
            state, metrics = step_fn(state, batch)
        first = metrics  # last step's metrics
        return state.master_params, first["loss"]

    # global sequence 8*S_LOCAL = 128 tokens, sharded contiguously
    x = rs.randn(B, 8 * S_LOCAL, HID).astype(np.float32)
    t = np.tanh(x[:, ::-1].copy())  # nontrivial target
    state = init_fn(params)
    masters, final_loss = jax.jit(run)(state, (jnp.asarray(x),
                                              jnp.asarray(t)))

    # baseline: untouched params' loss on the same batch (single-shard ref)
    from apex_tpu.kernels.flash_attention import mha_reference

    def ref_loss(p):
        qkv = (x @ np.asarray(p["w_qkv"])).reshape(B, 8 * S_LOCAL, 3,
                                                   H_HEADS, D)
        q, k, v = (np.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
        o = np.asarray(mha_reference(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), causal=True,
                                     scale=D ** -0.5))
        y = x + np.moveaxis(o, 1, 2).reshape(B, 8 * S_LOCAL, HID) \
            @ np.asarray(p["w_out"])
        return float(np.mean((y - t) ** 2))

    assert np.isfinite(float(final_loss))
    assert float(final_loss) < ref_loss(params), (
        float(final_loss), ref_loss(params))
    # trained masters evaluated on the FULL (unsharded) reference model also
    # improve — proving the sharded training optimized the real objective
    assert ref_loss(jax.tree_util.tree_map(np.asarray, masters)) \
        < ref_loss(params)
