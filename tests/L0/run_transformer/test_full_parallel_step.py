"""The full-parallelism integration test: dp × tp(sp) × pp × ep in ONE
jitted amp-O2 train step — the driver's dryrun_multichip contract, kept
honest in CI on the 8-virtual-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from apex_tpu.utils.compat import shard_map

from apex_tpu.transformer.testing import (build_full_parallel_step,
                                          factor_mesh_axes,
                                          make_full_parallel_inputs)

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow


def _run(devices, axes, *, opt_level="O2", n_steps=3, seed=0, seq=8,
         capacity_factor=1.25, num_chunks=1):
    dp, pp, tp = axes["data"], axes["pipe"], axes["model"]
    n = dp * pp * tp
    mesh = Mesh(np.array(devices[:n]).reshape(dp, pp, tp),
                ("data", "pipe", "model"))
    params, specs, mask, mb, tg, dims = make_full_parallel_inputs(
        n_stages=pp, tp=tp, dp=dp, n_experts=4, seed=seed, seq=seq,
        capacity_factor=capacity_factor, num_chunks=num_chunks)
    run = build_full_parallel_step(dims, mask, opt_level=opt_level,
                                   n_steps=n_steps)
    sharded = jax.jit(shard_map(
        run, mesh=mesh,
        in_specs=(specs, P(None, "data", "model"), P(None, "data", "model")),
        out_specs=P(), check_vma=False))
    return np.asarray(sharded(params, mb, tg))


def test_factor_mesh_axes():
    assert factor_mesh_axes(8) == {"data": 2, "pipe": 2, "model": 2}
    assert factor_mesh_axes(4) == {"data": 1, "pipe": 2, "model": 2}
    assert factor_mesh_axes(2) == {"data": 1, "pipe": 1, "model": 2}
    assert factor_mesh_axes(1) == {"data": 1, "pipe": 1, "model": 1}
    for n in (1, 2, 4, 8):
        f = factor_mesh_axes(n)
        assert f["data"] * f["pipe"] * f["model"] == n


@pytest.mark.parametrize("axes", [
    {"data": 2, "pipe": 2, "model": 2},
    {"data": 4, "pipe": 2, "model": 1},
    {"data": 1, "pipe": 2, "model": 4},
    {"data": 2, "pipe": 1, "model": 2},
])
def test_full_parallel_train_step(eight_devices, axes):
    losses = _run(eight_devices, axes)
    assert losses.shape == (3,)
    assert np.isfinite(losses).all(), losses
    # same batch each step: training must make progress
    assert losses[-1] < losses[0], losses


def test_tp_width_is_numerically_invisible(eight_devices):
    """Same seed → same GLOBAL model and batch; cutting it tp=2 vs tp=4
    (dp=1, pp=2 fixed) must produce the same fp32 loss trajectory — the
    parallel layout is an implementation detail, not a numerics change.

    capacity_factor is set high enough that no token drops: switch-MoE
    drops depend on which tokens share a shard, the one legitimately
    layout-dependent behavior."""
    l2 = _run(eight_devices, {"data": 1, "pipe": 2, "model": 2},
              opt_level="O0", n_steps=2, seed=11, capacity_factor=64)
    l4 = _run(eight_devices, {"data": 1, "pipe": 2, "model": 4},
              opt_level="O0", n_steps=2, seed=11, capacity_factor=64)
    np.testing.assert_allclose(l2, l4, rtol=1e-5, atol=1e-6)


# dp-width exact parity is intentionally NOT asserted: switch-MoE capacity
# is tokens-per-shard dependent, so changing dp legitimately changes which
# overflow tokens drop (a property of token-dropping routers, not a bug).
# The dispatch math itself is exactly parity-tested in test_moe.py; dp=2/4
# layouts are covered by the parametrized step test above.


def test_full_parallel_with_interleaved_pipeline(eight_devices):
    """dp2 × pp2(v=2 virtual chunks → 4 logical stages) × tp2 — the
    interleaved 1F1B schedule composed with every other axis."""
    losses = _run(eight_devices, {"data": 2, "pipe": 2, "model": 2},
                  seed=21, num_chunks=2)
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses
