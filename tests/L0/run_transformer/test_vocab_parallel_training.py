"""Vocab-parallel LM training composition.

The Megatron LM hot path end-to-end: VocabParallelEmbedding → TP MLP →
tied vocab-parallel logits → vocab_parallel_cross_entropy, trained for
several steps under shard_map over 'model' — asserted EXACTLY equal to the
same model trained densely on one device (the parallel layout must be an
implementation detail).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.tensor_parallel import (
    VocabParallelEmbedding, copy_to_tensor_model_parallel_region,
    vocab_parallel_cross_entropy)

VOCAB, HID, TPW = 64, 16, 4
B, S = 2, 8
LR = 0.1


def _init_tables(seed):
    rs = np.random.RandomState(seed)
    return {
        "emb": (rs.randn(VOCAB, HID) * 0.1).astype(np.float32),
        "w": (rs.randn(HID, HID) * 0.2).astype(np.float32),
    }


def _dense_loss(p, toks):
    x = p["emb"][toks]                       # [B, S, H]
    h = jnp.tanh(x @ p["w"])
    logits = h @ p["emb"].T                  # tied head: [B, S, V]
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(
        logp, toks[..., None], axis=-1))


def _dense_train(params, toks, steps):
    p = jax.tree_util.tree_map(jnp.asarray, params)
    losses = []
    for _ in range(steps):
        l, g = jax.value_and_grad(_dense_loss)(p, toks)
        p = jax.tree_util.tree_map(lambda a, b: a - LR * b, p, g)
        losses.append(float(l))
    return losses, p


def test_vocab_parallel_training_matches_dense(eight_devices):
    mesh = Mesh(np.array(eight_devices[:TPW]), ("model",))
    params = _init_tables(0)
    toks = jnp.asarray(np.random.RandomState(1).randint(0, VOCAB, (B, S)))

    emb_mod = VocabParallelEmbedding(num_embeddings=VOCAB, embedding_dim=HID,
                                     world_size=TPW)

    def tp_loss(p_local, toks):
        # embedding lookup (psum of masked partials inside the module)
        x = emb_mod.apply({"params": {"embedding": p_local["emb"]}}, toks)
        h = jnp.tanh(x @ p_local["w"])
        # Megatron's parallel-LM-head rule: the head's input goes through
        # copy_to (identity fwd, psum bwd) so every shard's dL/dh is the
        # FULL sum over vocab blocks — without it each shard back-props a
        # per-block partial and the replicated w / lookup grads are wrong
        h = copy_to_tensor_model_parallel_region(h, "model")
        # tied vocab-parallel head: local logits block [B, S, V/tp]
        logits_local = h @ p_local["emb"].T
        losses = vocab_parallel_cross_entropy(logits_local, toks)
        return jnp.mean(losses)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=({"emb": P("model"), "w": P()}, P()),
                       out_specs=(P(), {"emb": P("model"), "w": P()}),
                       check_vma=False)
    def train(p_sharded, toks):
        p = {"emb": p_sharded["emb"], "w": p_sharded["w"]}
        losses = []
        for _ in range(4):
            l, g = jax.value_and_grad(tp_loss)(p, toks)
            # with copy_to in place every shard's grads are complete (w:
            # identical full grad per shard; emb: the local vocab block's
            # full grad), so plain per-shard SGD keeps the copies in sync
            p = jax.tree_util.tree_map(lambda a, b: a - LR * b, p, g)
            losses.append(l)
        return jnp.stack(losses), p

    emb_sharded = jnp.asarray(params["emb"])  # [V, H] → P('model') shards
    p_sharded = {"emb": emb_sharded, "w": jnp.asarray(params["w"])}
    tp_losses, p_final = jax.jit(train)(p_sharded, toks)

    dense_losses, p_dense = _dense_train(params, toks, 4)
    np.testing.assert_allclose(np.asarray(tp_losses), dense_losses,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(p_final["emb"]),
                               np.asarray(p_dense["emb"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(p_final["w"]),
                               np.asarray(p_dense["w"]),
                               rtol=1e-5, atol=1e-6)
    # both actually learned
    assert tp_losses[-1] < tp_losses[0]
