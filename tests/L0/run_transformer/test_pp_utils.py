"""Tests for pipeline_parallel.utils (get_ltor_masks_and_position_ids,
listify_model) and schedules.build_model.

Oracle: a direct loop transcription of the reference algorithm
(apex/transformer/pipeline_parallel/utils.py — for each EOD at i:
attention_mask[(i+1):, :(i+1)] = 0; position_ids[(i+1):] -= delta)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.pipeline_parallel import (
    build_model, get_ltor_masks_and_position_ids, listify_model,
    pipeline_apply)


def _oracle(data, eod, reset_pos, reset_attn, mask_loss):
    b, s = data.shape
    attn = np.tril(np.ones((s, s), bool))
    attn = np.repeat(attn[None], b, 0)
    loss_mask = np.ones((b, s), np.float32)
    pos = np.repeat(np.arange(s)[None], b, 0).astype(np.int64)
    for bi in range(b):
        eods = np.nonzero(data[bi] == eod)[0]
        if mask_loss:
            loss_mask[bi, data[bi] == eod] = 0.0
        prev = 0
        for i in eods:
            if reset_attn:
                attn[bi, i + 1:, :i + 1] = False
            if reset_pos:
                pos[bi, i + 1:] -= (i + 1 - prev)
                prev = i + 1
    return ~attn[:, None], loss_mask, pos   # True = masked out


def test_ltor_masks_match_reference_loop():
    rng = np.random.RandomState(0)
    data = rng.randint(1, 50, size=(3, 24))
    data[0, [5, 13]] = 0          # two docs boundaries
    data[1, 0] = 0                # EOD at position 0
    data[2, 23] = 0               # EOD at the end
    for reset_pos in (False, True):
        for reset_attn in (False, True):
            for mask_loss in (False, True):
                am, lm, pid = get_ltor_masks_and_position_ids(
                    jnp.asarray(data), 0, reset_pos, reset_attn, mask_loss)
                ram, rlm, rpid = _oracle(
                    data, 0, reset_pos, reset_attn, mask_loss)
                np.testing.assert_array_equal(np.asarray(am), ram)
                np.testing.assert_array_equal(np.asarray(lm), rlm)
                np.testing.assert_array_equal(np.asarray(pid), rpid)


def test_ltor_shapes_and_causality():
    data = jnp.ones((2, 8), jnp.int32)
    am, lm, pid = get_ltor_masks_and_position_ids(data, 0)
    assert am.shape == (2, 1, 8, 8)
    assert am.dtype == jnp.bool_
    # strictly-upper triangle masked, diagonal+lower visible
    a = np.asarray(am)[0, 0]
    assert a[0, 1] and not a[1, 0] and not a[3, 3]
    np.testing.assert_array_equal(np.asarray(pid)[0], np.arange(8))
    np.testing.assert_array_equal(np.asarray(lm), 1.0)


def test_build_model_flags_and_order():
    stage = {"n": 0}

    def provider(pre_process, post_process, width=4):
        # provider is called in rank-major order; recover the logical stage
        # from the call index to check round-robin placement
        i = stage["n"]
        stage["n"] += 1
        return {"w": jnp.zeros((width,)), "pre": pre_process,
                "post": post_process, "idx": i}

    pp, v = 4, 2
    chunks = build_model(provider, num_stages=pp, num_chunks=v, width=8)
    assert len(chunks) == pp * v
    pre = [c["pre"] for c in chunks]
    post = [c["post"] for c in chunks]
    # pre_process only at logical stage 0 = (rank 0, chunk 0) = entry 0;
    # post_process only at stage pp*v-1 = (rank pp-1, chunk v-1) = last entry
    assert pre == [True] + [False] * (pp * v - 1)
    assert post == [False] * (pp * v - 1) + [True]
    assert chunks[0]["w"].shape == (8,)


def test_build_model_order_composes_correctly(eight_devices):
    """The real property build_model claims: stacking its list and sharding
    P('pipe') runs the interleaved pipeline in LOGICAL stage order
    s = chunk*pp + rank. Each provider call returns a distinct affine stage
    (call i applies x*2 + i); the pipelined output must equal composing the
    stages in s-order with the documented i(s) = rank*v + chunk mapping — a
    chunk-major build_model regression composes in the wrong order and
    fails."""
    pp, v = 4, 2
    calls = {"i": 0}

    def provider(pre_process, post_process):
        i = calls["i"]
        calls["i"] += 1
        return {"a": jnp.asarray(2.0), "b": jnp.asarray(float(i))}

    chunk_list = build_model(provider, num_stages=pp, num_chunks=v)
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *chunk_list)

    def stage_fn(c, x):
        return c["a"] * x + c["b"]

    mesh = Mesh(np.array(eight_devices[:pp]), ("pipe",))
    run = jax.jit(shard_map(
        functools.partial(pipeline_apply, stage_fn, num_stages=pp,
                          num_chunks=v, broadcast=True),
        mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P(),
        check_vma=False))
    x0 = jnp.full((3, 1), 1.0)
    out = np.asarray(run(stacked, x0))

    # sequential oracle: apply stages in logical order s, where stage s was
    # produced by provider call i = rank*v + chunk with s = chunk*pp + rank
    y = np.full((1,), 1.0)
    for s in range(pp * v):
        chunk, rank = divmod(s, pp)
        i = rank * v + chunk
        y = 2.0 * y + float(i)
    np.testing.assert_allclose(out[0], y, rtol=1e-6)

    m = {"x": 1}
    assert listify_model(m) == [m]
    assert listify_model([m, m]) == [m, m]
