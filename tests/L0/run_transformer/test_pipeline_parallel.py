"""Pipeline-parallel tests vs a sequential single-device reference.

Mirrors the reference's tests/L0/run_transformer/
test_pipeline_parallel_fwd_bwd.py, which runs a toy model under each
schedule and compares loss/grads against no-pipelining.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from apex_tpu.utils.compat import shard_map

from apex_tpu.transformer import pipeline_parallel as pp

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow

D = 8      # activation width (constant across stages, like the reference)
M = 6      # microbatches
PP = 4     # pipeline stages


def stage_fn(w, x):
    return jnp.tanh(x @ w)


def loss_fn(y, t):
    return jnp.mean((y - t) ** 2)


def _ref_loss(ws, microbatches, targets):
    """Sequential reference: run every microbatch through all stages."""
    def one(mb, t):
        h = mb
        for i in range(ws.shape[0]):
            h = stage_fn(ws[i], h)
        return loss_fn(h, t)
    losses = [one(microbatches[m], targets[m]) for m in range(M)]
    return sum(losses) / M


@pytest.fixture()
def pipe_mesh(eight_devices):
    return Mesh(np.array(eight_devices[:PP]), ("pipe",))


def _data():
    k = jax.random.PRNGKey(0)
    ws = jax.random.normal(k, (PP, D, D)) * 0.5
    mb = jax.random.normal(jax.random.PRNGKey(1), (M, 4, D))
    tg = jax.random.normal(jax.random.PRNGKey(2), (M, 4, D))
    return ws, mb, tg


def test_pipeline_apply_matches_sequential(pipe_mesh):
    ws, mb, _ = _data()

    @functools.partial(shard_map, mesh=pipe_mesh,
                       in_specs=(P("pipe"), P()), out_specs=P(),
                       check_vma=False)
    def run(ws_local, mb):
        w = ws_local[0]  # [1, D, D] local slice
        return pp.pipeline_apply(stage_fn, w, mb, num_stages=PP)

    out = run(ws, mb)
    h = mb
    for i in range(PP):
        h = stage_fn(ws[i], h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(h),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_loss_and_grads_match_sequential(pipe_mesh):
    ws, mb, tg = _data()

    pl = pp.make_pipeline_loss_fn(stage_fn, loss_fn, num_stages=PP)

    @functools.partial(shard_map, mesh=pipe_mesh,
                       in_specs=(P("pipe"), P(), P()),
                       out_specs=(P(), P("pipe")), check_vma=False)
    def run(ws_local, mb, tg):
        w = ws_local[0]
        l, g = jax.value_and_grad(pl)(w, (mb, tg))
        return l, g[None]

    loss, grads = run(ws, mb, tg)
    ref_loss, ref_grads = jax.value_and_grad(_ref_loss)(ws, mb, tg)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_grads),
                               rtol=1e-4, atol=1e-5)



def test_interleaved_pipeline(eight_devices):
    """2 devices × 2 chunks = 4 logical stages; chunk c on rank r is logical
    stage c*pp + r, so the stacked order is row r*v+c = stage c*pp+r."""
    pp_size, v = 2, 2
    mesh = Mesh(np.array(eight_devices[:pp_size]), ("pipe",))
    ws, mb, tg = _data()  # ws: [4, D, D] in logical-stage order

    # reorder: local row (r*v + c) must hold stage (c*pp + r)
    order = [c * pp_size + r for r in range(pp_size) for c in range(v)]
    ws_stacked = ws[jnp.asarray(order)]

    pl = pp.make_pipeline_loss_fn(stage_fn, loss_fn, num_stages=pp_size,
                                  num_chunks=v)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P("pipe"), P(), P()),
                       out_specs=(P(), P("pipe")), check_vma=False)
    def run(ws_local, mb, tg):
        l, g = jax.value_and_grad(pl)(ws_local, (mb, tg))
        return l, g

    loss, grads = run(ws_stacked, mb, tg)
    ref_loss, ref_grads = jax.value_and_grad(_ref_loss)(ws, mb, tg)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    inv = np.argsort(order)
    np.testing.assert_allclose(np.asarray(grads)[inv],
                               np.asarray(ref_grads), rtol=1e-4, atol=1e-5)


def test_no_pipelining_grad_accumulation():
    ws, mb, tg = _data()

    def full_loss(ws, mb1, tg1):
        h = mb1
        for i in range(PP):
            h = stage_fn(ws[i], h)
        return loss_fn(h, tg1)

    loss, grads = pp.forward_backward_no_pipelining(full_loss, ws, mb, tg)
    ref_loss, ref_grads = jax.value_and_grad(_ref_loss)(ws, mb, tg)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_grads),
                               rtol=1e-5, atol=1e-6)


def test_shift_ring(eight_devices):
    mesh = Mesh(np.array(eight_devices[:4]), ("pipe",))

    @functools.partial(shard_map, mesh=mesh, in_specs=(P("pipe"),),
                       out_specs=P("pipe"), check_vma=False)
    def shift(x):
        return pp.shift_right(x, n=4)

    x = jnp.arange(4.0)[:, None]
    out = shift(x)
    np.testing.assert_allclose(np.asarray(out)[:, 0], [3.0, 0.0, 1.0, 2.0])


def test_microbatch_calculators():
    c = pp.build_num_microbatches_calculator(
        global_batch_size=32, micro_batch_size=2, data_parallel_size=4)
    assert c.get() == 4
    r = pp.build_num_microbatches_calculator(
        rampup_batch_size=[8, 8, 100], global_batch_size=32,
        micro_batch_size=2, data_parallel_size=2)
    assert r.get() == 2  # start 8 / (2*2)
    r.update(200)
    assert r.get() == 8  # ramped to 32
    with pytest.raises(ValueError):
        pp.build_num_microbatches_calculator(
            global_batch_size=30, micro_batch_size=4, data_parallel_size=2)


def test_get_forward_backward_func():
    f = pp.get_forward_backward_func(None, 1)
    assert f is pp.forward_backward_no_pipelining
    f = pp.get_forward_backward_func(None, 4)
    assert f.func is pp.forward_backward_pipelining_without_interleaving
    f = pp.get_forward_backward_func(2, 4)
    assert f.func is pp.forward_backward_pipelining_with_interleaving


# --------------------------------------------------------------- 1F1B proper
def test_1f1b_matches_sequential(pipe_mesh):
    """Hand-scheduled 1F1B (loss, grads) == sequential oracle — same math
    as the autodiff path, different schedule."""
    ws, mb, tg = _data()

    @functools.partial(shard_map, mesh=pipe_mesh,
                       in_specs=(P("pipe"), P(), P()),
                       out_specs=(P(), P("pipe")), check_vma=False)
    def run(ws_local, mb, tg):
        l, g = pp.forward_backward_1f1b(stage_fn, loss_fn, ws_local[0],
                                        mb, tg, num_stages=PP)
        return l, g[None]

    loss, grads = jax.jit(run)(ws, mb, tg)
    ref_loss, ref_grads = jax.value_and_grad(_ref_loss)(ws, mb, tg)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_grads),
                               rtol=1e-4, atol=1e-5)


def test_1f1b_via_reference_shaped_api(pipe_mesh):
    """forward_backward_pipelining_without_interleaving(grad=True) routes to
    the 1F1B schedule and matches the oracle."""
    ws, mb, tg = _data()

    @functools.partial(shard_map, mesh=pipe_mesh,
                       in_specs=(P("pipe"), P(), P()),
                       out_specs=(P(), P("pipe")), check_vma=False)
    def run(ws_local, mb, tg):
        l, g = pp.forward_backward_pipelining_without_interleaving(
            stage_fn, loss_fn, ws_local[0], mb, tg, num_stages=PP)
        return l, g[None]

    loss, grads = jax.jit(run)(ws, mb, tg)
    ref_loss, ref_grads = jax.value_and_grad(_ref_loss)(ws, mb, tg)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_grads),
                               rtol=1e-4, atol=1e-5)


def test_1f1b_loss_scale_scales_grads_only(pipe_mesh):
    """loss_scale seeds the cotangent (amp composition): grads x scale,
    reported loss unscaled."""
    ws, mb, tg = _data()

    def run_with(scale):
        @functools.partial(shard_map, mesh=pipe_mesh,
                           in_specs=(P("pipe"), P(), P()),
                           out_specs=(P(), P("pipe")), check_vma=False)
        def run(ws_local, mb, tg):
            l, g = pp.forward_backward_1f1b(
                stage_fn, loss_fn, ws_local[0], mb, tg, num_stages=PP,
                loss_scale=scale)
            return l, g[None]
        return jax.jit(run)(ws, mb, tg)

    l1, g1 = run_with(None)
    l8, g8 = run_with(8.0)
    np.testing.assert_allclose(float(l1), float(l8), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g8), 8.0 * np.asarray(g1),
                               rtol=1e-5, atol=1e-6)


def test_1f1b_memory_flat_as_microbatches_double(pipe_mesh):
    """THE 1F1B property (VERDICT round-1 item 3): peak temp memory of the
    compiled step stays flat as M doubles, while the autodiff fill-drain
    path's residual stash grows with M."""
    D2 = 64

    def big_stage(w, x):
        return jnp.tanh(x @ w)

    def temp_bytes(fn, M):
        ws = jnp.ones((PP, D2, D2))
        mb = jnp.ones((M, 32, D2))
        tg = jnp.ones((M, 32, D2))
        c = jax.jit(fn).lower(ws, mb, tg).compile()
        return c.memory_analysis().temp_size_in_bytes

    def onef1b(ws, mb, tg):
        @functools.partial(shard_map, mesh=pipe_mesh,
                           in_specs=(P("pipe"), P(), P()),
                           out_specs=(P(), P("pipe")), check_vma=False)
        def run(ws_local, mb, tg):
            l, g = pp.forward_backward_1f1b(big_stage, loss_fn, ws_local[0],
                                            mb, tg, num_stages=PP)
            return l, g[None]
        return run(ws, mb, tg)

    def autodiff(ws, mb, tg):
        pl = pp.make_pipeline_loss_fn(big_stage, loss_fn, num_stages=PP)

        @functools.partial(shard_map, mesh=pipe_mesh,
                           in_specs=(P("pipe"), P(), P()),
                           out_specs=(P(), P("pipe")), check_vma=False)
        def run(ws_local, mb, tg):
            l, g = jax.value_and_grad(pl)(ws_local[0], (mb, tg))
            return l, g[None]
        return run(ws, mb, tg)

    m_small, m_big = 8, 32
    f_small = temp_bytes(onef1b, m_small)
    f_big = temp_bytes(onef1b, m_big)
    a_small = temp_bytes(autodiff, m_small)
    a_big = temp_bytes(autodiff, m_big)

    # autodiff residuals grow with M...
    assert a_big > 1.5 * a_small, (a_small, a_big)
    # ...1F1B's saved state does not (allow slack for per-tick scratch)
    assert f_big < 1.25 * f_small, (f_small, f_big)


@pytest.mark.parametrize("pp_size,v", [(4, 2), (2, 3)])
def test_interleaved_1f1b_matches_sequential(eight_devices, pp_size, v):
    """Hand-scheduled 1F1B at num_chunks>1 (VERDICT round-2 missing #1):
    round-robin stage s = chunk*pp + rank, grads == sequential oracle."""
    L = pp_size * v
    mesh = Mesh(np.array(eight_devices[:pp_size]), ("pipe",))
    k = jax.random.PRNGKey(3)
    ws = jax.random.normal(k, (L, D, D)) * (0.5 / v)
    mb = jax.random.normal(jax.random.PRNGKey(4), (M, 4, D))
    tg = jax.random.normal(jax.random.PRNGKey(5), (M, 4, D))

    def ref_loss(ws, microbatches, targets):
        def one(x, t):
            h = x
            for i in range(L):
                h = stage_fn(ws[i], h)
            return loss_fn(h, t)
        return sum(one(microbatches[m], targets[m]) for m in range(M)) / M

    order = [c * pp_size + r for r in range(pp_size) for c in range(v)]
    ws_stacked = ws[jnp.asarray(order)]

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P("pipe"), P(), P()),
                       out_specs=(P(), P("pipe")), check_vma=False)
    def run(ws_local, mb, tg):
        l, g = pp.forward_backward_1f1b(stage_fn, loss_fn, ws_local, mb, tg,
                                        num_stages=pp_size, num_chunks=v)
        return l, g

    loss, grads = jax.jit(run)(ws_stacked, mb, tg)
    ref_l, ref_g = jax.value_and_grad(ref_loss)(ws, mb, tg)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    inv = np.argsort(order)
    np.testing.assert_allclose(np.asarray(grads)[inv], np.asarray(ref_g),
                               rtol=1e-4, atol=1e-5)


def test_interleaved_reference_api_routes_to_1f1b(eight_devices):
    """get_forward_backward_func(vpp>1) grad path now runs the
    hand-scheduled interleaved 1F1B and matches the oracle."""
    pp_size, v = 2, 2
    L = pp_size * v
    mesh = Mesh(np.array(eight_devices[:pp_size]), ("pipe",))
    ws, mb, tg = _data()  # [4, D, D] = L stages

    def ref_loss(ws, microbatches, targets):
        def one(x, t):
            h = x
            for i in range(L):
                h = stage_fn(ws[i], h)
            return loss_fn(h, t)
        return sum(one(microbatches[m], targets[m]) for m in range(M)) / M

    order = [c * pp_size + r for r in range(pp_size) for c in range(v)]
    ws_stacked = ws[jnp.asarray(order)]
    fb = pp.get_forward_backward_func(v, pp_size)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P("pipe"), P(), P()),
                       out_specs=(P(), P("pipe")), check_vma=False)
    def run(ws_local, mb, tg):
        l, g = fb(stage_fn, loss_fn, ws_local, mb, tg)
        return l, g

    loss, grads = jax.jit(run)(ws_stacked, mb, tg)
    ref_l, ref_g = jax.value_and_grad(ref_loss)(ws, mb, tg)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    inv = np.argsort(order)
    np.testing.assert_allclose(np.asarray(grads)[inv], np.asarray(ref_g),
                               rtol=1e-4, atol=1e-5)


def test_interleaved_1f1b_memory_flat_as_microbatches_double(pipe_mesh):
    """VERDICT round-2 missing #1, the proof: at vpp=2/pp=4 the compiled
    step's peak temp memory stays flat as M doubles (the autodiff
    interleaved path grows with M)."""
    D2 = 64
    v = 2

    def big_stage(w, x):
        return jnp.tanh(x @ w)

    def temp_bytes(fn, M):
        ws = jnp.ones((PP * v, D2, D2))
        mb = jnp.ones((M, 32, D2))
        tg = jnp.ones((M, 32, D2))
        c = jax.jit(fn).lower(ws, mb, tg).compile()
        return c.memory_analysis().temp_size_in_bytes

    def onef1b(ws, mb, tg):
        @functools.partial(shard_map, mesh=pipe_mesh,
                           in_specs=(P("pipe"), P(), P()),
                           out_specs=(P(), P("pipe")), check_vma=False)
        def run(ws_local, mb, tg):
            l, g = pp.forward_backward_1f1b(big_stage, loss_fn, ws_local,
                                            mb, tg, num_stages=PP,
                                            num_chunks=v)
            return l, g
        return run(ws, mb, tg)

    def autodiff(ws, mb, tg):
        pl = pp.make_pipeline_loss_fn(big_stage, loss_fn, num_stages=PP,
                                      num_chunks=v)

        @functools.partial(shard_map, mesh=pipe_mesh,
                           in_specs=(P("pipe"), P(), P()),
                           out_specs=(P(), P("pipe")), check_vma=False)
        def run(ws_local, mb, tg):
            l, g = jax.value_and_grad(pl)(ws_local, (mb, tg))
            return l, g
        return run(ws, mb, tg)

    m_small, m_big = 8, 32
    f_small = temp_bytes(onef1b, m_small)
    f_big = temp_bytes(onef1b, m_big)
    a_small = temp_bytes(autodiff, m_small)
    a_big = temp_bytes(autodiff, m_big)

    assert a_big > 1.5 * a_small, (a_small, a_big)
    assert f_big < 1.25 * f_small, (f_small, f_big)


def test_1f1b_cotangent_dtype(pipe_mesh):
    """VERDICT round-2 weak #4a: the boundary cotangent rotates in fp32 by
    default; with bf16 stages the fp32 rotation tracks the fp32 oracle at
    least as closely as activation-dtype (bf16) rotation."""
    ws, mb, tg = _data()

    def bf16_stage(w, x):
        return jnp.tanh(jnp.asarray(x, jnp.bfloat16)
                        @ jnp.asarray(w, jnp.bfloat16)).astype(x.dtype)

    def run_with(cdt):
        @functools.partial(shard_map, mesh=pipe_mesh,
                           in_specs=(P("pipe"), P(), P()),
                           out_specs=(P(), P("pipe")), check_vma=False)
        def run(ws_local, mb, tg):
            l, g = pp.forward_backward_1f1b(
                bf16_stage, loss_fn, ws_local[0], mb, tg, num_stages=PP,
                cotangent_dtype=cdt)
            return l, g[None]
        return jax.jit(run)(ws, mb, tg)

    def ref(ws, mb, tg):
        def one(x, t):
            h = x
            for i in range(PP):
                h = bf16_stage(ws[i], h)
            return loss_fn(h, t)
        return sum(one(mb[m], tg[m]) for m in range(M)) / M

    _, ref_g = jax.value_and_grad(ref)(ws, mb, tg)
    _, g32 = run_with(jnp.float32)
    _, gact = run_with(None)
    err32 = float(jnp.max(jnp.abs(jnp.asarray(g32) - ref_g)))
    erract = float(jnp.max(jnp.abs(jnp.asarray(gact) - ref_g)))
    # bf16 stages bound both errors; fp32 rotation must not be worse
    assert err32 <= erract + 1e-6, (err32, erract)
    np.testing.assert_allclose(np.asarray(g32), np.asarray(ref_g),
                               rtol=0.1, atol=0.05)


def test_interleaved_pipeline_vpp3_pp4(eight_devices):
    """VERDICT round-1 weak #6: the round-robin stage mapping
    s = chunk*pp + rank asserted against a sequential oracle at vpp>2 AND
    pp>2 simultaneously (12 logical stages on a 4-device pipe axis)."""
    pp_size, v = 4, 3
    L = pp_size * v
    mesh = Mesh(np.array(eight_devices[:pp_size]), ("pipe",))
    k = jax.random.PRNGKey(3)
    ws = jax.random.normal(k, (L, D, D)) * (0.5 / v)  # keep tanh unsaturated
    mb = jax.random.normal(jax.random.PRNGKey(4), (M, 4, D))
    tg = jax.random.normal(jax.random.PRNGKey(5), (M, 4, D))

    def ref_loss(ws, microbatches, targets):
        def one(x, t):
            h = x
            for i in range(L):
                h = stage_fn(ws[i], h)
            return loss_fn(h, t)
        return sum(one(microbatches[m], targets[m])
                   for m in range(M)) / M

    # local row (r*v + c) holds logical stage (c*pp + r) — build_model's
    # rank-major layout
    order = [c * pp_size + r for r in range(pp_size) for c in range(v)]
    ws_stacked = ws[jnp.asarray(order)]

    pl = pp.make_pipeline_loss_fn(stage_fn, loss_fn, num_stages=pp_size,
                                  num_chunks=v)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P("pipe"), P(), P()),
                       out_specs=(P(), P("pipe")), check_vma=False)
    def run(ws_local, mb, tg):
        l, g = jax.value_and_grad(pl)(ws_local, (mb, tg))
        return l, g

    loss, grads = jax.jit(run)(ws_stacked, mb, tg)
    ref_l, ref_g = jax.value_and_grad(ref_loss)(ws, mb, tg)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    inv = np.argsort(order)
    np.testing.assert_allclose(np.asarray(grads)[inv], np.asarray(ref_g),
                               rtol=1e-4, atol=1e-5)


def test_build_model_flags_vpp3_pp4():
    """build_model marks pre/post process on exactly the true pipeline ends
    under the round-robin split."""
    calls = []

    def provider(pre_process, post_process):
        calls.append((pre_process, post_process))
        return jnp.zeros(())

    models = pp.build_model(provider, num_stages=4, num_chunks=3)
    assert len(models) == 12
    # rank-major: entry r*v + c is logical stage c*4 + r
    logical = [c * 4 + r for r in range(4) for c in range(3)]
    for (pre, post), s in zip(calls, logical):
        assert pre == (s == 0) and post == (s == 11), (s, pre, post)


def test_pipeline_remat_reduces_residuals(pipe_mesh):
    """remat=True shrinks the autodiff path's per-tick residual stash (the
    jax.checkpoint policy route of VERDICT item 3) while computing the
    same numbers."""
    D2 = 64

    def big_stage(w, x):
        h = jnp.tanh(x @ w)
        return jnp.tanh(h @ w.T) @ w     # 3 internal activations

    def temp_bytes(remat, M):
        pl = pp.make_pipeline_loss_fn(big_stage, loss_fn, num_stages=PP,
                                      remat=remat)

        @functools.partial(shard_map, mesh=pipe_mesh,
                           in_specs=(P("pipe"), P(), P()),
                           out_specs=(P(), P("pipe")), check_vma=False)
        def run(ws_local, mb, tg):
            l, g = jax.value_and_grad(pl)(ws_local[0], (mb, tg))
            return l, g[None]

        ws = jnp.ones((PP, D2, D2))
        mb = jnp.ones((M, 32, D2))
        tg = jnp.ones((M, 32, D2))
        c = jax.jit(run).lower(ws, mb, tg).compile()
        return c.memory_analysis().temp_size_in_bytes, c(ws, mb, tg)

    bytes_plain, (l0, g0) = temp_bytes(False, 16)
    bytes_remat, (l1, g1) = temp_bytes(True, 16)
    assert bytes_remat < 0.8 * bytes_plain, (bytes_remat, bytes_plain)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1), rtol=1e-5)
