"""Context parallelism: ring attention + Ulysses vs single-device oracle.

The reference has no CP (SURVEY §3.3); these tests hold the TPU build's
ring/all-to-all attention to the same oracle standard as the rest of the
kernel suite: exact match (loose fp32 tolerance) against the full-sequence
jnp reference, forward AND gradients, on a hermetic multi-device CPU mesh.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.kernels.flash_attention import mha_reference
from apex_tpu.transformer.context_parallel import (ring_attention,
                                                   ulysses_attention)

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow

B, H, S, D = 2, 4, 64, 16
AXIS = "context"


def _mesh(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(devs[:n]), (AXIS,))


def _qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (B, H, S, D), jnp.float32)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


def _sharded(fn, mesh):
    spec = P(None, None, AXIS, None)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n", [4, 8])
def test_ring_attention_forward(causal, n):
    mesh = _mesh(n)
    q, k, v = _qkv()
    want = mha_reference(q, k, v, causal=causal, scale=1.0 / D ** 0.5)
    fn = _sharded(functools.partial(ring_attention, axis_name=AXIS,
                                    causal=causal), mesh)
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grads(causal):
    mesh = _mesh(4)
    q, k, v = _qkv(1)

    def loss_ref(q, k, v):
        o = mha_reference(q, k, v, causal=causal, scale=1.0 / D ** 0.5)
        return jnp.sum(o * jnp.cos(o))

    ring = _sharded(functools.partial(ring_attention, axis_name=AXIS,
                                      causal=causal), mesh)

    def loss_ring(q, k, v):
        o = ring(q, k, v)
        return jnp.sum(o * jnp.cos(o))

    g_want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_got = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for got, want in zip(g_got, g_want):
        np.testing.assert_allclose(got, want, atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_forward(causal):
    mesh = _mesh(4)
    q, k, v = _qkv(2)
    want = mha_reference(q, k, v, causal=causal, scale=1.0 / D ** 0.5)
    fn = _sharded(functools.partial(ulysses_attention, axis_name=AXIS,
                                    causal=causal), mesh)
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_ulysses_grads():
    mesh = _mesh(4)
    q, k, v = _qkv(3)
    uly = _sharded(functools.partial(ulysses_attention, axis_name=AXIS,
                                     causal=True), mesh)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.tanh(fn(q, k, v)))

    ref = functools.partial(mha_reference, causal=True, scale=1.0 / D ** 0.5)
    g_want = jax.grad(loss(ref), argnums=(0, 1, 2))(q, k, v)
    g_got = jax.jit(jax.grad(loss(uly), argnums=(0, 1, 2)))(q, k, v)
    for got, want in zip(g_got, g_want):
        np.testing.assert_allclose(got, want, atol=5e-5, rtol=5e-5)


def test_ulysses_rejects_bad_heads():
    mesh = _mesh(8)  # 8 devices, H=4 heads → indivisible
    q, k, v = _qkv(4)
    fn = _sharded(functools.partial(ulysses_attention, axis_name=AXIS),
                  mesh)
    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(fn)(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_pallas_path_under_shard_map(causal):
    """Local seq 128 — pallas-ELIGIBLE shapes under shard_map (the
    production config). On CPU the dispatch must detect vma+interpret and
    take the reference path rather than crash in the pallas HLO interpreter;
    on a real TPU the same dispatch takes the Mosaic kernel. Guards the
    dispatch logic either way, forward and grads."""
    mesh = _mesh(4)
    b, h, s, d = 1, 2, 512, 32
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i + 7), (b, h, s, d),
                                 jnp.float32) for i in range(3))
    ref = functools.partial(mha_reference, causal=causal,
                            scale=1.0 / d ** 0.5)
    ring = _sharded(functools.partial(ring_attention, axis_name=AXIS,
                                      causal=causal), mesh)
    np.testing.assert_allclose(jax.jit(ring)(q, k, v), ref(q, k, v),
                               atol=2e-5, rtol=2e-5)
    loss_got = lambda *a: jnp.sum(jnp.sin(ring(*a)))
    loss_want = lambda *a: jnp.sum(jnp.sin(ref(*a)))
    g_got = jax.jit(jax.grad(loss_got, argnums=(0, 1, 2)))(q, k, v)
    g_want = jax.grad(loss_want, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_got, g_want):
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_ulysses_pallas_path_and_sharded_segment_ids():
    """Ulysses with pallas-eligible full seq + seq-sharded segment_ids
    (which must be all-gathered internally to match the post-all_to_all
    full-length sequence)."""
    mesh = _mesh(4)
    b, h, s, d = 1, 4, 256, 32
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i + 11), (b, h, s, d),
                                 jnp.float32) for i in range(3))
    segs = jnp.concatenate([jnp.zeros((b, s // 2), jnp.int32),
                            jnp.ones((b, s - s // 2), jnp.int32)], axis=1)
    want = mha_reference(q, k, v, causal=False, scale=1.0 / d ** 0.5,
                         segment_ids=segs)
    spec = P(None, None, AXIS, None)
    fn = shard_map(
        lambda q, k, v, s: ulysses_attention(q, k, v, axis_name=AXIS,
                                             segment_ids=s),
        mesh=mesh, in_specs=(spec, spec, spec, P(None, AXIS)),
        out_specs=spec)
    got = jax.jit(fn)(q, k, v, segs)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_ring_matches_bf16_flash_path():
    """bf16 I/O end-to-end (the production dtype) still matches fp32 oracle
    within bf16 tolerance."""
    mesh = _mesh(4)
    q, k, v = (t.astype(jnp.bfloat16) for t in _qkv(5))
    want = mha_reference(q, k, v, causal=True, scale=1.0 / D ** 0.5)
    fn = _sharded(functools.partial(ring_attention, axis_name=AXIS,
                                    causal=True), mesh)
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               atol=3e-2, rtol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_multi_axis_mesh(causal):
    """DP+CP: ring attention inside a shard_map with an ADDITIONAL manual
    axis ('data'). Regression: constants created inside the ring loop were
    marked varying over only the ring axis, so switch/fori_loop carries
    type-mismatched (vma {data,context} vs {context}) and tracing crashed."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    mesh = Mesh(np.array(devs[:8]).reshape(2, 4), ("data", AXIS))
    q, k, v = _qkv(9)
    want = mha_reference(q, k, v, causal=causal, scale=1.0 / D ** 0.5)
    spec = P("data", None, AXIS, None)
    fn = shard_map(functools.partial(ring_attention, axis_name=AXIS,
                                     causal=causal),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    loss_got = lambda *a: jnp.sum(jnp.sin(fn(*a)))
    loss_want = lambda *a: jnp.sum(jnp.sin(mha_reference(
        *a, causal=causal, scale=1.0 / D ** 0.5)))
    g_got = jax.jit(jax.grad(loss_got, argnums=(0, 1, 2)))(q, k, v)
    g_want = jax.grad(loss_want, argnums=(0, 1, 2))(q, k, v)
    for got_g, want_g in zip(g_got, g_want):
        np.testing.assert_allclose(got_g, want_g, atol=1e-4, rtol=1e-4)


def test_ulysses_attention_multi_axis_mesh():
    """Same DP+CP layout for the all-to-all path."""
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    mesh = Mesh(np.array(devs[:8]).reshape(2, 4), ("data", AXIS))
    q, k, v = _qkv(10)
    want = mha_reference(q, k, v, causal=True, scale=1.0 / D ** 0.5)
    spec = P("data", None, AXIS, None)
    fn = shard_map(functools.partial(ulysses_attention, axis_name=AXIS,
                                     causal=True),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    np.testing.assert_allclose(jax.jit(fn)(q, k, v), want,
                               atol=2e-5, rtol=2e-5)


# ------------------------------------------------------------------ zigzag
def _zz(x, n):
    from apex_tpu.transformer.context_parallel import zigzag_order
    return jnp.take(x, zigzag_order(x.shape[2], n), axis=2)


def _unzz(x, n):
    from apex_tpu.transformer.context_parallel import zigzag_inverse
    return jnp.take(x, zigzag_inverse(x.shape[2], n), axis=2)


def test_zigzag_order_roundtrip():
    from apex_tpu.transformer.context_parallel import (zigzag_inverse,
                                                       zigzag_order)
    order = np.asarray(zigzag_order(16, 4))
    # rank 0 holds chunks 0 and 7, rank 1 chunks 1 and 6, ...
    np.testing.assert_array_equal(order[:4], [0, 1, 14, 15])
    np.testing.assert_array_equal(order[4:8], [2, 3, 12, 13])
    inv = np.asarray(zigzag_inverse(16, 4))
    np.testing.assert_array_equal(order[inv], np.arange(16))
    np.testing.assert_array_equal(inv[order], np.arange(16))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n", [4, 8])
def test_ring_attention_zigzag_forward(causal, n):
    mesh = _mesh(n)
    q, k, v = _qkv(3)
    want = mha_reference(q, k, v, causal=causal, scale=1.0 / D ** 0.5)

    fn = _sharded(functools.partial(ring_attention, causal=causal,
                                    layout="zigzag"), mesh)
    got = _unzz(jax.jit(fn)(_zz(q, n), _zz(k, n), _zz(v, n)), n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_zigzag_grads(causal):
    n = 4
    mesh = _mesh(n)
    q, k, v = _qkv(4)
    scale = 1.0 / D ** 0.5

    def ref_loss(q, k, v):
        o = mha_reference(q, k, v, causal=causal, scale=scale)
        return (o.astype(jnp.float32) ** 2).sum()

    fn = _sharded(functools.partial(ring_attention, causal=causal,
                                    layout="zigzag"), mesh)
    jfn = jax.jit(fn)

    def zz_loss(q, k, v):
        o = jfn(_zz(q, n), _zz(k, n), _zz(v, n))
        return (_unzz(o, n).astype(jnp.float32) ** 2).sum()

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_zz = jax.grad(zz_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_zz, g_ref, "dq dk dv".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=name)


def test_ring_zigzag_matches_contiguous():
    """Same math, different layout: zigzag output (un-permuted) must equal
    the contiguous ring's output."""
    n = 4
    mesh = _mesh(n)
    q, k, v = _qkv(5)
    f_cont = jax.jit(_sharded(functools.partial(
        ring_attention, causal=True, layout="contiguous"), mesh))
    f_zz = jax.jit(_sharded(functools.partial(
        ring_attention, causal=True, layout="zigzag"), mesh))
    out_c = f_cont(q, k, v)
    out_z = _unzz(f_zz(_zz(q, n), _zz(k, n), _zz(v, n)), n)
    np.testing.assert_allclose(np.asarray(out_z), np.asarray(out_c),
                               rtol=2e-5, atol=2e-5)


def test_ring_zigzag_rejects_odd_local_seq():
    n = 4
    mesh = _mesh(n)
    q = jnp.zeros((1, 1, n * 3, 8))   # local_seq 3: odd
    fn = _sharded(functools.partial(ring_attention, causal=True,
                                    layout="zigzag"), mesh)
    with pytest.raises(ValueError, match="even local_seq"):
        jax.jit(fn)(q, q, q)
    with pytest.raises(ValueError, match="layout"):
        ring_attention(q, q, q, layout="spiral")


# --------------------------------------------------- ulysses bias + dropout
def test_ulysses_bias_matches_reference():
    n = 4
    mesh = _mesh(n)
    q, k, v = _qkv(6)
    bias = jax.random.normal(jax.random.PRNGKey(7), (B, 1, S, S)) * 0.3
    want = mha_reference(q, k, v, causal=False, scale=1.0 / D ** 0.5,
                         bias=bias)

    fn = shard_map(
        lambda q, k, v, b: ulysses_attention(q, k, v, causal=False, bias=b),
        mesh=mesh,
        in_specs=(P(None, None, AXIS, None),) * 3 + (P(),),
        out_specs=P(None, None, AXIS, None))
    got = jax.jit(fn)(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_per_head_bias_rejected():
    n = 4
    mesh = _mesh(n)
    q, k, v = _qkv(6)
    bias = jnp.zeros((B, H, S, S))
    fn = shard_map(
        lambda q, k, v, b: ulysses_attention(q, k, v, causal=False, bias=b),
        mesh=mesh,
        in_specs=(P(None, None, AXIS, None),) * 3 + (P(),),
        out_specs=P(None, None, AXIS, None))
    with pytest.raises(ValueError, match="per-head bias"):
        jax.jit(fn)(q, k, v, bias)


def test_ulysses_dropout_deterministic_and_sharded_heads_differ():
    n = 4
    mesh = _mesh(n)
    q, k, v = _qkv(8)
    fn = shard_map(
        lambda q, k, v, s: ulysses_attention(q, k, v, causal=False,
                                             dropout_rate=0.4,
                                             dropout_seed=s),
        mesh=mesh,
        in_specs=(P(None, None, AXIS, None),) * 3 + (P(),),
        out_specs=P(None, None, AXIS, None))
    f = jax.jit(fn)
    d1 = f(q, k, v, jnp.int32(5))
    d1b = f(q, k, v, jnp.int32(5))
    d2 = f(q, k, v, jnp.int32(6))
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d1b))
    assert not np.allclose(np.asarray(d1), np.asarray(d2))
    base = f(q, k, v, jnp.int32(5))  # same seed -> deterministic again
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(base))
