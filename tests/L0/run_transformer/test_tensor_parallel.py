"""TP layer/mapping/xent tests vs dense references on the 8-device CPU mesh.

Mirrors the reference's tests/L0/run_transformer/test_layers.py and
test_cross_entropy.py, which compare Megatron-parallel layers against plain
dense layers built from the gathered weights.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from apex_tpu.utils.compat import shard_map

from apex_tpu.transformer import tensor_parallel as tp

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow

TPW = 2  # tensor-parallel world size used in these tests


@pytest.fixture()
def model_mesh(eight_devices):
    return Mesh(np.array(eight_devices[:TPW]), ("model",))


def _stacked_init(module, x_local, mesh):
    """Init inside shard_map; return params with a leading [world] dim so a
    plain P('model') out_spec works for every leaf."""

    @functools.partial(shard_map, mesh=mesh, in_specs=(P(),),
                       out_specs=P("model"), check_vma=False)
    def init(x):
        v = module.init(jax.random.PRNGKey(0), x)
        return jax.tree_util.tree_map(lambda l: l[None], v)

    return init(x_local)


def test_column_parallel_linear_matches_dense(model_mesh):
    m = tp.ColumnParallelLinear(input_size=16, output_size=32,
                                world_size=TPW, gather_output=True)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
    stacked = _stacked_init(m, x, model_mesh)

    @functools.partial(shard_map, mesh=model_mesh,
                       in_specs=(P("model"), P()), out_specs=P(),
                       check_vma=False)
    def fwd(sv, x):
        v = jax.tree_util.tree_map(lambda l: l[0], sv)
        y = m.apply(v, x)
        return y  # gathered → replicated

    y = fwd(stacked, x)
    # dense reference from gathered columns
    k = np.concatenate([np.asarray(stacked["params"]["kernel"][i])
                        for i in range(TPW)], axis=-1)
    b = np.concatenate([np.asarray(stacked["params"]["bias"][i])
                        for i in range(TPW)], axis=-1)
    ref = np.asarray(x) @ k + b
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)


def test_row_parallel_linear_matches_dense(model_mesh):
    m = tp.RowParallelLinear(input_size=32, output_size=16,
                             world_size=TPW, input_is_parallel=True)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32))
    x_local_shape = jnp.zeros((4, 32 // TPW))
    stacked = _stacked_init(m, x_local_shape, model_mesh)

    @functools.partial(shard_map, mesh=model_mesh,
                       in_specs=(P("model"), P(None, "model")),
                       out_specs=P(), check_vma=False)
    def fwd(sv, x_local):
        v = jax.tree_util.tree_map(lambda l: l[0], sv)
        return m.apply(v, x_local)  # psum inside → replicated

    y = fwd(stacked, x)
    k = np.concatenate([np.asarray(stacked["params"]["kernel"][i])
                        for i in range(TPW)], axis=0)
    b = np.asarray(stacked["params"]["bias"][0])
    ref = np.asarray(x) @ k + b
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)


def test_column_row_grads_match_dense(model_mesh):
    """Megatron MLP block: column (no gather) → row (input parallel); grads
    of the local shards must equal the corresponding dense-grad slices."""
    col = tp.ColumnParallelLinear(input_size=8, output_size=16,
                                  world_size=TPW, gather_output=False,
                                  use_bias=False)
    row = tp.RowParallelLinear(input_size=16, output_size=8,
                               world_size=TPW, input_is_parallel=True,
                               use_bias=False)
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 8))

    @functools.partial(shard_map, mesh=model_mesh, in_specs=(P(),),
                       out_specs=P("model"), check_vma=False)
    def init(x):
        vc = col.init(jax.random.PRNGKey(0), x)
        h = col.apply(vc, x)
        vr = row.init(jax.random.PRNGKey(1), h)
        return jax.tree_util.tree_map(lambda l: l[None], (vc, vr))

    svc, svr = init(x)

    @functools.partial(shard_map, mesh=model_mesh,
                       in_specs=(P("model"), P("model"), P()),
                       out_specs=(P(), P("model"), P("model")),
                       check_vma=False)
    def lg(svc, svr, x):
        vc = jax.tree_util.tree_map(lambda l: l[0], svc)
        vr = jax.tree_util.tree_map(lambda l: l[0], svr)

        def loss_fn(args):
            vc, vr = args
            h = jax.nn.relu(col.apply(vc, x))
            y = row.apply(vr, h)
            return jnp.sum(y ** 2)

        l, (gc, gr) = jax.value_and_grad(loss_fn)((vc, vr))
        add = jax.tree_util.tree_map(lambda a: a[None], (gc, gr))
        return l, add[0], add[1]

    l, gc, gr = lg(svc, svr, x)

    # dense reference
    kc = np.concatenate([np.asarray(svc["params"]["kernel"][i])
                         for i in range(TPW)], axis=-1)
    kr = np.concatenate([np.asarray(svr["params"]["kernel"][i])
                         for i in range(TPW)], axis=0)

    def dense_loss(args):
        kc, kr = args
        h = jax.nn.relu(jnp.asarray(np.asarray(x)) @ kc)
        y = h @ kr
        return jnp.sum(y ** 2)

    lr, (gkc, gkr) = jax.value_and_grad(dense_loss)((jnp.asarray(kc),
                                                     jnp.asarray(kr)))
    np.testing.assert_allclose(float(l), float(lr), rtol=1e-5)
    half = 16 // TPW
    for i in range(TPW):
        np.testing.assert_allclose(
            np.asarray(gc["params"]["kernel"][i]),
            np.asarray(gkc)[:, i * half:(i + 1) * half], rtol=1e-5,
            atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(gr["params"]["kernel"][i]),
            np.asarray(gkr)[i * half:(i + 1) * half, :], rtol=1e-5,
            atol=1e-5)


def test_vocab_parallel_embedding(model_mesh):
    m = tp.VocabParallelEmbedding(num_embeddings=24, embedding_dim=8,
                                  world_size=TPW)
    ids = jnp.array([[0, 5, 11], [12, 17, 23]], jnp.int32)
    stacked = _stacked_init(m, ids, model_mesh)

    @functools.partial(shard_map, mesh=model_mesh,
                       in_specs=(P("model"), P()), out_specs=P(),
                       check_vma=False)
    def fwd(sv, ids):
        v = jax.tree_util.tree_map(lambda l: l[0], sv)
        return m.apply(v, ids)

    y = fwd(stacked, ids)
    table = np.concatenate([np.asarray(stacked["params"]["embedding"][i])
                            for i in range(TPW)], axis=0)
    np.testing.assert_allclose(np.asarray(y), table[np.asarray(ids)],
                               rtol=1e-6, atol=1e-6)


def test_vocab_parallel_cross_entropy(model_mesh):
    B, V = 6, 32
    logits = jax.random.normal(jax.random.PRNGKey(3), (B, V))
    target = jax.random.randint(jax.random.PRNGKey(4), (B,), 0, V)

    @functools.partial(shard_map, mesh=model_mesh,
                       in_specs=(P(None, "model"), P()), out_specs=P(),
                       check_vma=False)
    def xent(lg, t):
        return tp.vocab_parallel_cross_entropy(lg, t)

    loss = xent(logits, target)
    ref = -jax.nn.log_softmax(logits)[jnp.arange(B), target]
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_vocab_parallel_cross_entropy_grad(model_mesh):
    B, V = 4, 16
    logits = jax.random.normal(jax.random.PRNGKey(5), (B, V))
    target = jax.random.randint(jax.random.PRNGKey(6), (B,), 0, V)

    @functools.partial(shard_map, mesh=model_mesh,
                       in_specs=(P(None, "model"), P()),
                       out_specs=P(None, "model"), check_vma=False)
    def grad_fn(lg, t):
        return jax.grad(
            lambda l: jnp.mean(tp.vocab_parallel_cross_entropy(l, t)))(lg)

    g = grad_fn(logits, target)
    ref = jax.grad(lambda l: jnp.mean(
        -jax.nn.log_softmax(l)[jnp.arange(B), target]))(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_label_smoothing_cross_entropy():
    """world=1 path with smoothing vs optax reference."""
    import optax
    B, V = 5, 11
    logits = jax.random.normal(jax.random.PRNGKey(7), (B, V))
    target = jax.random.randint(jax.random.PRNGKey(8), (B,), 0, V)
    loss = tp.vocab_parallel_cross_entropy(logits, target,
                                           label_smoothing=0.1)
    onehot = jax.nn.one_hot(target, V)
    smoothed = onehot * 0.9 + 0.1 / V
    ref = optax.softmax_cross_entropy(logits, smoothed)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_mappings_roundtrip(model_mesh):
    x = jax.random.normal(jax.random.PRNGKey(9), (4, 8))

    @functools.partial(shard_map, mesh=model_mesh, in_specs=(P(),),
                       out_specs=P(), check_vma=False)
    def roundtrip(x):
        local = tp.scatter_to_tensor_model_parallel_region(x, "model", -1)
        back = tp.gather_from_tensor_model_parallel_region(local, "model", -1)
        return back

    np.testing.assert_allclose(np.asarray(roundtrip(x)), np.asarray(x))


def test_copy_reduce_duality(model_mesh):
    """copy_to: identity fwd, psum bwd; reduce_from: psum fwd, identity bwd."""
    x = jnp.ones((3,))

    @functools.partial(shard_map, mesh=model_mesh, in_specs=(P(),),
                       out_specs=P(), check_vma=False)
    def f(x):
        y = tp.copy_to_tensor_model_parallel_region(x, "model")
        g = jax.grad(lambda v: jnp.sum(
            tp.copy_to_tensor_model_parallel_region(v, "model")))(x)
        r = tp.reduce_from_tensor_model_parallel_region(x, "model")
        gr = jax.grad(lambda v: jnp.sum(
            tp.reduce_from_tensor_model_parallel_region(v, "model")))(x)
        return y, g, r, gr

    y, g, r, gr = f(x)
    np.testing.assert_allclose(np.asarray(y), 1.0)       # identity fwd
    np.testing.assert_allclose(np.asarray(g), TPW * 1.0)  # psum bwd
    np.testing.assert_allclose(np.asarray(r), TPW * 1.0)  # psum fwd
    np.testing.assert_allclose(np.asarray(gr), 1.0)       # identity bwd


def test_sequence_parallel_pair(model_mesh):
    """reduce_scatter fwd + all_gather bwd and vice versa, on a seq dim."""
    x = jax.random.normal(jax.random.PRNGKey(10), (8, 4))

    @functools.partial(shard_map, mesh=model_mesh, in_specs=(P(),),
                       out_specs=P("model"), check_vma=False)
    def rs(x):
        return tp.reduce_scatter_to_sequence_parallel_region(x, "model", 0)

    out = rs(x)  # each shard: sum over ranks of its seq slice → stacked
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(x) * TPW, rtol=1e-6)

    @functools.partial(shard_map, mesh=model_mesh,
                       in_specs=(P("model"),), out_specs=P(),
                       check_vma=False)
    def ag(xl):
        return tp.gather_from_sequence_parallel_region(xl, "model", 0)

    np.testing.assert_allclose(np.asarray(ag(out)), np.asarray(x) * TPW,
                               rtol=1e-6)


def test_utils():
    with pytest.raises(ValueError):
        tp.ensure_divisibility(7, 2)
    assert tp.divide(8, 2) == 4
    parts = tp.split_tensor_along_last_dim(jnp.ones((2, 8)), 4)
    assert len(parts) == 4 and parts[0].shape == (2, 2)
    assert tp.VocabUtility.vocab_range_from_global_vocab_size(100, 1, 4) == \
        (25, 50)


def test_rng_tracker():
    tr = tp.RNGStatesTracker()
    tr.add("a", 0)
    with pytest.raises(RuntimeError):
        tr.add("a", 1)
    with tr.fork("a") as k1:
        pass
    with tr.fork("a") as k2:
        pass
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    with pytest.raises(RuntimeError):
        with tr.fork("missing"):
            pass
    tp.model_parallel_manual_seed(123, tp_rank=0)
    with tp.get_rng_tracker().fork() as k:
        assert k is not None


def test_checkpoint_matches_plain():
    def f(w, x):
        return jnp.sum(jnp.tanh(x @ w) ** 2)

    w = jax.random.normal(jax.random.PRNGKey(0), (8, 8))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))
    g_plain = jax.grad(f)(w, x)
    g_ckpt = jax.grad(lambda w, x: tp.checkpoint(f, w, x))(w, x)
    np.testing.assert_allclose(np.asarray(g_plain), np.asarray(g_ckpt),
                               rtol=1e-6)
