"""Expert-parallel MoE tests.

The reference has no EP (SURVEY §3.3); parity bar here is internal: the
sharded layer must match its own single-device math exactly, because each
shard's routing/capacity is token-local and expert MLPs are per-slot.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.moe import MoEMLP, top1_routing

H, I, E, T = 16, 32, 8, 64


def test_top1_routing_shapes_and_capacity():
    logits = jax.random.normal(jax.random.PRNGKey(0), (T, E))
    C = 4
    dispatch, combine, aux = top1_routing(logits, E, C)
    assert dispatch.shape == (T, E, C) and combine.shape == (T, E, C)
    # every slot holds at most one token
    assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0 + 1e-6
    # per-expert token count ≤ capacity
    assert float(jnp.max(jnp.sum(dispatch, axis=(0, 2)))) <= C + 1e-6
    # combine weights are the gate probs of kept tokens
    kept = jnp.sum(dispatch, axis=(1, 2))
    gates = jnp.sum(combine, axis=(1, 2))
    assert np.all(np.asarray(gates[kept > 0]) > 0)
    assert np.isfinite(float(aux))


def test_moe_single_device_forward_and_grad():
    m = MoEMLP(hidden=H, intermediate=I, num_experts=E, axis_name=None)
    x = jax.random.normal(jax.random.PRNGKey(1), (T, H))
    params = m.init(jax.random.PRNGKey(2), x)["params"]
    y, aux = m.apply({"params": params}, x)
    assert y.shape == (T, H)
    assert np.isfinite(np.asarray(y)).all()

    def loss(p):
        y, aux = m.apply({"params": p}, x)
        return jnp.sum(y ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    total = sum(float(jnp.sum(jnp.abs(v)))
                for v in jax.tree_util.tree_leaves(g))
    assert np.isfinite(total) and total > 0
    # router must receive gradient (via combine weights)
    assert float(jnp.sum(jnp.abs(g["router"]["kernel"]))) > 0


def test_moe_rejects_indivisible_experts(eight_devices):
    mesh = Mesh(np.array(eight_devices), ("expert",))
    m = MoEMLP(hidden=H, intermediate=I, num_experts=6)  # 6 % 8 != 0
    x = jnp.zeros((8, T, H))

    with pytest.raises(ValueError, match="divisible"):
        jax.jit(jax.shard_map(
            lambda x: m.init(jax.random.PRNGKey(0), x[0]),
            mesh=mesh, in_specs=P("expert"), out_specs=P("expert"),
            check_vma=False))(x)


def test_moe_expert_parallel_matches_single_device(eight_devices):
    """Shard-local routing + a2a expert dispatch == per-shard single-device
    MoE with the full expert set (exact fp32 equivalence)."""
    mesh = Mesh(np.array(eight_devices), ("expert",))
    single = MoEMLP(hidden=H, intermediate=I, num_experts=E, axis_name=None)
    x_all = jax.random.normal(jax.random.PRNGKey(3), (8, T, H))
    params = single.init(jax.random.PRNGKey(4), x_all[0])["params"]

    # reference: run each shard's tokens through the full-expert layer
    ref = jnp.stack([single.apply({"params": params}, x_all[s])[0]
                     for s in range(8)])

    sharded = MoEMLP(hidden=H, intermediate=I, num_experts=E,
                     axis_name="expert")
    # shard expert weights along axis 0 (1 expert per device); router
    # replicated
    shard_params = {
        "router": params["router"],
        "w1": params["w1"], "b1": params["b1"],
        "w2": params["w2"], "b2": params["b2"],
    }
    specs = {
        "router": {"kernel": P(), "bias": P()},
        "w1": P("expert"), "b1": P("expert"),
        "w2": P("expert"), "b2": P("expert"),
    }

    def step(p, x):
        y, aux = sharded.apply({"params": p}, x[0])
        return y[None], aux

    y, aux = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(specs, P("expert")),
        out_specs=(P("expert"), P()),
        check_vma=False))(shard_params, x_all)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_moe_expert_parallel_grads_flow(eight_devices):
    mesh = Mesh(np.array(eight_devices), ("expert",))
    m = MoEMLP(hidden=H, intermediate=I, num_experts=E, axis_name="expert")
    x_all = jax.random.normal(jax.random.PRNGKey(5), (8, T, H))
    single = MoEMLP(hidden=H, intermediate=I, num_experts=E, axis_name=None)
    params = single.init(jax.random.PRNGKey(6), x_all[0])["params"]
    specs = {
        "router": {"kernel": P(), "bias": P()},
        "w1": P("expert"), "b1": P("expert"),
        "w2": P("expert"), "b2": P("expert"),
    }

    def loss(p, x):
        y, aux = m.apply({"params": p}, x[0])
        return jnp.sum(y ** 2) + 0.01 * aux

    def shard_loss(p, x):
        l = loss(p, x)
        return jax.lax.pmean(l, "expert")

    g = jax.jit(jax.shard_map(
        jax.grad(shard_loss), mesh=mesh,
        in_specs=(specs, P("expert")), out_specs=specs,
        check_vma=False))(params, x_all)
    total = sum(float(jnp.sum(jnp.abs(v)))
                for v in jax.tree_util.tree_leaves(g))
    assert np.isfinite(total) and total > 0
