"""Expert-parallel MoE tests.

The reference has no EP (SURVEY §3.3); parity bar here is internal: the
sharded layer must match its own single-device math exactly, because each
shard's routing/capacity is token-local and expert MLPs are per-slot.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.utils.compat import shard_map
from apex_tpu.transformer.moe import MoEMLP, top1_routing

H, I, E, T = 16, 32, 8, 64


def test_top1_routing_shapes_and_capacity():
    logits = jax.random.normal(jax.random.PRNGKey(0), (T, E))
    C = 4
    dispatch, combine, aux = top1_routing(logits, E, C)
    assert dispatch.shape == (T, E, C) and combine.shape == (T, E, C)
    # every slot holds at most one token
    assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0 + 1e-6
    # per-expert token count ≤ capacity
    assert float(jnp.max(jnp.sum(dispatch, axis=(0, 2)))) <= C + 1e-6
    # combine weights are the gate probs of kept tokens
    kept = jnp.sum(dispatch, axis=(1, 2))
    gates = jnp.sum(combine, axis=(1, 2))
    assert np.all(np.asarray(gates[kept > 0]) > 0)
    assert np.isfinite(float(aux))


def test_moe_single_device_forward_and_grad():
    m = MoEMLP(hidden=H, intermediate=I, num_experts=E, axis_name=None)
    x = jax.random.normal(jax.random.PRNGKey(1), (T, H))
    params = m.init(jax.random.PRNGKey(2), x)["params"]
    y, aux = m.apply({"params": params}, x)
    assert y.shape == (T, H)
    assert np.isfinite(np.asarray(y)).all()

    def loss(p):
        y, aux = m.apply({"params": p}, x)
        return jnp.sum(y ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    total = sum(float(jnp.sum(jnp.abs(v)))
                for v in jax.tree_util.tree_leaves(g))
    assert np.isfinite(total) and total > 0
    # router must receive gradient (via combine weights)
    assert float(jnp.sum(jnp.abs(g["router"]["kernel"]))) > 0


def test_moe_rejects_indivisible_experts(eight_devices):
    mesh = Mesh(np.array(eight_devices), ("expert",))
    m = MoEMLP(hidden=H, intermediate=I, num_experts=6)  # 6 % 8 != 0
    x = jnp.zeros((8, T, H))

    with pytest.raises(ValueError, match="divisible"):
        jax.jit(shard_map(
            lambda x: m.init(jax.random.PRNGKey(0), x[0]),
            mesh=mesh, in_specs=P("expert"), out_specs=P("expert"),
            check_vma=False))(x)


def test_moe_expert_parallel_matches_single_device(eight_devices):
    """Shard-local routing + a2a expert dispatch == per-shard single-device
    MoE with the full expert set (exact fp32 equivalence)."""
    mesh = Mesh(np.array(eight_devices), ("expert",))
    single = MoEMLP(hidden=H, intermediate=I, num_experts=E, axis_name=None)
    x_all = jax.random.normal(jax.random.PRNGKey(3), (8, T, H))
    params = single.init(jax.random.PRNGKey(4), x_all[0])["params"]

    # reference: run each shard's tokens through the full-expert layer
    ref = jnp.stack([single.apply({"params": params}, x_all[s])[0]
                     for s in range(8)])

    sharded = MoEMLP(hidden=H, intermediate=I, num_experts=E,
                     axis_name="expert")
    # shard expert weights along axis 0 (1 expert per device); router
    # replicated
    shard_params = {
        "router": params["router"],
        "w1": params["w1"], "b1": params["b1"],
        "w2": params["w2"], "b2": params["b2"],
    }
    specs = {
        "router": {"kernel": P(), "bias": P()},
        "w1": P("expert"), "b1": P("expert"),
        "w2": P("expert"), "b2": P("expert"),
    }

    def step(p, x):
        y, aux = sharded.apply({"params": p}, x[0])
        return y[None], aux

    y, aux = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(specs, P("expert")),
        out_specs=(P("expert"), P()),
        check_vma=False))(shard_params, x_all)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_moe_expert_parallel_grads_flow(eight_devices):
    mesh = Mesh(np.array(eight_devices), ("expert",))
    m = MoEMLP(hidden=H, intermediate=I, num_experts=E, axis_name="expert")
    x_all = jax.random.normal(jax.random.PRNGKey(5), (8, T, H))
    single = MoEMLP(hidden=H, intermediate=I, num_experts=E, axis_name=None)
    params = single.init(jax.random.PRNGKey(6), x_all[0])["params"]
    specs = {
        "router": {"kernel": P(), "bias": P()},
        "w1": P("expert"), "b1": P("expert"),
        "w2": P("expert"), "b2": P("expert"),
    }

    def loss(p, x):
        y, aux = m.apply({"params": p}, x[0])
        return jnp.sum(y ** 2) + 0.01 * aux

    def shard_loss(p, x):
        l = loss(p, x)
        return jax.lax.pmean(l, "expert")

    g = jax.jit(shard_map(
        jax.grad(shard_loss), mesh=mesh,
        in_specs=(specs, P("expert")), out_specs=specs,
        check_vma=False))(params, x_all)
    total = sum(float(jnp.sum(jnp.abs(v)))
                for v in jax.tree_util.tree_leaves(g))
    assert np.isfinite(total) and total > 0


# ------------------------------------------------------------- top-2 routing
def test_top2_routing_matches_manual_two_expert_mix():
    """With capacity ≥ T no token drops: each token's output weights must be
    the pair-renormalized top-2 softmax probs (GShard)."""
    from apex_tpu.transformer.moe import top2_routing

    logits = jax.random.normal(jax.random.PRNGKey(1), (T, E))
    dispatch, combine, aux = top2_routing(logits, E, T)   # no capacity limit
    probs = np.asarray(jax.nn.softmax(logits, -1))
    w = np.asarray(jnp.sum(combine, axis=2))              # [T, E]
    for t in range(T):
        order = np.argsort(probs[t])[::-1]
        e1, e2 = order[0], order[1]
        denom = probs[t, e1] + probs[t, e2]
        np.testing.assert_allclose(w[t, e1], probs[t, e1] / denom, rtol=1e-5)
        np.testing.assert_allclose(w[t, e2], probs[t, e2] / denom, rtol=1e-5)
        others = [e for e in range(E) if e not in (e1, e2)]
        np.testing.assert_allclose(w[t, others], 0.0, atol=1e-7)
    # every slot holds at most one token; counts ≤ 2T total
    assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0 + 1e-6
    assert np.isfinite(float(aux))


def test_top2_capacity_drops_second_choices_first():
    """GShard ordering: under capacity pressure, first choices occupy the
    queue ahead of every second choice."""
    from apex_tpu.transformer.moe import top2_routing

    # all tokens prefer expert 0 then expert 1
    logits = jnp.tile(jnp.array([[4.0, 2.0, 0.0, 0.0]]), (6, 1))
    C = 4
    dispatch, combine, _ = top2_routing(logits, 4, C)
    counts = np.asarray(jnp.sum(dispatch, axis=(0, 2)))   # per expert
    assert counts[0] == C            # first choices fill expert 0 to cap
    assert counts[1] == C            # second choices fill expert 1 to cap
    # tokens 0..3 keep their first choice; 4,5 dropped from expert 0
    kept0 = np.asarray(jnp.sum(dispatch[:, 0, :], axis=-1))
    np.testing.assert_array_equal(kept0, [1, 1, 1, 1, 0, 0])


def test_router_z_loss():
    from apex_tpu.transformer.moe import router_z_loss

    small = jnp.zeros((8, 4))
    big = jnp.full((8, 4), 50.0)
    # logsumexp(0,0,0,0) = log 4; z = (log 4)^2
    np.testing.assert_allclose(float(router_z_loss(small)),
                               np.log(4.0) ** 2, rtol=1e-6)
    assert float(router_z_loss(big)) > float(router_z_loss(small))


def test_top2_degenerate_softmax_no_phantom_second_choice():
    """A saturated router softmax (top-1 prob exactly 1.0 in fp32) has no
    valid second choice; the token must go ONLY to its first expert with
    full weight — not be dispatched twice at w=0.5 (regression guard)."""
    from apex_tpu.transformer.moe import top2_routing

    logits = jnp.array([[200.0, 0.0, 0.0, 0.0],     # saturated: p1 == 1.0
                        [1.0, 0.5, 0.0, 0.0]])       # normal top-2 row
    dispatch, combine, _ = top2_routing(logits, 4, 4)
    w = np.asarray(jnp.sum(combine, axis=2))         # [T, E]
    np.testing.assert_allclose(w[0], [1.0, 0.0, 0.0, 0.0], atol=1e-6)
    # saturated token occupies exactly one slot
    assert float(jnp.sum(dispatch[0])) == 1.0
    # normal row still splits across its two experts
    assert w[1, 0] > 0.5 and w[1, 1] > 0.0
    np.testing.assert_allclose(w[1, 0] + w[1, 1], 1.0, rtol=1e-6)


def test_moe_top2_expert_parallel_matches_single_device(eight_devices):
    """Top-2 sharded over the expert axis must equal its single-device
    self (same internal-parity bar as the top-1 test)."""
    mesh = Mesh(np.array(eight_devices[:4]), ("expert",))
    x = jax.random.normal(jax.random.PRNGKey(2), (T, H))

    m_local = MoEMLP(hidden=H, intermediate=I, num_experts=E,
                     router_top_k=2, router_z_weight=1e-3, axis_name=None)
    variables = m_local.init(jax.random.PRNGKey(3), x)
    y_local, aux_local = m_local.apply(variables, x)

    m_sharded = MoEMLP(hidden=H, intermediate=I, num_experts=E,
                       router_top_k=2, router_z_weight=1e-3,
                       axis_name="expert")

    e_local = E // 4
    params = dict(variables["params"])
    full = {"router": params["router"],
            "w1": params["w1"].reshape(4, e_local, H, I),
            "b1": params["b1"].reshape(4, e_local, I),
            "w2": params["w2"].reshape(4, e_local, I, H),
            "b2": params["b2"].reshape(4, e_local, H)}

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=({"router": P(), "w1": P("expert"), "b1": P("expert"),
                   "w2": P("expert"), "b2": P("expert")}, P()),
        out_specs=(P(), P()), check_vma=False)
    def run(p, x):
        local = {"params": {
            "router": p["router"],
            "w1": p["w1"][0], "b1": p["b1"][0],
            "w2": p["w2"][0], "b2": p["b2"][0],
        }}
        return m_sharded.apply(local, x)

    y_sh, aux_sh = run(full, x)
    np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_local),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(float(aux_sh), float(aux_local), rtol=1e-5)
