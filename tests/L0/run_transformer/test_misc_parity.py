"""P22–P26 long-tail parity: broadcast_data, log_util, GradScaler."""

import functools
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.amp import GradScaler, grad_scaler_state
from apex_tpu.transformer.log_util import (get_transformer_logger,
                                           set_logging_level)
from apex_tpu.transformer.tensor_parallel import broadcast_data


def test_broadcast_data(eight_devices):
    mesh = Mesh(np.array(eight_devices[:4]), ("model",))
    data = {"tokens": jnp.arange(12).reshape(4, 3),
            "mask": jnp.ones((4, 2))}

    @functools.partial(shard_map, mesh=mesh, in_specs=(P("model"),),
                       out_specs=P("model"), check_vma=False)
    def run(per_rank):
        # each rank starts with DIFFERENT data; broadcast_data must leave
        # every rank holding rank 0's pytree
        local = jax.tree_util.tree_map(lambda x: x[0], per_rank)
        out = broadcast_data(local)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    per_rank = jax.tree_util.tree_map(
        lambda x: jnp.stack([x * (r + 1) for r in range(4)]), data)
    out = run(per_rank)
    for leaf, orig in zip(jax.tree_util.tree_leaves(out),
                          jax.tree_util.tree_leaves(data)):
        for r in range(4):
            np.testing.assert_array_equal(np.asarray(leaf[r]),
                                          np.asarray(orig))


def test_log_util():
    lg = get_transformer_logger("layers")
    assert lg.name == "apex_tpu.transformer.layers"
    set_logging_level(logging.DEBUG)
    assert logging.getLogger("apex_tpu.transformer").level == logging.DEBUG
    set_logging_level(logging.WARNING)


def test_grad_scaler_min_scale_floor():
    s = GradScaler(init_scale=4.0, min_scale=1.0)
    assert s.get_scale() == 4.0
    # three overflows: 4 → 2 → 1 → clamped at min_scale
    for _ in range(3):
        s.unscale({"g": jnp.array([jnp.inf])})
        s.update()
    assert s.get_scale() == 1.0


def test_grad_scaler_growth_and_torch_names():
    s = GradScaler(init_scale=2.0, growth_interval=2)
    loss = s.scale(jnp.float32(1.0))
    assert float(loss) == 2.0
    for _ in range(2):
        s.unscale({"g": jnp.array([1.0])})
        s.update()
    assert s.get_scale() == 4.0  # doubled after growth_interval clean steps


def test_grad_scaler_rejects_asymmetric_schedule():
    with pytest.raises(ValueError, match="backoff"):
        GradScaler(growth_factor=2.0, backoff_factor=0.25)


def test_grad_scaler_state_functional():
    st = grad_scaler_state(init_scale=8.0, min_scale=2.0)
    assert float(st.loss_scale) == 8.0
    assert st.min_loss_scale == 2.0


def test_broadcast_from_nonzero_src(eight_devices):
    """comm.broadcast_from with src != 0 (regression: the old ppermute
    formulation rejected one-to-many perms outright)."""
    from apex_tpu.comm import broadcast_from

    mesh = Mesh(np.array(eight_devices[:4]), ("model",))

    @functools.partial(shard_map, mesh=mesh, in_specs=(P("model"),),
                       out_specs=P("model"), check_vma=False)
    def run(x):
        return broadcast_from(x[0], "model", src=2)[None]

    per_rank = jnp.arange(4.0).reshape(4, 1) * 10
    out = np.asarray(run(per_rank))
    np.testing.assert_array_equal(out[:, 0], [20.0] * 4)
