"""Async pipelined heartbeat — dispatch-ahead decode with deferred
token readback (``Scheduler(pipeline_depth >= 1)``), hermetic.

The acceptance bar from the issue, as tests:

- **bitwise parity**: the greedy output stream at ``pipeline_depth >=
  1`` is identical to the ``pipeline_depth=0`` sync oracle over a mixed
  stream — chunk-boundary prompts, EOS discovered mid-pipeline,
  QueueFull backpressure, speculative decoding on and off, prefix hits,
  and a seeded chaos plan. Every comparison runs both modes through the
  SAME engine (reset between passes), so parity never crosses
  separately-jitted executables;
- **zero new compiled programs**: pipelining reuses the sync path's
  executables verbatim — trace counters pinned unchanged across a
  pipelined run;
- **zero leaked pages at drain**: the pool auditor reconciles to zero
  pages in use after every pipelined stream, including the chaos one;
- **rollback after speculated finality**: a slot whose EOS lands while
  younger speculated steps are in flight discards those steps' tokens
  (``serving.heartbeat.discarded``), and the slot's next occupant still
  produces the sync path's exact tokens — host rollback is length
  arithmetic, device state needs no undo;
- **watchdog semantics under pipelining** (satellite): the budget
  applies to the HOST portion of a beat (wall minus device-wait), so a
  beat dominated by healthy device execution never trips, while an
  injected host stall still does; the PR 8 warm-start exemption keeps
  working when tracing happens on a dispatch-ahead beat;
- the ``serving.heartbeat.*`` host-think / device-wait / duty-cycle
  telemetry lands on every beat, sync and pipelined;
- :class:`~apex_tpu.serving.DraftWorker` unit behavior: precomputed ==
  inline (purity), inline fallback, exception surfacing, idempotent
  submit, bounded unclaimed results, idempotent stop.

Everything runs on CPU with a tiny model (the kernels take their
interpret/reference paths); wall-clock wins are the bench's claim, not
this file's — here the contract is exactness and accounting.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.amp.policy import resolve_policy
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.serving import (DraftWorker, Engine, FaultPlan, FaultPolicy,
                              FaultSpec, QueueFull, Request, RequestStatus,
                              Scheduler, SpecConfig)

pytestmark = pytest.mark.serving

VOCAB = 101
CHUNK = 8


def _tiny_lm(max_seq_len=64, **kw):
    return TransformerLM(vocab_size=VOCAB, hidden=32, num_layers=2,
                         num_heads=4, max_seq_len=max_seq_len, **kw)


@pytest.fixture(scope="module")
def lm_and_params():
    m = _tiny_lm()
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                    train=False)["params"]
    return m, params


def _mk_engine(lm_and_params, *, slots=3, pool=0, seed=5, **kw):
    m, params = lm_and_params
    return Engine(m, params, slots=slots, max_len=64, prefill_len=24,
                  chunk_len=CHUNK, prefix_pool=pool,
                  policy=resolve_policy("O0", verbose=False), seed=seed,
                  **kw)


@pytest.fixture(scope="module")
def engine(lm_and_params):
    """One shared paged engine: the sync oracle pass and every
    pipelined pass run the SAME compiled programs (reset between runs),
    so bitwise comparisons never cross executables."""
    return _mk_engine(lm_and_params)


def _mixed_stream():
    """Prompt lengths below / at / straddling chunk boundaries
    (chunk_len=8), budgets long and short — the parity sweep's
    workload."""
    rng = np.random.default_rng(42)
    return [Request(prompt=list(rng.integers(1, VOCAB, size=n)),
                    max_new_tokens=b)
            for n, b in [(5, 12), (8, 4), (13, 6), (21, 4), (3, 9),
                         (16, 5), (7, 1), (11, 7)]]


def _serve(engine, stream, **sched_kw):
    """Run ``stream`` to completion; returns the per-request token
    lists in SUBMISSION order (completion order differs across
    pipeline depths — that reordering is scheduling, not output)."""
    sched = Scheduler(engine, **sched_kw)
    sched.run(stream)
    return [list(r.output_tokens) for r in stream], sched


# ------------------------------------------------------------ validation
def test_pipeline_depth_validation_and_worker_lifecycle(engine):
    engine.reset()
    with pytest.raises(ValueError, match="pipeline_depth"):
        Scheduler(engine, pipeline_depth=-1)
    # depth 0 (the default) never spins the worker thread — the sync
    # oracle path carries zero threading machinery
    assert Scheduler(engine)._worker is None
    sched = Scheduler(engine, pipeline_depth=2)
    assert sched._worker is not None
    sched._worker.stop()            # idempotent; finalizer runs it again


# ------------------------------------------------- the headline parity
def test_depth_parity_zero_new_programs_zero_leaks(engine):
    """THE acceptance pin: a mixed chunk-boundary stream served at
    depths 1 and 3 is bitwise the depth-0 stream, through the same
    executables (zero new compiled programs), with zero pages leaked
    at drain and an empty pipeline left behind."""
    engine.reset()
    oracle, sync_sched = _serve(engine, _mixed_stream())
    programs0 = engine.compiled_programs
    for depth in (1, 3):
        engine.reset()
        got, sched = _serve(engine, _mixed_stream(),
                            pipeline_depth=depth)
        assert got == oracle, f"depth {depth} diverged from sync oracle"
        assert engine.compiled_programs == programs0, \
            f"depth {depth} traced new programs"
        assert not sched._pipeline, "run() left steps in flight"
        assert sched.auditor.audit(engine)["pages_in_use"] == 0
    engine.reset()


def test_eos_mid_pipeline_discards_and_slot_reuse(lm_and_params):
    """Rollback after speculated finality: EOS is the one terminal the
    dispatcher cannot predict, so a slot's EOS discovered at reconcile
    invalidates its in-flight speculated successors
    (``serving.heartbeat.discarded``) — and because host rollback is
    pure length arithmetic and the rejected K/V is overwritten
    write-then-attend, the slot's NEXT occupant emits the sync path's
    exact tokens. One slot, so the follow-up request reuses the EXACT
    slot that rolled back."""
    eng = _mk_engine(lm_and_params, slots=1, seed=11)
    # find an EOS id the greedy stream first emits MID-generation
    # (index >= 2): declaring an id the stream opens with would finish
    # the request at prefill, before anything is ever in flight
    probe = Request(prompt=[13, 5, 88], max_new_tokens=12)
    _serve(eng, [probe])
    toks = probe.output_tokens
    eos_id = next(t for i, t in enumerate(toks)
                  if i >= 2 and t not in toks[:i])
    mk = lambda: [Request(prompt=[13, 5, 88], max_new_tokens=20),
                  Request(prompt=[9, 4, 2, 8], max_new_tokens=6)]

    eng.reset()
    oracle, _ = _serve(eng, mk(), eos_id=eos_id)

    eng.reset()
    reg = telemetry.MetricsRegistry()
    reqs = mk()
    sched = Scheduler(eng, eos_id=eos_id, pipeline_depth=3,
                      registry=reg)
    sched.run(reqs)
    got = [list(r.output_tokens) for r in reqs]
    assert got == oracle
    assert reqs[0].finish_reason == "eos"
    # the speculated successors of the EOS beat were really in flight
    # and really discarded — the rollback actually happened
    assert reg.snapshot()["counters"].get(
        "serving.heartbeat.discarded", 0) >= 1, \
        "EOS mid-pipeline discarded nothing — the pin exercised no " \
        "rollback"
    assert sched.auditor.audit(eng)["pages_in_use"] == 0

    # the LAST-request strand regression (found by end-to-end drive):
    # a stream whose final request EOSes with speculated successors in
    # flight must still drain — `pending` counts the pipeline, so
    # run()'s `while pending` loop reconciles (and discards) the
    # stragglers instead of exiting with steps stranded in flight
    eng.reset()
    reg = telemetry.MetricsRegistry()
    sched = Scheduler(eng, eos_id=eos_id, pipeline_depth=3,
                      registry=reg)
    (solo,) = sched.run([Request(prompt=[13, 5, 88],
                                 max_new_tokens=20)])
    assert solo.finish_reason == "eos"
    assert not sched._pipeline, \
        "run() exited with dispatched steps stranded in flight"
    assert reg.snapshot()["counters"].get(
        "serving.heartbeat.discarded", 0) >= 1


def test_queue_full_backpressure_parity(engine):
    """QueueFull under pipelining: submit still raises at capacity, and
    a stream pushed through run()'s backpressure absorption emits the
    sync path's exact tokens."""
    engine.reset()
    oracle, _ = _serve(engine, _mixed_stream(), max_queue=2)
    engine.reset()
    sched = Scheduler(engine, max_queue=2, pipeline_depth=2)
    sched.submit(Request(prompt=[1], max_new_tokens=2))
    sched.submit(Request(prompt=[2], max_new_tokens=2))
    with pytest.raises(QueueFull):
        sched.submit(Request(prompt=[3], max_new_tokens=2))
    while sched.pending:
        sched.step()
    engine.reset()
    got, _ = _serve(engine, _mixed_stream(), max_queue=2,
                    pipeline_depth=2)
    assert got == oracle
    engine.reset()


# ------------------------------------------------- speculative + prefix
@pytest.fixture(scope="module")
def spec_engine(lm_and_params):
    return _mk_engine(lm_and_params, spec=SpecConfig(draft_len=4))


def _repetitive_stream():
    """Prompts whose trailing n-grams recur, so the prompt-lookup
    drafter actually drafts (and the verify program actually runs)."""
    base = [11, 12, 13, 14, 11, 12, 13, 14, 11, 12]
    return [Request(prompt=list(base), max_new_tokens=12),
            Request(prompt=[5, 6, 5, 6, 5, 6, 5], max_new_tokens=10),
            Request(prompt=list(range(1, 14)), max_new_tokens=6)]


def test_speculative_parity_with_threaded_drafter(spec_engine):
    """Speculative on: the pipelined beat settles the pipeline before
    verify, drafts on the worker thread, and still emits the sync
    speculative stream bit-for-bit — with speculation genuinely
    engaged (accepted tokens > 0) and no new programs."""
    eng = spec_engine
    eng.reset()
    oracle, _ = _serve(eng, _repetitive_stream(), speculative=True)
    programs0 = eng.compiled_programs
    eng.reset()
    reqs = _repetitive_stream()
    got, sched = _serve(eng, reqs, speculative=True, pipeline_depth=2)
    assert [list(t) for t in got] == oracle
    assert eng.compiled_programs == programs0
    assert sum(r.spec_accepted for r in reqs) > 0, \
        "speculation never engaged — the parity proved nothing"
    assert sched.auditor.audit(eng)["pages_in_use"] == 0
    eng.reset()


def test_prefix_hit_stream_parity_with_hash_offload(lm_and_params):
    """Prefix retention under pipelining: block hashing runs on the
    worker thread from submit time, and the hit/miss/registration
    stream (and every emitted token) matches the sync path exactly —
    precomputed and inline keys are interchangeable bit-for-bit."""
    eng = _mk_engine(lm_and_params, pool=16)
    shared = list(range(1, 17))
    mk = lambda: [Request(prompt=shared + [30 + i], max_new_tokens=6)
                  for i in range(4)]
    oracle, s0 = _serve(eng, mk(), retain_prefixes=True)
    hits0 = eng.prefix_cache.hits          # cumulative across resets
    eng.reset(clear_prefixes=True)
    got, s1 = _serve(eng, mk(), retain_prefixes=True, pipeline_depth=2)
    assert got == oracle
    assert eng.prefix_cache.hits - hits0 == hits0, \
        "the pipelined pass matched a different hit stream"
    assert hits0 > 0, "no hits — the parity proved nothing"


# ------------------------------------------------------------- chaos
def test_chaos_stream_unfaulted_bitwise_and_zero_leaks(engine):
    """A seeded fault plan (host stall, transient chunk + decode
    exceptions, a non-finite decode slot) against the PIPELINED beat:
    un-faulted requests bitwise-match the fault-free sync run, faulted
    ones reach typed terminals, zero new programs, zero leaked
    pages."""
    engine.reset()
    clean_reqs = _mixed_stream()
    Scheduler(engine, fault_policy=FaultPolicy(backoff_base_s=0.0,
                                               audit_every_n=1)).run(
        clean_reqs)
    clean = [list(r.output_tokens) for r in clean_reqs]
    traces0 = (engine.chunk_traces, engine.decode_traces,
               engine.prefill_traces)

    engine.reset()
    plan = FaultPlan([
        FaultSpec(kind="stall", tick=1, stall_s=0.02),
        FaultSpec(kind="exception", tick=2, site="chunk"),
        FaultSpec(kind="nonfinite", tick=4, slot=0),
        FaultSpec(kind="exception", tick=6, site="decode", slot=1),
    ])
    reg = telemetry.MetricsRegistry()
    engine.set_registry(reg)
    sched = Scheduler(
        engine, registry=reg, fault_plan=plan, pipeline_depth=2,
        fault_policy=FaultPolicy(backoff_base_s=0.0, max_retries=1,
                                 audit_every_n=1))
    reqs = _mixed_stream()
    try:
        sched.run(reqs)
    finally:
        engine.set_registry(None)
    faulted = [r for r in reqs if r.retries > 0
               or r.status is RequestStatus.FAILED]
    assert faulted, "the plan must actually fault requests"
    for r in reqs:
        assert r.status.terminal
    for i, r in enumerate(reqs):
        if r.status is RequestStatus.FINISHED:
            # greedy retries are full cold restarts through the same
            # programs: finished requests reproduce the clean tokens
            # whether or not they absorbed a fault
            assert list(r.output_tokens) == clean[i], \
                f"request {i} diverged under pipelined chaos"
    assert (engine.chunk_traces, engine.decode_traces,
            engine.prefill_traces) == traces0
    assert sched.auditor.audit(engine)["pages_in_use"] == 0
    assert reg.snapshot()["counters"]["serving.faults.transient"] >= 1
    engine.reset()


def test_requeued_request_never_consumes_stale_inflight_tokens(
        lm_and_params):
    """The quarantine-requeue lineage pin (found by review): a
    quarantined request keeps its uid through requeue, so if it
    re-admits into the SAME slot while pre-quarantine steps are still
    in flight, a uid check at reconcile alone would emit their
    garbage-lineage tokens into the retried stream. ``_free_slot``
    drops the slot's in-flight entries eagerly instead — the retried
    request must reproduce the fault-free stream bitwise. One slot +
    empty queue + zero backoff forces same-slot re-admission on the
    very next beat (the exact collision window); the one-chunk prompt
    flips to running the same beat it admits."""
    eng = _mk_engine(lm_and_params, slots=1, seed=23)
    clean = Request(prompt=[4, 9, 1], max_new_tokens=8)
    Scheduler(eng).run([clean])

    eng.reset()
    reg = telemetry.MetricsRegistry()
    eng.set_registry(reg)
    # non-finite injected at dispatch tick 3: with depth 2 the verdict
    # lands at reconcile two beats later, while two younger speculated
    # steps of the same lineage sit in flight
    plan = FaultPlan([FaultSpec(kind="nonfinite", tick=3, slot=0)])
    sched = Scheduler(
        eng, registry=reg, fault_plan=plan, pipeline_depth=2,
        fault_policy=FaultPolicy(backoff_base_s=0.0, max_retries=2))
    r = Request(prompt=[4, 9, 1], max_new_tokens=8)
    try:
        sched.run([r])
    finally:
        eng.set_registry(None)
    assert plan.stats()["injected_nonfinite"] == 1
    assert r.retries >= 1, "the fault never landed — nothing retried"
    assert r.status is RequestStatus.FINISHED
    assert list(r.output_tokens) == list(clean.output_tokens), \
        "retried stream diverged — a stale in-flight token leaked " \
        "into the re-admitted request"
    # the invalidated lineage really was in flight and was discarded
    assert reg.snapshot()["counters"].get(
        "serving.heartbeat.discarded", 0) >= 1
    assert sched.auditor.audit(eng)["pages_in_use"] == 0


def test_deferred_reconcile_failure_is_contained(lm_and_params):
    """Containment at the DEFERRED force (found by review): on async
    backends a dispatched step's runtime error surfaces at the first
    read inside ``decode_reconcile`` — beats later, in
    ``_reconcile_oldest`` — not at the wrapped dispatch site. The
    scheduler must quarantine the step's batch exactly like a sync
    decode-site fault (requeue → clean bitwise retry), never let the
    exception crash ``run()``. Simulated by failing the engine's
    reconcile once (the CPU backend's synchronous donated calls can't
    produce it for real)."""
    eng = _mk_engine(lm_and_params, slots=1, seed=31)
    clean = Request(prompt=[6, 2, 7], max_new_tokens=6)
    Scheduler(eng).run([clean])

    eng.reset()
    orig = eng.decode_reconcile
    fails = {"left": 1}

    def flaky(pending, valid=None):
        out = orig(pending, valid=valid)
        if fails["left"]:
            fails["left"] -= 1
            raise RuntimeError("deferred device failure")
        return out

    reg = telemetry.MetricsRegistry()
    eng.decode_reconcile = flaky
    try:
        sched = Scheduler(
            eng, registry=reg, pipeline_depth=2,
            fault_policy=FaultPolicy(backoff_base_s=0.0, max_retries=2))
        r = Request(prompt=[6, 2, 7], max_new_tokens=6)
        sched.run([r])
    finally:
        del eng.decode_reconcile
    assert fails["left"] == 0, "the failure never fired"
    assert r.retries >= 1
    assert r.status is RequestStatus.FINISHED
    assert list(r.output_tokens) == list(clean.output_tokens), \
        "retry after a deferred reconcile failure diverged"
    assert reg.snapshot()["counters"]["serving.faults.transient"] >= 1
    assert not sched._pipeline
    assert sched.auditor.audit(eng)["pages_in_use"] == 0


# ------------------------------------------------- heartbeat telemetry
def test_heartbeat_host_device_split_emitted_every_beat(engine):
    """serving.heartbeat.host_s / device_wait_s land as histograms with
    one observation per beat (sync AND pipelined), and the duty-cycle
    gauge stays a fraction."""
    engine.reset()
    for depth in (0, 2):
        reg = telemetry.MetricsRegistry()
        sched = Scheduler(engine, registry=reg, pipeline_depth=depth)
        sched.submit(Request(prompt=[3, 1, 4], max_new_tokens=5))
        beats = 0
        while sched.pending:
            sched.step()
            beats += 1
        snap = reg.snapshot()
        h = snap["histograms"]
        assert h["serving.heartbeat.host_s"]["count"] == beats
        assert h["serving.heartbeat.device_wait_s"]["count"] == beats
        assert h["serving.heartbeat.host_s"]["mean"] >= 0.0
        assert 0.0 <= snap["gauges"]["serving.heartbeat.duty_cycle"] \
            <= 1.0
        engine.reset()


# ------------------------------------------------- watchdog semantics
def test_watchdog_budgets_host_portion_not_device_wait(engine):
    """Satellite pin: under pipelining the watchdog budgets HOST time.
    A beat whose wall is dominated by device-wait (simulated: the
    reconcile charges a sleep to ``device_wait_s``) never breaches a
    budget smaller than that wall — while an injected host stall of the
    same size still does."""
    engine.reset()
    # warm every program so tracing exemptions don't participate here
    Scheduler(engine).run([Request(prompt=[5, 6], max_new_tokens=3)])

    engine.reset()
    orig = engine.decode_reconcile

    def device_heavy(pending, valid=None):
        out = orig(pending, valid=valid)
        time.sleep(0.05)
        engine.device_wait_s += 0.05    # a slow DEVICE, not a slow host
        return out

    stalls = []
    engine.decode_reconcile = device_heavy
    try:
        sched = Scheduler(
            engine, pipeline_depth=1,
            fault_policy=FaultPolicy(watchdog_budget_s=0.03,
                                     on_stall=stalls.append))
        sched.run([Request(prompt=[5, 6], max_new_tokens=6)])
    finally:
        del engine.decode_reconcile     # restore the bound method
    assert not stalls, \
        "device-wait tripped the watchdog — the budget must cover " \
        "host think-time only"

    # the same budget against a HOST stall of the same magnitude trips
    engine.reset()
    plan = FaultPlan([FaultSpec(kind="stall", tick=1, stall_s=0.05)])
    sched = Scheduler(
        engine, pipeline_depth=1, fault_plan=plan,
        fault_policy=FaultPolicy(watchdog_budget_s=0.03,
                                 on_stall=stalls.append))
    sched.run([Request(prompt=[5, 6], max_new_tokens=6)])
    assert len(stalls) >= 1 and stalls[0] > 0.03
    engine.reset()


def test_watchdog_warm_start_exemption_on_dispatch_ahead_beat(
        lm_and_params):
    """The PR 8 warm-start regression, re-pinned under pipelining: a
    COLD engine's tracing beats are exempt from an impossible budget
    (counted as ``serving.watchdog.warmup_s``) even though tracing now
    happens at DISPATCH time, and warm beats breach — warmups + stalls
    partition the run exactly. A warmed engine stops claiming
    warm-up."""
    eng = _mk_engine(lm_and_params, seed=9)
    assert eng.compiled_programs == 0
    stalls = []
    reg = telemetry.MetricsRegistry()
    sched = Scheduler(
        eng, registry=reg, pipeline_depth=2,
        fault_policy=FaultPolicy(backoff_base_s=0.0,
                                 watchdog_budget_s=1e-9,
                                 on_stall=stalls.append))
    steps = 0
    sched.submit(Request(prompt=[5, 6, 7], max_new_tokens=4))
    while sched.pending:
        sched.step()
        steps += 1
    snap = reg.snapshot()
    warmups = snap["histograms"]["serving.watchdog.warmup_s"]["count"]
    stalls_n = snap["counters"].get("serving.watchdog.stall", 0)
    assert warmups >= 1, "the dispatch-ahead tracing beat was not " \
        "accounted as warm-up"
    assert warmups + stalls_n == steps
    assert len(stalls) == stalls_n
    sched.submit(Request(prompt=[5, 6, 7], max_new_tokens=2))
    more = 0
    while sched.pending:
        sched.step()
        more += 1
    snap = reg.snapshot()
    assert snap["histograms"]["serving.watchdog.warmup_s"]["count"] \
        == warmups, "a warm engine must not keep claiming warm-up"
    assert snap["counters"]["serving.watchdog.stall"] == stalls_n + more


# --------------------------------------------------- engine async halves
def test_decode_dispatch_reconcile_is_decode_step(engine):
    """The split is the sync step: dispatch + reconcile back-to-back
    returns decode_step's exact tokens (same program, same operands),
    a PendingDecode reads back exactly once, and every forced read
    charges device_wait_s."""
    engine.reset()
    tok = engine.prefill_chunked(0, [5, 9, 2])
    active = [True] + [False] * (engine.slots - 1)
    last = np.zeros(engine.slots, np.int64)
    last[0] = tok
    temps = np.zeros(engine.slots, np.float32)
    a = engine.decode_step(list(last), active, temps)
    pending = engine.decode_dispatch(
        np.asarray([int(a[0])] + [0] * (engine.slots - 1)), active,
        temps)
    dw0 = engine.device_wait_s
    toks, finite, dt = engine.decode_reconcile(pending)
    assert toks.shape == (engine.slots,) and finite.shape \
        == (engine.slots,)
    assert dt >= 0 and engine.device_wait_s > dw0
    with pytest.raises(RuntimeError, match="already reconciled"):
        engine.decode_reconcile(pending)
    engine.sync()                       # the explicit barrier is cheap
    engine.reset()


# -------------------------------------------------------- DraftWorker
def test_draft_worker_precomputed_equals_inline_and_fallback():
    w = DraftWorker()
    try:
        cfg = SpecConfig(draft_len=3)
        toks = [1, 2, 3, 1, 2, 3, 1]
        from apex_tpu.serving import draft_tokens
        inline = draft_tokens(toks, cfg)
        w.submit("k", lambda: draft_tokens(toks, cfg))
        assert w.take("k", lambda: draft_tokens(toks, cfg)) == inline
        # never submitted: take runs the closure inline
        assert w.take("nope", lambda: draft_tokens(toks, cfg)) == inline
        # results are consumed on take: a second take recomputes inline
        w.submit("k2", lambda: 42)
        assert w.take("k2", lambda: 0) == 42
        assert w.take("k2", lambda: 7) == 7
    finally:
        w.stop()


def test_draft_worker_surfaces_exceptions_and_is_idempotent():
    w = DraftWorker()
    try:
        w.submit("boom", lambda: 1 / 0)
        with pytest.raises(ZeroDivisionError):
            w.take("boom", lambda: None)
        # idempotent submit: a completed key is not re-run
        calls = []
        w.submit("once", lambda: calls.append(1) or len(calls))
        assert w.take("once", lambda: -1) == 1
        w.submit("once2", lambda: calls.append(1) or len(calls))
        w.submit("once2", lambda: calls.append(1) or len(calls))
        assert w.take("once2", lambda: -1) == 2
        assert len(calls) == 2
    finally:
        w.stop()
    # stop is idempotent, and a stopped worker degrades to inline
    w.stop()
    w.submit("late", lambda: 1)
    assert w.take("late", lambda: 9) == 9


def test_draft_worker_bounds_unclaimed_results():
    w = DraftWorker()
    try:
        n = w._MAX_UNCLAIMED + 40
        for i in range(n):
            w.submit(("job", i), lambda i=i: i)
        # drain: wait for the queue to empty via a sentinel take
        assert w.take(("job", n - 1), lambda: -1) == n - 1
        with w._lock:
            assert len(w._results) <= w._MAX_UNCLAIMED
        # an aged-out key recomputes inline — no wrong answers, no leak
        assert w.take(("job", 0), lambda: "inline") in (0, "inline")
    finally:
        w.stop()
