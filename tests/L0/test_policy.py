"""Policy/opt-level tests — mirrors tests/L0/run_amp/test_basic_casts.py and
the frontend option-resolution behavior (apex/amp/frontend.py)."""

import jax.numpy as jnp
import pytest

from apex_tpu.amp import resolve_policy
from apex_tpu.amp.policy import opt_levels


def test_opt_level_tables_match_apex():
    assert set(opt_levels) == {"O0", "O1", "O2", "O3"}
    assert opt_levels["O0"]["loss_scale"] == 1.0
    assert opt_levels["O1"]["loss_scale"] == "dynamic"
    assert opt_levels["O2"]["loss_scale"] == "dynamic"
    assert opt_levels["O3"]["loss_scale"] == 1.0
    assert opt_levels["O2"]["master_weights"] is True
    assert opt_levels["O2"]["keep_batchnorm_fp32"] is True
    assert opt_levels["O3"]["keep_batchnorm_fp32"] is False
    assert opt_levels["O1"]["patch_torch_functions"] is True


def test_bad_opt_level_raises():
    with pytest.raises(ValueError):
        resolve_policy("O4")
    with pytest.raises(ValueError):
        resolve_policy("02")  # zero, not the letter — apex's classic footgun


@pytest.mark.parametrize("half", [jnp.bfloat16, jnp.float16])
def test_o2_dtypes(half):
    p = resolve_policy("O2", half_dtype=half, verbose=False)
    assert p.param_dtype == jnp.dtype(half)
    assert p.compute_dtype == jnp.dtype(half)
    assert p.wants_master_weights
    assert p.keep_bn_fp32
    assert p.loss_scale == "dynamic"


def test_o0_is_fp32_noop():
    p = resolve_policy("O0", verbose=False)
    assert p.param_dtype == jnp.float32
    assert p.compute_dtype == jnp.float32
    assert not p.wants_master_weights
    assert p.loss_scale == 1.0


def test_o1_compute_half_params_fp32():
    p = resolve_policy("O1", verbose=False)
    assert p.param_dtype == jnp.float32
    assert p.compute_dtype == jnp.bfloat16
    assert p.patch_torch_functions


def test_kwarg_overrides_beat_table():
    p = resolve_policy("O2", loss_scale=128.0, master_weights=False,
                       keep_batchnorm_fp32="False", verbose=False)
    assert p.loss_scale == 128.0
    assert not p.wants_master_weights
    assert not p.keep_bn_fp32
    with pytest.raises(ValueError):
        resolve_policy("O2", keep_batchnorm_fp32="nope", verbose=False)


def test_cast_params_keeps_norms_fp32():
    p = resolve_policy("O2", half_dtype=jnp.bfloat16, verbose=False)
    params = {
        "conv1": {"kernel": jnp.ones((3, 3), jnp.float32)},
        "bn1": {"scale": jnp.ones((3,), jnp.float32),
                "bias": jnp.zeros((3,), jnp.float32)},
        "dense": {"kernel": jnp.ones((4, 4), jnp.float32)},
    }
    out = p.cast_params(params)
    assert out["conv1"]["kernel"].dtype == jnp.bfloat16
    assert out["dense"]["kernel"].dtype == jnp.bfloat16
    assert out["bn1"]["scale"].dtype == jnp.float32
    assert out["bn1"]["bias"].dtype == jnp.float32


def test_cast_params_o3_casts_everything():
    p = resolve_policy("O3", half_dtype=jnp.bfloat16, verbose=False)
    params = {"bn": {"scale": jnp.ones((3,), jnp.float32)}}
    out = p.cast_params(params)
    assert out["bn"]["scale"].dtype == jnp.bfloat16


def test_cast_to_compute_skips_non_float():
    p = resolve_policy("O2", verbose=False)
    tree = {"x": jnp.ones((2,), jnp.float32), "idx": jnp.arange(3)}
    out = p.cast_to_compute(tree)
    assert out["x"].dtype == jnp.bfloat16
    assert out["idx"].dtype == jnp.int32


def test_banner_mentions_resolved_options():
    p = resolve_policy("O2", verbose=False)
    b = p.banner()
    assert "O2" in b and "master_weights" in b and "loss_scale" in b


def test_o1_op_tables():
    from apex_tpu.amp import lists

    assert lists.compute_dtype_for("matmul") == jnp.bfloat16
    assert lists.compute_dtype_for("conv2d") == jnp.bfloat16
    assert lists.compute_dtype_for("softmax") == jnp.float32
    assert lists.compute_dtype_for("mse_loss") == jnp.float32
    assert lists.compute_dtype_for("add") is None
    assert lists.promote_dtype(jnp.float16, jnp.float32) == jnp.float32


def test_cast_if_autocast_enabled():
    """apex/_autocast_utils.py — _cast_if_autocast_enabled parity (P43)."""
    import jax.numpy as jnp

    from apex_tpu._autocast_utils import _cast_if_autocast_enabled
    from apex_tpu.amp import resolve_policy

    x = jnp.ones((2,), jnp.float32)
    i = jnp.ones((2,), jnp.int32)
    # disabled: pass-through
    assert _cast_if_autocast_enabled(x, i) == (x, i)
    pol = resolve_policy(opt_level="O2", loss_scale=1.0)
    cx, ci = _cast_if_autocast_enabled(x, i, policy=pol)
    assert cx.dtype == jnp.bfloat16 and ci.dtype == jnp.int32
    cx, = _cast_if_autocast_enabled(x, dtype=jnp.float16)
    assert cx.dtype == jnp.float16
