"""Test-wide environment: hermetic multi-device CPU backend.

Mirrors the reference's strategy of faking multi-node as multi-process
single-node (SURVEY §5.2) — but better: XLA's host-platform device-count flag
gives 8 virtual devices in ONE process, so every collective/mesh test runs
with no hardware (tests/distributed/ equivalents run here hermetically).

Must run before jax initializes its backends, hence module-level in conftest.
"""

import os

# Force (not setdefault): the driver environment pins JAX_PLATFORMS=axon (the
# one real TPU); the test suite must be hermetic CPU with 8 virtual devices.
# The axon sitecustomize imports jax at interpreter start, so jax has already
# captured JAX_PLATFORMS=axon — update the live config too (backends are still
# uninitialized when conftest runs, so this takes effect).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
