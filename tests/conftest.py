"""Test-wide environment: hermetic multi-device CPU backend.

Mirrors the reference's strategy of faking multi-node as multi-process
single-node (SURVEY §5.2) — but better: XLA's host-platform device-count flag
gives 8 virtual devices in ONE process, so every collective/mesh test runs
with no hardware (tests/distributed/ equivalents run here hermetically).

Must run before jax initializes its backends, hence module-level in conftest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
