"""Test-wide environment: hermetic multi-device CPU backend.

Mirrors the reference's strategy of faking multi-node as multi-process
single-node (SURVEY §5.2) — but better: XLA's host-platform device-count flag
gives 8 virtual devices in ONE process, so every collective/mesh test runs
with no hardware (tests/distributed/ equivalents run here hermetically).

Must run before jax initializes its backends, hence module-level in conftest.
"""

import os
import sys


def _tpu_only_invocation():
    """True when every selected test path targets tests/tpu — the on-silicon
    tier (tests/tpu/conftest.py) must see the REAL device, so the CPU
    forcing below is skipped for `pytest tests/tpu ...` invocations.

    `APEX_TPU_SILICON=1` is the explicit, invocation-proof override (use it
    under pytest-xdist or option-heavy command lines, where argv sniffing
    cannot classify reliably: option VALUES that happen to be paths, or
    xdist workers re-execing with a different argv). Otherwise, selection
    detection is filesystem-based (an argv entry that exists on disk is a
    test path; `-k`/`-m` expression values are not), with a cwd fallback
    for `cd tests/tpu && pytest` — which covers the documented plain
    `pytest tests/tpu` invocation.
    """
    here = os.path.dirname(os.path.abspath(__file__))     # .../tests
    tpu_dir = os.path.realpath(os.path.join(here, "tpu"))

    def is_tpu_path(a):
        p = os.path.realpath(os.path.abspath(a.split("::")[0]))
        return p == tpu_dir or p.startswith(tpu_dir + os.sep)

    selected = [a for a in sys.argv[1:]
                if not a.startswith("-") and os.path.exists(a.split("::")[0])]
    if os.environ.get("APEX_TPU_SILICON"):
        # explicit opt-in — but never let a leaked env var silently break
        # the hermetic suite: using the override for anything but a
        # tests/tpu selection (including a bare `pytest` from the repo
        # root) is a configuration error, named loudly here. xdist WORKERS
        # re-exec with an empty argv and the rootdir cwd, so they must
        # trust the master's classification (PYTEST_XDIST_WORKER marks
        # them) — the master itself still validates the selection.
        if os.environ.get("PYTEST_XDIST_WORKER"):
            return True
        non_tpu = [a for a in selected if not is_tpu_path(a)]
        if not selected and not is_tpu_path(os.getcwd()):
            non_tpu = [os.getcwd()]
        if non_tpu:
            raise RuntimeError(
                f"APEX_TPU_SILICON is set but non-silicon tests are "
                f"selected ({non_tpu[:3]}): unset it to run the "
                f"hermetic suite")
        return True
    if selected:
        return all(is_tpu_path(a) for a in selected)
    return is_tpu_path(os.getcwd())


if not _tpu_only_invocation():
    # Force (not setdefault): the driver environment pins JAX_PLATFORMS=axon
    # (the one real TPU); the hermetic suite must be CPU with 8 virtual
    # devices. The axon sitecustomize imports jax at interpreter start, so
    # jax has already captured JAX_PLATFORMS=axon — update the live config
    # too (backends are still uninitialized when conftest runs, so this
    # takes effect). Under `pytest tests/` the tests/tpu tier self-skips
    # (its conftest requires a tpu backend).
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
