"""L1 integration tier: opt-level convergence parity.

Mirror of the reference's tests/L1/ (common/main_amp.py --deterministic +
compare.py): run the SAME deterministic workload under different opt levels
and assert the half-precision runs track the fp32 run — loss curves within
dtype tolerance and final weights allclose. This is the miniature of the
driver's "top-1 parity" criterion.

Three workloads, matching BASELINE configs 1, 3, and 4:
- ResNet-ish conv net (BatchNorm, SGD momentum) — examples/imagenet shape
- small transformer LM (FusedLayerNorm, flash-attn, FusedAdam) — LM shape
- tiny BERT pretraining (MLM+NSP heads, FusedLAMB) — BERT-LAMB shape
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
from apex_tpu.models.resnet import create_model
from apex_tpu.models.transformer_lm import TransformerLM
from apex_tpu.optimizers import fused_adam, fused_sgd

ITERS = 12


def _run_resnet(opt_level, iters=ITERS):
    policy = amp.resolve_policy(opt_level=opt_level, loss_scale="dynamic")
    model = create_model("resnet18", num_classes=10,
                         dtype=policy.compute_dtype)
    rng = jax.random.PRNGKey(0)
    x0 = jnp.zeros((2, 32, 32, 3), jnp.float32)
    variables = model.init(rng, x0, train=True)
    params = variables["params"]
    bstats = variables.get("batch_stats", {})

    def loss_fn(p, mstate, batch):
        x, y = batch
        logits, upd = model.apply({"params": p, "batch_stats": mstate}, x,
                                  train=True, mutable=["batch_stats"])
        loss = softmax_cross_entropy_loss(
            jnp.asarray(logits, jnp.float32), y).mean()
        return loss, upd["batch_stats"]

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_sgd(0.02,
                                                              momentum=0.9),
                                           policy, with_model_state=True)
    state = init_fn(params, bstats)
    jit_step = jax.jit(step_fn)
    # fixed batch (overfit): a converging trajectory, so dtype noise stays
    # bounded instead of compounding through SGD chaos — same reason the
    # reference's L1 runs use --deterministic + fixed data order
    k = jax.random.PRNGKey(100)
    x = jax.random.normal(k, (8, 32, 32, 3))
    y = jax.random.randint(jax.random.fold_in(k, 1), (8,), 0, 10)
    losses = []
    for i in range(iters):
        state, m = jit_step(state, (x, y))
        losses.append(float(m["loss"]))
    final = state.master_params if state.master_params is not None \
        else state.params
    return np.asarray(losses), jax.tree_util.tree_map(
        lambda v: np.asarray(v, np.float32), final)


def _run_lm(opt_level, iters=ITERS):
    policy = amp.resolve_policy(opt_level=opt_level, loss_scale="dynamic")
    model = TransformerLM(vocab_size=64, hidden=64, num_layers=2,
                          num_heads=4, max_seq_len=16,
                          dtype=policy.compute_dtype)
    toks0 = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks0, train=False)["params"]

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch[:, :-1], train=True)
        return softmax_cross_entropy_loss(logits, batch[:, 1:]).mean()

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_adam(1e-3), policy)
    state = init_fn(params)
    jit_step = jax.jit(step_fn)
    losses = []
    for i in range(iters):
        batch = jax.random.randint(jax.random.PRNGKey(200 + i), (4, 17), 0,
                                   64)
        state, m = jit_step(state, batch)
        losses.append(float(m["loss"]))
    final = state.master_params if state.master_params is not None \
        else state.params
    return np.asarray(losses), jax.tree_util.tree_map(
        lambda v: np.asarray(v, np.float32), final)


@pytest.fixture(scope="module")
def resnet_o0():
    return _run_resnet("O0")


@pytest.fixture(scope="module")
def lm_o0():
    return _run_lm("O0")


@pytest.mark.parametrize("opt_level,loss_rtol,w_atol", [
    ("O1", 0.08, 0.02),
    ("O2", 0.08, 0.02),
    ("O3", 0.15, 0.05),   # pure-half: loosest bar, like apex's O3 caveat
])
def test_resnet_opt_level_parity(resnet_o0, opt_level, loss_rtol, w_atol):
    l0, w0 = resnet_o0
    l, w = _run_resnet(opt_level)
    assert np.isfinite(l).all()
    np.testing.assert_allclose(l, l0, rtol=loss_rtol, atol=0.05)
    flat0 = np.concatenate([v.ravel() for v in
                            jax.tree_util.tree_leaves(w0)])
    flat = np.concatenate([v.ravel() for v in jax.tree_util.tree_leaves(w)])
    # weight drift bounded (compare.py asserts allclose on checkpoints)
    assert np.abs(flat - flat0).mean() < w_atol


@pytest.mark.parametrize("opt_level,loss_rtol", [
    ("O1", 0.05), ("O2", 0.05),
])
def test_lm_opt_level_parity(lm_o0, opt_level, loss_rtol):
    l0, w0 = lm_o0
    l, w = _run_lm(opt_level)
    assert np.isfinite(l).all()
    np.testing.assert_allclose(l, l0, rtol=loss_rtol, atol=0.08)
    # both must actually be LEARNING, not just agreeing
    assert l[-1] < l[0] and l0[-1] < l0[0]


def test_o0_is_deterministic(resnet_o0):
    l0, _ = resnet_o0
    l1, _ = _run_resnet("O0")
    np.testing.assert_array_equal(l0, l1)


# ---------------------------------------------------- config 4: BERT + LAMB
def _run_bert(opt_level, iters=ITERS):
    from apex_tpu.models.bert import BertConfig, BertForPreTraining
    from apex_tpu.optimizers import fused_lamb

    policy = amp.resolve_policy(opt_level=opt_level, loss_scale="dynamic")
    cfg = BertConfig(vocab_size=96, hidden_size=48, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=96,
                     max_position_embeddings=32,
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)  # deterministic runs
    model = BertForPreTraining(cfg, dtype=policy.compute_dtype)
    B, S, Pm = 4, 16, 3
    ids0 = jnp.zeros((B, S), jnp.int32)
    tt0 = jnp.zeros((B, S), jnp.int32)
    am0 = jnp.ones((B, S), jnp.int32)
    pos0 = jnp.zeros((B, Pm), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids0, tt0, am0, pos0,
                        train=False)["params"]

    def loss_fn(p, batch):
        ids, tt, am, pos, labels, nsp = batch
        mlm, nspl = model.apply({"params": p}, ids, tt, am, pos, train=True)
        l_mlm = softmax_cross_entropy_loss(
            mlm.reshape(-1, cfg.vocab_size), labels.reshape(-1)).mean()
        l_nsp = softmax_cross_entropy_loss(nspl, nsp).mean()
        return l_mlm + l_nsp

    init_fn, step_fn = amp.make_train_step(
        loss_fn, fused_lamb(1e-3, weight_decay=0.01), policy)
    state = init_fn(params)
    jit_step = jax.jit(step_fn)
    losses = []
    for i in range(iters):
        k = jax.random.PRNGKey(300 + i)
        ks = jax.random.split(k, 4)
        batch = (jax.random.randint(ks[0], (B, S), 0, 96),
                 jnp.zeros((B, S), jnp.int32),
                 jnp.ones((B, S), jnp.int32),
                 jax.random.randint(ks[1], (B, Pm), 0, S),
                 jax.random.randint(ks[2], (B, Pm), 0, 96),
                 jax.random.randint(ks[3], (B,), 0, 2))
        state, m = jit_step(state, batch)
        losses.append(float(m["loss"]))
    final = state.master_params if state.master_params is not None \
        else state.params
    return np.asarray(losses), jax.tree_util.tree_map(
        lambda v: np.asarray(v, np.float32), final)


@pytest.fixture(scope="module")
def bert_o0():
    return _run_bert("O0")


@pytest.mark.parametrize("opt_level,loss_rtol", [
    ("O1", 0.05), ("O2", 0.05),
])
def test_bert_lamb_opt_level_parity(bert_o0, opt_level, loss_rtol):
    """BASELINE config 4: BERT pretraining shape with FusedLAMB — bf16
    policies must track the fp32 loss trajectory."""
    l0, w0 = bert_o0
    l, w = _run_bert(opt_level)
    assert np.isfinite(l).all()
    np.testing.assert_allclose(l, l0, rtol=loss_rtol, atol=0.08)
    assert l[-1] < l[0] and l0[-1] < l0[0]   # both learning
