"""Pallas causal-softmax kernel (N8) parity tests vs the fp32 jnp reference.

Mirrors the reference's contrib test pattern: fused kernel against a composed
reference with dtype-dependent tolerances (SURVEY §5.1).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels.causal_softmax import (causal_softmax,
                                             causal_softmax_reference)


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-6),
                                       (jnp.bfloat16, 1e-2)])
@pytest.mark.parametrize("shape", [(2, 3, 128, 128), (1, 2, 256, 384),
                                   (4, 8, 128)])
def test_forward_parity(dtype, tol, shape):
    x = jax.random.normal(jax.random.PRNGKey(0), shape, dtype) * 3.0
    out = causal_softmax(x, scale=0.5)
    ref = causal_softmax_reference(x, scale=0.5)
    assert out.dtype == dtype
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=tol, atol=tol)
    # rows sum to 1, strict upper triangle is zero
    s = np.asarray(out, np.float32)
    np.testing.assert_allclose(s.sum(-1), 1.0, rtol=2 * tol, atol=2 * tol)
    sq, sk = shape[-2], shape[-1]
    mask = np.triu(np.ones((sq, sk), bool), k=1)
    assert (np.abs(s[..., mask]) < tol).all()


def test_backward_parity():
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 128, 128), jnp.float32)

    def f_kernel(x):
        return jnp.sum(jnp.sin(causal_softmax(x, scale=0.7) * 3.0))

    def f_ref(x):
        return jnp.sum(jnp.sin(causal_softmax_reference(x, scale=0.7) * 3.0))

    gk = jax.grad(f_kernel)(x)
    gr = jax.grad(f_ref)(x)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                               rtol=1e-5, atol=1e-6)


def test_unaligned_falls_back():
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 7, 33), jnp.float32)
    out = causal_softmax(x)  # 33 % 128 != 0 → reference path, still correct
    ref = causal_softmax_reference(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_fused_scale_mask_softmax_routes_causal():
    """FusedScaleMaskSoftmax(causal) → the Pallas path (VERDICT round-1
    item 8), numerically matching the kernel reference."""
    from apex_tpu.transformer.enums import AttnMaskType
    from apex_tpu.transformer.functional.fused_softmax import (
        FusedScaleMaskSoftmax)

    x = jax.random.normal(jax.random.PRNGKey(3), (2, 4, 128, 128),
                          jnp.bfloat16)
    m = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal, scale=0.25)
    out = m(x)
    ref = causal_softmax_reference(x, scale=0.25)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=1e-2, atol=1e-2)
