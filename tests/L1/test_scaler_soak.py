"""Scaler-DYNAMICS soak (VERDICT round-4 weak #7): every other parity
test runs ≤ a few dozen steps and never sees the scaler move. This one
trains a real fp16 LM step for hundreds of steps with a SMALL
scale_window so the full life cycle happens many times —
growth-at-window, natural overflow at the fp16 boundary,
hysteresis-buffered backoff, regrowth — and checks the whole loss-scale
trajectory STEP-FOR-STEP against an independent reference automaton of
the SURVEY §4.2 schedule (apex scaler.py update_scale + Megatron
DynamicGradScaler hysteresis), fed only the observed found_inf bits.
A mid-dynamics checkpoint/resume must continue the cycle bitwise.

The driver lives here so tests/tpu/test_scaler_soak_on_silicon.py can
run the same soak through the real Mosaic lowerings.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.amp.scaler import init_scaler
from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
from apex_tpu.models.transformer_lm import create_lm
from apex_tpu.optimizers import fused_adam

TINY = float(np.finfo(np.float32).tiny)


def reference_scaler_trace(found_infs, *, window, hysteresis,
                           factor=2.0, init=2.0 ** 16,
                           max_scale=2.0 ** 24):
    """Pure-python re-derivation of the schedule from first principles
    (apex amp scaler.py + hysteresis): NOT a call into the library —
    the soak would otherwise test update_scale against itself."""
    scale, unskipped, hyst = init, 0, hysteresis
    out = []
    for fi in found_infs:
        if fi:
            hyst = max(hyst - 1, 0)
            if hyst <= 0:
                scale = max(scale / factor, TINY)
            unskipped = 0
        else:
            unskipped += 1
        if unskipped >= window:
            scale = min(scale * factor, max_scale)
            unskipped = 0
            hyst = hysteresis
        out.append((scale, unskipped, hyst))
    return out


def build_step(window, hysteresis, lr=3e-3):
    policy = amp.resolve_policy(opt_level="O2", half_dtype=jnp.float16,
                                loss_scale="dynamic", verbose=False)
    model = create_lm("tiny", vocab_size=64, max_seq_len=16,
                      dtype=policy.model_dtype)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 16), jnp.int32), train=False)["params"]

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch[:, :-1], train=True)
        return softmax_cross_entropy_loss(
            jnp.asarray(logits, jnp.float32), batch[:, 1:]).mean()

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_adam(lr),
                                           policy)
    state = init_fn(params)
    state = state.replace(scaler=init_scaler(
        "dynamic", scale_window=window, hysteresis=hysteresis))
    return state, jax.jit(step_fn)


def batch_at(it):
    return jax.random.randint(jax.random.PRNGKey(1000 + it), (8, 17),
                              0, 64)


def run_soak(n_steps, window, hysteresis, ckpt_at=None, tmp_path=None):
    """Run the soak; returns (trace rows, final state, resumed state or
    None). ``ckpt_at`` saves mid-dynamics and separately resumes to the
    end for the bitwise comparison."""
    from apex_tpu.utils.checkpoint import (resume_train_checkpoint,
                                           save_train_checkpoint)

    state, step = build_step(window, hysteresis)
    trace = []
    ckpt, resumed = None, None
    for it in range(n_steps):
        if ckpt_at is not None and it == ckpt_at:
            ckpt = os.path.join(str(tmp_path), "soak.npz")
            save_train_checkpoint(ckpt, state, it, jax.random.PRNGKey(0))
        state, metrics = step(state, batch_at(it))
        trace.append((bool(metrics["found_inf"]),
                      float(state.scaler.loss_scale),
                      int(state.scaler.unskipped),
                      int(state.scaler.hysteresis_left)))
    if ckpt is not None:
        re_state, start, _ = resume_train_checkpoint(
            ckpt, state, jax.random.PRNGKey(0), step_limit=n_steps,
            limit_flag="--iters")
        for it in range(start, n_steps):
            re_state, _ = step(re_state, batch_at(it))
        resumed = re_state
    return trace, state, resumed


def assert_soak_dynamics(trace, window, hysteresis, min_overflows,
                         min_growths):
    found = [t[0] for t in trace]
    ref = reference_scaler_trace(found, window=window,
                                 hysteresis=hysteresis)
    for i, ((fi, scale, unsk, hy), (r_scale, r_unsk, r_hy)) in enumerate(
            zip(trace, ref)):
        assert (scale, unsk, hy) == (r_scale, r_unsk, r_hy), (
            f"step {i}: scaler {(scale, unsk, hy)} != "
            f"reference {(r_scale, r_unsk, r_hy)} (found_inf={fi}; "
            f"window={window} hysteresis={hysteresis})")
    n_overflow = sum(found)
    scales = [t[1] for t in trace]
    n_growth = sum(1 for a, b in zip(scales, scales[1:]) if b > a)
    assert n_overflow >= min_overflows, \
        f"soak too tame: only {n_overflow} overflows — no dynamics tested"
    assert n_growth >= min_growths, \
        f"scale only grew {n_growth} times over {len(trace)} steps"


def test_scaler_full_cycle_over_300_steps(tmp_path):
    """300 fp16 steps, window 8, hysteresis 2: the scale must climb
    from 2^16, hit the fp16 overflow boundary, back off through the
    hysteresis budget, and regrow — with every transition matching the
    reference automaton exactly; params/masters/opt state and the
    remaining trajectory must survive a step-150 checkpoint bitwise."""
    window, hysteresis, n = 8, 2, 300
    trace, state, resumed = run_soak(n, window, hysteresis,
                                     ckpt_at=150, tmp_path=tmp_path)
    assert_soak_dynamics(trace, window, hysteresis,
                         min_overflows=3, min_growths=10)
    # overflow steps froze the model: loss stayed finite throughout
    assert all(np.isfinite(t[1]) for t in trace)
    # mid-dynamics resume: bitwise identical end state, scaler included
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_scaler_hysteresis_one_is_classic_apex(tmp_path):
    """hysteresis=1 (apex amp's classic immediate backoff) follows the
    same automaton with the tolerance degenerate."""
    window, n = 6, 150
    trace, _, _ = run_soak(n, window, hysteresis=1)
    assert_soak_dynamics(trace, window, 1, min_overflows=2,
                         min_growths=8)
