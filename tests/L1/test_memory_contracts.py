"""Hermetic structural half of the kernel memory contracts.

The byte-priced half lives in tests/tpu/test_memory_contracts_on_silicon.py (XLA
buffer assignment on the real backend — the CPU backend's
``memory_analysis`` excludes its temp arena, so peaks carry no signal
here). What CAN be asserted hermetically is the *structure* the pricing
rests on: the residual pytrees the custom_vjp forward rules save. These
are backend-independent — ``jax.eval_shape`` of the fwd rule shows
exactly which tensors backward will consume.

Contracts (the reference's own claims):
- xentropy bprop-in-fprop (apex/contrib/csrc/xentropy/xentropy_kernel.cu):
  residuals are (logits, labels, mlse) — nothing new of size [N, V].
- flash attention (apex/contrib/fmha — fmhalib): residuals are
  (q, k, v, o, lse) — all O(s*d) or O(s); never O(s^2).
"""

import jax
import jax.numpy as jnp

S = jax.ShapeDtypeStruct


def _residual_leaves(shapes_tree):
    """ShapeDtypeStruct leaves of a residual pytree (drops static ints)."""
    return [l for l in jax.tree_util.tree_leaves(shapes_tree)
            if hasattr(l, "shape")]


def test_xentropy_residuals_are_bprop_in_fprop():
    """Beyond the input logits/labels themselves, the saved residual is
    one [N, 1] mlse vector — no [N, V] tensor of any dtype."""
    from apex_tpu.kernels import xentropy as xk

    n, v = 256, 1024
    res = jax.eval_shape(
        lambda lg, lb: xk._xent_fwd(lg, lb, 0.0, True)[1],
        S((n, v), jnp.bfloat16), S((n,), jnp.int32))
    leaves = _residual_leaves(res)
    # exactly ONE [N, V] leaf may appear: the pass-through bf16 logits.
    # A second one (e.g. a regressed fp32 softmax residual) is precisely
    # the contract violation this test exists to catch.
    nv_leaves = [l for l in leaves if l.size == n * v]
    assert len(nv_leaves) == 1 and nv_leaves[0].dtype == jnp.bfloat16, \
        [(l.shape, l.dtype) for l in nv_leaves]
    # total residual bytes = logits + labels + mlse, nothing else
    total = sum(l.size * l.dtype.itemsize for l in leaves)
    assert total <= n * v * 2 + n * 4 + n * 8, \
        [(l.shape, l.dtype) for l in leaves]


def test_flash_residuals_scale_linearly_with_seq():
    """No residual leaf has s^2 elements; total residual bytes beyond the
    (q, k, v) inputs is O(s*d) (the saved o + lse), at any s."""
    from apex_tpu.kernels import flash_attention as fk

    for s in (512, 1024):
        b, h, d = 1, 2, 128
        q = S((b, h, s, d), jnp.bfloat16)
        res = jax.eval_shape(
            lambda q, k, v: fk._flash_fwd(
                q, k, v, None, None, None, True, d ** -0.5, 128, 128,
                True, 0.0)[1],
            q, q, q)
        leaves = _residual_leaves(res)
        for l in leaves:
            # no leaf as large as ANY s^2-class buffer ([s,s] or bigger),
            # and every leaf is within the O(s*d) input/output class
            assert l.size < s * s, f"s^2 residual {l.shape} at s={s}"
            assert l.size <= b * h * s * d, (l.shape, l.dtype)
        # everything beyond the flattened inputs: o [bh, s, d] + lse — O(s*d)
        total = sum(l.size * l.dtype.itemsize for l in leaves)
        inputs = 3 * b * h * s * d * 2
        assert total - inputs <= b * h * s * (2 * d + 8), total


def test_fused_softmax_residuals_match_reference_not_more():
    """Honest structure of the N8 softmax kernels (BASELINE.md's negative
    rows): the custom_vjp saves EXACTLY the input-dtype probs — the
    reference's saved softmax_results
    (apex/csrc/megatron/scaled_*_softmax backward), half the bytes of an
    fp32 save — and nothing else. The peak-memory rows price negative
    because XLA's composition rematerializes instead; this test pins the
    residual to reference parity so a regression (e.g. an extra fp32
    copy) cannot hide behind the already-negative row."""
    from apex_tpu.kernels import causal_softmax as ck
    from apex_tpu.kernels import masked_softmax as mk

    n, sq, sk = 4, 256, 256
    res = jax.eval_shape(
        lambda x: ck._causal_fwd(x, 1.0, True)[1],
        S((n, sq, sk), jnp.bfloat16))
    leaves = _residual_leaves(res)
    assert [(l.shape, l.dtype) for l in leaves] == \
        [((n, sq, sk), jnp.bfloat16)], leaves

    res = jax.eval_shape(
        lambda x, m: mk._masked_fwd(x, m, 1.0, 1, True)[1],
        S((n, sq, sk), jnp.bfloat16), S((n, sq, sk), jnp.int8))
    leaves = _residual_leaves(res)
    assert [(l.shape, l.dtype) for l in leaves] == \
        [((n, sq, sk), jnp.bfloat16)], leaves


def test_flash_residual_structure_is_independent_of_masking_flags():
    """Causal and non-causal save the same O(s*d) residual class —
    the no-s^2 contract isn't an artifact of the causal skip."""
    from apex_tpu.kernels import flash_attention as fk

    b, h, s, d = 1, 1, 512, 128
    q = S((b, h, s, d), jnp.bfloat16)
    for causal in (False, True):
        res = jax.eval_shape(
            lambda q, k, v: fk._flash_fwd(
                q, k, v, None, None, None, causal, d ** -0.5, 128, 128,
                True, 0.0)[1],
            q, q, q)
        for l in _residual_leaves(res):
            assert l.size < s * s, (causal, l.shape)


def test_layer_norm_memory_efficient_residuals_swap_x_for_y():
    """The structural half of the round-5 LN contract: default saves the
    INPUT (x, gamma, mean, rstd); memory_efficient saves the OUTPUT
    (y, gamma, beta, rstd) and NOT x — the output aliases the value the
    downstream op keeps anyway, so the input can die (apex
    fused_layer_norm.py memory_efficient semantics)."""
    import importlib

    # the kernels package re-exports the layer_norm FUNCTION, which
    # shadows the submodule on attribute-style import
    lnk = importlib.import_module("apex_tpu.kernels.layer_norm")

    n, h = 64, 256
    args = (S((n, h), jnp.bfloat16), S((h,), jnp.float32),
            S((h,), jnp.float32))

    def residuals(me):
        return jax.eval_shape(
            lambda x, g, b: lnk._layer_norm_fwd(
                x, g, b, 1e-5, False, True, me)[1], *args)

    df, me = residuals(False), residuals(True)
    # default: two [n, 1] stat vectors (mean, rstd) + x + gamma
    assert sum(1 for l in _residual_leaves(df) if l.shape == (n, 1)) == 2
    # me: ONE stat vector (rstd only — mean is not needed), y + g + b;
    # identical [n, h] footprint otherwise (y swapped for x)
    me_leaves = _residual_leaves(me)
    assert sum(1 for l in me_leaves if l.shape == (n, 1)) == 1
    # each variant keeps exactly ONE [n, h] tensor — default the input,
    # me the output. The byte win is NOT in the leaf sum (y is x-sized;
    # me additionally carries beta): it is that y ALIASES the value the
    # downstream op saves anyway, so the input x can die — the compiled
    # half (tests/tpu/test_memory_contracts_on_silicon.py + bench_memory
    # layer_norm) prices that sharing at the stack level.
    for tree in (df, me):
        assert sum(1 for l in _residual_leaves(tree)
                   if l.shape == (n, h)) == 1
