"""Real-data paths of the LM and BERT recipes (VERDICT r3 missing #4 /
SURVEY P38: the reference's recipes are real-data-first).

Checked-in pre-tokenized fixtures under tests/data/ drive
``--data`` end to end; behavior must match the synthetic path modulo the
batch source (same metrics surface, same training dynamics).

Regeneration: tiny_lm_tokens.npy is a noisy order-1 recurrence
(seed 7: t[i] = (3*t[i-1]+7) % 128, 15% uniform resample, 8192 tokens);
tiny_bert_shard.npz draws 64 examples (seed 11, seq 32, 5 MLM slots with
20% padded ids, vocab<1000) with half-open attention masks and
second-half token_type_ids.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                os.pardir))

_DATA = os.path.join(os.path.dirname(__file__), os.pardir, "data")


def test_lm_trains_on_pretokenized_npy():
    from examples.lm import main_amp as lm

    data = os.path.join(_DATA, "tiny_lm_tokens.npy")
    common = ["--size", "tiny", "--vocab-size", "128", "--seq-len", "32",
              "-b", "16", "--iters", "8", "--deterministic",
              "--opt-level", "O0", "--lr", "3e-3"]
    m_real = lm.main(common + ["--data", data])
    hist = m_real["loss_history"]
    assert all(np.isfinite(hist)), hist
    # the stream is a learnable recurrence: loss must fall well below the
    # uniform floor's neighborhood within 8 iters
    assert hist[-1] < hist[0] - 0.1, hist

    # identical surface to the synthetic path: same metrics, same step
    m_syn = lm.main(common)
    assert set(m_real) == set(m_syn)
    assert len(m_syn["loss_history"]) == len(hist)


def test_lm_fused_head_trains_and_resumes_bitwise(tmp_path):
    """--fused-head (kernels/lm_head_loss.py wired into the recipe):
    same learnability bar as the default path, deterministic, and
    bitwise save/resume — the fused tail must not perturb the recipe's
    checkpoint/restart contract."""
    import jax

    from examples.lm import main_amp as lm

    data = os.path.join(_DATA, "tiny_lm_tokens.npy")
    ckpt = os.path.join(tmp_path, "lm_fused.npz")
    common = ["--size", "tiny", "--vocab-size", "128", "--seq-len", "32",
              "-b", "8", "--deterministic", "--opt-level", "O2",
              "--lr", "3e-3", "--data", data, "--fused-head"]
    m_full = lm.main(common + ["--iters", "8"])
    hist = m_full["loss_history"]
    assert all(np.isfinite(hist)), hist
    assert hist[-1] < hist[0] - 0.1, hist
    lm.main(common + ["--iters", "4", "--save", ckpt])
    m_res = lm.main(common + ["--iters", "8", "--resume", ckpt])
    np.testing.assert_array_equal(m_res["loss_history"],
                                  m_full["loss_history"][4:])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        m_res["final_state"].params, m_full["final_state"].params)


def test_lm_fused_head_parallel_needs_vocab_parallel():
    """Under the parallel tiers the flag rides the op's axis_name mode,
    which needs the head sharded over 'model' — plain dp/tp without
    --vocab-parallel is rejected with the pointer."""
    import pytest

    from examples.lm import main_amp as lm

    with pytest.raises(SystemExit, match="vocab-parallel"):
        lm.main(["--size", "tiny", "--vocab-size", "128", "--seq-len",
                 "32", "--iters", "1", "--fused-head",
                 "--data-parallel", "2"])


def test_lm_single_chip_save_resume_bitwise(tmp_path):
    """--save/--resume on the single-chip path too (review r4: the flags
    must not be parallel-only): interrupted-at-4 + resumed reproduces
    the uninterrupted 8-iter run bitwise on the real-data stream."""
    import jax

    from examples.lm import main_amp as lm

    data = os.path.join(_DATA, "tiny_lm_tokens.npy")
    ckpt = os.path.join(tmp_path, "lm.npz")
    common = ["--size", "tiny", "--vocab-size", "128", "--seq-len", "32",
              "-b", "8", "--deterministic", "--opt-level", "O2",
              "--lr", "3e-3", "--data", data]
    m_full = lm.main(common + ["--iters", "8"])
    lm.main(common + ["--iters", "4", "--save", ckpt])
    m_res = lm.main(common + ["--iters", "8", "--resume", ckpt])
    np.testing.assert_array_equal(m_res["loss_history"],
                                  m_full["loss_history"][4:])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        m_res["final_state"].params, m_full["final_state"].params)


def test_bert_trains_on_pretokenized_npz():
    from examples.bert_lamb import main_amp as bert

    data = os.path.join(_DATA, "tiny_bert_shard.npz")
    m = bert.main(["--bert-model", "tiny", "--max_seq_length", "32",
                   "--max_predictions_per_seq", "5",
                   "--train_batch_size", "8", "--max_steps", "8",
                   "--learning_rate", "1e-3", "--opt-level", "O0",
                   "--data", data])
    hist = m["loss_history"]
    assert all(np.isfinite(hist)), hist
    assert hist[-1] < hist[0], hist


def test_bert_data_validation_rejects_mismatches(tmp_path):
    from examples.bert_lamb.main_amp import _DATA_KEYS, load_pretokenized

    good = os.path.join(_DATA, "tiny_bert_shard.npz")
    data = load_pretokenized(good, seq_len=32, n_pred=5)
    assert set(data) == set(_DATA_KEYS)
    assert len({len(v) for v in data.values()}) == 1   # aligned N

    with pytest.raises(SystemExit, match="--max_seq_length"):
        load_pretokenized(good, seq_len=64, n_pred=5)
    with pytest.raises(SystemExit, match="--max_predictions_per_seq"):
        load_pretokenized(good, seq_len=32, n_pred=20)

    bad = os.path.join(tmp_path, "bad.npz")
    np.savez(bad, input_ids=data["input_ids"])
    with pytest.raises(SystemExit, match="missing fields"):
        load_pretokenized(bad, seq_len=32, n_pred=5)


def test_bert_two_phase_pretraining_handoff(tmp_path):
    """The reference's BERT workflow (DeepLearningExamples
    run_pretraining): phase 1 at short sequences, --save, then phase 2 at
    longer sequences via --init-checkpoint — model weights carry over
    (fp32 masters), optimizer and schedule restart, the shared position
    table (--max_position_embeddings) covers both phases. Plus --resume:
    an interrupted phase continues bitwise."""
    import jax

    from examples.bert_lamb import main_amp as bert

    ckpt = os.path.join(tmp_path, "phase1.npz")
    common = ["--bert-model", "tiny", "--max_predictions_per_seq", "5",
              "--train_batch_size", "4", "--learning_rate", "1e-3",
              "--max_position_embeddings", "64"]
    # interrupted phase 1: 6 of 10 schedule steps, then save
    p1 = bert.main(common + ["--max_seq_length", "32", "--max_steps", "6",
                             "--total_steps", "10", "--save", ckpt])
    assert np.isfinite(p1["loss_history"]).all()

    # phase 2: longer sequences, fresh optimizer, params carried over
    p2 = bert.main(common + ["--max_seq_length", "64", "--max_steps", "4",
                             "--init-checkpoint", ckpt])
    assert np.isfinite(p2["loss_history"]).all()

    # --resume continues phase 1 bitwise (same 10-step schedule)
    full = bert.main(common + ["--max_seq_length", "32",
                               "--max_steps", "10"])
    res = bert.main(common + ["--max_seq_length", "32",
                              "--max_steps", "10", "--resume", ckpt])
    np.testing.assert_array_equal(res["loss_history"],
                                  full["loss_history"][6:])

    # --resume and --init-checkpoint are exclusive; oversized sequences
    # are rejected against the position table
    with pytest.raises(SystemExit, match="exclusive"):
        bert.main(common + ["--max_seq_length", "32", "--resume", ckpt,
                            "--init-checkpoint", ckpt])
    with pytest.raises(SystemExit, match="position table"):
        bert.main(["--bert-model", "tiny", "--max_seq_length", "128",
                   "--max_position_embeddings", "64"])


def test_window_sampler_reaches_final_token():
    """Regression (review r4): randint's exclusive bound is
    len-seq_len, so the LAST window start — and with it the stream's
    final token — is reachable. At the minimum accepted stream length
    (seq_len+2) there are exactly two starts; both must occur."""
    import jax

    from examples.lm.main_amp import data_batch

    stream = np.arange(34, dtype=np.int32)          # seq_len 32 minimum
    seen_last = False
    starts = set()
    for k in range(20):
        batch = np.asarray(data_batch(stream, jax.random.PRNGKey(k),
                                      batch_size=4, seq_len=32))
        assert batch.shape == (4, 33)
        starts.update(batch[:, 0].tolist())
        seen_last |= bool((batch[:, -1] == 33).any())
    assert starts == {0, 1}, starts
    assert seen_last, "final token never sampled"


def test_loaders_reject_silent_clamp_classes(tmp_path):
    """Every id class jit's gathers would clamp silently is rejected at
    load: .npz-for-.npy confusion, out-of-range segment ids, non-binary
    NSP labels (review r4-high)."""
    from examples.bert_lamb.main_amp import load_pretokenized
    from examples.lm.main_amp import load_token_stream

    with pytest.raises(SystemExit, match="archive"):
        load_token_stream(os.path.join(_DATA, "tiny_bert_shard.npz"),
                          128, 32)

    good = dict(np.load(os.path.join(_DATA, "tiny_bert_shard.npz")))

    def _write(**overrides):
        path = os.path.join(tmp_path, "bad.npz")
        np.savez(path, **{**good, **overrides})
        return path

    bad_tt = good["token_type_ids"].copy()
    bad_tt[0, 0] = 3
    with pytest.raises(SystemExit, match="segment"):
        load_pretokenized(_write(token_type_ids=bad_tt), 32, 5)

    bad_nsp = good["next_sentence_labels"].copy()
    bad_nsp[0] = 2
    with pytest.raises(SystemExit, match="binary"):
        load_pretokenized(_write(next_sentence_labels=bad_nsp), 32, 5)
