"""Long-context recipe integration: ring-attention LM trains end to end,
and zigzag/contiguous layouts compute the same math (they differ only in
which rank owns which chunks)."""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from examples.long_context import main_amp  # noqa: E402


@pytest.mark.slow
def test_ring_lm_trains_and_layouts_agree():
    common = ["--ring", "4", "--seq-len", "256", "--hidden", "64",
              "--layers", "1", "--heads", "2", "--vocab", "128",
              "--iters", "4", "--lr", "3e-3"]
    # O2 (bf16) trains: memorizes the fixed batch
    loss_o2 = main_amp.main(common + ["--layout", "zigzag"])
    assert loss_o2 < 4.5, loss_o2
    # layout equivalence at fp32: zigzag and contiguous are the same
    # computation with different chunk ownership — only reassociation
    # noise may differ
    loss_zig = main_amp.main(common + ["--layout", "zigzag",
                                       "--opt-level", "O0"])
    loss_con = main_amp.main(common + ["--layout", "contiguous",
                                       "--opt-level", "O0"])
    assert loss_zig < 4.5 and loss_con < 4.5, (loss_zig, loss_con)
    assert abs(loss_zig - loss_con) < 1e-4, (loss_zig, loss_con)


@pytest.mark.slow
def test_data_parallel_composes_with_ring():
    """--data-parallel shards the batch over a 'data' axis OUTSIDE the
    context ring (mesh [data, context], grads averaged over both axes);
    the fixed global batch makes dp2 reproduce the dp1 trajectory
    exactly — DDP as a pure layout change."""
    common = ["--ring", "2", "--seq-len", "128", "--hidden", "64",
              "--layers", "1", "--heads", "2", "--vocab", "128",
              "--iters", "3", "-b", "4", "--lr", "3e-3",
              "--opt-level", "O0"]
    loss_dp1 = main_amp.main(common)
    loss_dp2 = main_amp.main(common + ["--data-parallel", "2"])
    assert abs(loss_dp1 - loss_dp2) < 1e-4, (loss_dp1, loss_dp2)


@pytest.mark.slow
def test_ulysses_mode_matches_ring():
    """--attn ulysses computes the same attention a different way (a2a head
    scatter vs KV rotation): identical data + init → same fp32 loss."""
    common = ["--ring", "4", "--seq-len", "256", "--hidden", "64",
              "--layers", "1", "--heads", "4", "--vocab", "128",
              "--iters", "3", "--lr", "3e-3", "--opt-level", "O0",
              "--layout", "contiguous"]
    loss_ring = main_amp.main(common + ["--attn", "ring"])
    loss_uly = main_amp.main(common + ["--attn", "ulysses"])
    assert abs(loss_ring - loss_uly) < 1e-3, (loss_ring, loss_uly)


@pytest.mark.slow
def test_ring_lm_trains_on_real_data():
    """--data: the fixed batch becomes real windows from the checked-in
    token stream (LM loader validation included), and the learnable
    recurrence drives the loss well below the uniform floor — real
    long-context data end to end through the ring (SURVEY P38)."""
    import os

    data = os.path.join(os.path.dirname(__file__), os.pardir, "data",
                        "tiny_lm_tokens.npy")
    loss = main_amp.main(["--ring", "4", "--seq-len", "256", "--hidden",
                          "64", "--layers", "1", "--heads", "2",
                          "--vocab", "128", "--iters", "6",
                          "--lr", "3e-3", "--data", data])
    assert loss < 3.5, loss
