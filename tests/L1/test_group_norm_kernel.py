"""Pallas GroupNorm kernel (N23) parity vs the fp32 jnp oracle
(interpret mode; the on-silicon run lives in tests/tpu)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels.group_norm import group_norm_nhwc, group_norm_reference


def _data(n=2, h=8, w=8, c=256, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(ks[0], (n, h, w, c), dtype) * 2.0 + 0.5
    g = jax.random.normal(ks[1], (c,), jnp.float32) + 1.0
    b = jax.random.normal(ks[2], (c,), jnp.float32)
    return x, g, b


@pytest.mark.parametrize("act", [None, "silu"])
@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-5),
                                       (jnp.bfloat16, 2e-2)])
def test_forward_parity(act, dtype, tol):
    x, g, b = _data(dtype=dtype)
    out = group_norm_nhwc(x, 16, g, b, act=act, interpret=True)
    ref = group_norm_reference(x, 16, g, b, act=act)
    assert out.dtype == dtype
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=tol, atol=tol)


@pytest.mark.parametrize("act", [None, "silu"])
def test_backward_parity(act):
    x, g, b = _data()

    def lk(x, g, b):
        return jnp.sum(jnp.sin(
            group_norm_nhwc(x, 16, g, b, act=act, interpret=True) * 2.0))

    def lr(x, g, b):
        return jnp.sum(jnp.sin(
            group_norm_reference(x, 16, g, b, act=act) * 2.0))

    gk = jax.grad(lk, argnums=(0, 1, 2))(x, g, b)
    gr = jax.grad(lr, argnums=(0, 1, 2))(x, g, b)
    for a, r in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-4, atol=2e-5)


def test_unpadded_spatial_and_3d_input():
    # S=17 rows: spatial padding path; [N, S, C] form accepted
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 17, 128))
    g = jnp.ones((128,))
    b = jnp.zeros((128,))
    out = group_norm_nhwc(x, 8, g, b, interpret=True)
    ref = group_norm_reference(x, 8, g, b)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_fallbacks_and_validation():
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 4, 4, 320))
    g, b = jnp.ones((320,)), jnp.zeros((320,))
    # 320 % 128 != 0 → composed fallback, still correct
    out = group_norm_nhwc(x, 32, g, b, act="silu")
    ref = group_norm_reference(x, 32, g, b, act="silu")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)
    # non-affine → fallback
    out2 = group_norm_nhwc(x, 32, None, None)
    ref2 = group_norm_reference(x, 32, None, None)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               rtol=1e-6)
    with pytest.raises(ValueError, match="not divisible"):
        group_norm_nhwc(x, 7, g, b)
    with pytest.raises(ValueError, match="unsupported act"):
        group_norm_nhwc(x, 32, g, b, act="gelu")


def test_contrib_module_routes_to_kernel():
    from apex_tpu.contrib.group_norm import GroupNorm

    x = jax.random.normal(jax.random.PRNGKey(3), (2, 4, 4, 256))
    mod = GroupNorm(num_groups=8, num_channels=256, act="silu")
    v = mod.init(jax.random.PRNGKey(4), x)
    out = mod.apply(v, x)
    ref = group_norm_reference(x, 8, v["params"]["scale"],
                               v["params"]["bias"], act="silu")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_large_mean_numerical_stability():
    """E[x^2]-E[x]^2 formulations cancel catastrophically at mean>>std;
    the kernel's Welford/Chan block combine (welford_parallel semantics)
    must stay finite and match the centered oracle."""
    x = 1000.0 + jax.random.normal(jax.random.PRNGKey(5),
                                   (2, 16, 16, 256), jnp.float32) * 0.01
    g = jnp.ones((256,))
    b = jnp.zeros((256,))
    out = group_norm_nhwc(x, 16, g, b, interpret=True)
    ref = group_norm_reference(x, 16, g, b)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)
