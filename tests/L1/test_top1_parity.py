"""Top-1 parity across opt levels (VERDICT round-1 item 5; sharpened in
round 3 per round-2 weak #5).

The driver's north star is img/s "with top-1 parity"; the reference proves
parity by running the imagenet recipe at each opt level and comparing
accuracy (tests/L1 cross product + the 76.x% convergence bar). Hermetic
equivalent: a LEARNABLE-but-not-trivial synthetic task — class-dependent
2-D sinusoid patterns (10 classes, conv structure required, noise tuned so
accuracy sits below the ceiling) — trained at each opt level and evaluated
on the same fixed held-out set through the recipe's own validate().

Controls: (a) a no-learning run (lr=0) must score ~chance — the harness
resolves failure, the bar is not vacuous; (b) O3 (pure half, no master
weights) is run and RECORDED — apex documents O3 as "may diverge /
accuracy loss is expected"; we assert only that it runs finite, not that
it matches O0 (asserting parity there would contradict the reference's own
semantics).
"""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from examples.imagenet.main_amp import (make_eval_step, make_loss_fn,
                                        validate)  # noqa: E402
from apex_tpu import amp  # noqa: E402
from apex_tpu.models import create_model  # noqa: E402

CLASSES = 10
SIZE = 16
STEPS = 120
BATCH = 32


def _learnable_batch(key, n):
    """Class-dependent 2-D sinusoid gratings + noise: ten (fx, fy)
    frequency pairs, so the net must use spatial structure (not channel
    means); noise 1.1 keeps a 120-step run around the mid-90s top-1, off
    the 100% ceiling so precision differences can show."""
    kl, kn = jax.random.split(key)
    labels = jax.random.randint(kl, (n,), 0, CLASSES)
    xx = jnp.arange(SIZE, dtype=jnp.float32)[:, None]
    yy = jnp.arange(SIZE, dtype=jnp.float32)[None, :]
    fx = (labels % 5 + 1).astype(jnp.float32)[:, None, None]
    fy = (labels // 5 + 1).astype(jnp.float32)[:, None, None]
    base = jnp.sin(2 * jnp.pi * fx * xx[None] / SIZE) \
        * jnp.cos(2 * jnp.pi * fy * yy[None] / SIZE)
    images = jnp.stack([base, -base, 0.5 * base], -1)
    images = images + jax.random.normal(kn, images.shape) * 1.1
    return images, labels


def _train_and_eval(opt_level, lr=0.05, **policy_kw):
    policy = amp.resolve_policy(opt_level=opt_level, verbose=False,
                                **policy_kw)
    model_dtype = None if policy.patch_torch_functions \
        else policy.compute_dtype
    model = create_model("resnet18", num_classes=CLASSES, dtype=model_dtype)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, SIZE, SIZE, 3)), train=True)
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}

    init_fn, step_fn = amp.make_train_step(
        make_loss_fn(model), optax.sgd(lr, momentum=0.9), policy,
        has_aux=True, with_model_state=True)
    state = init_fn(params, model_state)
    jit_step = jax.jit(step_fn)
    for it in range(STEPS):
        batch = _learnable_batch(jax.random.PRNGKey(it), BATCH)
        state, metrics = jit_step(state, batch)

    jit_eval = jax.jit(make_eval_step(model))
    val = [_learnable_batch(jax.random.PRNGKey(50_000 + i), BATCH)
           for i in range(4)]
    prec1, prec5 = validate(jit_eval, state, iter(val), quiet=True)
    return prec1, prec5, float(metrics["loss"])


@pytest.mark.slow
def test_top1_parity_o2_vs_o0():
    p1_o0, _, loss_o0 = _train_and_eval("O0")
    p1_o2, _, loss_o2 = _train_and_eval("O2")
    # the task is learnable: both runs must be far above chance (10%)
    assert p1_o0 > 80.0, f"O0 failed to learn: top-1 {p1_o0}"
    assert p1_o2 > 80.0, f"O2 failed to learn: top-1 {p1_o2}"
    # and agree within run noise — the driver's "top-1 parity" criterion
    # (tightened round 3: 10 classes, off-ceiling accuracy, ±4 points)
    assert abs(p1_o0 - p1_o2) <= 4.0, (p1_o0, p1_o2)


@pytest.mark.slow
def test_top1_parity_o1_engine():
    """O1 (per-op table engine) learns the same task to the same accuracy."""
    p1_o0, _, _ = _train_and_eval("O0")
    p1_o1, _, _ = _train_and_eval("O1")
    assert p1_o1 > 80.0, f"O1 failed to learn: top-1 {p1_o1}"
    assert abs(p1_o0 - p1_o1) <= 4.0, (p1_o0, p1_o1)


@pytest.mark.slow
def test_harness_detects_no_learning():
    """Negative control for the HARNESS: an lr=0 run must score ~chance.
    If this fails, the validate() bar is vacuous (e.g. a saturating task
    or a leaking eval) and every parity assertion above is meaningless."""
    p1, _, _ = _train_and_eval("O0", lr=0.0)
    assert p1 < 25.0, f"no-learning run scored {p1}: harness is vacuous"


@pytest.mark.slow
def test_o3_runs_and_is_recorded():
    """O3 negative control (VERDICT round-2 weak #5): pure half weights,
    no master copy — apex documents this mode as speed-over-accuracy and
    expects possible divergence, so parity is NOT asserted; the run must
    execute finite and its top-1 is printed for the record. Observing a
    gap here validates that the harness can resolve precision configs."""
    p1_o0, _, _ = _train_and_eval("O0")
    p1_o3, _, loss_o3 = _train_and_eval("O3")
    assert np.isfinite(loss_o3)
    print(f"O3 top-1 {p1_o3:.2f} vs O0 {p1_o0:.2f} "
          f"(divergence is expected apex behavior)")
    # bf16 O3 on this small task usually still learns; require only
    # above-chance, never parity
    assert p1_o3 > 15.0, f"O3 collapsed entirely: {p1_o3}"
