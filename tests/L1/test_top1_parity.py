"""Top-1 parity across opt levels (VERDICT round-1 item 5).

The driver's north star is img/s "with top-1 parity"; the reference proves
parity by running the imagenet recipe at each opt level and comparing
accuracy (tests/L1 cross product + the 76.x% convergence bar). Hermetic
equivalent: a LEARNABLE synthetic task (class-dependent channel shift +
noise) that a few hundred ResNet steps actually learn, trained at O0 and at
O2, then evaluated on the same fixed held-out set through the recipe's own
validate() — top-1 must agree within noise.
"""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from examples.imagenet.main_amp import (make_eval_step, make_loss_fn,
                                        validate)  # noqa: E402
from apex_tpu import amp  # noqa: E402
from apex_tpu.models import create_model  # noqa: E402

CLASSES = 4
SIZE = 16
STEPS = 60
BATCH = 32


def _learnable_batch(key, n):
    """Images whose channel means encode the class + noise: linearly
    separable enough that a short ResNet run reaches high top-1."""
    kl, kn = jax.random.split(key)
    labels = jax.random.randint(kl, (n,), 0, CLASSES)
    base = (labels[:, None, None, None].astype(jnp.float32)
            / CLASSES * 2.0 - 1.0)
    shift = jnp.stack([base[..., 0] * c for c in (1.0, -1.0, 0.5)], -1)
    images = shift + jax.random.normal(kn, (n, SIZE, SIZE, 3)) * 0.3
    return images, labels


def _train_and_eval(opt_level):
    policy = amp.resolve_policy(opt_level=opt_level, verbose=False)
    model_dtype = None if policy.patch_torch_functions \
        else policy.compute_dtype
    model = create_model("resnet18", num_classes=CLASSES, dtype=model_dtype)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((2, SIZE, SIZE, 3)), train=True)
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}

    init_fn, step_fn = amp.make_train_step(
        make_loss_fn(model), optax.sgd(0.05, momentum=0.9), policy,
        has_aux=True, with_model_state=True)
    state = init_fn(params, model_state)
    jit_step = jax.jit(step_fn)
    for it in range(STEPS):
        batch = _learnable_batch(jax.random.PRNGKey(it), BATCH)
        state, metrics = jit_step(state, batch)

    jit_eval = jax.jit(make_eval_step(model))
    val = [_learnable_batch(jax.random.PRNGKey(50_000 + i), BATCH)
           for i in range(4)]
    prec1, prec5 = validate(jit_eval, state, iter(val), quiet=True)
    return prec1, prec5, float(metrics["loss"])


@pytest.mark.slow
def test_top1_parity_o2_vs_o0():
    p1_o0, _, loss_o0 = _train_and_eval("O0")
    p1_o2, _, loss_o2 = _train_and_eval("O2")
    # the task is learnable: both runs must be far above chance (25%)
    assert p1_o0 > 70.0, f"O0 failed to learn: top-1 {p1_o0}"
    assert p1_o2 > 70.0, f"O2 failed to learn: top-1 {p1_o2}"
    # and agree within run noise — the driver's "top-1 parity" criterion
    assert abs(p1_o0 - p1_o2) <= 6.0, (p1_o0, p1_o2)


@pytest.mark.slow
def test_top1_parity_o1_engine():
    """O1 (per-op table engine) learns the same task to the same accuracy."""
    p1_o0, _, _ = _train_and_eval("O0")
    p1_o1, _, _ = _train_and_eval("O1")
    assert p1_o1 > 70.0, f"O1 failed to learn: top-1 {p1_o1}"
    assert abs(p1_o0 - p1_o1) <= 6.0, (p1_o0, p1_o1)
