"""Pallas masked-softmax kernel (N8's arbitrary-mask variant) parity tests
vs the fp32 jnp reference — the padded-mask BERT path (VERDICT round-2
missing #3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.kernels.masked_softmax import (masked_softmax,
                                             masked_softmax_reference)


def _mask(key, shape, p=0.3):
    m = jax.random.bernoulli(jax.random.PRNGKey(key), p, shape)
    # never fully mask a row (the reference's padding masks always keep
    # at least the unpadded prefix)
    return m.at[..., 0].set(False)


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-6),
                                       (jnp.bfloat16, 1e-2)])
@pytest.mark.parametrize("shape", [(2, 3, 128, 128), (1, 2, 256, 384),
                                   (4, 8, 128)])
def test_forward_parity(dtype, tol, shape):
    x = jax.random.normal(jax.random.PRNGKey(0), shape, dtype) * 3.0
    m = _mask(1, shape)
    out = masked_softmax(x, m, scale=0.5)
    ref = masked_softmax_reference(x, m, scale=0.5)
    assert out.dtype == dtype
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=tol, atol=tol)
    s = np.asarray(out, np.float32)
    np.testing.assert_allclose(s.sum(-1), 1.0, rtol=2 * tol, atol=2 * tol)
    # masked entries have (underflowed-to-)zero probability
    assert (np.abs(s[np.asarray(m & jnp.ones(shape, bool))]) < tol).all()


def test_head_broadcast_mask():
    """The reference's [b, 1, sq, sk] mask against [b, h, sq, sk] logits:
    the kernel folds the h-broadcast into the block index map."""
    b, h, sq, sk = 2, 4, 128, 256
    x = jax.random.normal(jax.random.PRNGKey(2), (b, h, sq, sk))
    m = _mask(3, (b, 1, sq, sk))
    out = masked_softmax(x, m)
    ref = masked_softmax_reference(x, m)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_backward_parity():
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 128, 128))
    m = _mask(5, (2, 128, 128))

    def f_kernel(x):
        return jnp.sum(jnp.sin(masked_softmax(x, m, scale=0.7) * 3.0))

    def f_ref(x):
        return jnp.sum(jnp.sin(masked_softmax_reference(x, m, 0.7) * 3.0))

    gk = jax.grad(f_kernel)(x)
    gr = jax.grad(f_ref)(x)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                               rtol=1e-5, atol=1e-6)


def test_unaligned_and_odd_broadcast_fall_back():
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 7, 33))
    m = _mask(7, (2, 7, 33))
    np.testing.assert_allclose(np.asarray(masked_softmax(x, m)),
                               np.asarray(masked_softmax_reference(x, m)),
                               rtol=1e-6)
    # (1, h) leading mask is not prefix-contiguous → reference path
    x = jax.random.normal(jax.random.PRNGKey(8), (2, 4, 128, 128))
    m = _mask(9, (1, 4, 128, 128))
    np.testing.assert_allclose(np.asarray(masked_softmax(x, m)),
                               np.asarray(masked_softmax_reference(x, m)),
                               rtol=1e-6, atol=1e-6)


def test_fused_scale_mask_softmax_routes_padding():
    """FusedScaleMaskSoftmax(padding) → the Pallas masked kernel path,
    numerically matching the composed reference."""
    from apex_tpu.transformer.enums import AttnMaskType
    from apex_tpu.transformer.functional.fused_softmax import (
        FusedScaleMaskSoftmax, scaled_masked_softmax)

    b, h, sq, sk = 2, 2, 128, 128
    x = jax.random.normal(jax.random.PRNGKey(10), (b, h, sq, sk),
                          jnp.bfloat16)
    m = _mask(11, (b, 1, sq, sk))
    fn = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.padding,
                               scale=0.25)
    out = fn(x, m)
    ref = masked_softmax_reference(x, m, scale=0.25)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=1e-2, atol=1e-2)
    # kwarg path parity too
    out2 = scaled_masked_softmax(x, m, scale=0.25)
    np.testing.assert_allclose(np.asarray(out2, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=1e-2, atol=1e-2)
