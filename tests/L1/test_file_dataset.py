"""File-backed imagenet loader (VERDICT round-1 item 5: a real-data path,
not synthetic-only). Round-trips an npz dataset through the recipe's
loader, trains on it via main(), and checks validate() runs on the val
split."""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from examples.imagenet import main_amp  # noqa: E402


def _write_dataset(tmp_path, n_train=48, n_val=16, size=16, classes=4):
    rng = np.random.RandomState(0)

    def split(n):
        labels = rng.randint(0, classes, size=n).astype(np.int32)
        base = labels[:, None, None, None].astype(np.float32)
        images = (base * 40 + rng.randn(n, size, size, 3) * 10 + 100)
        return images.clip(0, 255).astype(np.uint8), labels

    ti, tl = split(n_train)
    vi, vl = split(n_val)
    np.savez(tmp_path / "train.npz", images=ti, labels=tl)
    np.savez(tmp_path / "val.npz", images=vi, labels=vl)
    return tmp_path


def test_load_file_dataset_dir_and_npz(tmp_path):
    d = _write_dataset(tmp_path)
    ds = main_amp.load_file_dataset(str(d))
    assert set(ds) == {"train", "val"}
    images, labels = ds["train"]
    assert images.dtype == np.float32        # uint8 → normalized float
    assert abs(images.mean()) < 3.0          # roughly centered
    assert labels.dtype == np.int32

    # single-npz form
    f = tmp_path / "all.npz"
    np.savez(f, train_images=images, train_labels=labels)
    ds2 = main_amp.load_file_dataset(str(f))
    assert "train" in ds2 and "val" not in ds2

    with pytest.raises(SystemExit):
        empty = tmp_path / "empty.npz"
        np.savez(empty, other=np.zeros(3))
        main_amp.load_file_dataset(str(empty))


def test_file_batches_shuffle_and_drop():
    images = np.arange(10)[:, None].astype(np.float32)
    labels = np.arange(10).astype(np.int32)
    batches = list(main_amp.file_batches(images, labels, 4, seed=0))
    assert len(batches) == 2                      # drop_last
    seen = np.concatenate([b[1] for b in batches])
    assert len(set(seen.tolist())) == 8           # no dupes
    full = list(main_amp.file_batches(images, labels, 4, drop_last=False))
    assert sum(b[1].shape[0] for b in full) == 10


@pytest.mark.slow
def test_main_trains_and_validates_on_file_data(tmp_path, capsys):
    d = _write_dataset(tmp_path)
    main_amp.main([str(d), "--arch", "resnet18", "-b", "16",
                   "--image-size", "16", "--num-classes", "4",
                   "--opt-level", "O2", "--epochs", "2", "--lr", "0.05"])
    out = capsys.readouterr().out
    assert "file dataset: 48 train images" in out
    assert "Prec@1" in out and "best Prec@1" in out
