"""Contrib tail tests: conv_bias_relu, cudnn_gbn, nccl_allocator,
gpu_direct_storage, openfold_triton (mirrors apex/contrib/test/)."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest


# --------------------------------------------------------- conv_bias_relu
def _ref_conv(x, w, stride, pad):
    from jax import lax
    return lax.conv_general_dilated(
        x, w, (stride, stride), ((pad, pad), (pad, pad)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def test_conv_bias_relu_matches_composed():
    from apex_tpu.contrib.conv_bias_relu import (ConvBiasReLU, conv_bias,
                                                 conv_bias_relu)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 8, 4), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 4, 6) * 0.1, jnp.float32)
    b = jnp.asarray(rng.randn(6) * 0.1, jnp.float32)

    ref = jnp.maximum(_ref_conv(x, w, 1, 1) + b, 0)
    np.testing.assert_allclose(np.asarray(conv_bias_relu(x, w, b, 1, 1)),
                               np.asarray(ref), rtol=1e-5, atol=1e-5)
    # Function-object .apply parity (reference autograd-Function surface)
    np.testing.assert_allclose(np.asarray(ConvBiasReLU.apply(x, w, b, 1, 1)),
                               np.asarray(ref), rtol=1e-5, atol=1e-5)
    # no-relu variant keeps negatives
    y = conv_bias(x, w, b, 1, 1)
    assert (np.asarray(y) < 0).any()


def test_conv_bias_mask_relu_and_frozen_scale_grads():
    from apex_tpu.contrib.conv_bias_relu import (conv_bias_mask_relu,
                                                 conv_frozen_scale_bias_relu)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(1, 6, 6, 3), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 3, 5) * 0.2, jnp.float32)
    b = jnp.zeros((5,), jnp.float32)
    mask = jnp.asarray(rng.rand(1, 6, 6, 5) > 0.5, jnp.float32)
    y = conv_bias_mask_relu(x, w, b, mask, 1, 1)
    ref = jnp.maximum((_ref_conv(x, w, 1, 1) + b) * mask, 0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    # frozen scale/bias: no gradient to scale (reference marks them frozen)
    scale = jnp.asarray(rng.rand(5) + 0.5, jnp.float32)
    fb = jnp.asarray(rng.randn(5) * 0.1, jnp.float32)
    gscale = jax.grad(
        lambda s: conv_frozen_scale_bias_relu(x, w, s, fb, 1, 1).sum())(scale)
    np.testing.assert_allclose(np.asarray(gscale), 0.0)
    gw = jax.grad(
        lambda ww: conv_frozen_scale_bias_relu(x, ww, scale, fb, 1, 1).sum())(w)
    assert np.isfinite(np.asarray(gw)).all() and np.abs(np.asarray(gw)).sum() > 0


# -------------------------------------------------------------- cudnn_gbn
def test_cudnn_gbn_matches_groupbn():
    from apex_tpu.contrib.cudnn_gbn import GroupBatchNorm2d
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 5, 5, 8), jnp.float32)
    m = GroupBatchNorm2d(num_features=8)
    variables = m.init(jax.random.PRNGKey(0), x, use_running_average=False)
    y, _ = m.apply(variables, x, use_running_average=False,
                   mutable=["batch_stats"])
    # per-channel normalization over N,H,W
    yn = np.asarray(y).reshape(-1, 8)
    np.testing.assert_allclose(yn.mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(yn.std(0), 1.0, atol=1e-2)


# --------------------------------------------------------- nccl_allocator
def test_nccl_allocator_noop_api():
    from apex_tpu.contrib import nccl_allocator
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        nccl_allocator.init()
        assert nccl_allocator.is_initialized()
        with nccl_allocator.nccl_mem():
            x = jnp.ones((4,))
        assert float(x.sum()) == 4.0


# ----------------------------------------------------- gpu_direct_storage
def test_gds_save_load_roundtrip(tmp_path):
    from apex_tpu.contrib.gpu_direct_storage import load_data, save_data
    x = jnp.asarray(np.random.RandomState(3).randn(16, 8), jnp.float32)
    path = str(tmp_path / "t.npz")
    save_data(path, x)
    y = load_data(path, jnp.zeros((16, 8), jnp.float32))
    np.testing.assert_allclose(np.asarray(y), np.asarray(x))
    with pytest.raises(ValueError):
        load_data(path, jnp.zeros((8, 8), jnp.float32))     # shape mismatch
    with pytest.raises(ValueError):
        load_data(path, jnp.zeros((16, 8), jnp.int8))       # dtype mismatch


def test_gds_bfloat16_roundtrip(tmp_path):
    """bfloat16 is the default AMP dtype on TPU — must round-trip exactly
    (plain npy serializes ml_dtypes as void and cannot cast them back)."""
    from apex_tpu.contrib.gpu_direct_storage import load_data, save_data
    x = jnp.asarray(np.random.RandomState(7).randn(8, 4), jnp.bfloat16)
    path = str(tmp_path / "bf16.npz")
    save_data(path, x)
    y = load_data(path, jnp.zeros((8, 4), jnp.bfloat16))
    assert y.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(y, np.float32), np.asarray(x, np.float32))


# ------------------------------------------------------- openfold_triton
def test_openfold_layer_norm_alias():
    from apex_tpu.contrib.openfold_triton import LayerNormSmallShapeOptImpl
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(6, 128), jnp.float32)
    w = jnp.ones((128,), jnp.float32)
    b = jnp.zeros((128,), jnp.float32)
    y = LayerNormSmallShapeOptImpl(x, w, b)
    ref = (x - x.mean(-1, keepdims=True)) / jnp.sqrt(
        x.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_openfold_evoformer_attention():
    from apex_tpu.contrib.openfold_triton import evoformer_attention
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(2, 4, 16, 32) * 0.3, jnp.float32)
    k = jnp.asarray(rng.randn(2, 4, 16, 32) * 0.3, jnp.float32)
    v = jnp.asarray(rng.randn(2, 4, 16, 32) * 0.3, jnp.float32)
    bias = jnp.asarray(rng.randn(2, 4, 16, 16) * 0.1, jnp.float32)
    gate = jnp.asarray(rng.randn(2, 4, 16, 32), jnp.float32)

    out = evoformer_attention(q, k, v, bias=bias, gate=gate)

    scale = 32 ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale + bias
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(logits, -1), v)
    ref = ref * jax.nn.sigmoid(gate)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    # bias-free, gate-free path == vanilla attention
    out2 = evoformer_attention(q, k, v)
    ref2 = jnp.einsum(
        "bhqk,bhkd->bhqd",
        jax.nn.softmax(jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale, -1), v)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               rtol=1e-4, atol=1e-4)


def test_openfold_evoformer_5d():
    """OpenFold's real evoformer tensors are 5D ([batch, n_seq, heads,
    n_res, c]) with a pair bias broadcast over n_seq — leading dims must
    collapse into the kernel batch and match the explicit composition."""
    from apex_tpu.contrib.openfold_triton import evoformer_attention
    rng = np.random.RandomState(6)
    B, N, H, R, C = 2, 3, 2, 8, 16
    q = jnp.asarray(rng.randn(B, N, H, R, C) * 0.3, jnp.float32)
    k = jnp.asarray(rng.randn(B, N, H, R, C) * 0.3, jnp.float32)
    v = jnp.asarray(rng.randn(B, N, H, R, C) * 0.3, jnp.float32)
    bias = jnp.asarray(rng.randn(B, 1, H, R, R) * 0.1, jnp.float32)
    gate = jnp.asarray(rng.randn(B, N, H, R, C), jnp.float32)

    out = evoformer_attention(q, k, v, bias=bias, gate=gate)
    assert out.shape == (B, N, H, R, C)

    scale = C ** -0.5
    logits = jnp.einsum("bnhqd,bnhkd->bnhqk", q, k) * scale + bias
    ref = jnp.einsum("bnhqk,bnhkd->bnhqd", jax.nn.softmax(logits, -1), v)
    ref = ref * jax.nn.sigmoid(gate)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
