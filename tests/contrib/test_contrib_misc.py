"""Contrib tier tests: clip_grad, focal_loss, index_mul_2d, group_norm,
sparsity, transducer, fmha, multihead_attn (mirrors apex/contrib/test/)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import optax


# -------------------------------------------------------------- clip_grad
def test_clip_grad_norm_matches_optax():
    from apex_tpu.contrib.clip_grad import clip_grad_norm
    grads = {"a": jnp.full((64,), 3.0), "b": {"c": jnp.full((32, 4), -2.0)}}
    clipped, norm = clip_grad_norm(grads, max_norm=1.0)
    flat = np.concatenate([np.full(64, 3.0), np.full(128, -2.0)])
    ref_norm = np.linalg.norm(flat)
    np.testing.assert_allclose(float(norm), ref_norm, rtol=1e-5)
    cflat = np.concatenate([np.asarray(clipped["a"]),
                            np.asarray(clipped["b"]["c"]).ravel()])
    np.testing.assert_allclose(np.linalg.norm(cflat), 1.0, rtol=1e-4)
    # no-op when under the bound
    small = {"a": jnp.full((8,), 1e-3)}
    out, _ = clip_grad_norm(small, max_norm=1.0)
    np.testing.assert_allclose(np.asarray(out["a"]), 1e-3, rtol=1e-5)


# ------------------------------------------------------------- focal_loss
def test_focal_loss_matches_autodiff():
    from apex_tpu.contrib.focal_loss import focal_loss

    def manual(lg, t, alpha=0.25, gamma=2.0):
        p = jax.nn.sigmoid(lg)
        ce = -(t * jnp.log(p) + (1 - t) * jnp.log1p(-p))
        pt = p * t + (1 - p) * (1 - t)
        at = alpha * t + (1 - alpha) * (1 - t)
        return at * (1 - pt) ** gamma * ce

    lg = jax.random.normal(jax.random.PRNGKey(0), (16, 8)) * 2
    t = jax.random.bernoulli(jax.random.PRNGKey(1),
                             0.3, (16, 8)).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(focal_loss(lg, t)),
                               np.asarray(manual(lg, t)), rtol=1e-5,
                               atol=1e-6)
    g = jax.grad(lambda x: jnp.sum(focal_loss(x, t)))(lg)
    gr = jax.grad(lambda x: jnp.sum(manual(x, t)))(lg)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=1e-4,
                               atol=1e-5)


# ----------------------------------------------------------- index_mul_2d
def test_index_mul_2d():
    from apex_tpu.contrib.index_mul_2d import index_mul_2d
    in1 = jax.random.normal(jax.random.PRNGKey(0), (10, 4))
    in2 = jax.random.normal(jax.random.PRNGKey(1), (6, 4))
    idx = jnp.array([0, 3, 3, 9, 1, 5])
    out = index_mul_2d(in1, in2, idx)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(in1)[np.asarray(idx)] *
                               np.asarray(in2), rtol=1e-6)
    # grads flow to both inputs (scatter-add into in1)
    g1 = jax.grad(lambda a: jnp.sum(index_mul_2d(a, in2, idx)))(in1)
    assert np.asarray(g1)[3].sum() != 0  # duplicated index accumulated
    np.testing.assert_allclose(np.asarray(g1)[3],
                               (np.asarray(in2)[1] + np.asarray(in2)[2]),
                               rtol=1e-6)


# ------------------------------------------------------------- group_norm
def test_group_norm_nhwc_matches_flax():
    from apex_tpu.contrib.group_norm import GroupNorm
    import flax.linen as nn
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 32))
    m = GroupNorm(num_groups=4, num_channels=32)
    v = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(v, x)
    ref_m = nn.GroupNorm(num_groups=4)
    ref_v = ref_m.init(jax.random.PRNGKey(1), x)
    ref = ref_m.apply(ref_v, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)


def test_group_norm_silu():
    from apex_tpu.contrib.group_norm import group_norm_nhwc
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 8))
    y = group_norm_nhwc(x, 2, act="silu")
    base = group_norm_nhwc(x, 2)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(base) /
                               (1 + np.exp(-np.asarray(base))),
                               rtol=1e-4, atol=1e-5)


# --------------------------------------------------------------- sparsity
def test_asp_mask_2of4():
    from apex_tpu.contrib.sparsity import create_mask, apply_masks, \
        compute_sparse_masks
    w = jax.random.normal(jax.random.PRNGKey(0), (16, 64))
    mask = create_mask(w)
    m = np.asarray(mask).reshape(-1, 4)
    assert (m.sum(-1) == 2).all()  # exactly 2 of every 4 kept
    # kept entries are the 2 largest |w| in each group
    g = np.abs(np.asarray(w)).reshape(-1, 4)
    for row, keep in zip(g, m):
        kept = row[keep]
        dropped = row[~keep]
        assert kept.min() >= dropped.max() - 1e-7
    params = {"dense": {"kernel": w, "bias": jnp.zeros((64,))}}
    masks = compute_sparse_masks(params)
    assert np.asarray(masks["dense"]["bias"]).all()  # bias not pruned
    pruned = apply_masks(params, masks)
    assert (np.asarray(pruned["dense"]["kernel"]) == 0).mean() == 0.5


def test_asp_masked_optimizer_keeps_sparsity():
    from apex_tpu.contrib.sparsity import ASP
    w = jax.random.normal(jax.random.PRNGKey(0), (8, 16))
    params = {"kernel": w}
    pruned, tx = ASP.prune_trained_model(params, optax.sgd(0.1))
    state = tx.init(pruned)
    grads = {"kernel": jnp.ones_like(w)}
    upd, state = tx.update(grads, state, pruned)
    new_p = optax.apply_updates(pruned, upd)
    zeros_before = np.asarray(pruned["kernel"]) == 0
    assert (np.asarray(new_p["kernel"])[zeros_before] == 0).all()


def test_permutation_search_improves_or_equal():
    from apex_tpu.contrib.sparsity import permutation_search
    w = jax.random.normal(jax.random.PRNGKey(5), (32, 16))
    perm, gain = permutation_search(w, n_iter=200)
    assert sorted(perm.tolist()) == list(range(16))
    assert gain >= 0.0


# ------------------------------------------------------------- transducer
def test_transducer_joint():
    from apex_tpu.contrib.transducer import transducer_joint
    f = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 8))
    g = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 8))
    out = transducer_joint(f, g, relu=True)
    ref = np.maximum(np.asarray(f)[:, :, None] + np.asarray(g)[:, None], 0)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)


def _rnnt_ref(log_probs, labels, T, U, blank=0):
    """O(TU) numpy dynamic program."""
    lp = np.asarray(log_probs, np.float64)
    alpha = np.full((T, U + 1), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(T):
        for u in range(U + 1):
            if t == 0 and u == 0:
                continue
            cands = []
            if t > 0:
                cands.append(alpha[t - 1, u] + lp[t - 1, u, blank])
            if u > 0:
                cands.append(alpha[t, u - 1] + lp[t, u - 1, labels[u - 1]])
            alpha[t, u] = np.logaddexp.reduce(cands) if cands else -np.inf
    return -(alpha[T - 1, U] + lp[T - 1, U, blank])


def test_transducer_loss_matches_dp():
    from apex_tpu.contrib.transducer import transducer_loss
    B, T, U, V = 2, 6, 3, 5
    lp = jax.nn.log_softmax(
        jax.random.normal(jax.random.PRNGKey(0), (B, T, U + 1, V)), -1)
    labels = jax.random.randint(jax.random.PRNGKey(1), (B, U), 1, V)
    f_len = jnp.array([T, T - 2])
    y_len = jnp.array([U, U - 1])
    loss = transducer_loss(lp, labels, f_len, y_len)
    for b in range(B):
        ref = _rnnt_ref(np.asarray(lp[b]), np.asarray(labels[b]),
                        int(f_len[b]), int(y_len[b]))
        np.testing.assert_allclose(float(loss[b]), ref, rtol=1e-4)


def test_transducer_loss_grad_finite():
    from apex_tpu.contrib.transducer import transducer_loss
    B, T, U, V = 1, 4, 2, 4
    x = jax.random.normal(jax.random.PRNGKey(0), (B, T, U + 1, V))
    labels = jnp.ones((B, U), jnp.int32)
    g = jax.grad(lambda x: jnp.sum(transducer_loss(
        jax.nn.log_softmax(x, -1), labels, jnp.array([T]),
        jnp.array([U]))))(x)
    assert np.isfinite(np.asarray(g)).all()


# ------------------------------------------------------------------- fmha
def test_fmha_packed_matches_padded():
    from apex_tpu.contrib.fmha import fmha
    H, D = 2, 64
    lens = [128, 128]  # two packed sequences
    total = sum(lens)
    qkv = jax.random.normal(jax.random.PRNGKey(0), (total, 3, H, D))
    cu = jnp.array([0, 128, 256], jnp.int32)
    out = fmha(qkv, cu, heads=H)
    assert out.shape == (total, H, D)
    # per-sequence check vs reference attention
    from apex_tpu.kernels.flash_attention import mha_reference
    for start, ln in ((0, 128), (128, 128)):
        q = qkv[start:start + ln, 0].transpose(1, 0, 2)[None]
        k = qkv[start:start + ln, 1].transpose(1, 0, 2)[None]
        v = qkv[start:start + ln, 2].transpose(1, 0, 2)[None]
        ref = mha_reference(q, k, v, scale=1.0 / D ** 0.5)[0] \
            .transpose(1, 0, 2)
        np.testing.assert_allclose(np.asarray(out[start:start + ln]),
                                   np.asarray(ref), rtol=1e-4, atol=1e-4)


# --------------------------------------------------------- multihead_attn
def test_self_multihead_attn_matches_manual():
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn
    S, B, E, H = 128, 2, 64, 4
    m = SelfMultiheadAttn(embed_dim=E, num_heads=H, use_bias=False)
    x = jax.random.normal(jax.random.PRNGKey(0), (S, B, E))
    v = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(v, x, mask_future_timesteps=True, is_training=False)
    assert y.shape == (S, B, E)

    # manual reference from the same weights
    wqkv = np.asarray(v["params"]["qkv_proj"]["kernel"])
    wout = np.asarray(v["params"]["out_proj"]["kernel"])
    xx = np.asarray(x)
    qkv = xx @ wqkv
    q, k, vv = np.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(S, B, H, E // H).transpose(1, 2, 0, 3)

    qh, kh, vh = heads(q), heads(k), heads(vv)
    s = np.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(E // H)
    mask = np.triu(np.ones((S, S), bool), 1)
    s = np.where(mask, -1e30, s)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    o = np.einsum("bhqk,bhkd->bhqd", p, vh)
    o = o.transpose(2, 0, 1, 3).reshape(S, B, E)
    ref = o @ wout
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-3, atol=2e-3)


def test_self_attn_norm_add_residual():
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn
    S, B, E = 128, 1, 64
    m = SelfMultiheadAttn(embed_dim=E, num_heads=4, include_norm_add=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (S, B, E)) * 100
    v = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(v, x, is_training=False)
    # with huge input, residual dominates → output ≈ x (pre-LN keeps attn
    # contribution O(1))
    corr = np.corrcoef(np.asarray(y).ravel(), np.asarray(x).ravel())[0, 1]
    assert corr > 0.99


def test_encdec_attn_shapes():
    from apex_tpu.contrib.multihead_attn import EncdecMultiheadAttn
    m = EncdecMultiheadAttn(embed_dim=64, num_heads=4)
    q = jax.random.normal(jax.random.PRNGKey(0), (128, 2, 64))
    kv = jax.random.normal(jax.random.PRNGKey(1), (256, 2, 64))
    v = m.init(jax.random.PRNGKey(2), q, kv)
    y = m.apply(v, q, kv, is_training=False)
    assert y.shape == (128, 2, 64)


def test_self_attn_prob_dropout_path():
    """Dropout is applied to the softmax probabilities (reference
    semantics), so a dropout run differs from deterministic but keeps
    row-stochastic structure in expectation."""
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn
    S, B, E = 128, 1, 64
    m = SelfMultiheadAttn(embed_dim=E, num_heads=4, dropout=0.5)
    x = jax.random.normal(jax.random.PRNGKey(0), (S, B, E))
    v = m.init(jax.random.PRNGKey(1), x)
    det = m.apply(v, x, is_training=False)
    drop = m.apply(v, x, is_training=True,
                   rngs={"dropout": jax.random.PRNGKey(2)})
    assert not np.allclose(np.asarray(det), np.asarray(drop))
    drop2 = m.apply(v, x, is_training=True,
                    rngs={"dropout": jax.random.PRNGKey(2)})
    np.testing.assert_allclose(np.asarray(drop), np.asarray(drop2))


def test_groupbn_nhwc_add_relu():
    """contrib.groupbn BatchNorm2d_NHWC (reference: bnp batch_norm_add_relu):
    BN vs flax reference, fused residual add + ReLU, and the bn_group guard."""
    import flax.linen as fnn

    from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 8, 16))
    z = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 8, 16))
    m = BatchNorm2d_NHWC(num_features=16, fuse_relu=True)
    variables = m.init(jax.random.PRNGKey(2), x, z,
                       use_running_average=False)
    y, _ = m.apply(variables, x, z, use_running_average=False,
                   mutable=["batch_stats"])

    ref_bn = fnn.BatchNorm(use_running_average=False, momentum=0.9,
                           epsilon=1e-5)
    rv = ref_bn.init(jax.random.PRNGKey(2), x)
    ref, _ = ref_bn.apply(rv, x, mutable=["batch_stats"])
    expect = np.maximum(np.asarray(ref) + np.asarray(z), 0)
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5, atol=1e-5)

    with pytest.raises(ValueError, match="bn_group"):
        BatchNorm2d_NHWC(num_features=16, bn_group=2).init(
            jax.random.PRNGKey(0), x)


def test_self_attn_additive_mask():
    """Reference: fast_self_multihead_attn_additive_mask — a float mask
    ADDED to the logits (−inf-style for disallowed positions) must match
    applying the same mask in an explicit softmax composition."""
    import flax.linen as nn
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

    S, B, E, H = 10, 2, 32, 4
    x = jax.random.normal(jax.random.PRNGKey(0), (S, B, E))
    # forbid attention to the last 3 keys, additively
    mask = jnp.zeros((1, 1, S, S)).at[:, :, :, -3:].set(-1e30)

    m = SelfMultiheadAttn(embed_dim=E, num_heads=H)
    variables = m.init(jax.random.PRNGKey(1), x, is_training=False)
    out_masked = m.apply(variables, x, attn_mask=mask, is_training=False)
    out_plain = m.apply(variables, x, is_training=False)
    assert not np.allclose(np.asarray(out_masked), np.asarray(out_plain))

    # oracle: same projections, explicit softmax with the additive mask
    qkv_k = variables["params"]["qkv_proj"]["kernel"]
    out_k = variables["params"]["out_proj"]["kernel"]
    qkv = jnp.einsum("sbe,ef->sbf", x, qkv_k)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    d = E // H
    def heads(t):
        return t.reshape(S, B, H, d).transpose(1, 2, 0, 3)
    qh, kh, vh = heads(q), heads(k), heads(v)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / d ** 0.5 + mask
    p = jax.nn.softmax(s, -1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    o = o.transpose(2, 0, 1, 3).reshape(S, B, E)
    ref = jnp.einsum("sbe,ef->sbf", o, out_k)
    np.testing.assert_allclose(np.asarray(out_masked), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_self_attn_padding_mask_fast_matches_default():
    """Key-padding masks on the FUSED path (additive −inf key bias) must
    reproduce the explicit-probs path exactly — including the reference's
    semantics that padded QUERIES still attend normally."""
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

    S, B, E, H = 12, 3, 32, 4
    x = jax.random.normal(jax.random.PRNGKey(0), (S, B, E))
    # mask the last 4 keys of batch 0, none of batch 1, half of batch 2
    pad = np.zeros((B, S), bool)
    pad[0, -4:] = True
    pad[2, ::2] = True
    pad = jnp.asarray(pad)

    m_fast = SelfMultiheadAttn(embed_dim=E, num_heads=H, impl="fast")
    m_def = SelfMultiheadAttn(embed_dim=E, num_heads=H, impl="default")
    variables = m_fast.init(jax.random.PRNGKey(1), x, is_training=False)

    out_fast = m_fast.apply(variables, x, key_padding_mask=pad,
                            is_training=False)
    out_def = m_def.apply(variables, x, key_padding_mask=pad,
                          is_training=False)
    np.testing.assert_allclose(np.asarray(out_fast), np.asarray(out_def),
                               rtol=2e-5, atol=2e-5)
    # and the mask actually does something
    out_nomask = m_fast.apply(variables, x, is_training=False)
    assert not np.allclose(np.asarray(out_fast), np.asarray(out_nomask))

    # fused dropout composes with the padding mask (deterministic per rng)
    m_drop = SelfMultiheadAttn(embed_dim=E, num_heads=H, dropout=0.4,
                               impl="fast")
    vd = m_drop.init(jax.random.PRNGKey(2), x)
    d1 = m_drop.apply(vd, x, key_padding_mask=pad, is_training=True,
                      rngs={"dropout": jax.random.PRNGKey(3)})
    d2 = m_drop.apply(vd, x, key_padding_mask=pad, is_training=True,
                      rngs={"dropout": jax.random.PRNGKey(3)})
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))


def test_self_attn_invalid_impl_raises():
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn
    m = SelfMultiheadAttn(embed_dim=16, num_heads=2, impl="Fast")
    x = jnp.zeros((4, 1, 16))
    with pytest.raises(ValueError, match="impl"):
        m.init(jax.random.PRNGKey(0), x, is_training=False)


def test_transducer_loss_wavefront_larger_odd_shapes():
    """The diagonal-wavefront scan at sizes that exercise masking corners
    (T<U+1 region, ragged lengths) vs the fp64 DP oracle; grads finite."""
    from apex_tpu.contrib.transducer import transducer_loss
    B, T, U, V = 3, 7, 11, 6
    lp = jax.nn.log_softmax(
        jax.random.normal(jax.random.PRNGKey(5), (B, T, U + 1, V)), -1)
    labels = jax.random.randint(jax.random.PRNGKey(6), (B, U), 1, V)
    f_len = jnp.array([T, T - 3, 2])
    y_len = jnp.array([U, U - 4, 1])
    loss = jax.jit(transducer_loss)(lp, labels, f_len, y_len)
    for b in range(B):
        ref = _rnnt_ref(np.asarray(lp)[b], np.asarray(labels)[b],
                        int(f_len[b]), int(y_len[b]))
        np.testing.assert_allclose(float(loss[b]), ref, rtol=1e-5,
                                   err_msg=f"sample {b}")

    g = jax.jit(jax.grad(lambda lp: jnp.sum(transducer_loss(
        lp, labels, f_len, y_len))))(lp)
    assert np.isfinite(np.asarray(g)).all()
