"""Mirrors apex/contrib/test/xentropy/test_label_smoothing.py: fused xent vs
log_softmax+NLL composition, smoothing on/off, half I/O, grads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.xentropy import SoftmaxCrossEntropyLoss
from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss, \
    xent_reference

N, V = 128, 512


def _data(dtype=jnp.float32):
    logits = jax.random.normal(jax.random.PRNGKey(0), (N, V), dtype) * 2
    labels = jax.random.randint(jax.random.PRNGKey(1), (N,), 0, V)
    return logits, labels


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_forward(smoothing):
    logits, labels = _data()
    out = softmax_cross_entropy_loss(logits, labels, smoothing)
    ref = xent_reference(logits, labels, smoothing)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_backward(smoothing):
    logits, labels = _data()
    g = jax.grad(lambda l: jnp.sum(
        softmax_cross_entropy_loss(l, labels, smoothing)))(logits)
    gr = jax.grad(lambda l: jnp.sum(
        xent_reference(l, labels, smoothing)))(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=1e-4, atol=1e-5)


def test_half_io():
    logits, labels = _data(jnp.bfloat16)
    out = softmax_cross_entropy_loss(logits, labels, 0.1)
    assert out.dtype == jnp.float32  # losses fp32 like the reference
    ref = xent_reference(logits, labels, 0.1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_unaligned_vocab_falls_back():
    logits = jax.random.normal(jax.random.PRNGKey(2), (7, 33))
    labels = jax.random.randint(jax.random.PRNGKey(3), (7,), 0, 33)
    out = softmax_cross_entropy_loss(logits, labels, 0.0)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(xent_reference(logits, labels)),
                               rtol=1e-5, atol=1e-5)


def test_apply_api():
    logits, labels = _data()
    out = SoftmaxCrossEntropyLoss.apply(logits, labels, 0.0, -1, True)
    ref = xent_reference(logits, labels)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n", [256, 16])
def test_multi_block_batches(n):
    """Regression: batches spanning several row blocks (block slicing of the
    label/lse rows inside the kernels)."""
    logits = jax.random.normal(jax.random.PRNGKey(4), (n, 128))
    labels = jax.random.randint(jax.random.PRNGKey(5), (n,), 0, 128)
    out = softmax_cross_entropy_loss(logits, labels, 0.1)
    ref = xent_reference(logits, labels, 0.1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    g = jax.grad(lambda l: jnp.sum(
        softmax_cross_entropy_loss(l, labels, 0.1)))(logits)
    gr = jax.grad(lambda l: jnp.sum(xent_reference(l, labels, 0.1)))(logits)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=1e-4, atol=1e-5)
