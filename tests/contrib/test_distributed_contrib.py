"""Distributed contrib tests on the 8-device CPU mesh: ZeRO-sharded
optimizers vs single-process fused Adam (mirrors
apex/contrib/test/optimizers/test_dist_adam.py) and halo exchange (mirrors
test_peer_halo_exchange_module.py)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from apex_tpu.utils.compat import shard_map

from apex_tpu import comm

# Heavy multi-device CPU-emulation tier: inert at the seed (shard_map
# import errors) until the apex_tpu.utils.compat shim made this file
# runnable on the hermetic jax, but too costly for the tier-1 wall-time
# budget. Deselect from the fast tier; run with -m slow (or on the axon
# toolchain, whose jax these tests target first).
pytestmark = pytest.mark.slow

WORLD = 4


@pytest.fixture()
def data_mesh(eight_devices):
    mesh = Mesh(np.array(eight_devices[:WORLD]), ("data",))
    comm.set_mesh(mesh)
    yield mesh
    comm.reset_mesh()


def _params():
    k = jax.random.PRNGKey(0)
    return {"w": jax.random.normal(k, (33, 7)),  # odd sizes force padding
            "b": jnp.zeros((5,))}


def test_dist_adam_matches_fused_adam(data_mesh):
    """Sharded-state Adam must produce the same params as unsharded Adam on
    the mean gradient (the reference test compares DistributedFusedAdam to
    FusedAdam the same way)."""
    from apex_tpu.contrib.optimizers import distributed_fused_adam
    from apex_tpu.optimizers.fused_adam import fused_adam

    params = _params()
    tx = distributed_fused_adam(1e-2, world_size=WORLD)
    state = tx.init(params)

    # per-rank grads: rank r gets grads scaled by (r+1); mean = 2.5x base
    base = {"w": jnp.ones((33, 7)), "b": jnp.full((5,), 2.0)}

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P(), P(), P("data")), out_specs=P(),
                       check_vma=False)
    def sharded_step(params, state_and_base, rank_scale):
        state, base = state_and_base
        grads = jax.tree_util.tree_map(lambda g: g * rank_scale[0], base)
        upd, new_state = tx.update(grads, state, params)
        return optax.apply_updates(params, upd)

    scales = jnp.arange(1.0, WORLD + 1)  # mean 2.5
    new_params = jax.jit(sharded_step)(params, (state, base), scales)

    ref_tx = fused_adam(1e-2)
    ref_state = ref_tx.init(params)
    mean_grads = jax.tree_util.tree_map(lambda g: g * 2.5, base)
    ref_upd, _ = ref_tx.update(mean_grads, ref_state, params)
    ref_params = optax.apply_updates(params, ref_upd)

    for k in params:
        np.testing.assert_allclose(np.asarray(new_params[k]),
                                   np.asarray(ref_params[k]),
                                   rtol=1e-5, atol=1e-6)


def test_dist_adam_state_is_sharded(data_mesh):
    from apex_tpu.contrib.optimizers import distributed_fused_adam
    params = _params()
    n = 33 * 7 + 5
    tx = distributed_fused_adam(1e-2, world_size=WORLD)
    state = tx.init(params)
    padded = ((n + WORLD - 1) // WORLD) * WORLD
    assert state.m_shard.shape == (padded // WORLD,)  # 1/world of the state


def test_dist_lamb_runs_and_differs_by_trust_ratio(data_mesh):
    from apex_tpu.contrib.optimizers import distributed_fused_lamb
    params = {"w": jax.random.normal(jax.random.PRNGKey(1), (16, 8))}
    tx = distributed_fused_lamb(1e-2, world_size=WORLD) \
        if "world_size" in distributed_fused_lamb.__code__.co_varnames \
        else distributed_fused_lamb(1e-2)
    state = tx.init(params)

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P(), P()), out_specs=P(),
                       check_vma=False)
    def step(params, state):
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        upd, _ = tx.update(grads, state, params)
        return optax.apply_updates(params, upd)

    out = jax.jit(step)(params, state)
    assert np.isfinite(np.asarray(out["w"])).all()
    assert not np.allclose(np.asarray(out["w"]), np.asarray(params["w"]))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_dist_lamb_matches_fused_lamb(data_mesh, dtype):
    """distributed_fused_lamb == fused_lamb on the mean gradient for the
    same constructor args (VERDICT: the two LAMBs must agree — same
    multi_tensor_lamb.cu math, different state placement). Grads are large
    enough that the global-norm clip stage engages, proving the distributed
    path has one. bf16 params exercise the update-stays-fp32-through-the-
    trust-ratio-stage requirement."""
    from apex_tpu.contrib.optimizers import distributed_fused_lamb
    from apex_tpu.optimizers.fused_lamb import fused_lamb

    kw = dict(learning_rate=1e-2, weight_decay=0.01, max_grad_norm=1.0,
              use_nvlamb=False)
    params = {"w": jax.random.normal(jax.random.PRNGKey(1),
                                     (16, 8)).astype(dtype),
              "b": jax.random.normal(jax.random.PRNGKey(2),
                                     (5,)).astype(dtype)}
    base = {"w": jnp.full((16, 8), 4.0), "b": jnp.full((5,), -3.0)}
    steps = 3

    tx = distributed_fused_lamb(axis_name="data", world_size=WORLD, **kw)
    state = tx.init(params)

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P(), P(), P("data")), out_specs=P(),
                       check_vma=False)
    def run(params, state, rank_scale):
        for _ in range(steps):
            grads = jax.tree_util.tree_map(lambda g: g * rank_scale[0], base)
            upd, state = tx.update(grads, state, params)
            params = optax.apply_updates(params, upd)
        return params

    scales = jnp.arange(1.0, WORLD + 1)  # mean 2.5
    dist_params = jax.jit(run)(params, state, scales)

    ref_tx = fused_lamb(**kw)
    ref_state = ref_tx.init(params)
    ref_params = params
    mean_grads = jax.tree_util.tree_map(lambda g: g * 2.5, base)
    for _ in range(steps):
        upd, ref_state = ref_tx.update(mean_grads, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, upd)

    # sanity: the clip stage must actually have engaged
    gn = float(jnp.sqrt(sum(jnp.sum((g * 2.5) ** 2)
                            for g in jax.tree_util.tree_leaves(base))))
    assert gn > 1.0
    for k in params:
        np.testing.assert_allclose(np.asarray(dist_params[k]),
                                   np.asarray(ref_params[k]),
                                   rtol=1e-5, atol=1e-6)


def test_dist_lamb_nvlamb_switch_matches_fused_lamb(data_mesh):
    """weight_decay=0 + use_nvlamb=False forces trust ratio 1.0 in BOTH
    LAMBs (the kernel's NVLAMB switch) — previously only fused_lamb did."""
    from apex_tpu.contrib.optimizers import distributed_fused_lamb
    from apex_tpu.optimizers.fused_lamb import fused_lamb

    params = {"w": jax.random.normal(jax.random.PRNGKey(3), (8, 4)) * 5.0}
    grads = {"w": jnp.full((8, 4), 0.1)}  # below max_grad_norm: no clip

    for nv in (False, True):
        kw = dict(learning_rate=1e-2, weight_decay=0.0, max_grad_norm=1e9,
                  use_nvlamb=nv)
        tx = distributed_fused_lamb(axis_name="data", world_size=WORLD, **kw)
        state = tx.init(params)

        @functools.partial(shard_map, mesh=data_mesh,
                           in_specs=(P(), P()), out_specs=P(),
                           check_vma=False)
        def run(params, state):
            upd, _ = tx.update(grads, state, params)
            return optax.apply_updates(params, upd)

        dist_out = jax.jit(run)(params, state)
        ref_tx = fused_lamb(**kw)
        upd, _ = ref_tx.update(grads, ref_tx.init(params), params)
        ref_out = optax.apply_updates(params, upd)
        np.testing.assert_allclose(np.asarray(dist_out["w"]),
                                   np.asarray(ref_out["w"]),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f"use_nvlamb={nv}")


def test_zero_state_resharded_roundtrip(data_mesh, tmp_path):
    """ZeRO optimizer-state save/restore across a world-size change
    (reference: DistributedFusedAdam.state_dict reconstitution — SURVEY §6
    checkpoint (c)): train 2 steps at world 4, checkpoint via the sharded
    writer, restore under a world-2 mesh, train 2 more steps; the result
    must equal 4 uninterrupted steps (oracle: fused_lamb on mean grads)."""
    from jax.sharding import NamedSharding
    from apex_tpu.contrib.optimizers import (DistAdamState,
                                             distributed_fused_lamb,
                                             reshard_zero_state)
    from apex_tpu.optimizers.fused_lamb import fused_lamb
    from apex_tpu.utils.sharded_checkpoint import load_sharded, save_sharded

    kw = dict(learning_rate=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    # n = 13*3 + 7 = 46: pads to 48 at world 4, 46 at world 2 — the repad
    # path is actually exercised
    params = {"w": jax.random.normal(jax.random.PRNGKey(5), (13, 3)),
              "b": jnp.zeros((7,))}
    n = 46
    base = {"w": jnp.full((13, 3), 2.0), "b": jnp.full((7,), -1.0)}

    def make_run(mesh, world, steps):
        tx = distributed_fused_lamb(axis_name="data", world_size=world, **kw)
        sspec = DistAdamState(count=P(), m_shard=P("data"),
                              v_shard=P("data"))

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(), sspec, P("data")),
                           out_specs=(P(), sspec), check_vma=False)
        def run(params, state, rank_scale):
            for _ in range(steps):
                grads = jax.tree_util.tree_map(
                    lambda g: g * rank_scale[0], base)
                upd, state = tx.update(grads, state, params)
                params = optax.apply_updates(params, upd)
            return params, state

        return jax.jit(run)

    # phase 1: world 4, concatenated state representation [48]
    state4 = DistAdamState(count=jnp.zeros((), jnp.int32),
                           m_shard=jnp.zeros((48,), jnp.float32),
                           v_shard=jnp.zeros((48,), jnp.float32))
    scales4 = jnp.arange(1.0, 5.0)  # mean 2.5
    p_mid, state_mid = make_run(data_mesh, 4, 2)(params, state4, scales4)

    # checkpoint: place the concatenated state sharded over the 4-dev mesh
    # and write through the real sharded writer
    sh4 = NamedSharding(data_mesh, P("data"))
    state_placed = DistAdamState(
        count=state_mid.count,
        m_shard=jax.device_put(state_mid.m_shard, sh4),
        v_shard=jax.device_put(state_mid.v_shard, sh4))
    save_sharded(str(tmp_path), state_placed, step=2)

    # restore under a DIFFERENT mesh (2 devices) — resharded restore
    mesh2 = Mesh(np.array(data_mesh.devices.flatten()[:2]), ("data",))
    sh2 = NamedSharding(mesh2, P("data"))
    template = DistAdamState(
        count=jnp.zeros((), jnp.int32),
        m_shard=jax.device_put(jnp.zeros((48,), jnp.float32), sh2),
        v_shard=jax.device_put(jnp.zeros((48,), jnp.float32), sh2))
    restored, step = load_sharded(str(tmp_path), template)
    assert step == 2
    state2 = reshard_zero_state(restored, n, 2)  # strip pad48 → pad46
    assert state2.m_shard.shape == (46,)

    # phase 2: world 2, same mean gradient (scales (2,3) → mean 2.5)
    p_mid = jax.tree_util.tree_map(np.asarray, p_mid)  # off the 4-dev mesh
    state2 = jax.tree_util.tree_map(np.asarray, state2)
    scales2 = jnp.asarray([2.0, 3.0])
    p_final, _ = make_run(mesh2, 2, 2)(p_mid, state2, scales2)

    # oracle: 4 uninterrupted fused_lamb steps on the mean grads
    ref_tx = fused_lamb(**kw)
    ref_state = ref_tx.init(params)
    ref_params = params
    mean_grads = jax.tree_util.tree_map(lambda g: g * 2.5, base)
    for _ in range(4):
        upd, ref_state = ref_tx.update(mean_grads, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, upd)

    for k in params:
        np.testing.assert_allclose(np.asarray(p_final[k]),
                                   np.asarray(ref_params[k]),
                                   rtol=1e-5, atol=1e-6)


def test_wrapper_state_dict_semantics(data_mesh):
    """Wrapper checkpoint API: world-1 round-trips and rebuilds the
    transformation for the new world; a world>1 instance holding only its
    per-rank shard refuses to checkpoint (the concatenated state must be
    gathered first)."""
    from apex_tpu.contrib.optimizers import DistributedFusedLAMB

    params = {"w": jnp.ones((5, 7))}  # n=35: pads to 36 at world 2

    opt1 = DistributedFusedLAMB(params, lr=1e-2, world_size=1)
    sd = opt1.state_dict()
    assert sd["world"] == 1 and sd["num_params"] == 35
    opt1.load_state_dict(sd, new_world=2)
    assert opt1.state.m_shard.shape == (36,)
    assert opt1._world == 2  # tx rebuilt: next step's shard math uses 2

    opt4 = DistributedFusedLAMB(params, lr=1e-2, world_size=4)
    assert opt4.state.m_shard.shape == (9,)  # per-rank shard
    with pytest.raises(ValueError, match="gather shards"):
        opt4.state_dict()


def test_halo_exchange_1d(data_mesh):
    from apex_tpu.contrib.peer_memory import halo_exchange_1d
    # global [WORLD*4, 3] sharded along dim 0 (rows)
    x = jnp.arange(WORLD * 4 * 3, dtype=jnp.float32).reshape(WORLD * 4, 3)

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P("data"),), out_specs=P("data"),
                       check_vma=False)
    def ex(xl):
        return halo_exchange_1d(xl, 1, "data", dim=0)

    out = ex(x)  # each shard: [1+4+1, 3] → gathered [WORLD*6, 3]
    out = np.asarray(out).reshape(WORLD, 6, 3)
    xg = np.asarray(x).reshape(WORLD, 4, 3)
    for r in range(WORLD):
        np.testing.assert_array_equal(out[r, 1:5], xg[r])
        if r > 0:
            np.testing.assert_array_equal(out[r, 0], xg[r - 1, -1])
        else:
            np.testing.assert_array_equal(out[r, 0], 0)
        if r < WORLD - 1:
            np.testing.assert_array_equal(out[r, 5], xg[r + 1, 0])
        else:
            np.testing.assert_array_equal(out[r, 5], 0)


def test_spatial_bottleneck_matches_dense(data_mesh):
    """SpatialBottleneck with H sharded over 4 ranks == Bottleneck on the
    full image (reference: bottleneck test comparing spatial vs serial)."""
    from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck
    N, Hh, W, C = 1, 16, 8, 8
    x = jax.random.normal(jax.random.PRNGKey(0), (N, Hh, W, C))

    dense = Bottleneck(in_channels=C, bottleneck_channels=4, out_channels=C)
    dv = dense.init(jax.random.PRNGKey(1), x, train=False)
    ref = dense.apply(dv, x, train=False)

    spatial = SpatialBottleneck(in_channels=C, bottleneck_channels=4,
                                out_channels=C, axis_name="data")

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P(), P(None, "data")),
                       out_specs=P(None, "data"), check_vma=False)
    def run(variables, xl):
        return spatial.apply(variables, xl, train=False)

    out = run(dv, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_deprecated_optimizer_aliases():
    """The P32 deprecated wrappers stay importable and forward correctly
    (an eager package import would break ALL contrib.optimizers imports if
    a forwarding target moved)."""
    import warnings
    from apex_tpu.contrib.optimizers import FP16_Optimizer, FusedSGD
    from apex_tpu.fp16_utils import FP16_Optimizer as Real16
    from apex_tpu.optimizers import FusedSGD as RealSGD

    assert issubclass(FP16_Optimizer, Real16)
    assert issubclass(FusedSGD, RealSGD)
    params = {"w": jnp.ones((4,))}
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        opt = FusedSGD(params, lr=0.1)
        FP16_Optimizer(optax.sgd(0.1), params)
    assert sum("deprecated" in str(x.message) for x in w) >= 2
    out = opt.step({"w": jnp.full((4,), 0.5)})
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0 - 0.05, rtol=1e-6)
