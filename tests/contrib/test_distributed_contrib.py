"""Distributed contrib tests on the 8-device CPU mesh: ZeRO-sharded
optimizers vs single-process fused Adam (mirrors
apex/contrib/test/optimizers/test_dist_adam.py) and halo exchange (mirrors
test_peer_halo_exchange_module.py)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from apex_tpu import comm

WORLD = 4


@pytest.fixture()
def data_mesh(eight_devices):
    mesh = Mesh(np.array(eight_devices[:WORLD]), ("data",))
    comm.set_mesh(mesh)
    yield mesh
    comm.reset_mesh()


def _params():
    k = jax.random.PRNGKey(0)
    return {"w": jax.random.normal(k, (33, 7)),  # odd sizes force padding
            "b": jnp.zeros((5,))}


def test_dist_adam_matches_fused_adam(data_mesh):
    """Sharded-state Adam must produce the same params as unsharded Adam on
    the mean gradient (the reference test compares DistributedFusedAdam to
    FusedAdam the same way)."""
    from apex_tpu.contrib.optimizers import distributed_fused_adam
    from apex_tpu.optimizers.fused_adam import fused_adam

    params = _params()
    tx = distributed_fused_adam(1e-2, world_size=WORLD)
    state = tx.init(params)

    # per-rank grads: rank r gets grads scaled by (r+1); mean = 2.5x base
    base = {"w": jnp.ones((33, 7)), "b": jnp.full((5,), 2.0)}

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P(), P(), P("data")), out_specs=P(),
                       check_rep=False)
    def sharded_step(params, state_and_base, rank_scale):
        state, base = state_and_base
        grads = jax.tree_util.tree_map(lambda g: g * rank_scale[0], base)
        upd, new_state = tx.update(grads, state, params)
        return optax.apply_updates(params, upd)

    scales = jnp.arange(1.0, WORLD + 1)  # mean 2.5
    new_params = jax.jit(sharded_step)(params, (state, base), scales)

    ref_tx = fused_adam(1e-2)
    ref_state = ref_tx.init(params)
    mean_grads = jax.tree_util.tree_map(lambda g: g * 2.5, base)
    ref_upd, _ = ref_tx.update(mean_grads, ref_state, params)
    ref_params = optax.apply_updates(params, ref_upd)

    for k in params:
        np.testing.assert_allclose(np.asarray(new_params[k]),
                                   np.asarray(ref_params[k]),
                                   rtol=1e-5, atol=1e-6)


def test_dist_adam_state_is_sharded(data_mesh):
    from apex_tpu.contrib.optimizers import distributed_fused_adam
    params = _params()
    n = 33 * 7 + 5
    tx = distributed_fused_adam(1e-2, world_size=WORLD)
    state = tx.init(params)
    padded = ((n + WORLD - 1) // WORLD) * WORLD
    assert state.m_shard.shape == (padded // WORLD,)  # 1/world of the state


def test_dist_lamb_runs_and_differs_by_trust_ratio(data_mesh):
    from apex_tpu.contrib.optimizers import distributed_fused_lamb
    params = {"w": jax.random.normal(jax.random.PRNGKey(1), (16, 8))}
    tx = distributed_fused_lamb(1e-2, world_size=WORLD) \
        if "world_size" in distributed_fused_lamb.__code__.co_varnames \
        else distributed_fused_lamb(1e-2)
    state = tx.init(params)

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P(), P()), out_specs=P(),
                       check_rep=False)
    def step(params, state):
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        upd, _ = tx.update(grads, state, params)
        return optax.apply_updates(params, upd)

    out = jax.jit(step)(params, state)
    assert np.isfinite(np.asarray(out["w"])).all()
    assert not np.allclose(np.asarray(out["w"]), np.asarray(params["w"]))


def test_halo_exchange_1d(data_mesh):
    from apex_tpu.contrib.peer_memory import halo_exchange_1d
    # global [WORLD*4, 3] sharded along dim 0 (rows)
    x = jnp.arange(WORLD * 4 * 3, dtype=jnp.float32).reshape(WORLD * 4, 3)

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P("data"),), out_specs=P("data"),
                       check_rep=False)
    def ex(xl):
        return halo_exchange_1d(xl, 1, "data", dim=0)

    out = ex(x)  # each shard: [1+4+1, 3] → gathered [WORLD*6, 3]
    out = np.asarray(out).reshape(WORLD, 6, 3)
    xg = np.asarray(x).reshape(WORLD, 4, 3)
    for r in range(WORLD):
        np.testing.assert_array_equal(out[r, 1:5], xg[r])
        if r > 0:
            np.testing.assert_array_equal(out[r, 0], xg[r - 1, -1])
        else:
            np.testing.assert_array_equal(out[r, 0], 0)
        if r < WORLD - 1:
            np.testing.assert_array_equal(out[r, 5], xg[r + 1, 0])
        else:
            np.testing.assert_array_equal(out[r, 5], 0)


def test_spatial_bottleneck_matches_dense(data_mesh):
    """SpatialBottleneck with H sharded over 4 ranks == Bottleneck on the
    full image (reference: bottleneck test comparing spatial vs serial)."""
    from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck
    N, Hh, W, C = 1, 16, 8, 8
    x = jax.random.normal(jax.random.PRNGKey(0), (N, Hh, W, C))

    dense = Bottleneck(in_channels=C, bottleneck_channels=4, out_channels=C)
    dv = dense.init(jax.random.PRNGKey(1), x, train=False)
    ref = dense.apply(dv, x, train=False)

    spatial = SpatialBottleneck(in_channels=C, bottleneck_channels=4,
                                out_channels=C, axis_name="data")

    @functools.partial(shard_map, mesh=data_mesh,
                       in_specs=(P(), P(None, "data")),
                       out_specs=P(None, "data"), check_rep=False)
    def run(variables, xl):
        return spatial.apply(variables, xl, train=False)

    out = run(dv, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
