"""Transformer LM recipe — BASELINE.json config 3.

"FusedLayerNorm + FusedAdam transformer LM (WikiText-2)": a causal LM built
from the framework's fused tiers (apex_tpu.models.transformer_lm), trained
with apex_tpu.optimizers.fused_adam under an amp opt-level, LM loss via the
fused xentropy kernel. The reference has no in-repo LM recipe (it supplies
FusedAdam/FusedLayerNorm to external Megatron/DeepLearningExamples scripts);
this is the standalone equivalent, argument-shaped like examples/imagenet.

No network access: --synthetic generates token streams with a Zipfian
unigram distribution (WikiText-2-like vocab statistics); point --data at a
pre-tokenized .npy to train on real text.
"""

from __future__ import annotations

import os as _os
import sys as _sys

# run as a script from anywhere: put the repo root on sys.path (the reference
# relies on `pip install apex`; this repo is used in-tree)
_REPO_ROOT = _os.path.abspath(_os.path.join(_os.path.dirname(__file__),
                                            _os.pardir, _os.pardir))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
from apex_tpu.models.transformer_lm import create_lm
from apex_tpu.optimizers import fused_adam


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="apex_tpu transformer LM recipe")
    p.add_argument("--data", default=None,
                   help="pre-tokenized int32 .npy (else synthetic)")
    p.add_argument("--size", default="small",
                   choices=["tiny", "small", "medium", "gpt2"])
    p.add_argument("--vocab-size", type=int, default=32768)
    p.add_argument("-b", "--batch-size", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--weight-decay", type=float, default=0.01)
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--loss-scale", default="dynamic")
    p.add_argument("--smoothing", type=float, default=0.0,
                   help="label smoothing (fused xentropy kernel)")
    p.add_argument("--fused-head", action="store_true",
                   help="fuse the tied LM head into the loss "
                        "(kernels/lm_head_loss.py): logits never hit HBM "
                        "and the head GEMMs run in the amp half dtype — "
                        "measured 1.4x faster at the GPT-2 tail shape with "
                        "the [B,S,V] logits residual gone. Single-chip, "
                        "or with --vocab-parallel under shard_map (the "
                        "op's axis_name mode fuses Megatron's CE "
                        "reductions into the sharded head GEMM); off by "
                        "default so the default trajectory stays the "
                        "parallel tiers' oracle")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--deterministic", action="store_true")
    p.add_argument("--remat", action="store_true",
                   help="activation checkpointing per block (memory lever)")
    p.add_argument("--accum-steps", type=int, default=1, metavar="N",
                   help="in-jit microbatch gradient accumulation "
                        "(amp.make_train_step accum_steps): the step "
                        "scans N microbatches of batch-size/N, paying "
                        "ONE unscale + optimizer + scaler update per "
                        "window — apex's delay_unscale recipe, compiled. "
                        "Single-chip path only: the parallel tiers' "
                        "1F1B/no-pipelining schedules already accumulate "
                        "over --microbatches")
    # ---- model-parallel tier (SURVEY P22-P24): dp x tp x pp over a
    # ('data','pipe','model') mesh; any value > 1 selects the parallel path
    p.add_argument("--data-parallel", type=int, default=1, metavar="DP",
                   help="data-parallel ranks (DDP grad psum)")
    p.add_argument("--tensor-parallel", type=int, default=1, metavar="TP",
                   help="Megatron TP: QKV/MLP column+row parallel")
    p.add_argument("--pipeline-parallel", type=int, default=1, metavar="PP",
                   help="pipeline stages, hand-scheduled 1F1B when > 1")
    p.add_argument("--virtual-pipeline", type=int, default=1, metavar="VPP",
                   help="virtual chunks per stage (interleaved 1F1B)")
    p.add_argument("--sequence-parallel", action="store_true",
                   help="Megatron SP: LN/residual activations sharded "
                        "along sequence over the TP group (needs tp>1)")
    p.add_argument("--vocab-parallel", action="store_true",
                   help="Megatron parallel LM head: the output projection "
                        "sharded over the vocab dim with "
                        "vocab_parallel_cross_entropy (needs tp>1; "
                        "exclusive with --sequence-parallel)")
    p.add_argument("--zero", action="store_true",
                   help="ZeRO: shard optimizer state over the data axis "
                        "(contrib DistributedFusedAdam — mean-reduce-"
                        "scatter grads, shard-local update, all-gather "
                        "params; needs dp>1). Under --partitioning "
                        "gspmd the same sharding is ONE PartitionSpec "
                        "on the m/v superbuffers — XLA does the rest")
    p.add_argument("--opt-layout", default="tree",
                   choices=["tree", "flat"],
                   help="fused_adam state layout: per-leaf 'tree' "
                        "(default; XLA-fused update at the HBM roofline "
                        "— BASELINE.md round-5 kernel tier) or the "
                        "'flat' superbuffer (bitwise-identical; the "
                        "layout ZeRO shards, forced automatically under "
                        "gspmd --zero)")
    p.add_argument("--microbatches", type=int, default=None,
                   help="pipeline microbatches (default 2*pp)")
    p.add_argument("--partitioning", default="shard_map",
                   choices=["shard_map", "gspmd"],
                   help="how the mesh is driven: explicit shard_map "
                        "collectives (default), or 'gspmd' — plain "
                        "jax.jit over the SAME 1-device program with "
                        "NamedShardings built from the TP modules' "
                        "kernel_partition_spec(); XLA's SPMD partitioner "
                        "inserts the collectives (dp x tp, + --zero)")
    p.add_argument("--prof-device", type=int, default=0, metavar="N",
                   help="after training, time N extra steps on the "
                        "DEVICE lanes of a profiler capture and print "
                        "device tokens/s (the apex recipes' --prof, on "
                        "the round-5 device-time basis). Observation-"
                        "only: runs on a copy of the state; prints n/a "
                        "on backends with no device lanes")
    p.add_argument("--save", default=None, metavar="CKPT",
                   help="write the final train state (params, masters, "
                        "optimizer state incl. ZeRO shards, scaler) plus "
                        "the step count to this .npz")
    p.add_argument("--resume", default=None, metavar="CKPT",
                   help="restore a --save checkpoint and continue: with "
                        "--deterministic the resumed run reproduces the "
                        "uninterrupted trajectory exactly")
    p.add_argument("--layers", type=int, default=None,
                   help="override the size preset's layer count (parallel "
                        "path; must divide by pp*vpp)")
    p.add_argument("--telemetry", default=None, metavar="SPEC",
                   help="stream per-step telemetry (loss, grad norm, "
                        "scaler trajectory, step time) from inside the "
                        "jitted step: JSONL path, 'stdout', or 'null'; "
                        "summarize with python -m apex_tpu.telemetry "
                        "(sharded paths emit one record per rank)")
    # ---- serving tier (apex_tpu.serving): generate after training
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="after training, serve N-token generations from "
                        "synthetic prompts through the apex_tpu.serving "
                        "engine (compiled KV-cache prefill + decode-step "
                        "programs, continuous batching) and print "
                        "tokens/s + time-to-first-token. Single-chip "
                        "path only")
    p.add_argument("--gen-prompts", type=int, default=8, metavar="K",
                   help="number of synthetic prompts to serve (their "
                        "lengths vary to exercise continuous batching)")
    p.add_argument("--gen-slots", type=int, default=4,
                   help="concurrent decode slots (batch width of the "
                        "compiled decode step)")
    p.add_argument("--gen-prompt-len", type=int, default=32,
                   help="prefill program capacity (prompts are sampled "
                        "at 1..this many tokens)")
    p.add_argument("--gen-temperature", type=float, default=0.0,
                   help="sampling temperature (0 = greedy)")
    p.add_argument("--gen-top-k", type=int, default=0,
                   help="top-k truncation for sampled decode (0 = off)")
    return p.parse_args(argv)


def synthetic_tokens(rng, batch, seq_len, vocab):
    """Zipf-ish unigram stream: token ranks follow 1/(r+10)."""
    ranks = jnp.arange(vocab, dtype=jnp.float32)
    logits = -jnp.log(ranks + 10.0)
    return jax.random.categorical(rng, logits, shape=(batch, seq_len + 1))


def load_token_stream(path, vocab_size, seq_len):
    """Load + validate a pre-tokenized flat .npy for --data. Out-of-vocab
    ids are rejected here because under jit the embedding gather would
    clamp them silently — wrong training, not a crash."""
    data = np.load(path)
    if not isinstance(data, np.ndarray):
        raise SystemExit(f"--data {path!r} is an archive (.npz?); "
                         "expected a flat .npy token stream")
    if data.ndim != 1:
        raise SystemExit(f"--data {path!r} must be a flat token stream; "
                         f"got shape {data.shape}")
    if not np.issubdtype(data.dtype, np.integer):
        raise SystemExit(f"--data {path!r} holds {data.dtype} values; "
                         "token streams must be integers (floats would "
                         "truncate silently)")
    if len(data) < seq_len + 2:
        raise SystemExit(f"--data holds {len(data)} tokens; need at least "
                         f"seq_len+2 = {seq_len + 2}")
    lo, hi = int(data.min()), int(data.max())
    if lo < 0 or hi >= vocab_size:
        raise SystemExit(f"--data token ids span [{lo}, {hi}]; "
                         f"--vocab-size is {vocab_size}")
    return data


def data_batch(data, rng, batch_size, seq_len):
    """Random [batch, seq_len+1] windows from the flat stream — the same
    sampler on the single-chip and model-parallel paths. Gathered in
    numpy and shipped as ONE host-to-device transfer; maxval is
    exclusive, so len-seq_len admits the last valid window start."""
    idx = np.asarray(jax.random.randint(rng, (batch_size,), 0,
                                        len(data) - seq_len))
    return jnp.asarray(np.stack([data[i:i + seq_len + 1] for i in idx]))


# --------------------------------------------------------------------------
# Model-parallel tier: Megatron-composed LM over a (data, pipe, model) mesh.
#
# Reference composition (SURVEY P22-P24, §4.5): Megatron trainers drive
# apex's ColumnParallelLinear/RowParallelLinear (TP) and the 1F1B pipeline
# schedules through a training loop with amp O2 master weights + the dynamic
# loss scaler. This is that loop, TPU-first: blocks pipelined with the
# hand-scheduled collective-permute 1F1B (activation memory flat in the
# microbatch count; in-flight bound in schedules.forward_backward_1f1b), QKV/MLP
# column+row-parallel over 'model', DDP as one grad psum over 'data',
# embedding/head replicated with grads completed via the 1F1B
# input-cotangent / loss-param hooks, all inside ONE jitted train step built
# by amp.make_train_step(grad_fn=...) — unscale -> found_inf -> skip/step ->
# master->model copy semantics identical to the single-chip path.
# --------------------------------------------------------------------------

def build_parallel_lm(args, policy):
    """Build (mesh, state, jit_step, n_params) for the dp x tp x pp LM.

    Returns a jitted ``step(state, tokens) -> (state, metrics)`` already
    shard_mapped over the mesh; ``tokens`` is the GLOBAL int32 batch
    ``[B, seq_len+1]``, sharded over 'data' by the step itself.
    """
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.utils.compat import shard_map

    from apex_tpu import comm
    from apex_tpu.kernels.layer_norm import layer_norm
    from apex_tpu.models.transformer_lm import _LM_SIZES
    from apex_tpu.transformer import pipeline_parallel as pp_mod
    from apex_tpu.transformer.tensor_parallel.layers import (
        ColumnParallelLinear, RowParallelLinear)

    dp, tp = args.data_parallel, args.tensor_parallel
    pp, vpp = args.pipeline_parallel, args.virtual_pipeline
    gspmd = getattr(args, "partitioning", "shard_map") == "gspmd"
    hidden, layers, heads = _LM_SIZES[args.size]
    if args.layers:
        layers = args.layers
    L = pp * vpp
    if layers % L:
        raise SystemExit(f"--size {args.size} has {layers} layers; needs "
                         f"layers % (pp*vpp) == 0, got pp*vpp={L}")
    if vpp > 1 and pp == 1:
        raise SystemExit("--virtual-pipeline needs --pipeline-parallel > 1")
    if heads % tp:
        raise SystemExit(f"heads {heads} must divide by tp {tp}")
    if hidden % heads:
        raise SystemExit(f"hidden {hidden} must divide by heads {heads}")
    sp_on = bool(args.sequence_parallel)
    if sp_on and tp < 2:
        raise SystemExit("--sequence-parallel needs --tensor-parallel > 1")
    if sp_on and args.seq_len % tp:
        raise SystemExit(f"--seq-len {args.seq_len} must divide by tp {tp} "
                         "under --sequence-parallel")
    vp_on = bool(args.vocab_parallel)
    if vp_on and tp < 2:
        raise SystemExit("--vocab-parallel needs --tensor-parallel > 1")
    if vp_on and sp_on:
        raise SystemExit("--vocab-parallel and --sequence-parallel are "
                         "currently exclusive (the head's seq layouts "
                         "differ)")
    if vp_on and args.vocab_size % tp:
        raise SystemExit(f"--vocab-size {args.vocab_size} must divide by "
                         f"tp {tp} under --vocab-parallel")
    zero_on = bool(args.zero)
    if zero_on and dp < 2:
        raise SystemExit("--zero needs --data-parallel > 1")
    if gspmd and (pp > 1 or vpp > 1 or sp_on or vp_on):
        raise SystemExit(
            "--partitioning gspmd drives dp x tp (optionally --zero); "
            "pipeline/sequence/vocab-parallel run under the (default) "
            "shard_map path")
    # Under GSPMD the module MATH is the 1-device program (world 1, no
    # mappings.py collectives); tp lives only in the sharding specs.
    tpm = 1 if gspmd else tp
    per_stage = layers // L
    H, V, S = hidden, args.vocab_size, args.seq_len
    inner = 4 * H
    M = args.microbatches or 2 * pp
    B = args.batch_size
    if B % dp or (B // dp) % M:
        raise SystemExit(f"batch {B} must divide by dp*microbatches "
                         f"({dp}*{M})")
    n_dev = dp * pp * tp
    devices = comm.ensure_devices(n_dev)
    mesh = Mesh(np.array(devices[:n_dev]).reshape(dp, pp, tp),
                ("data", "pipe", "model"))

    h_local, d_head = heads // tpm, H // heads
    mdt = policy.model_dtype  # thread into the TP modules (ADVICE round-2)
    # Under SP the column linears all-gather the sequence (dim 0 — hence
    # the recipe's seq-first [s, mb, H] activation layout) and the row
    # linears reduce-scatter it back: the TP allreduce split into its two
    # halves around the seq-sharded LN/residual region (SURVEY §3.3 SP).
    col_qkv = ColumnParallelLinear(input_size=H, output_size=3 * H,
                                   use_bias=False, world_size=tpm, dtype=mdt,
                                   sequence_parallel_enabled=sp_on)
    row_proj = RowParallelLinear(input_size=H, output_size=H, use_bias=True,
                                 input_is_parallel=True, world_size=tpm,
                                 dtype=mdt,
                                 sequence_parallel_enabled=sp_on)
    col_mlp = ColumnParallelLinear(input_size=H, output_size=inner,
                                   use_bias=False, world_size=tpm, dtype=mdt,
                                   sequence_parallel_enabled=sp_on)
    row_mlp = RowParallelLinear(input_size=inner, output_size=H,
                                use_bias=True, input_is_parallel=True,
                                world_size=tpm, dtype=mdt,
                                sequence_parallel_enabled=sp_on)

    # ---- parameters. TP-sharded leaves ("col") carry an explicit model-
    # shard dim [L, tp, per_stage, ...] so the HOST holds the full weight
    # and shard_map hands each (pipe, model) rank its own block — the
    # functional analogue of the reference's _initialize_affine_weight_gpu
    # scatter (the full weight is drawn in canonical layout and split, so
    # the same seed yields the same MATH at every dp/tp/pp — testable
    # against the 1-device configuration). Replicated-per-stage leaves
    # ("rep") are [L, per_stage, ...].
    def init_params(rng):
        def nrm(k, shape, std):
            return (jax.random.normal(k, shape) * std).astype(jnp.float32)

        ks = iter(jax.random.split(rng, 8))
        # canonical full weights; head dim layout [3, heads, d_head]
        qkv_full = nrm(next(ks), (L, per_stage, H, 3, heads, d_head), 0.02)
        proj_full = nrm(next(ks), (L, per_stage, heads, d_head, H), 0.02)
        mlp_in_full = nrm(next(ks), (L, per_stage, H, inner), 0.02)
        mlp_out_full = nrm(next(ks), (L, per_stage, inner, H), 0.02)
        col = {
            # rank r owns heads [r*h_local, (r+1)*h_local)
            "qkv_k": jnp.stack(
                [qkv_full[:, :, :, :, r * h_local:(r + 1) * h_local]
                 .reshape(L, per_stage, H, 3 * H // tpm)
                 for r in range(tpm)], axis=1),
            "proj_k": jnp.stack(
                [proj_full[:, :, r * h_local:(r + 1) * h_local]
                 .reshape(L, per_stage, H // tpm, H)
                 for r in range(tpm)], axis=1),
            "mlp_in_k": jnp.stack(
                [mlp_in_full[..., r * (inner // tpm):(r + 1) * (inner // tpm)]
                 for r in range(tpm)], axis=1),
            "mlp_out_k": jnp.stack(
                [mlp_out_full[:, :, r * (inner // tpm):(r + 1) * (inner // tpm)]
                 for r in range(tpm)], axis=1),
        }
        rep = {
            "ln1_s": jnp.ones((L, per_stage, H)),
            "ln1_b": jnp.zeros((L, per_stage, H)),
            "ln2_s": jnp.ones((L, per_stage, H)),
            "ln2_b": jnp.zeros((L, per_stage, H)),
            "proj_b": jnp.zeros((L, per_stage, H)),
            "mlp_out_b": jnp.zeros((L, per_stage, H)),
        }
        emb = {"wte": nrm(next(ks), (V, H), 0.02),
               "wpe": nrm(next(ks), (S, H), 0.01)}
        head_full = nrm(next(ks), (H, V), 0.02)
        if vp_on:
            # Megatron parallel head: vocab columns split over tp; drawn
            # full-first so the math is tp-invariant like the col leaves
            head_k = jnp.stack(
                [head_full[:, r * (V // tp):(r + 1) * (V // tp)]
                 for r in range(tp)], axis=0)       # [tp, H, V/tp]
        else:
            head_k = head_full
        head = {"ln_s": jnp.ones((H,)), "ln_b": jnp.zeros((H,)),
                "kernel": head_k}
        return {"emb": emb, "stages": {"col": col, "rep": rep},
                "head": head}

    order = _stage_order(pp, vpp)

    def maybe_rep(p):
        # Under SP, LN/bias params act on seq-LOCAL activations, so each
        # model rank's grad is partial: identity-fwd/psum-bwd completes it
        # (Megatron's SP LN-grad allreduce; mappings.copy_to_...).
        if sp_on:
            from apex_tpu.transformer.tensor_parallel.mappings import (
                copy_to_tensor_model_parallel_region)
            return copy_to_tensor_model_parallel_region(p, "model")
        return p

    def block_fn(bp, x):
        # x: [s_local_or_s, mb, H] — seq-first (the SP shard dim is dim 0)
        mb = x.shape[1]
        cdt = x.dtype
        h = layer_norm(x.reshape(-1, H), maybe_rep(bp["rep"]["ln1_s"]),
                       maybe_rep(bp["rep"]["ln1_b"])
                       ).reshape(x.shape).astype(cdt)
        qkv = col_qkv.apply({"params": {"kernel": bp["col"]["qkv_k"]}}, h)
        s_full = qkv.shape[0]              # SP: seq gathered back to full
        qkv = qkv.reshape(s_full, mb, 3, h_local, d_head)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        att = jnp.einsum("qbhd,kbhd->bhqk", q, k)
        # N8 fused path: scale+causal-mask+softmax in one Pallas pass
        # (fp32 math, half I/O), jnp fallback on unaligned shapes
        from apex_tpu.transformer.functional.fused_softmax import (
            scaled_upper_triang_masked_softmax)
        att = scaled_upper_triang_masked_softmax(
            att, scale=float(1.0 / np.sqrt(d_head))).astype(cdt)
        ctx = jnp.einsum("bhqk,kbhd->qbhd", att, v).reshape(
            s_full, mb, h_local * d_head)
        x = x + row_proj.apply(
            {"params": {"kernel": bp["col"]["proj_k"],
                        "bias": maybe_rep(bp["rep"]["proj_b"])}},
            ctx).astype(cdt)
        h = layer_norm(x.reshape(-1, H), maybe_rep(bp["rep"]["ln2_s"]),
                       maybe_rep(bp["rep"]["ln2_b"])
                       ).reshape(x.shape).astype(cdt)
        h = col_mlp.apply({"params": {"kernel": bp["col"]["mlp_in_k"]}}, h)
        # tanh GELU, matching models/transformer_lm.py EXACTLY — this
        # block IS the single-chip model's math under TP sharding, and
        # the parallel-vs-oracle trajectory parity is asserted bitwise
        h = jax.nn.gelu(jnp.asarray(h, jnp.float32),
                        approximate=True).astype(cdt)
        h = row_mlp.apply({"params": {"kernel": bp["col"]["mlp_out_k"],
                                      "bias": maybe_rep(
                                          bp["rep"]["mlp_out_b"])}}, h)
        return (x + h.astype(cdt)).astype(cdt)

    def stage_fn(sp, x):
        for i in range(per_stage):
            bp = jax.tree_util.tree_map(lambda l: l[i], sp)
            x = block_fn(bp, x)
        return x

    def lm_loss(y, tgt, head):
        # y: [s_local_or_s, mb, H], tgt: [s_local_or_s, mb] (seq-first).
        # head params are used RAW (no maybe_rep): under SP every head
        # grad (LN and kernel alike) is seq-chunk-partial and the caller
        # psums the whole head tree over 'model' once — mixing in
        # copy_to's psum-bwd here would double-count the LN grads.
        hh = layer_norm(y.reshape(-1, H), head["ln_s"], head["ln_b"])
        if vp_on:
            if args.fused_head:
                # fused vocab-parallel tail (kernels/lm_head_loss.py
                # axis_name mode): the op emits copy_to's psum-bwd on
                # dx itself and fuses Megatron's CE reductions into the
                # chunked head GEMM — the [S*mb, V_loc] logits never
                # materialize. head["kernel"] is [H, V_loc]; the .T
                # view fuses into the chunk GEMMs' dimension numbers.
                from apex_tpu.kernels.lm_head_loss import lm_head_xentropy
                losses = lm_head_xentropy(
                    hh, head["kernel"].T, tgt.reshape(-1),
                    smoothing=args.smoothing, compute_dtype=y.dtype,
                    axis_name="model")
                return losses.mean()
            # Megatron parallel-LM-head rule (P23): the head input goes
            # through copy_to (identity fwd, psum bwd) so every vocab
            # shard back-props the FULL dL/dh; the local logits block
            # feeds the all-reduce-based parallel cross entropy. Head
            # grads come out complete per shard (kernel: its vocab
            # block; LN: identical on every rank) — no caller psum.
            from apex_tpu.transformer.tensor_parallel import (
                copy_to_tensor_model_parallel_region,
                vocab_parallel_cross_entropy)
            hh = copy_to_tensor_model_parallel_region(hh, "model")
            logits = jnp.dot(jnp.asarray(hh, y.dtype),
                             jnp.asarray(head["kernel"], y.dtype))
            losses = vocab_parallel_cross_entropy(
                logits, tgt.reshape(-1), label_smoothing=args.smoothing,
                axis_name="model")
            return losses.mean()
        logits = jnp.dot(jnp.asarray(hh, y.dtype),
                         jnp.asarray(head["kernel"], y.dtype))
        losses = softmax_cross_entropy_loss(
            jnp.asarray(logits, jnp.float32), tgt.reshape(-1),
            smoothing=args.smoothing)
        l = losses.mean()
        if sp_on:
            # each model rank sees a seq chunk; return local/tp so the
            # collective transposes make the optimized objective the
            # GLOBAL mean, and psum value-only so the reported loss is
            # the global mean too (testing.build_full_parallel_step's
            # mb_loss rule)
            l = l / tp
            l = l + jax.lax.stop_gradient(jax.lax.psum(l, "model") - l)
        return l

    cdtype = policy.compute_dtype
    s_loc = S // tp if sp_on else S

    def slice_wpe(wpe):
        """This rank's position-embedding rows under SP (full rows else)."""
        if sp_on:
            wpe = jax.lax.dynamic_slice_in_dim(
                wpe, jax.lax.axis_index("model") * s_loc, s_loc, axis=0)
        return wpe

    def _psum_model(tree):
        return jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, "model"), tree)

    def grad_fn(params, batch, loss_scale):
        tokens = batch                               # [B/dp, S+1] int32
        # seq-first streams: [M, S, mb]
        inp = tokens[:, :-1].reshape(M, -1, S).transpose(0, 2, 1)
        tgt = tokens[:, 1:].reshape(M, -1, S).transpose(0, 2, 1)
        if sp_on:
            # slice token ids (not embeddings) to the rank's chunk: the
            # lookup then costs 1/tp, and the vjp scatter only touches
            # local positions (psum over 'model' completes demb)
            mr = jax.lax.axis_index("model")
            tgt = jax.lax.dynamic_slice_in_dim(tgt, mr * s_loc, s_loc,
                                               axis=1)
            inp = jax.lax.dynamic_slice_in_dim(inp, mr * s_loc, s_loc,
                                               axis=1)

        def embed(ep):
            wpe = slice_wpe(jnp.asarray(ep["wpe"], cdtype))
            return jnp.asarray(ep["wte"], cdtype)[inp] \
                + wpe[None, :, None, :]        # [M, s_loc, mb, H]

        # strip the model-shard dim shard_map left on the col leaves
        sp_local = {"col": jax.tree_util.tree_map(lambda l: l[:, 0],
                                                  params["stages"]["col"]),
                    "rep": params["stages"]["rep"]}
        if vpp == 1:
            sp_local = jax.tree_util.tree_map(lambda l: l[0], sp_local)
        head_local = dict(params["head"])
        if vp_on:
            head_local["kernel"] = params["head"]["kernel"][0]

        def pack_head_grads(hg):
            if vp_on:
                hg = dict(hg)
                hg["kernel"] = hg["kernel"][None]
            return hg

        if pp == 1:
            # TP-only (no pipe axis): reference fwd_bwd_no_pipelining —
            # grad accumulation over the microbatch stream
            def mb_loss_fn(p3, mb_tokens, t3):
                # mb_tokens: [s_loc, mb] seq-first (pre-sliced under SP)
                wpe = slice_wpe(jnp.asarray(p3["emb"]["wpe"], cdtype))
                x = jnp.asarray(p3["emb"]["wte"], cdtype)[mb_tokens] \
                    + wpe[:, None, :]
                return lm_loss(stage_fn(p3["sp"], x), t3, p3["head"])

            loss, g3 = pp_mod.forward_backward_no_pipelining(
                mb_loss_fn,
                {"emb": params["emb"], "sp": sp_local,
                 "head": head_local},
                inp, tgt, accum_dtype=jnp.float32)
            g3 = jax.tree_util.tree_map(
                lambda g: g * jnp.asarray(loss_scale, g.dtype), g3)
            emb_g, head_g = g3["emb"], g3["head"]
            if sp_on:
                # per-rank seq chunks contribute partial emb/head grads
                emb_g, head_g = _psum_model(emb_g), _psum_model(head_g)
            sgrads = g3["sp"]
            if vpp == 1:
                sgrads = jax.tree_util.tree_map(lambda g: g[None], sgrads)
            return loss, {
                "emb": emb_g,
                "stages": {"col": jax.tree_util.tree_map(
                    lambda g: g[:, None], sgrads["col"]),
                    "rep": sgrads["rep"]},
                "head": pack_head_grads(head_g),
            }

        x_stream, emb_vjp = jax.vjp(embed, params["emb"])
        loss, sgrads, aux = pp_mod.forward_backward_1f1b(
            stage_fn, lm_loss, sp_local, x_stream, tgt,
            num_stages=pp, num_chunks=vpp, loss_scale=loss_scale,
            loss_params=head_local, return_input_cotangents=True)
        if vpp == 1:
            sgrads = jax.tree_util.tree_map(lambda g: g[None], sgrads)
        (demb,) = emb_vjp(jnp.asarray(aux["input_cotangents"],
                                      x_stream.dtype))
        head_g = aux["loss_param_grads"]
        if sp_on:
            demb, head_g = _psum_model(demb), _psum_model(head_g)
        return loss, {
            "emb": demb,
            "stages": {"col": jax.tree_util.tree_map(lambda g: g[:, None],
                                                     sgrads["col"]),
                       "rep": sgrads["rep"]},
            "head": pack_head_grads(head_g),
        }

    if zero_on and not gspmd:
        _inner_grad_fn = grad_fn

        def grad_fn(params, batch, loss_scale):  # noqa: F811
            loss, grads = _inner_grad_fn(params, batch, loss_scale)
            # the grad psum normally pmean's the reported loss inside
            # make_train_step; ZeRO hands grads over un-averaged, so the
            # metric needs the global-batch mean here
            return jax.lax.pmean(loss, "data"), grads

        # ZeRO (contrib DistributedFusedAdam): the transformation does its
        # own mean-reduce-scatter over 'data', updates its 1/dp state
        # shard, and all-gathers params — so grads are handed over
        # UN-averaged (grad_average_axis=None) and found_inf must sync
        # over 'data' explicitly (no grad psum carries the infs).
        from apex_tpu.contrib.optimizers import distributed_fused_adam
        optimizer = distributed_fused_adam(
            args.lr, weight_decay=args.weight_decay, adam_w_mode=True,
            axis_name="data", world_size=dp)
        grad_avg_axis = None
    else:
        # plain fused_adam — including gspmd --zero, where ZeRO-1 is a
        # sharding SPEC on the m/v superbuffers (_finish_gspmd), not a
        # different optimizer. That spec (P('data') on a 1-D buffer) is
        # what forces layout="flat" there; every other path defaults to
        # the per-leaf tree layout (round 5 — 4x less optimizer time).
        layout = "flat" if (zero_on and gspmd) else args.opt_layout
        optimizer = fused_adam(args.lr, weight_decay=args.weight_decay,
                               adam_w_mode=True, layout=layout)
        grad_avg_axis = "data" if dp > 1 else None
    # stage/col leaves are shard-local to pipe/model: their infs never ride
    # a grad psum, so found_inf must sync explicitly (make_train_step docs)
    sync = tuple(ax for ax, size in (("pipe", pp), ("model", tp))
                 if size > 1)
    if zero_on:
        sync = ("data",) + sync
    if gspmd:
        # one LOGICAL program: the loss is the global-batch mean and the
        # grads are its true gradients — XLA's SPMD partitioner inserts
        # the data-parallel reduction itself, and found_inf is a single
        # global value (no axis to sync over)
        grad_avg_axis, sync = None, ()
    init_fn, step_fn = amp.make_train_step(
        None, optimizer, policy, grad_fn=grad_fn,
        grad_average_axis=grad_avg_axis,
        overflow_sync_axes=sync or None,
        telemetry=bool(args.telemetry))

    params = init_params(jax.random.PRNGKey(args.seed))
    params["stages"] = jax.tree_util.tree_map(
        lambda l: l[order], params["stages"])

    def _keys(path):
        return [getattr(k, "key", getattr(k, "name", None)) for k in path]

    if gspmd:
        return _finish_gspmd(args, mesh, init_fn, step_fn, params, _keys,
                             H=H, V=V, inner=inner, tp=tp, zero=zero_on)

    def param_spec(path, _leaf):
        keys = _keys(path)
        if "col" in keys:
            return P("pipe", "model")
        if "stages" in keys:
            return P("pipe")
        if vp_on and "head" in keys and "kernel" in keys:
            return P("model")
        return P()

    pspec = jax.tree_util.tree_map_with_path(param_spec, params)

    # Per-rank local param shapes → the amp state (masters, scaler, and
    # fused_adam's FLAT m/v superbuffers) must be created INSIDE shard_map
    # so each rank's optimizer state covers exactly its own shards.
    def local_struct(path, l):
        keys = _keys(path)
        shape = list(l.shape)
        if "col" in keys:
            shape[0] //= pp
            shape[1] //= tp
        elif "stages" in keys:
            shape[0] //= pp
        elif vp_on and "head" in keys and "kernel" in keys:
            shape[0] //= tp
        return jax.ShapeDtypeStruct(tuple(shape), l.dtype)

    local_params = jax.tree_util.tree_map_with_path(local_struct, params)
    state_shapes = jax.eval_shape(init_fn, local_params)

    def state_spec(path, sds):
        keys = _keys(path)
        if "col" in keys:
            return P("pipe", "model")
        if "stages" in keys:
            return P("pipe")
        if vp_on and "head" in keys and "kernel" in keys:
            return P("model")
        if zero_on and ("m_shard" in keys or "v_shard" in keys):
            # ZeRO m/v shard (DistAdamState fields, matched by name):
            # rank-local over data AND (pipe, model)
            return P(("data", "pipe", "model"))
        if keys and keys[-1] in ("m", "v") and len(sds.shape) == 1:
            # flat superbuffer (FusedAdamState.m/.v, matched by field
            # name — ADVICE r3: a coincidental same-size 1-D leaf must
            # not be swept in): rank-local, stacked over the
            # (pipe, model) product on the global axis
            return P(("pipe", "model"))
        return P()

    sspec = jax.tree_util.tree_map_with_path(state_spec, state_shapes)
    sharded_init = jax.jit(shard_map(init_fn, mesh=mesh, in_specs=(pspec,),
                                     out_specs=sspec, check_vma=False))
    state = sharded_init(params)

    sharded = shard_map(step_fn, mesh=mesh,
                        in_specs=(sspec, P("data")),
                        out_specs=(sspec, P()), check_vma=False)
    jit_step = jax.jit(sharded, donate_argnums=(0,))
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    return mesh, state, jit_step, n_params


def _finish_gspmd(args, mesh, init_fn, step_fn, params, _keys, *,
                  H, V, inner, tp, zero=False):
    """The GSPMD/pjit tier (SURVEY §3.3 TP row: "pjit with sharded weight
    specs — the mappings collapse into sharding constraints").

    The step is the SAME 1-device program build_parallel_lm composed (tp=1
    module math, no mappings.py collectives, no shard_map); the dp x tp
    distribution comes ENTIRELY from NamedShardings built from the TP
    modules' own ``kernel_partition_spec()``: column kernels P(None,
    'model'), row kernels P('model', None), the embedding table vocab-
    sharded P('model', None), the LM head as a vocab-column parallel
    linear, the batch P('data'). XLA's SPMD partitioner inserts the TP
    all-reduces and the DP grad reduction that the shard_map path spells
    out explicitly — trajectory parity between the two paths and the
    1-device oracle is asserted in tests/distributed/
    test_lm_gspmd.py. fp32 masters ride the same specs as their params.

    ``zero`` (--zero under gspmd) is ZeRO-1 the GSPMD way: the flat
    Adam m/v superbuffers get ``P('data')`` — one spec line, no
    collective code — so each device holds 1/dp of the optimizer state
    (GSPMD pads non-divisible lengths). The shard_map path implements
    the same semantics explicitly (contrib DistributedFusedAdam:
    psum_scatter → shard-local update → all_gather); without ``zero``
    the superbuffers stay replicated.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu.transformer.tensor_parallel.layers import (
        ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)

    B, S = args.batch_size, args.seq_len
    # the specs come from the MODULES — these four instances are the
    # single source of truth for how each kernel class shards over tp
    spec_col = ColumnParallelLinear(
        input_size=H, output_size=3 * H,
        world_size=tp).kernel_partition_spec()        # P(None, 'model')
    spec_row = RowParallelLinear(
        input_size=inner, output_size=H,
        world_size=tp).kernel_partition_spec()        # P('model', None)
    spec_emb = VocabParallelEmbedding(
        num_embeddings=V, embedding_dim=H,
        world_size=tp).kernel_partition_spec()        # P('model', None)
    spec_head = ColumnParallelLinear(
        input_size=H, output_size=V,
        world_size=tp).kernel_partition_spec()        # vocab-column head

    matrix_spec = {"qkv_k": spec_col, "mlp_in_k": spec_col,
                   "proj_k": spec_row, "mlp_out_k": spec_row}

    def extend(spec, ndim):
        # col leaves are stacked [L=1, shard=1, layers, <matrix dims>]:
        # the module spec names the trailing matrix dims, leading stack
        # dims stay replicated
        return P(*([None] * (ndim - len(spec)) + list(spec)))

    def leaf_spec(path, leaf):
        keys = _keys(path)
        ndim = len(getattr(leaf, "shape", ()))
        if "col" in keys:
            return extend(matrix_spec[keys[-1]], ndim)
        if "wte" in keys:
            return spec_emb
        if "head" in keys and "kernel" in keys:
            return spec_head
        if zero and keys and keys[-1] in ("m", "v") and ndim == 1:
            # ZeRO-1 as a sharding spec: the flat Adam superbuffers
            # (FusedAdamState.m/.v, matched by field name like the
            # shard_map path's state_spec) live 1/dp per device
            return P("data")
        return P()

    state_shapes = jax.eval_shape(init_fn, params)
    state_sh = jax.tree_util.tree_map_with_path(
        lambda path, sds: NamedSharding(mesh, leaf_spec(path, sds)),
        state_shapes)
    batch_struct = jax.ShapeDtypeStruct((B, S + 1), jnp.int32)
    batch_sh = NamedSharding(mesh, P("data"))
    metrics_shapes = jax.eval_shape(step_fn, state_shapes, batch_struct)[1]
    metrics_sh = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P()), metrics_shapes)

    state = jax.jit(init_fn, out_shardings=state_sh)(params)
    jit_step = jax.jit(step_fn, in_shardings=(state_sh, batch_sh),
                       out_shardings=(state_sh, metrics_sh),
                       donate_argnums=(0,))
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    return mesh, state, jit_step, n_params


def _stage_order(pp, vpp):
    """Rank-major pipe layout: global row r*vpp + c holds logical stage
    c*pp + r (the interleaved schedule's round-robin split). Shared by the
    scatter in build_parallel_lm and its inverse in canonicalize_params."""
    return np.asarray([c * pp + r for r in range(pp) for c in range(vpp)])


def canonicalize_params(params, *, pp, vpp, heads, vocab_parallel=False):
    """Invert build_parallel_lm's (pipe, model) scatter back to the
    canonical full-weight layout init_params drew from.

    The scatter is pure layout — rank-major stage permutation, explicit tp
    shard dim on the "col" leaves, vocab-column split on the parallel head
    — so two runs at different dp/tp/pp agree iff their canonicalized
    trees agree. This is the reference's cross-rank master-param
    consistency check (SURVEY §5 — amp_master_params/compare.py) in
    functional form: tests and the multichip dryrun compare WHOLE final
    param/master trees, not a loss scalar.
    """
    inv = np.argsort(_stage_order(pp, vpp))

    def unstage(l):
        # global row i holds logical stage order[i]; sort rows into
        # logical-stage order, then flatten [L, per_stage, ...] -> layers
        l = l[inv]
        return l.reshape((l.shape[0] * l.shape[1],) + l.shape[2:])

    col = params["stages"]["col"]
    qkv = col["qkv_k"][inv]            # [L, tp, per_stage, H, 3H/tp]
    Ld, tpd, per_stage, H = qkv.shape[:4]
    d_head = H // heads
    h_local = heads // tpd
    qkv_full = jnp.concatenate(
        [qkv[:, r].reshape(Ld, per_stage, H, 3, h_local, d_head)
         for r in range(tpd)], axis=4)
    proj = col["proj_k"][inv]          # [L, tp, per_stage, H/tp, H]
    proj_full = jnp.concatenate(
        [proj[:, r].reshape(Ld, per_stage, h_local, d_head, H)
         for r in range(tpd)], axis=2)
    mlp_in_full = jnp.concatenate(     # [L, tp, per_stage, H, inner/tp]
        [col["mlp_in_k"][inv][:, r] for r in range(tpd)], axis=-1)
    mlp_out_full = jnp.concatenate(    # [L, tp, per_stage, inner/tp, H]
        [col["mlp_out_k"][inv][:, r] for r in range(tpd)], axis=2)

    def layers_first(l):
        return l.reshape((Ld * per_stage,) + l.shape[2:])

    head = dict(params["head"])
    if vocab_parallel:                 # [tp, H, V/tp] -> [H, V]
        head["kernel"] = jnp.concatenate(
            [head["kernel"][r] for r in range(head["kernel"].shape[0])],
            axis=-1)
    return {
        "emb": params["emb"],
        "stages": {
            "qkv": layers_first(qkv_full),
            "proj": layers_first(proj_full),
            "mlp_in": layers_first(mlp_in_full),
            "mlp_out": layers_first(mlp_out_full),
            **{k: unstage(v) for k, v in params["stages"]["rep"].items()},
        },
        "head": head,
    }


def canonicalize_from_args(params, args):
    """canonicalize_params with the knobs read off the parsed recipe args."""
    from apex_tpu.models.transformer_lm import _LM_SIZES
    heads = _LM_SIZES[args.size][2]
    return canonicalize_params(params, pp=args.pipeline_parallel,
                               vpp=args.virtual_pipeline, heads=heads,
                               vocab_parallel=bool(args.vocab_parallel))


def assert_trees_close(got, want, rtol=2e-4, atol=5e-5):
    """Leaf-for-leaf allclose over whole pytrees, failing with the leaf's
    key path. Shared by the hermetic parity tests and the multichip
    dryrun so both certify the same canonicalized-tree agreement.

    atol is 5e-5, not 1e-5: parallel-vs-sequential reduction order is
    legitimate fp32 roundoff, and the tanh-GELU switch showed single
    elements (1 in 1e5) landing at ~2e-5 — reduction-order noise passed
    through the nonlinearity's curvature, not a parity bug."""
    jax.tree_util.tree_map_with_path(
        lambda path, a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=rtol, atol=atol,
            err_msg=jax.tree_util.keystr(path)),
        got, want)


def run_parallel(args, policy):
    if args.iters < 1:
        raise SystemExit("--iters must be >= 1")
    if args.remat:
        raise SystemExit("--remat is not supported on the model-parallel "
                         "path (the 1F1B schedule already recomputes "
                         "in-backward); drop the flag")
    tele = _maybe_telemetry(args)   # sink must exist before the first step
    mesh, state, jit_step, n_params = build_parallel_lm(args, policy)
    print(f"=> LM {args.size} dp={args.data_parallel} "
          f"tp={args.tensor_parallel} pp={args.pipeline_parallel} "
          f"vpp={args.virtual_pipeline}"
          f"{' sp' if args.sequence_parallel else ''}"
          f"{' vocab-parallel' if args.vocab_parallel else ''}"
          f"{' zero' if args.zero else ''}"
          f"{' gspmd' if args.partitioning == 'gspmd' else ''}, "
          f"params: {n_params:,}")
    data = None
    if args.data:
        data = load_token_stream(args.data, args.vocab_size, args.seq_len)
    rng = jax.random.PRNGKey(args.seed)
    state, start_it, rng = _maybe_resume(args, state, rng)
    t0, toks, metrics = None, 0, None
    loss_history = []
    with mesh:
        for it in range(start_it, args.iters):
            rng, sub = jax.random.split(rng)
            if args.deterministic:
                sub = jax.random.PRNGKey(it)
            if data is not None:
                batch = data_batch(data, sub, args.batch_size,
                                   args.seq_len)
            else:
                batch = synthetic_tokens(sub, args.batch_size,
                                         args.seq_len, args.vocab_size)
            state, metrics = jit_step(state, batch)
            loss_history.append(metrics["loss"])
            if it == start_it + 2:
                metrics["loss"].block_until_ready()
                t0 = time.perf_counter()
                toks = 0
            toks += args.batch_size * args.seq_len
            if it % 10 == 0 or it == args.iters - 1:
                print(f"[{it}/{args.iters}] loss "
                      f"{float(metrics['loss']):.4f} loss_scale "
                      f"{float(metrics['loss_scale']):g}")
    jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
    if t0 is not None and args.iters - start_it > 3:
        dt = time.perf_counter() - t0
        print(f"throughput: "
              f"{(toks - args.batch_size * args.seq_len) / dt:,.0f} tokens/s")
    _maybe_prof_device(args, jit_step, state, batch)
    _maybe_save(args, state, rng)
    _finish_telemetry(tele)
    metrics = dict(metrics)
    metrics["final_state"] = state
    # one device-to-host transfer for the whole history
    metrics["loss_history"] = np.asarray(jnp.stack(loss_history),
                                         np.float32).tolist()
    return metrics


def _maybe_telemetry(args):
    """--telemetry SPEC: fresh default registry + sink (JSONL path,
    'stdout', 'null'); the step's in-jit emission lands there."""
    if not args.telemetry:
        return None
    from apex_tpu import telemetry
    return telemetry.start_run(args.telemetry)


def _finish_telemetry(tele):
    if tele is None:
        return
    jax.effects_barrier()      # flush in-flight step callbacks
    tele.emit_snapshot()       # final aggregate + comm-health line
    tele.close()


def _maybe_resume(args, state, rng):
    """--resume via the shared helper (jit re-shards the restored host
    arrays per the step's in_specs on entry, so the same call serves the
    single-chip and shard_mapped paths)."""
    if not args.resume:
        return state, 0, rng
    from apex_tpu.utils.checkpoint import resume_train_checkpoint
    return resume_train_checkpoint(args.resume, state, rng,
                                   step_limit=args.iters,
                                   limit_flag="--iters")


def _maybe_prof_device(args, jit_step, state, batch):
    """--prof-device N: print device tokens/s for N extra steps via
    pyprof.device_throughput_line (observation-only — copied state,
    never raises; see pyprof.step_device_throughput's docstring)."""
    from apex_tpu import pyprof

    line = pyprof.device_throughput_line(
        jit_step, state, batch, args.prof_device,
        args.batch_size * args.seq_len, "tokens/s")
    if line:
        print(line)


def _maybe_save(args, state, rng):
    if not args.save:
        return
    from apex_tpu.utils.checkpoint import save_train_checkpoint
    save_train_checkpoint(args.save, state, args.iters, rng)


def _maybe_generate(args, model, params, tele):
    """--generate N: serve synthetic variable-length prompts through the
    compiled KV-cache engine (apex_tpu.serving) with the just-trained
    params — the recipe's end-to-end inference leg. Returns the
    completed requests (for callers/tests inspecting the outputs)."""
    if not args.generate:
        return None
    import numpy as _np

    from apex_tpu import serving

    plen = min(args.gen_prompt_len, args.seq_len - 1)
    max_len = min(args.seq_len, plen + args.generate)
    engine = serving.Engine(model, params, slots=args.gen_slots,
                            max_len=max_len, prefill_len=plen,
                            top_k=args.gen_top_k, registry=tele)
    sched = serving.Scheduler(engine, registry=tele,
                              max_queue=max(args.gen_prompts, 1))
    rng = _np.random.default_rng(args.seed)
    reqs = [serving.Request(
        prompt=rng.integers(1, args.vocab_size,
                            size=int(rng.integers(1, plen + 1))).tolist(),
        max_new_tokens=args.generate, temperature=args.gen_temperature)
        for _ in range(args.gen_prompts)]
    t0 = time.perf_counter()
    done = sched.run(reqs)
    dt = time.perf_counter() - t0
    toks = sum(len(r.output_tokens) for r in done)
    ttfts = [r.ttft_s for r in done if r.ttft_s is not None]
    print(f"=> generate: {len(done)} requests, {toks} tokens in "
          f"{dt:.2f}s ({toks / dt:,.0f} tokens/s), "
          f"ttft p50 {sorted(ttfts)[len(ttfts) // 2] * 1e3:.1f} ms, "
          f"compiled programs: {engine.compiled_programs}")
    preview = done[0]
    print(f"   sample [{preview.finish_reason}]: "
          f"{list(preview.prompt)[:8]}... -> "
          f"{preview.output_tokens[:16]}")
    return done


def main(argv=None):
    args = parse_args(argv)
    if args.iters < 1:
        raise SystemExit("--iters must be >= 1")
    if args.accum_steps < 1:
        raise SystemExit("--accum-steps must be >= 1")
    if args.generate and (args.gen_prompts < 1 or args.gen_slots < 1
                          or args.gen_prompt_len < 1):
        raise SystemExit("--generate needs --gen-prompts, --gen-slots and "
                         "--gen-prompt-len all >= 1")
    if args.batch_size % args.accum_steps:
        raise SystemExit(f"--batch-size {args.batch_size} must divide by "
                         f"--accum-steps {args.accum_steps}")
    policy = amp.resolve_policy(opt_level=args.opt_level,
                                loss_scale=args.loss_scale)
    print(policy.banner())
    if (args.data_parallel * args.tensor_parallel
            * args.pipeline_parallel * args.virtual_pipeline) > 1:
        if args.generate:
            raise SystemExit(
                "--generate runs on the single-chip path only (the "
                "serving engine consumes the flax param tree, not the "
                "parallel tiers' scattered stage layout); drop the "
                "parallelism flags or serve from a --save checkpoint")
        if args.accum_steps > 1:
            raise SystemExit(
                "--accum-steps composes with the single-chip path only: "
                "the parallel tiers drive amp via grad_fn (1F1B / "
                "no-pipelining schedules), which already accumulate over "
                "--microbatches — raise --microbatches there instead")
        if args.fused_head and not args.vocab_parallel:
            raise SystemExit("--fused-head under the parallel tiers "
                             "needs --vocab-parallel AND "
                             "--tensor-parallel >= 2 (the fused op's "
                             "axis_name mode shards the head over "
                             "'model'); without them the replicated-"
                             "head tail keeps the materialized loss")
        if args.fused_head and getattr(args, "partitioning",
                                       "shard_map") == "gspmd":
            raise SystemExit("--fused-head is shard_map-only under "
                             "parallelism (gspmd keeps the materialized "
                             "vocab-parallel loss)")
        return run_parallel(args, policy)
    if args.partitioning == "gspmd":
        raise SystemExit("--partitioning gspmd needs a mesh: pass "
                         "--data-parallel and/or --tensor-parallel > 1")

    model = create_lm(args.size, vocab_size=args.vocab_size,
                      max_seq_len=args.seq_len, remat=args.remat,
                      dtype=policy.model_dtype)
    rng = jax.random.PRNGKey(args.seed)
    sample = jnp.zeros((2, args.seq_len), jnp.int32)
    params = model.init(rng, sample, train=False)["params"]

    optimizer = fused_adam(args.lr, weight_decay=args.weight_decay,
                           adam_w_mode=True)

    if args.fused_head:
        from apex_tpu.amp.autocast import resolve_dtype
        from apex_tpu.kernels.lm_head_loss import lm_head_xentropy
        head_dtype = resolve_dtype(policy.model_dtype, "linear",
                                   jnp.float32)

        def loss_fn(p, batch):
            tokens = batch
            hidden = model.apply({"params": p}, tokens[:, :-1], train=True,
                                 features_only=True)
            losses = lm_head_xentropy(hidden, p["wte"]["embedding"],
                                      tokens[:, 1:],
                                      smoothing=args.smoothing,
                                      compute_dtype=head_dtype)
            return losses.mean()
    else:
        def loss_fn(p, batch):
            tokens = batch
            logits = model.apply({"params": p}, tokens[:, :-1], train=True)
            losses = softmax_cross_entropy_loss(logits, tokens[:, 1:],
                                                smoothing=args.smoothing)
            return losses.mean()

    tele = _maybe_telemetry(args)
    init_fn, step_fn = amp.make_train_step(loss_fn, optimizer, policy,
                                           telemetry=tele is not None,
                                           accum_steps=args.accum_steps)
    state = init_fn(params)
    jit_step = jax.jit(step_fn, donate_argnums=(0,))

    data = None
    if args.data:
        data = load_token_stream(args.data, args.vocab_size, args.seq_len)

    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    print(f"=> LM {args.size}, params: {n_params:,}")

    state, start_it, rng = _maybe_resume(args, state, rng)
    t0 = None
    toks = 0
    metrics = None
    loss_history = []
    for it in range(start_it, args.iters):
        rng, sub = jax.random.split(rng)
        if args.deterministic:
            sub = jax.random.PRNGKey(it)
        if data is not None:
            batch = data_batch(data, sub, args.batch_size, args.seq_len)
        else:
            batch = synthetic_tokens(sub, args.batch_size, args.seq_len,
                                     args.vocab_size)
        # [B, S+1] → [N, B/N, S+1]: the microbatch scan axis of
        # make_train_step(accum_steps=N); identity at N=1
        batch = amp.to_microbatches(batch, args.accum_steps)
        state, metrics = jit_step(state, batch)
        loss_history.append(metrics["loss"])
        if it == start_it + 4:
            metrics["loss"].block_until_ready()
            t0 = time.perf_counter()
            toks = 0
        toks += args.batch_size * args.seq_len
        if it % 10 == 0 or it == args.iters - 1:
            print(f"[{it}/{args.iters}] loss {float(metrics['loss']):.4f} "
                  f"loss_scale {float(metrics['loss_scale']):g}")
    jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
    if t0 is not None and args.iters - start_it > 5:
        dt = time.perf_counter() - t0
        print(f"throughput: "
              f"{(toks - args.batch_size * args.seq_len) / dt:,.0f} tokens/s")
    if metrics is None:
        _finish_telemetry(tele)
        return None
    _maybe_prof_device(args, jit_step, state, batch)
    _maybe_save(args, state, rng)
    _maybe_generate(args, model, state.params, tele)
    _finish_telemetry(tele)
    metrics = dict(metrics)
    metrics["final_state"] = state
    # one device-to-host transfer for the whole history
    metrics["loss_history"] = np.asarray(jnp.stack(loss_history),
                                         np.float32).tolist()
    return metrics


if __name__ == "__main__":
    main()
