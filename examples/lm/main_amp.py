"""Transformer LM recipe — BASELINE.json config 3.

"FusedLayerNorm + FusedAdam transformer LM (WikiText-2)": a causal LM built
from the framework's fused tiers (apex_tpu.models.transformer_lm), trained
with apex_tpu.optimizers.fused_adam under an amp opt-level, LM loss via the
fused xentropy kernel. The reference has no in-repo LM recipe (it supplies
FusedAdam/FusedLayerNorm to external Megatron/DeepLearningExamples scripts);
this is the standalone equivalent, argument-shaped like examples/imagenet.

No network access: --synthetic generates token streams with a Zipfian
unigram distribution (WikiText-2-like vocab statistics); point --data at a
pre-tokenized .npy to train on real text.
"""

from __future__ import annotations

import os as _os
import sys as _sys

# run as a script from anywhere: put the repo root on sys.path (the reference
# relies on `pip install apex`; this repo is used in-tree)
_REPO_ROOT = _os.path.abspath(_os.path.join(_os.path.dirname(__file__),
                                            _os.pardir, _os.pardir))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
from apex_tpu.models.transformer_lm import create_lm
from apex_tpu.optimizers import fused_adam


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="apex_tpu transformer LM recipe")
    p.add_argument("--data", default=None,
                   help="pre-tokenized int32 .npy (else synthetic)")
    p.add_argument("--size", default="small",
                   choices=["tiny", "small", "medium", "gpt2"])
    p.add_argument("--vocab-size", type=int, default=32768)
    p.add_argument("-b", "--batch-size", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--weight-decay", type=float, default=0.01)
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--loss-scale", default="dynamic")
    p.add_argument("--smoothing", type=float, default=0.0,
                   help="label smoothing (fused xentropy kernel)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--deterministic", action="store_true")
    p.add_argument("--remat", action="store_true",
                   help="activation checkpointing per block (memory lever)")
    return p.parse_args(argv)


def synthetic_tokens(rng, batch, seq_len, vocab):
    """Zipf-ish unigram stream: token ranks follow 1/(r+10)."""
    ranks = jnp.arange(vocab, dtype=jnp.float32)
    logits = -jnp.log(ranks + 10.0)
    return jax.random.categorical(rng, logits, shape=(batch, seq_len + 1))


def main(argv=None):
    args = parse_args(argv)
    policy = amp.resolve_policy(opt_level=args.opt_level,
                                loss_scale=args.loss_scale)
    print(policy.banner())

    model = create_lm(args.size, vocab_size=args.vocab_size,
                      max_seq_len=args.seq_len, remat=args.remat,
                      dtype=policy.model_dtype)
    rng = jax.random.PRNGKey(args.seed)
    sample = jnp.zeros((2, args.seq_len), jnp.int32)
    params = model.init(rng, sample, train=False)["params"]

    optimizer = fused_adam(args.lr, weight_decay=args.weight_decay,
                           adam_w_mode=True)

    def loss_fn(p, batch):
        tokens = batch
        logits = model.apply({"params": p}, tokens[:, :-1], train=True)
        losses = softmax_cross_entropy_loss(logits, tokens[:, 1:],
                                            smoothing=args.smoothing)
        return losses.mean()

    init_fn, step_fn = amp.make_train_step(loss_fn, optimizer, policy)
    state = init_fn(params)
    jit_step = jax.jit(step_fn, donate_argnums=(0,))

    data = None
    if args.data:
        data = np.load(args.data)

    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    print(f"=> LM {args.size}, params: {n_params:,}")

    t0 = None
    toks = 0
    for it in range(args.iters):
        rng, sub = jax.random.split(rng)
        if args.deterministic:
            sub = jax.random.PRNGKey(it)
        if data is not None:
            idx = jax.random.randint(sub, (args.batch_size,), 0,
                                     len(data) - args.seq_len - 1)
            batch = jnp.stack([jnp.asarray(
                data[int(i):int(i) + args.seq_len + 1]) for i in idx])
        else:
            batch = synthetic_tokens(sub, args.batch_size, args.seq_len,
                                     args.vocab_size)
        state, metrics = jit_step(state, batch)
        if it == 4:
            metrics["loss"].block_until_ready()
            t0 = time.perf_counter()
            toks = 0
        toks += args.batch_size * args.seq_len
        if it % 10 == 0 or it == args.iters - 1:
            print(f"[{it}/{args.iters}] loss {float(metrics['loss']):.4f} "
                  f"loss_scale {float(metrics['loss_scale']):g}")
    jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
    if t0 is not None and args.iters > 5:
        dt = time.perf_counter() - t0
        print(f"throughput: "
              f"{(toks - args.batch_size * args.seq_len) / dt:,.0f} tokens/s")


if __name__ == "__main__":
    main()
