"""ImageNet recipe — the framework's canonical end-to-end example.

Mirrors the reference recipe (examples/imagenet/main_amp.py — main/train/
data_prefetcher/adjust_learning_rate/accuracy) argument-for-argument where it
makes sense on TPU:

- ``--arch``/``-b``/``--lr``/``--momentum``/``--weight-decay``/``--epochs``
- ``--opt-level O0..O3``, ``--loss-scale``, ``--keep-batchnorm-fp32``
- ``--sync_bn`` converts BatchNorm to SyncBatchNorm over the data axis
- ``--prof N`` profiles N iterations (jax.profiler trace instead of nvtx)
- ``--deterministic`` fixes seeds and data

TPU-first differences: no DistributedDataParallel wrapper object — data
parallelism is a mesh axis handed to amp.make_train_step(grad_average_axis=
"data") and batch sharding; no data_prefetcher side-stream — synthetic batches
are generated on device, and real input pipelines belong to grain/tf.data
outside this library's scope. Throughput is printed as img/s, the driver's
north-star unit.
"""

from __future__ import annotations

import os as _os
import sys as _sys

# run as a script from anywhere: put the repo root on sys.path (the reference
# relies on `pip install apex`; this repo is used in-tree)
_REPO_ROOT = _os.path.abspath(_os.path.join(_os.path.dirname(__file__),
                                            _os.pardir, _os.pardir))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

import argparse
import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu import amp
from apex_tpu.models import create_model
from apex_tpu.utils.compat import shard_map


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="apex_tpu ImageNet recipe")
    p.add_argument("data", nargs="?", default=None,
                   help="dataset path (unused for --synthetic, the default)")
    p.add_argument("--arch", "-a", default="resnet18")
    p.add_argument("-b", "--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--iters", type=int, default=50,
                   help="iterations per epoch for synthetic data")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--opt-level", default="O0")
    p.add_argument("--loss-scale", default=None)
    p.add_argument("--keep-batchnorm-fp32", default=None)
    p.add_argument("--sync_bn", action="store_true")
    p.add_argument("--prof-device", type=int, default=0, metavar="N",
                   help="after training, time N extra steps on the "
                        "profiler's DEVICE lanes and print device img/s "
                        "(observation-only — runs on a copy of the "
                        "state; n/a without device lanes)")
    p.add_argument("--prof", type=int, default=0)
    p.add_argument("--accum-steps", type=int, default=1, metavar="N",
                   help="in-jit microbatch gradient accumulation "
                        "(amp.make_train_step accum_steps): each optimizer "
                        "step scans N microbatches of batch-size/N, paying "
                        "ONE grad allreduce + unscale + scaler update per "
                        "window — apex's delay_unscale recipe, compiled. "
                        "Composes with --data-parallel (the microbatch "
                        "rows shard over the data mesh)")
    p.add_argument("--telemetry", default=None, metavar="SPEC",
                   help="stream per-step telemetry (loss, grad norm, "
                        "scaler trajectory, step time) from inside the "
                        "jitted step: JSONL path, 'stdout', or 'null'; "
                        "summarize with python -m apex_tpu.telemetry")
    p.add_argument("--deterministic", action="store_true")
    p.add_argument("--resume", default=None,
                   help="checkpoint file (or dir: newest ckpt) to resume")
    p.add_argument("--checkpoint-dir", default=None,
                   help="save ckpt_{epoch}.npz here after each epoch")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--synthetic", action="store_true", default=True)
    p.add_argument("--host-data", action="store_true",
                   help="generate batches on host and feed them through "
                        "data_prefetcher (exercises the real-data "
                        "host->device path with copy/compute overlap)")
    p.add_argument("--data-parallel", type=int, default=1,
                   help="size of the data mesh axis (devices)")
    return p.parse_args(argv)


def build_policy(args):
    overrides = {}
    if args.loss_scale is not None:
        overrides["loss_scale"] = (
            args.loss_scale if args.loss_scale == "dynamic"
            else float(args.loss_scale))
    if args.keep_batchnorm_fp32 is not None:
        overrides["keep_batchnorm_fp32"] = args.keep_batchnorm_fp32
    return amp.resolve_policy(opt_level=args.opt_level, **overrides)


def cross_entropy(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def topk_hits(logits, labels, ks=(1, 5)):
    """Per-batch top-k hit counts via one lax.top_k(max(ks)) — shared by
    training metrics and validate()."""
    kmax = min(max(ks), logits.shape[-1])
    _, top = jax.lax.top_k(logits, kmax)
    return [jnp.sum(jnp.any(top[:, :min(k, kmax)] == labels[:, None],
                            axis=1))
            for k in ks]


def topk_accuracy(logits, labels, ks=(1, 5)):
    """examples/imagenet/main_amp.py — accuracy(output, target, topk)."""
    n = labels.shape[0]
    return [100.0 * h.astype(jnp.float32) / n
            for h in topk_hits(logits, labels, ks)]


def adjust_learning_rate(base_lr, epoch, steps_per_epoch):
    """Step schedule of the reference recipe: /10 at epochs 30, 60, 80."""
    def schedule(count):
        ep = count // steps_per_epoch
        factor = ((ep >= 30).astype(jnp.float32) + (ep >= 60) + (ep >= 80))
        return base_lr * (0.1 ** factor)
    return schedule


def make_loss_fn(model):
    def loss_fn(params, model_state, batch):
        images, labels = batch
        outputs, mutated = model.apply(
            {"params": params, **model_state}, images, train=True,
            mutable=list(model_state.keys()) or False)
        loss = cross_entropy(outputs, labels)
        return loss, (mutated, outputs)
    return loss_fn


def make_eval_step(model):
    """Eval step (reference: main_amp.py — validate's inner loop): frozen
    batch stats, per-batch (top1 hits, top5 hits, summed loss, count)."""

    def eval_step(params, model_state, batch):
        images, labels = batch
        logits = model.apply({"params": params, **model_state}, images,
                             train=False)
        logits = jnp.asarray(logits, jnp.float32)
        hit1, hit5 = topk_hits(logits, labels)
        loss = cross_entropy(logits, labels) * labels.shape[0]
        return hit1, hit5, loss, labels.shape[0]

    return eval_step


def validate(jit_eval, state, batches, epoch=None, quiet=False):
    """Reference: main_amp.py — validate(val_loader, model, criterion):
    full pass over the held-out set, prints and returns (prec1, prec5).
    """
    h1 = h5 = n = 0
    loss_sum = 0.0
    for batch in batches:
        b1, b5, bl, bn = jit_eval(state.params, state.model_state, batch)
        h1 += int(b1)
        h5 += int(b5)
        loss_sum += float(bl)
        n += int(bn)
    prec1 = 100.0 * h1 / max(n, 1)
    prec5 = 100.0 * h5 / max(n, 1)
    if not quiet:
        tag = f"Epoch {epoch} " if epoch is not None else ""
        print(f"{tag}* Prec@1 {prec1:.3f} Prec@5 {prec5:.3f} "
              f"val-loss {loss_sum / max(n, 1):.4f}")
    return prec1, prec5


# ImageNet channel statistics (the reference's data_prefetcher normalizes
# with these on the GPU: main_amp.py — data_prefetcher mean/std)
_MEAN = np.array([0.485, 0.456, 0.406], np.float32) * 255.0
_STD = np.array([0.229, 0.224, 0.225], np.float32) * 255.0


def load_file_dataset(path):
    """File-backed dataset: ``path`` is an .npz (keys train_images,
    train_labels[, val_images, val_labels]) or a directory containing
    train.npz / val.npz with keys images, labels. Images are NHWC; uint8
    images are normalized with the ImageNet statistics (the prefetcher's
    job in the reference), float images are used as-is."""

    def norm(images):
        images = np.asarray(images)
        if images.dtype == np.uint8:
            return ((images.astype(np.float32) - _MEAN) / _STD)
        return images.astype(np.float32)

    splits = {}
    if os.path.isdir(path):
        for split in ("train", "val"):
            f = os.path.join(path, f"{split}.npz")
            if os.path.exists(f):
                with np.load(f) as z:
                    splits[split] = (norm(z["images"]),
                                     np.asarray(z["labels"], np.int32))
    else:
        with np.load(path) as z:
            for split in ("train", "val"):
                if f"{split}_images" in z:
                    splits[split] = (norm(z[f"{split}_images"]),
                                     np.asarray(z[f"{split}_labels"],
                                                np.int32))
    if "train" not in splits:
        raise SystemExit(f"=> no train split found under {path!r}")
    return splits


def file_batches(images, labels, batch_size, seed=None, drop_last=True):
    """Shuffled (seeded) host batches over a file-backed split."""
    n = images.shape[0]
    idx = np.arange(n)
    if seed is not None:
        np.random.RandomState(seed).shuffle(idx)
    stop = (n // batch_size) * batch_size if drop_last else n
    for i in range(0, stop, batch_size):
        take = idx[i:i + batch_size]
        yield images[take], labels[take]


def synthetic_batch(rng, batch_size, image_size, num_classes):
    images = jax.random.normal(
        rng, (batch_size, image_size, image_size, 3), jnp.float32)
    labels = jax.random.randint(rng, (batch_size,), 0, num_classes)
    return images, labels


class data_prefetcher:
    """Reference: main_amp.py — class data_prefetcher (side CUDA stream that
    uploads + normalizes the NEXT batch while the current step computes).

    TPU version: ``jax.device_put`` dispatches asynchronously, so issuing the
    next batch's transfer BEFORE blocking on the current step gives the same
    copy/compute overlap without any stream management. Wraps any iterator
    of host (numpy) batches; used for the --host-data path (real-data I/O
    shape), while the default synthetic path generates on device."""

    def __init__(self, loader, sharding=None):
        self.loader = iter(loader)
        self.sharding = sharding
        self._preload()

    def _put(self, batch):
        if self.sharding is not None:
            return jax.device_put(batch, self.sharding)
        return jax.device_put(batch)

    def _preload(self):
        try:
            self.next_batch = self._put(next(self.loader))
        except StopIteration:
            self.next_batch = None

    def next(self):
        batch = self.next_batch
        if batch is not None:
            self._preload()   # issue next transfer before caller blocks
        return batch

    def __iter__(self):
        while True:
            batch = self.next()
            if batch is None:
                return
            yield batch


def main(argv=None):
    args = parse_args(argv)
    if args.accum_steps < 1:
        raise SystemExit("--accum-steps must be >= 1")
    if args.batch_size % args.accum_steps:
        raise SystemExit(f"--batch-size {args.batch_size} must divide by "
                         f"--accum-steps {args.accum_steps}")
    if args.data_parallel > 1 and \
            (args.batch_size // args.accum_steps) % args.data_parallel:
        raise SystemExit(
            f"microbatch rows {args.batch_size // args.accum_steps} must "
            f"divide by --data-parallel {args.data_parallel}")
    policy = build_policy(args)
    print(policy.banner())

    norm_cls = None
    axis_name = None
    if args.data_parallel > 1:
        axis_name = "data"
    if args.sync_bn:
        from apex_tpu.parallel import SyncBatchNorm
        norm_cls = functools.partial(SyncBatchNorm, axis_name=axis_name)

    model = create_model(
        args.arch, num_classes=args.num_classes, dtype=policy.model_dtype,
        param_dtype=jnp.float32, norm_cls=norm_cls)

    rng = jax.random.PRNGKey(args.seed)
    sample = jnp.zeros((2, args.image_size, args.image_size, 3), jnp.float32)
    variables = model.init(rng, sample, train=True)
    model_state = {k: v for k, v in variables.items() if k != "params"}
    params = variables["params"]

    # dataset first: a file-backed dataset defines iters/epoch, which the
    # LR schedule's epoch-30/60/80 boundaries depend on (reference:
    # adjust_learning_rate is driven by the real loader length)
    dataset = load_file_dataset(args.data) if args.data else None
    if dataset is not None:
        n_train = dataset["train"][0].shape[0]
        args.iters = max(n_train // args.batch_size, 1)
        print(f"=> file dataset: {n_train} train images, "
              f"{args.iters} iters/epoch")

    steps_per_epoch = args.iters
    schedule = adjust_learning_rate(args.lr, 0, steps_per_epoch)
    optimizer = optax.chain(
        optax.add_decayed_weights(args.weight_decay),
        optax.sgd(schedule, momentum=args.momentum),
    )

    tele = None
    if args.telemetry:
        from apex_tpu import telemetry
        tele = telemetry.start_run(args.telemetry)

    init_fn, step_fn = amp.make_train_step(
        make_loss_fn(model), optimizer, policy, has_aux=True,
        with_model_state=True, grad_average_axis=axis_name,
        telemetry=tele is not None, accum_steps=args.accum_steps)
    state = init_fn(params, model_state)

    def to_microbatches(batch):
        """amp.to_microbatches bound to --accum-steps: the leading
        microbatch axis the step scans over (identity at N=1, so every
        data path below stays shape-stable)."""
        return amp.to_microbatches(batch, args.accum_steps)

    if axis_name is not None:
        from apex_tpu import comm
        mesh = comm.make_mesh({"data": args.data_parallel})
        from jax.sharding import NamedSharding, PartitionSpec as P
        # with accumulation the leading axis is the microbatch scan axis
        # (replicated); the data mesh shards the per-microbatch rows
        bspec = P("data") if args.accum_steps == 1 else P(None, "data")
        batch_sharding = (NamedSharding(mesh, bspec),
                          NamedSharding(mesh, bspec))
        replicated = NamedSharding(mesh, P())
        state = jax.device_put(state, replicated)
        jit_step = jax.jit(
            shard_map(
                step_fn, mesh=mesh,
                in_specs=(P(), (bspec, bspec)),
                out_specs=P(),
                check_vma=False))
    else:
        batch_sharding = None
        jit_step = jax.jit(step_fn)

    start_epoch = 0
    if args.resume:
        # reference: main_amp.py --resume (torch.load of model+optimizer+
        # epoch); here the whole AmpState round-trips through one file
        from apex_tpu.utils import latest_checkpoint, load_checkpoint
        path = args.resume
        if os.path.isdir(path):
            path = latest_checkpoint(path)
            if path is None:
                raise SystemExit(
                    f"=> no checkpoint found in {args.resume!r}")
        state, step, extra = load_checkpoint(path, state)
        start_epoch = extra.get("epoch", step)
        print(f"=> resumed from {path} (epoch {start_epoch})")

    print(f"=> model {args.arch}, params: "
          f"{sum(np.prod(p.shape) for p in jax.tree_util.tree_leaves(params)):,}")

    ckpt = None
    if args.checkpoint_dir:
        from apex_tpu.utils import AsyncCheckpointer
        os.makedirs(args.checkpoint_dir, exist_ok=True)
        ckpt = AsyncCheckpointer()
    def host_batches(epoch_seed, n):
        hrng = np.random.RandomState(epoch_seed)
        for _ in range(n):
            yield (hrng.randn(args.batch_size, args.image_size,
                              args.image_size, 3).astype(np.float32),
                   hrng.randint(0, args.num_classes,
                                size=(args.batch_size,)).astype(np.int32))

    # validation: the file dataset's val split when present, otherwise a
    # FIXED held-out synthetic set so top-1 is still a measured number
    jit_eval = jax.jit(make_eval_step(model))
    if dataset is not None and "val" in dataset:
        def val_batches():
            return file_batches(*dataset["val"], args.batch_size,
                                drop_last=False)
    else:
        _val = [synthetic_batch(jax.random.PRNGKey(10_000 + i),
                                args.batch_size, args.image_size,
                                args.num_classes)
                for i in range(4)]

        def val_batches():
            return iter(_val)

    best_prec1 = 0.0
    last_batch = None          # for --prof-device after the loops
    for epoch in range(start_epoch, args.epochs):
        t0 = None
        imgs = 0
        prefetcher = None
        if dataset is not None:
            # microbatch reshape happens on HOST, before the prefetcher's
            # device_put lays the batch out per batch_sharding
            prefetcher = data_prefetcher(
                map(to_microbatches,
                    file_batches(*dataset["train"], args.batch_size,
                                 seed=args.seed + epoch)),
                sharding=batch_sharding)
        elif args.host_data:
            prefetcher = data_prefetcher(
                map(to_microbatches,
                    host_batches(args.seed + epoch, args.iters)),
                sharding=batch_sharding)
        for it in range(args.iters):
            if prefetcher is not None:
                batch = prefetcher.next()
                if batch is None:
                    break
            else:
                rng, sub = jax.random.split(rng)
                if args.deterministic:
                    sub = jax.random.PRNGKey(it)
                batch = to_microbatches(
                    synthetic_batch(sub, args.batch_size,
                                    args.image_size, args.num_classes))
                if batch_sharding is not None:
                    batch = jax.device_put(batch, batch_sharding)
            if args.prof and it == 5:
                jax.profiler.start_trace("/tmp/apex_tpu_trace")
            last_batch = batch
            state, metrics = jit_step(state, batch)
            if args.prof and it == 5 + args.prof:
                metrics["loss"].block_until_ready()
                jax.profiler.stop_trace()
            if it == 4:  # skip compile + warmup, like the reference's prof skip
                metrics["loss"].block_until_ready()
                t0 = time.perf_counter()
                imgs = 0
            imgs += args.batch_size
            if it % 10 == 0 or it == args.iters - 1:
                loss = float(metrics["loss"])
                scale = float(metrics["loss_scale"])
                print(f"Epoch {epoch} [{it}/{args.iters}] "
                      f"loss {loss:.4f} loss_scale {scale:g}")
        jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
        if t0 is not None and args.iters > 5:
            dt = time.perf_counter() - t0
            print(f"Epoch {epoch}: {(imgs - args.batch_size) / dt:.1f} img/s")
        # validation pass each epoch (reference: prec1 = validate(...);
        # best_prec1 tracked for the checkpoint's is_best flag)
        prec1, _ = validate(jit_eval, state, val_batches(), epoch=epoch)
        best_prec1 = max(best_prec1, prec1)
        if ckpt is not None:
            path = os.path.join(args.checkpoint_dir,
                                f"ckpt_{epoch + 1}.npz")
            ckpt.save(path, state, step=epoch + 1,
                      extra={"epoch": epoch + 1, "best_prec1": best_prec1})
            print(f"=> saved {path}")
    if ckpt is not None:
        ckpt.wait()
    if args.prof_device:
        # shared observation-only rendering (copied state, never raises).
        # A zero-iteration run (--epochs 0, or a resume already at the
        # epoch limit) never bound a batch — report n/a, don't crash.
        from apex_tpu import pyprof

        if last_batch is None:
            print("device throughput: n/a (no training step ran)")
        else:
            line = pyprof.device_throughput_line(
                jit_step, state, last_batch, args.prof_device,
                args.batch_size, "img/s")
            if line:
                print(line)
    if tele is not None:
        jax.effects_barrier()      # flush in-flight step callbacks
        tele.emit_snapshot()       # final aggregate + comm-health line
        tele.close()
    print(f"=> best Prec@1 {best_prec1:.3f}")
    return state


if __name__ == "__main__":
    main()
