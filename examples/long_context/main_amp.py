"""Long-context LM recipe — context-parallel ring attention end to end.

The reference has no long-context distribution (SURVEY §6: Megatron-SP is
its only sequence-scaling mechanism); this recipe shows the framework's
beyond-parity answer: a causal LM whose SEQUENCE is sharded over a
``context`` mesh axis, attention computed exactly with
:func:`apex_tpu.transformer.context_parallel.ring_attention` (KV rotating
around the ring via ppermute, zigzag layout balancing the causal work),
composed with amp mixed precision and the fused LN/xentropy kernels.

Every rank holds seq_len/ring_size tokens: the attention memory AND the
activation memory per chip stay flat as sequence length scales with the
ring — the point of context parallelism.

Run hermetically (8 virtual devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context/main_amp.py --ring 4 --seq-len 2048
"""

from __future__ import annotations

import os as _os
import sys as _sys

_REPO_ROOT = _os.path.abspath(_os.path.join(_os.path.dirname(__file__),
                                            _os.pardir, _os.pardir))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import flax.linen as nn

from apex_tpu import amp, comm
from apex_tpu.utils.compat import shard_map
from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.transformer.context_parallel import (ring_attention,
                                                   ulysses_attention,
                                                   zigzag_order)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="context-parallel LM recipe")
    p.add_argument("--ring", type=int, default=4,
                   help="context-axis size (ring width)")
    p.add_argument("--seq-len", type=int, default=2048,
                   help="GLOBAL sequence length (local = seq/ring)")
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("-b", "--batch-size", type=int, default=2)
    p.add_argument("--data-parallel", type=int, default=1, metavar="DP",
                   help="DDP over a 'data' axis composed OUTSIDE the "
                        "context ring (mesh [data, context]; grads "
                        "averaged over both axes)")
    p.add_argument("--iters", type=int, default=8)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--layout", default="zigzag",
                   choices=["zigzag", "contiguous"])
    p.add_argument("--attn", default="ring", choices=["ring", "ulysses"],
                   help="ring: KV rotates via ppermute; ulysses: "
                        "all-to-all head scatter (needs heads %% ring == 0)")
    p.add_argument("--data", default=None,
                   help="pre-tokenized int32 .npy token stream — the "
                        "fixed training batch becomes real long-context "
                        "windows instead of uniform noise")
    return p.parse_args(argv)


class RingBlock(nn.Module):
    """Pre-LN block whose attention runs over the context ring. Must be
    applied inside shard_map with the 'context' axis bound; x is the LOCAL
    sequence shard [B, s_local, H]."""

    hidden: int
    heads: int
    layout: str
    attn: str = "ring"
    # policy.model_dtype from the recipe: half under O2/O3, None under O1
    # (the autocast engine's per-op table decides), fp32 under O0.
    dtype: object = None

    @nn.compact
    def __call__(self, x):
        from apex_tpu.amp.autocast import resolve_dtype

        dtype = resolve_dtype(self.dtype, "linear", jnp.float32)
        B, S, H = x.shape
        d = self.hidden // self.heads
        h = FusedLayerNorm(normalized_shape=H, name="ln_attn")(x)
        qkv = nn.Dense(3 * H, dtype=dtype, name="qkv")(h)
        qkv = qkv.reshape(B, S, 3, self.heads, d)
        q, k, v = (jnp.moveaxis(qkv[:, :, i], 1, 2) for i in range(3))
        if self.attn == "ulysses":
            out = ulysses_attention(q, k, v, causal=True)
        else:
            out = ring_attention(q, k, v, causal=True, layout=self.layout)
        out = jnp.moveaxis(out, 1, 2).reshape(B, S, H)
        x = x + nn.Dense(H, dtype=dtype, name="proj")(out)
        h = FusedLayerNorm(normalized_shape=H, name="ln_mlp")(x)
        h = nn.Dense(4 * H, dtype=dtype, name="mlp_in")(h)
        h = nn.gelu(jnp.asarray(h, jnp.float32), approximate=True)
        h = nn.Dense(H, dtype=dtype, name="mlp_out")(
            jnp.asarray(h, dtype))
        return x + h


class RingLM(nn.Module):
    vocab: int
    hidden: int
    layers: int
    heads: int
    max_seq: int
    layout: str
    attn: str = "ring"
    dtype: object = None  # threaded into every RingBlock

    @nn.compact
    def __call__(self, tokens, positions):
        """tokens/positions: LOCAL shards [B, s_local] (positions carry the
        zigzag permutation so embeddings match the attention layout)."""
        wte = nn.Embed(self.vocab, self.hidden, name="wte")
        wpe = self.param("wpe", nn.initializers.normal(stddev=0.02),
                         (self.max_seq, self.hidden), jnp.float32)
        x = wte(tokens) + wpe[positions]
        for i in range(self.layers):
            x = RingBlock(self.hidden, self.heads, self.layout, self.attn,
                          dtype=self.dtype, name=f"block_{i}")(x)
        x = FusedLayerNorm(normalized_shape=self.hidden, name="ln_f")(x)
        return wte.attend(jnp.asarray(x, jnp.float32))


def main(argv=None):
    args = parse_args(argv)
    policy = amp.resolve_policy(opt_level=args.opt_level)
    dp = args.data_parallel
    if dp < 1:
        raise SystemExit(f"--data-parallel must be >= 1, got {dp}")
    if args.batch_size % dp:
        raise SystemExit(f"--batch-size {args.batch_size} must divide by "
                         f"--data-parallel {dp}")
    devices = comm.ensure_devices(dp * args.ring)
    mesh = Mesh(np.array(devices[:dp * args.ring]).reshape(dp, args.ring),
                ("data", "context"))
    comm.set_mesh(mesh)
    S, n = args.seq_len, args.ring
    if args.attn == "ulysses":
        # ulysses permutes heads, not the sequence: contiguous layout only
        args.layout = "contiguous"
        if args.heads % n:
            raise SystemExit(f"--attn ulysses needs heads % ring == 0 "
                             f"({args.heads} % {n})")
    chunk = 2 * n if args.layout == "zigzag" else n
    if S % chunk:
        raise SystemExit(f"--seq-len must divide by {chunk} "
                         f"({args.layout} chunks over a ring of {n})")
    model = RingLM(args.vocab, args.hidden, args.layers, args.heads,
                   max_seq=S, layout=args.layout, attn=args.attn,
                   dtype=policy.model_dtype)

    # zigzag layout: permute the GLOBAL sequence once on the host; each
    # rank then owns balanced front+back chunks of the causal triangle
    order = (np.asarray(zigzag_order(S, n)) if args.layout == "zigzag"
             else np.arange(S))
    positions = jnp.asarray(order)[None].repeat(args.batch_size, 0)

    rng = np.random.RandomState(0)
    if args.data:
        # real windows from a token stream (the LM recipe's validated
        # loader — out-of-vocab ids rejected, not clamped); targets are
        # the TRUE next tokens, though position S-1 stays masked below
        # so both data sources train the identical objective
        from examples.lm.main_amp import load_token_stream
        stream = load_token_stream(args.data, args.vocab, S)
        starts = rng.randint(0, len(stream) - S, size=args.batch_size)
        win = np.stack([stream[st:st + S + 1] for st in starts])
        tokens_global = win[:, :S].astype(np.int32)
        targets_global = win[:, 1:].astype(np.int32)
    else:
        tokens_global = rng.randint(
            0, args.vocab, size=(args.batch_size, S)).astype(np.int32)
        # next-token targets in GLOBAL order, permuted like the inputs
        targets_global = np.roll(tokens_global, -1, axis=1)
    tokens = jnp.asarray(tokens_global[:, order])
    targets = jnp.asarray(targets_global[:, order])

    def loss_fn(params, batch):
        toks, tgts, pos = batch
        logits = model.apply({"params": params}, toks, pos)
        losses = softmax_cross_entropy_loss(
            logits.reshape(-1, args.vocab), tgts.reshape(-1))
        # mask the final global position (no next token); its zigzag slot
        # lives wherever position == S-1. Per-rank valid counts are
        # UNEQUAL (one rank owns S-1), so normalize by the psum'd GLOBAL
        # count — a mean of per-rank means would over-weight that rank's
        # tokens. The ring-size factor makes grad_average_axis's pmean
        # recover exactly the global-mean gradient.
        valid = (pos.reshape(-1) != S - 1)
        local_sum = jnp.sum(jnp.where(valid, losses, 0.0))
        global_cnt = jax.lax.psum(jnp.sum(valid), "context")
        ring = jax.lax.psum(1, "context")
        return ring * local_sum / global_cnt

    from apex_tpu.optimizers.fused_adam import fused_adam

    # grad_average_axis: params are REPLICATED over the ring while each
    # rank's loss covers only its sequence shard — grads must be averaged
    # over the context axis (Megatron-SP's grad allreduce for sequence-
    # parallel regions) or every rank trains on a different objective
    # the average spans BOTH axes (make_train_step accepts axis tuples):
    # mean over per-data-shard means, each shard's mean already exact over
    # its ring (the reference DDP objective); at dp=1 the data axis has
    # size 1 and the extra pmean is the identity
    init_fn, step_fn = amp.make_train_step(
        loss_fn, fused_adam(args.lr), policy,
        grad_average_axis=("data", "context"))

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), (P("data", "context"),
                                       P("data", "context"),
                                       P("data", "context"))),
                       out_specs=(P(), P()), check_vma=False)
    def sharded_step(state, batch):
        new_state, metrics = step_fn(state, batch)
        return new_state, metrics["loss"]

    # init under shard_map: ring_attention traces collectives, so the
    # context axis must be bound even at init (params come out identical
    # on every rank — same key, rank-independent shapes)
    s_local = S // n

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P("data", "context"), P("data", "context")),
                       out_specs=P(), check_vma=False)
    def init_params(toks, pos):
        return model.init(jax.random.PRNGKey(0), toks, pos)["params"]

    params = init_params(tokens, positions)
    n_params = sum(np.prod(p.shape)
                   for p in jax.tree_util.tree_leaves(params))
    print(f"=> ring={n} dp={dp} layout={args.layout} global seq {S} "
          f"(local {s_local}), params {n_params:,}")
    state = jax.device_put(init_fn(params), NamedSharding(mesh, P()))
    sharding = NamedSharding(mesh, P("data", "context"))
    batch = tuple(jax.device_put(t, sharding)
                  for t in (tokens, targets, positions))

    jit_step = jax.jit(sharded_step)
    t0 = None
    for it in range(args.iters):
        state, loss = jit_step(state, batch)
        if it == 0:
            jax.block_until_ready(loss)
            t0 = time.perf_counter()
        print(f"[{it}] loss {float(loss):.4f}")
    jax.block_until_ready(loss)
    if args.iters > 1:
        dt = time.perf_counter() - t0
        tok_s = args.batch_size * S * (args.iters - 1) / dt
        kind = args.attn if args.attn == "ulysses" else args.layout
        print(f"=> {tok_s:.0f} tokens/s ({kind} ring of {n})")
    return float(loss)


if __name__ == "__main__":
    main()
