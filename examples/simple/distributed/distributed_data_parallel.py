"""Minimal data-parallel example — reference:
examples/simple/distributed/distributed_data_parallel.py (+ run.sh).

The reference spawns one process per GPU (torch.distributed.launch), wraps a
one-layer model in apex.parallel.DistributedDataParallel, and checks grads
average across ranks. The TPU version needs no launcher: a
``jax.sharding.Mesh`` over however many devices exist (real chips, or
virtual CPU devices via ``--xla_force_host_platform_device_count``), the
batch sharded along the ``data`` axis, and one psum inside the jitted step.

Run it anywhere:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/simple/distributed/distributed_data_parallel.py
"""

import os as _os
import sys as _sys

_REPO_ROOT = _os.path.abspath(_os.path.join(_os.path.dirname(__file__),
                                            _os.pardir, _os.pardir,
                                            _os.pardir))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp, comm


def main():
    n = len(jax.devices())
    mesh = comm.make_mesh({"data": n})
    print(f"=> {n} devices, mesh axes {mesh.axis_names}")

    # the reference's toy model: Linear(4096, 2048) -> relu -> Linear(2048, 10)
    def model(params, x):
        h = jax.nn.relu(x @ params["w1"])
        return h @ params["w2"]

    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(4096, 2048).astype(np.float32) * 0.01),
        "w2": jnp.asarray(rng.randn(2048, 10).astype(np.float32) * 0.01),
    }

    def loss_fn(p, batch):
        x, y = batch
        logits = model(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            jnp.asarray(logits, jnp.float32), y).mean()

    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic")
    init_fn, step_fn = amp.make_train_step(
        loss_fn, optax.sgd(0.1), policy, grad_average_axis="data")
    state = init_fn(params)

    jit_step = jax.jit(jax.shard_map(
        step_fn, mesh=mesh, in_specs=(P(), (P("data"), P("data"))),
        out_specs=P(), check_vma=False))

    state = jax.device_put(state, NamedSharding(mesh, P()))
    for it in range(10):
        x = jnp.asarray(rng.randn(8 * n, 4096).astype(np.float32))
        y = jnp.asarray(rng.randint(0, 10, size=(8 * n,)))
        batch = jax.device_put(
            (x, y), (NamedSharding(mesh, P("data")),
                     NamedSharding(mesh, P("data"))))
        state, metrics = jit_step(state, batch)
        print(f"[{it}] loss {float(metrics['loss']):.4f}")
    print("final loss_scale:", float(state.scaler.loss_scale))


if __name__ == "__main__":
    main()
