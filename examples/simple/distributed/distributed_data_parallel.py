"""Minimal data-parallel example — reference:
examples/simple/distributed/distributed_data_parallel.py (+ run.sh).

The reference spawns one process per GPU (torch.distributed.launch), wraps a
one-layer model in apex.parallel.DistributedDataParallel, and checks grads
average across ranks. The TPU version needs no launcher: a
``jax.sharding.Mesh`` over however many devices exist (real chips, or
virtual CPU devices via ``--xla_force_host_platform_device_count``), the
batch sharded along the ``data`` axis, and one psum inside the jitted step.

Run it anywhere:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/simple/distributed/distributed_data_parallel.py
"""

import os as _os
import sys as _sys

_REPO_ROOT = _os.path.abspath(_os.path.join(_os.path.dirname(__file__),
                                            _os.pardir, _os.pardir,
                                            _os.pardir))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp, comm
from apex_tpu.utils.compat import shard_map


def manual_ddp_loop(mesh, n, model, params, iters=10):
    """The reference's ACTUAL recipe shape: wrap the model in
    DistributedDataParallel, then hand-write the iteration — scaled loss →
    backward → ddp.reduce_gradients → unscale/found_inf → cond-skip step →
    update_scale (examples/simple/distributed/distributed_data_parallel.py +
    the amp README manual loop). Deliberately self-contained (an example
    users copy); tests/distributed/test_ddp_facade.py asserts the same
    recipe shape hermetically. Returns the final params for the parity
    check against make_train_step."""
    from apex_tpu.parallel import DistributedDataParallel
    from apex_tpu.amp import init_scaler, unscale, update_scale
    from apex_tpu.amp.scaler import scale_loss as scale_loss_fn

    ddp = DistributedDataParallel(module=model, axis_name="data",
                                  gradient_predivide_factor=2.0)
    tx = optax.sgd(0.1)
    opt_state = tx.init(params)
    scaler = init_scaler("dynamic")

    def step(params, opt_state, scaler, batch):
        def scaled(p):
            x, y = batch
            logits = ddp(p, x)  # forward through the DDP wrapper
            loss = optax.softmax_cross_entropy_with_integer_labels(
                jnp.asarray(logits, jnp.float32), y).mean()
            return scale_loss_fn(loss, scaler), loss

        grads, loss = jax.grad(scaled, has_aux=True)(params)
        grads = ddp.reduce_gradients(grads)     # the facade under test
        grads, found_inf = unscale(grads, scaler, jnp.float32)

        def do(_):
            upd, new_opt = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, upd), new_opt

        def skip(_):
            return params, opt_state

        params2, opt2 = jax.lax.cond(found_inf, skip, do, operand=None)
        return params2, opt2, update_scale(scaler, found_inf), \
            jax.lax.pmean(loss, "data")

    jit_step = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(), (P("data"), P("data"))),
        out_specs=(P(), P(), P(), P()), check_vma=False))

    rng = np.random.RandomState(0)
    for it in range(iters):
        x = jnp.asarray(rng.randn(8 * n, 4096).astype(np.float32))
        y = jnp.asarray(rng.randint(0, 10, size=(8 * n,)))
        batch = jax.device_put(
            (x, y), (NamedSharding(mesh, P("data")),
                     NamedSharding(mesh, P("data"))))
        params, opt_state, scaler, loss = jit_step(params, opt_state,
                                                   scaler, batch)
        print(f"[manual {it}] loss {float(loss):.4f}")
    return params


def main():
    n = len(jax.devices())
    mesh = comm.make_mesh({"data": n})
    print(f"=> {n} devices, mesh axes {mesh.axis_names}")

    # the reference's toy model: Linear(4096, 2048) -> relu -> Linear(2048, 10)
    def model(params, x):
        h = jax.nn.relu(x @ params["w1"])
        return h @ params["w2"]

    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(4096, 2048).astype(np.float32) * 0.01),
        "w2": jnp.asarray(rng.randn(2048, 10).astype(np.float32) * 0.01),
    }

    def loss_fn(p, batch):
        x, y = batch
        logits = model(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            jnp.asarray(logits, jnp.float32), y).mean()

    policy = amp.resolve_policy(opt_level="O2", loss_scale="dynamic")
    init_fn, step_fn = amp.make_train_step(
        loss_fn, optax.sgd(0.1), policy, grad_average_axis="data")
    state = init_fn(params)

    jit_step = jax.jit(shard_map(
        step_fn, mesh=mesh, in_specs=(P(), (P("data"), P("data"))),
        out_specs=P(), check_vma=False))

    state = jax.device_put(state, NamedSharding(mesh, P()))
    for it in range(10):
        x = jnp.asarray(rng.randn(8 * n, 4096).astype(np.float32))
        y = jnp.asarray(rng.randint(0, 10, size=(8 * n,)))
        batch = jax.device_put(
            (x, y), (NamedSharding(mesh, P("data")),
                     NamedSharding(mesh, P("data"))))
        state, metrics = jit_step(state, batch)
        print(f"[{it}] loss {float(metrics['loss']):.4f}")
    print("final loss_scale:", float(state.scaler.loss_scale))

    # same batches through the manual DDP-wrapper loop (O0-equivalent math:
    # fp32 model + dynamic scaler): must land on the same weights as an
    # O0 make_train_step run — proving the facade, not just the builder
    policy0 = amp.resolve_policy(opt_level="O0", loss_scale="dynamic")
    init0, step0 = amp.make_train_step(loss_fn, optax.sgd(0.1), policy0,
                                       grad_average_axis="data")
    jit0 = jax.jit(shard_map(
        step0, mesh=mesh, in_specs=(P(), (P("data"), P("data"))),
        out_specs=P(), check_vma=False))
    rng0 = np.random.RandomState(0)
    st0 = jax.device_put(init0(params), NamedSharding(mesh, P()))
    for it in range(10):
        x = jnp.asarray(rng0.randn(8 * n, 4096).astype(np.float32))
        y = jnp.asarray(rng0.randint(0, 10, size=(8 * n,)))
        batch = jax.device_put(
            (x, y), (NamedSharding(mesh, P("data")),
                     NamedSharding(mesh, P("data"))))
        st0, _ = jit0(st0, batch)

    manual = manual_ddp_loop(mesh, n, model, params, iters=10)
    for k in params:
        np.testing.assert_allclose(np.asarray(manual[k]),
                                   np.asarray(st0.params[k]),
                                   rtol=1e-5, atol=1e-6)
    print("manual DDP-facade loop == make_train_step: parity OK")


if __name__ == "__main__":
    main()
