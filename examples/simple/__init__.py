"""apex_tpu examples (regular package so in-repo imports beat any site-packages \"examples\" distribution)."""
