"""Detectron-style SyncBN training shape (BASELINE config 5).

Reference context: the driver's config 5 is "SyncBatchNorm multi-chip
(Detectron-style Mask R-CNN)". The training characteristics that make that
workload exercise apex are: tiny per-chip batches (2 images) where
BatchNorm statistics are meaningless without cross-chip sync, a conv-heavy
FPN backbone, multi-scale feature maps, and amp+DDP composition. This
example reproduces exactly those characteristics — an FPN over a strided
conv backbone with SyncBatchNorm at every norm site, a dense per-pixel
head (the mask-head training shape), amp O0–O3, and DDP over a `data`
mesh axis — without dragging in box/ROI machinery that exercises nothing
apex-related.

Run (single chip):    python examples/detection/main_amp.py --iters 8
Hermetic multi-chip:  JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/detection/main_amp.py --data-parallel 8 --iters 4
"""

from __future__ import annotations

import argparse
import functools
import os as _os
import sys as _sys
import time
from typing import Any

# run as a script from anywhere: put the repo root on sys.path
_REPO_ROOT = _os.path.abspath(
    _os.path.join(_os.path.dirname(__file__), "..", ".."))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

import jax
import jax.numpy as jnp
import numpy as np
import optax
import flax.linen as nn

from apex_tpu import amp
from apex_tpu.parallel import SyncBatchNorm
from apex_tpu.utils.compat import shard_map


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--opt-level", default="O2")
    p.add_argument("-b", "--batch-size", type=int, default=2,
                   help="per-chip batch (detection-typical: 2)")
    p.add_argument("--image-size", type=int, default=256)
    p.add_argument("--num-classes", type=int, default=21)
    p.add_argument("--iters", type=int, default=20,
                   help="training iterations (>= 1)")
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--data-parallel", type=int, default=1)
    p.add_argument("--no-sync-bn", action="store_true",
                   help="plain BatchNorm (shows why SyncBN matters at b=2)")
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args(argv)


class ConvStage(nn.Module):
    """Two 3x3 convs + norm + relu, downsampling by 2 (a bottleneck-stage
    stand-in: conv-heavy, norm at every site like Detectron backbones)."""

    features: int
    norm: Any

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(self.features, (3, 3), strides=(2, 2), use_bias=False,
                    dtype=x.dtype)(x)
        x = self.norm()(x, use_running_average=not train)
        x = nn.relu(x)
        y = nn.Conv(self.features, (3, 3), use_bias=False, dtype=x.dtype)(x)
        y = self.norm()(y, use_running_average=not train)
        return nn.relu(x + y)                    # residual


class FPNSegModel(nn.Module):
    """FPN backbone + dense per-pixel head (the mask-head training shape)."""

    num_classes: int
    norm: Any
    dtype: Any = jnp.float32
    widths: tuple = (32, 64, 128, 256)           # C2..C5
    fpn_width: int = 64

    @nn.compact
    def __call__(self, images, train: bool = True):
        x = jnp.asarray(images, self.dtype)
        feats = []
        for w in self.widths:
            x = ConvStage(w, self.norm)(x, train)
            feats.append(x)                       # strides 2, 4, 8, 16

        # FPN: lateral 1x1 + top-down upsample-add, smoothing 3x3
        laterals = [nn.Conv(self.fpn_width, (1, 1), dtype=self.dtype)(f)
                    for f in feats]
        p = laterals[-1]
        pyramid = [p]
        for lat in laterals[-2::-1]:
            b, h, w_, c = lat.shape
            p = jax.image.resize(p, (b, h, w_, c), "nearest") + lat
            pyramid.append(p)
        pyramid = [nn.Conv(self.fpn_width, (3, 3), dtype=self.dtype)(t)
                   for t in pyramid[::-1]]        # P2..P5 (fine→coarse)

        # dense head on the finest level (mask-head shape: convs + norm)
        h = pyramid[0]
        for _ in range(2):
            h = nn.Conv(self.fpn_width, (3, 3), use_bias=False,
                        dtype=self.dtype)(h)
            h = self.norm()(h, use_running_average=not train)
            h = nn.relu(h)
        logits = nn.Conv(self.num_classes, (1, 1), dtype=jnp.float32)(h)
        # upsample to input resolution (per-pixel supervision)
        b, hh, ww, c = logits.shape
        full = images.shape[1]
        return jax.image.resize(logits, (b, full, full, c), "nearest")


def main(argv=None):
    args = parse_args(argv)
    if args.iters < 1:
        raise SystemExit("--iters must be >= 1")
    if args.data_parallel > 1:
        from apex_tpu import comm as _comm
        _comm.ensure_devices(args.data_parallel)
    policy = amp.resolve_policy(opt_level=args.opt_level,
                                loss_scale="dynamic")
    print(policy.banner())

    axis_name = "data" if args.data_parallel > 1 else None
    bn_axis = None if args.no_sync_bn else axis_name
    norm = functools.partial(SyncBatchNorm, axis_name=bn_axis,
                             dtype=jnp.float32)

    model = FPNSegModel(num_classes=args.num_classes, norm=norm,
                        dtype=policy.model_dtype)
    rng = jax.random.PRNGKey(args.seed)
    sample = jnp.zeros((1, args.image_size, args.image_size, 3),
                       jnp.float32)
    variables = model.init(rng, sample, train=True)
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}

    def loss_fn(p, mstate, batch):
        images, labels = batch
        logits, updated = model.apply(
            {"params": p, **mstate}, images, train=True,
            mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            jnp.asarray(logits, jnp.float32), labels).mean()
        return loss, updated

    optimizer = optax.sgd(args.lr, momentum=0.9)
    init_fn, step_fn = amp.make_train_step(
        loss_fn, optimizer, policy, with_model_state=True,
        grad_average_axis=axis_name)
    state = init_fn(params, model_state)

    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    print(f"=> FPN-seg model, params: {n_params:,}, "
          f"sync_bn={'off' if args.no_sync_bn else 'on'}")

    if axis_name is not None:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from apex_tpu import comm
        mesh = comm.make_mesh({"data": args.data_parallel})
        state = jax.device_put(state, NamedSharding(mesh, P()))
        jit_step = jax.jit(shard_map(
            step_fn, mesh=mesh,
            in_specs=(P(), (P("data"), P("data"))),
            out_specs=P(), check_vma=False))
        global_batch = args.batch_size * args.data_parallel
        batch_sharding = (NamedSharding(mesh, P("data")),
                          NamedSharding(mesh, P("data")))
    else:
        jit_step = jax.jit(step_fn)
        global_batch = args.batch_size
        batch_sharding = None

    t0 = None
    for it in range(args.iters):
        key = jax.random.PRNGKey(1000 + it)
        images = jax.random.normal(
            key, (global_batch, args.image_size, args.image_size, 3),
            jnp.float32)
        labels = jax.random.randint(
            jax.random.fold_in(key, 1),
            (global_batch, args.image_size, args.image_size), 0,
            args.num_classes)
        batch = (images, labels)
        if batch_sharding is not None:
            batch = jax.device_put(batch, batch_sharding)
        state, metrics = jit_step(state, batch)
        if it == 1:
            metrics["loss"].block_until_ready()
            t0 = time.perf_counter()
            done = 0
        if it >= 2:
            done = it - 1
        if it % 5 == 0 or it == args.iters - 1:
            print(f"[{it}/{args.iters}] loss {float(metrics['loss']):.4f} "
                  f"loss_scale {float(state.scaler.loss_scale):.0f}")
    metrics["loss"].block_until_ready()
    if t0 is not None and done > 0:
        rate = done * global_batch / (time.perf_counter() - t0)
        print(f"=> {rate:.1f} img/s (global batch {global_batch})")


if __name__ == "__main__":
    main()
