"""DCGAN amp example — reference: examples/dcgan/main_amp.py.

The reference adapts pytorch/examples DCGAN to apex amp with TWO models and
TWO optimizers sharing loss scalers (its README calls out the
``amp.initialize([netD, netG], [optD, optG], num_losses=3)`` pattern). The
TPU version keeps that structure: one amp policy, separate AmpStates for D
and G, three logical losses (errD_real, errD_fake, errG), synthetic data.

Run:  python examples/dcgan/main_amp.py --iters 20 --opt-level O2
"""

import os as _os
import sys as _sys

_REPO_ROOT = _os.path.abspath(_os.path.join(_os.path.dirname(__file__),
                                            _os.pardir, _os.pardir))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

import argparse
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu import amp


class Generator(nn.Module):
    """DCGAN G: project + 3 transposed convs (reference netG, trimmed)."""
    feat: int = 32

    @nn.compact
    def __call__(self, z):
        x = nn.Dense(4 * 4 * self.feat * 4)(z)
        x = x.reshape(z.shape[0], 4, 4, self.feat * 4)
        for mult in (2, 1):
            x = nn.ConvTranspose(self.feat * mult, (4, 4), strides=(2, 2),
                                 padding="SAME")(x)
            x = nn.GroupNorm(num_groups=8)(x)
            x = nn.relu(x)
        x = nn.ConvTranspose(3, (4, 4), strides=(2, 2), padding="SAME")(x)
        return jnp.tanh(x)  # 32x32x3


class Discriminator(nn.Module):
    """DCGAN D: 3 strided convs + head (reference netD, trimmed)."""
    feat: int = 32

    @nn.compact
    def __call__(self, x):
        for mult in (1, 2, 4):
            x = nn.Conv(self.feat * mult, (4, 4), strides=(2, 2),
                        padding="SAME")(x)
            x = nn.leaky_relu(x, 0.2)
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(1)(x)[:, 0]


def bce_logits(logits, target):
    logits = jnp.asarray(logits, jnp.float32)
    return optax.sigmoid_binary_cross_entropy(
        logits, jnp.full_like(logits, target)).mean()


def main(argv=None):
    p = argparse.ArgumentParser(description="apex_tpu DCGAN amp example")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("-b", "--batch-size", type=int, default=32)
    p.add_argument("--nz", type=int, default=64)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--loss-scale", default="dynamic")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    policy = amp.resolve_policy(opt_level=args.opt_level,
                                loss_scale=args.loss_scale)
    print(policy.banner())

    netG, netD = Generator(), Discriminator()
    rng = jax.random.PRNGKey(args.seed)
    kG, kD, rng = jax.random.split(rng, 3)
    z0 = jnp.zeros((2, args.nz))
    x0 = jnp.zeros((2, 32, 32, 3))
    paramsG = netG.init(kG, z0)["params"]
    paramsD = netD.init(kD, x0)["params"]

    adam = optax.adam(args.lr, b1=0.5, b2=0.999)

    # D step: real + fake losses (the reference's errD_real/errD_fake are
    # loss ids 0 and 1 of num_losses=3)
    def lossD(pD, batch):
        real, fake = batch
        errD_real = bce_logits(netD.apply({"params": pD}, real), 1.0)
        errD_fake = bce_logits(netD.apply({"params": pD}, fake), 0.0)
        return errD_real + errD_fake

    # G step: fool D through frozen D params (loss id 2)
    def lossG(pG, batch):
        z, pD = batch
        fake = netG.apply({"params": pG}, z)
        return bce_logits(netD.apply({"params": pD}, fake), 1.0)

    initD, stepD = amp.make_train_step(lossD, adam, policy)
    initG, stepG = amp.make_train_step(lossG, adam, policy)
    stateD, stateG = initD(paramsD), initG(paramsG)
    jitD = jax.jit(stepD)
    jitG = jax.jit(stepG)
    jit_gen = jax.jit(lambda pG, z: netG.apply({"params": pG}, z))

    t0 = None
    for it in range(args.iters):
        rng, kz, kx = jax.random.split(rng, 3)
        real = jax.random.uniform(kx, (args.batch_size, 32, 32, 3),
                                  minval=-1.0, maxval=1.0)
        z = jax.random.normal(kz, (args.batch_size, args.nz))
        fake = jit_gen(policy.cast_params(amp.master_params(stateG)), z)
        stateD, mD = jitD(stateD, (real, jax.lax.stop_gradient(fake)))
        stateG, mG = jitG(
            stateG, (z, policy.cast_params(amp.master_params(stateD))))
        if it == 2:
            mG["loss"].block_until_ready()
            t0 = time.perf_counter()
        if it % 5 == 0 or it == args.iters - 1:
            print(f"[{it}/{args.iters}] loss_D {float(mD['loss']):.4f} "
                  f"loss_G {float(mG['loss']):.4f} "
                  f"scale {float(mD['loss_scale']):g}")
    if t0 is not None and args.iters > 3:
        dt = time.perf_counter() - t0
        print(f"{(args.iters - 3) * args.batch_size / dt:.1f} img/s")


if __name__ == "__main__":
    main()
