"""BERT pretraining recipe — BASELINE.json config 4.

"BERT-large pretraining with FusedLAMB + amp O2": the apex-powered NVIDIA
DeepLearningExamples BERT recipe (run_pretraining.py — apex.optimizers.
FusedLAMB + amp + fused kernels), rebuilt standalone on the framework's own
tiers: apex_tpu.models.bert (flash-attention encoder, FusedLayerNorm),
apex_tpu.optimizers.fused_lamb (NVLAMB trust-ratio update), MLM+NSP loss via
the fused xentropy kernel, amp O2 master weights + dynamic loss scaling.

LAMB exists for exactly this workload: 64k-batch phase-1 pretraining (You et
al. 2019). The recipe keeps DeepLearningExamples' argument names
(--train_batch_size, --max_seq_length, --max_predictions_per_seq,
--warmup_proportion) and the poly-decay warmup schedule.

Data: ``--data shards.npz`` loads pre-tokenized examples carrying the
DeepLearningExamples hdf5-shard fields (input_ids, token_type_ids,
attention_mask, masked_lm_positions, masked_lm_ids,
next_sentence_labels); without it, synthetic batches with the same
schema (no network in this environment).
"""

from __future__ import annotations

import os as _os
import sys as _sys

# run as a script from anywhere: put the repo root on sys.path (the reference
# relies on `pip install apex`; this repo is used in-tree)
_REPO_ROOT = _os.path.abspath(_os.path.join(_os.path.dirname(__file__),
                                            _os.pardir, _os.pardir))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu import amp
from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
from apex_tpu.models.bert import BertForPreTraining, create_bert
from apex_tpu.optimizers import fused_lamb


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="apex_tpu BERT-LAMB pretraining")
    p.add_argument("--bert-model", default="tiny",
                   choices=["tiny", "base", "large"])
    p.add_argument("--train_batch_size", type=int, default=8)
    p.add_argument("--max_seq_length", type=int, default=128)
    p.add_argument("--max_predictions_per_seq", type=int, default=20)
    p.add_argument("--learning_rate", type=float, default=6e-3)
    p.add_argument("--warmup_proportion", type=float, default=0.2843)
    p.add_argument("--max_steps", type=int, default=30)
    p.add_argument("--prof-device", type=int, default=0, metavar="N",
                   help="after training, time N extra steps on the "
                        "profiler's DEVICE lanes and print device "
                        "sequences/s (observation-only — runs on a copy "
                        "of the state; n/a without device lanes)")
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--loss-scale", default="dynamic")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--data-parallel", type=int, default=1, metavar="N",
                   help="DDP over an N-way 'data' mesh axis (LAMB update "
                        "on psum-averaged grads — the reference's "
                        "multi-GPU BERT-LAMB shape)")
    p.add_argument("--data", default=None,
                   help="pre-tokenized .npz with the BERT input schema "
                        "(input_ids, token_type_ids, attention_mask, "
                        "masked_lm_positions, masked_lm_ids, "
                        "next_sentence_labels) — the DeepLearningExamples "
                        "hdf5 shards' fields; synthetic batches otherwise")
    p.add_argument("--max_position_embeddings", type=int, default=None,
                   help="position-table size (default: max_seq_length). "
                        "Set 512 in BOTH phases for the reference's "
                        "phase1(seq128)→phase2(seq512) workflow, or "
                        "--init-checkpoint cannot carry the weights over")
    p.add_argument("--total_steps", type=int, default=None,
                   help="length of the lr schedule (default: max_steps). "
                        "Set it to the FULL run length when saving an "
                        "interrupted run (--max_steps < --total_steps), "
                        "so the resumed run continues the same schedule "
                        "— DeepLearningExamples' max_steps vs "
                        "steps_this_run split")
    p.add_argument("--save", default=None, metavar="CKPT",
                   help="write the final train state + step to this .npz")
    p.add_argument("--resume", default=None, metavar="CKPT",
                   help="restore a --save checkpoint (full state) and "
                        "continue the same phase")
    p.add_argument("--accum-steps", type=int, default=1, metavar="N",
                   help="in-jit microbatch gradient accumulation "
                        "(amp.make_train_step accum_steps): each LAMB "
                        "step scans N microbatches of batch-size/N, "
                        "paying ONE grad allreduce + unscale + scaler "
                        "update per window — the reference recipe's "
                        "gradient_accumulation_steps, compiled. Composes "
                        "with --data-parallel")
    p.add_argument("--telemetry", default=None, metavar="SPEC",
                   help="stream per-step telemetry (loss, grad norm, "
                        "scaler trajectory, step time) from inside the "
                        "jitted step: JSONL path, 'stdout', or 'null'; "
                        "summarize with python -m apex_tpu.telemetry")
    p.add_argument("--init-checkpoint", default=None, metavar="CKPT",
                   help="DeepLearningExamples --init_checkpoint: load "
                        "ONLY the model params from a --save checkpoint; "
                        "masters re-derived, optimizer and schedule start "
                        "fresh (the phase1→phase2 handoff). Run both "
                        "phases with the same --bert-model, "
                        "--max_position_embeddings, and --opt-level")
    return p.parse_args(argv)


_DATA_KEYS = ("input_ids", "token_type_ids", "attention_mask",
              "masked_lm_positions", "masked_lm_ids",
              "next_sentence_labels")


def _check_id_range(name, arr, hi_exclusive, what):
    """One rule for every id field: out-of-range ids would be CLAMPED by
    XLA's gather under jit — silently wrong training, not a crash — so
    they are rejected at load."""
    lo, hi = int(arr.min()), int(arr.max())
    if lo < 0 or hi >= hi_exclusive:
        raise SystemExit(
            f"--data {name} span [{lo}, {hi}]; {what} (jit would clamp "
            "the gather silently)")


def load_pretokenized(path, seq_len, n_pred, vocab_size=None):
    """Load + validate a pre-tokenized .npz against the run's shapes and
    (when given) the model's vocab — every id class jit's gathers would
    otherwise clamp silently is rejected here."""
    with np.load(path) as z:
        missing = [k for k in _DATA_KEYS if k not in z]
        if missing:
            raise SystemExit(f"--data {path!r} is missing fields "
                             f"{missing}; need {list(_DATA_KEYS)}")
        data = {k: np.asarray(z[k]) for k in _DATA_KEYS}
    if data["input_ids"].shape[1] != seq_len:
        raise SystemExit(
            f"--data sequences are {data['input_ids'].shape[1]} long; "
            f"--max_seq_length is {seq_len}")
    if data["masked_lm_positions"].shape[1] != n_pred:
        raise SystemExit(
            f"--data has {data['masked_lm_positions'].shape[1]} "
            f"prediction slots; --max_predictions_per_seq is {n_pred}")
    counts = {k: len(v) for k, v in data.items()}
    if len(set(counts.values())) != 1:
        raise SystemExit(f"--data fields disagree on example count: "
                         f"{counts}")
    if len(data["input_ids"]) == 0:
        raise SystemExit(f"--data {path!r} holds zero examples")
    _check_id_range("masked_lm_positions", data["masked_lm_positions"],
                    seq_len, f"sequences are {seq_len} long")
    _check_id_range("token_type_ids", data["token_type_ids"], 2,
                    "BERT has 2 segment embeddings")
    _check_id_range("next_sentence_labels", data["next_sentence_labels"],
                    2, "NSP is binary")
    for k in ("input_ids", "masked_lm_ids"):
        if vocab_size is not None:
            _check_id_range(k, data[k], vocab_size,
                            f"the vocab is {vocab_size}")
        elif int(data[k].min()) < 0:   # negatives rejected regardless
            raise SystemExit(f"--data {k} holds negative ids (jit would "
                             "clamp the gather silently)")
    return data


def synthetic_bert_batch(rng, batch, seq_len, n_pred, vocab):
    ks = jax.random.split(rng, 5)
    input_ids = jax.random.randint(ks[0], (batch, seq_len), 0, vocab)
    lengths = jax.random.randint(ks[1], (batch,), seq_len // 2, seq_len + 1)
    attention_mask = (jnp.arange(seq_len)[None] < lengths[:, None]) \
        .astype(jnp.int32)
    token_type_ids = (jnp.arange(seq_len)[None] >=
                      (lengths // 2)[:, None]).astype(jnp.int32)
    masked_lm_positions = jax.random.randint(ks[2], (batch, n_pred), 0,
                                             seq_len // 2)
    masked_lm_ids = jax.random.randint(ks[3], (batch, n_pred), 1, vocab)
    next_sentence_labels = jax.random.randint(ks[4], (batch,), 0, 2)
    return (input_ids, token_type_ids, attention_mask, masked_lm_positions,
            masked_lm_ids, next_sentence_labels)


def make_schedule(lr, max_steps, warmup_proportion):
    """DeepLearningExamples PolyWarmUpScheduler: linear warmup, poly decay."""
    warmup = max(1, int(max_steps * warmup_proportion))
    return optax.join_schedules(
        [optax.linear_schedule(0.0, lr, warmup),
         optax.polynomial_schedule(lr, 0.0, power=1.0,
                                   transition_steps=max_steps - warmup)],
        [warmup])


def _phase_handoff_params(path, init_fn, params):
    """DeepLearningExamples phase1→phase2 handoff: carry the MODEL over
    (fp32 masters preferred), restart optimizer + schedule. The position
    table must be sized identically in both phases
    (--max_position_embeddings 512 there) or shapes won't match. Scoped
    in a helper so the restored phase-1 state (params + masters + both
    LAMB moments — ~4x model size) frees as soon as params are copied
    out."""
    from apex_tpu.utils.checkpoint import load_checkpoint
    # abstract template: shapes/dtypes for validation without
    # materializing a throwaway full train state
    restored, from_step, _ = load_checkpoint(
        path, jax.eval_shape(init_fn, params))
    src = amp.master_params(restored)
    out = jax.tree_util.tree_map(lambda m, p: jnp.asarray(m, p.dtype),
                                 src, params)
    print(f"=> initialized model from {path} "
          f"(phase handoff at step {from_step}; fresh optimizer)")
    return out


def main(argv=None):
    args = parse_args(argv)
    if args.max_steps < 1:
        raise SystemExit("--max_steps must be >= 1")
    if args.train_batch_size % max(args.data_parallel, 1):
        raise SystemExit(f"--train_batch_size {args.train_batch_size} "
                         f"must divide by --data-parallel "
                         f"{args.data_parallel}")
    if args.accum_steps < 1:
        raise SystemExit("--accum-steps must be >= 1")
    if args.train_batch_size % (args.accum_steps
                                * max(args.data_parallel, 1)):
        raise SystemExit(
            f"--train_batch_size {args.train_batch_size} must divide by "
            f"--accum-steps x --data-parallel "
            f"({args.accum_steps} x {max(args.data_parallel, 1)})")
    if args.resume and args.init_checkpoint:
        raise SystemExit("--resume (continue the phase) and "
                         "--init-checkpoint (fresh phase from saved "
                         "params) are exclusive")
    if args.data_parallel > 1:
        # before ANY arrays exist: ensure_devices may switch backends
        # (virtual CPU fallback) and refuses once state is live
        from apex_tpu import comm
        comm.ensure_devices(args.data_parallel)
    policy = amp.resolve_policy(opt_level=args.opt_level,
                                loss_scale=args.loss_scale)
    print(policy.banner())

    cfg = create_bert(args.bert_model,
                      max_position_embeddings=(
                          args.max_position_embeddings
                          or args.max_seq_length))
    if args.max_seq_length > cfg.max_position_embeddings:
        raise SystemExit(
            f"--max_seq_length {args.max_seq_length} exceeds the "
            f"position table ({cfg.max_position_embeddings}); raise "
            "--max_position_embeddings")
    model = BertForPreTraining(cfg, dtype=policy.model_dtype)
    rng = jax.random.PRNGKey(args.seed)
    b0 = synthetic_bert_batch(rng, 2, args.max_seq_length,
                              args.max_predictions_per_seq, cfg.vocab_size)
    params = model.init(rng, *b0[:4], train=False)["params"]

    if args.total_steps is not None and args.total_steps < args.max_steps:
        raise SystemExit(
            f"--total_steps {args.total_steps} < --max_steps "
            f"{args.max_steps}: the schedule would pin lr to 0 past "
            "total_steps (swapped flags?)")
    schedule = make_schedule(args.learning_rate,
                             args.total_steps or args.max_steps,
                             args.warmup_proportion)
    optimizer = fused_lamb(schedule, weight_decay=0.01)

    def loss_fn(p, batch):
        (input_ids, token_type_ids, attention_mask, mlm_pos, mlm_ids,
         nsp_labels, dropout_rng) = batch
        mlm_logits, nsp_logits = model.apply(
            {"params": p}, input_ids, token_type_ids, attention_mask,
            mlm_pos, train=True, rngs={"dropout": dropout_rng})
        # masked positions with id 0 are padding of the prediction slots
        # (DeepLearningExamples masks them out of the mean)
        mlm_losses = softmax_cross_entropy_loss(mlm_logits, mlm_ids)
        valid = (mlm_ids != 0).astype(jnp.float32)
        mlm_loss = jnp.sum(mlm_losses * valid) / jnp.maximum(
            jnp.sum(valid), 1.0)
        nsp_loss = softmax_cross_entropy_loss(nsp_logits, nsp_labels).mean()
        return mlm_loss + nsp_loss

    tele = None
    if args.telemetry:
        from apex_tpu import telemetry
        tele = telemetry.start_run(args.telemetry)

    dp = args.data_parallel
    init_fn, step_fn = amp.make_train_step(
        loss_fn, optimizer, policy,
        grad_average_axis="data" if dp > 1 else None,
        telemetry=tele is not None, accum_steps=args.accum_steps)

    def to_microbatches(batch):
        """amp.to_microbatches on the ARRAY leaves; the dropout key stays
        scalar — it is split into per-microbatch keys inside the step,
        after any per-rank fold."""
        if args.accum_steps == 1:
            return batch
        *arrays, drop = batch
        return amp.to_microbatches(tuple(arrays),
                                   args.accum_steps) + (drop,)
    start_it = 0
    if args.init_checkpoint:
        params = _phase_handoff_params(args.init_checkpoint, init_fn,
                                       params)
    state = init_fn(params)
    if args.resume:
        from apex_tpu.utils.checkpoint import resume_train_checkpoint
        state, start_it, rng = resume_train_checkpoint(
            args.resume, state, rng, step_limit=args.max_steps,
            limit_flag="--max_steps")
    if dp > 1:
        # reference shape: apex DDP over the batch + FusedLAMB — here one
        # grad psum over the 'data' axis (examples/imagenet's pattern);
        # the dropout rng is folded per-rank so masks differ across shards
        from apex_tpu.utils.compat import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu import comm

        devices = comm.ensure_devices(dp)
        mesh = Mesh(np.array(devices[:dp]), ("data",))

        def sharded_step(state, batch):
            *arrays, drop = batch
            drop = jax.random.fold_in(drop, jax.lax.axis_index("data"))
            if args.accum_steps > 1:
                drop = jax.random.split(drop, args.accum_steps)
            return step_fn(state, tuple(arrays) + (drop,))

        # with accumulation the leading axis is the microbatch scan axis
        # (replicated); the data mesh shards the per-microbatch rows
        bspec = P("data") if args.accum_steps == 1 else P(None, "data")
        jit_step = jax.jit(shard_map(
            sharded_step, mesh=mesh,
            in_specs=(P(), (bspec,) * 6 + (P(),)),
            out_specs=(P(), P()), check_vma=False),
            donate_argnums=(0,))
        ctx = mesh
    else:
        import contextlib
        if args.accum_steps > 1:
            def local_step(state, batch):
                *arrays, drop = batch
                drop = jax.random.split(drop, args.accum_steps)
                return step_fn(state, tuple(arrays) + (drop,))
            jit_step = jax.jit(local_step, donate_argnums=(0,))
        else:
            jit_step = jax.jit(step_fn, donate_argnums=(0,))
        ctx = contextlib.nullcontext()

    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    print(f"=> BERT-{args.bert_model} dp={dp}, params: {n_params:,}")

    data = None
    if args.data:
        data = load_pretokenized(args.data, args.max_seq_length,
                                 args.max_predictions_per_seq,
                                 vocab_size=cfg.vocab_size)
        print(f"=> {len(data['input_ids'])} pre-tokenized examples "
              f"from {args.data}")

    t0 = None
    seqs = 0
    metrics = None
    loss_history = []
    with ctx:
        for it in range(start_it, args.max_steps):
            rng, sub = jax.random.split(rng)
            sub, drop = jax.random.split(sub)
            if data is not None:
                idx = np.asarray(jax.random.randint(
                    sub, (args.train_batch_size,), 0,
                    len(data["input_ids"])))
                batch = tuple(jnp.asarray(data[k][idx])
                              for k in _DATA_KEYS) + (drop,)
            else:
                batch = synthetic_bert_batch(sub, args.train_batch_size,
                                             args.max_seq_length,
                                             args.max_predictions_per_seq,
                                             cfg.vocab_size) + (drop,)
            batch = to_microbatches(batch)
            state, metrics = jit_step(state, batch)
            loss_history.append(metrics["loss"])
            if it == start_it + 4:
                metrics["loss"].block_until_ready()
                t0 = time.perf_counter()
                seqs = 0
            seqs += args.train_batch_size
            if it % 10 == 0 or it == args.max_steps - 1:
                print(f"[{it}/{args.max_steps}] loss "
                      f"{float(metrics['loss']):.4f} "
                      f"loss_scale {float(metrics['loss_scale']):g}")
    jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
    if tele is not None:
        jax.effects_barrier()      # flush in-flight step callbacks
        tele.emit_snapshot()       # final aggregate + comm-health line
        tele.close()
    if t0 is not None and args.max_steps - start_it > 5:
        dt = time.perf_counter() - t0
        print(f"throughput: "
              f"{(seqs - args.train_batch_size) / dt:,.1f} sequences/s")
    if metrics is None:
        return None
    if args.prof_device:
        # shared observation-only rendering (copied state, never raises)
        from apex_tpu import pyprof

        line = pyprof.device_throughput_line(
            jit_step, state, batch, args.prof_device,
            args.train_batch_size, "sequences/s")
        if line:
            print(line)
    if args.save:
        from apex_tpu.utils.checkpoint import save_train_checkpoint
        save_train_checkpoint(args.save, state, args.max_steps, rng)
    metrics = dict(metrics)
    # one device-to-host transfer for the whole history, not one per step
    metrics["loss_history"] = np.asarray(jnp.stack(loss_history),
                                         np.float32).tolist()
    return metrics


if __name__ == "__main__":
    main()
