"""Serving benchmark: continuous-batching decode throughput (tokens/s).

Exercises the full ``apex_tpu.serving`` stack — compiled chunk-prefill +
decode-step programs over a bf16 slot KV cache, continuous-batching
scheduler — on a stream of synthetic variable-length requests, and
prints ONE final JSON line::

  {"metric": "serving_decode_tokens_per_sec", "value": N,
   "unit": "tokens/s", ...}

Methodology matches bench.py: a warmup window (compiles the programs;
discarded), then >= BENCH_SERVING_WINDOWS measured windows reported as
median + min + spread so one line carries its own noise bars. The line
also carries the latency layer: time-to-first-token p50/p95/p99 — now
decomposed into queue-wait and prefill-chunk compute — and per-decode-
step p50/p95/p99 from the telemetry registry's streaming histograms,
plus mean slot occupancy / padding waste.

``--mixed-prompts`` runs the head-of-line-blocking leg the chunked
prefill exists for: an interleaved short/long prompt stream served
twice — chunked (the default scheduler) vs monolithic
(``chunked=False``, the PR 3 baseline) — emitting one row JSON line per
mode and a final line whose payoff fields are per-class TTFT p50/p99
(``ttft_short_p99_ms`` chunked vs monolithic) and aggregate tokens/s.
Both modes serve greedy streams, so the leg also asserts token-identical
outputs — the chunked path must win on latency without moving a single
token.

Regime note: the chunked win presumes silicon's cost model, where a
``[slots, 1]`` decode step is far cheaper than a monolithic
``[1, prefill_len]`` prefill — then interleaving bounds the stall at
one chunk for near-free throughput. On the CPU fallback the reference
decode path attends the FULL cache per slot, inverting the ratio
(decode is the priciest program), so the staggered admission's extra
partial-occupancy decode steps read as a throughput loss there: CPU
rows of this leg are a correctness/plumbing signal, the perf claim is
the TPU rows'. ``BENCH_SERVING_CHUNK_BUDGET`` (default 1) trades the
per-tick stall bound against admission throughput (Sarathi's
token-budget knob).

Wrapped in ``guard_bench_main`` — EVERY outcome (backend init failure,
OOM, bad env) still ends in a parseable JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

METRIC = "serving_decode_tokens_per_sec"
MIXED_METRIC = "serving_mixed_prompts_tokens_per_sec"

SIZE = os.environ.get("BENCH_SERVING_SIZE", "small")
VOCAB = int(os.environ.get("BENCH_SERVING_VOCAB", "32768"))
SLOTS = int(os.environ.get("BENCH_SERVING_SLOTS", "8"))
MAX_LEN = int(os.environ.get("BENCH_SERVING_MAX_LEN", "512"))
PREFILL_LEN = int(os.environ.get("BENCH_SERVING_PREFILL", "128"))
CHUNK_LEN = int(os.environ.get("BENCH_SERVING_CHUNK", "0"))  # 0 = default
REQUESTS = int(os.environ.get("BENCH_SERVING_REQUESTS", "24"))
NEW_TOKENS = int(os.environ.get("BENCH_SERVING_NEW_TOKENS", "64"))
WINDOWS = int(os.environ.get("BENCH_SERVING_WINDOWS", "3"))
TOP_K = int(os.environ.get("BENCH_SERVING_TOP_K", "0"))
SHORT_LEN = int(os.environ.get("BENCH_SERVING_SHORT", "16"))
CHUNK_BUDGET = int(os.environ.get("BENCH_SERVING_CHUNK_BUDGET", "1"))


def _median(xs):
    xs = sorted(xs)
    mid = len(xs) // 2
    return xs[mid] if len(xs) % 2 else 0.5 * (xs[mid - 1] + xs[mid])


def _requests(rng):
    from apex_tpu.serving import Request

    reqs = []
    for _ in range(REQUESTS):
        n = int(rng.integers(1, PREFILL_LEN + 1))
        budget = max(1, min(NEW_TOKENS, MAX_LEN - n))
        reqs.append(Request(
            prompt=rng.integers(1, VOCAB, size=n).tolist(),
            max_new_tokens=budget))
    return reqs


def _mixed_requests(rng):
    """Interleaved short/long arrivals — the stream where monolithic
    prefill's head-of-line blocking shows: every short prompt queued
    behind a long one pays the long one's full prefill."""
    from apex_tpu.serving import Request

    reqs = []
    for i in range(REQUESTS):
        if i % 2 == 0:
            n = int(rng.integers(1, max(2, SHORT_LEN + 1)))
        else:
            n = int(rng.integers(max(1, PREFILL_LEN // 2),
                                 PREFILL_LEN + 1))
        budget = max(1, min(NEW_TOKENS, MAX_LEN - n))
        reqs.append(Request(
            prompt=rng.integers(1, VOCAB, size=n).tolist(),
            max_new_tokens=budget))
    return reqs


def _build_engine(registry=None):
    import jax
    import jax.numpy as jnp

    from apex_tpu import serving
    from apex_tpu.models.transformer_lm import create_lm

    model = create_lm(SIZE, vocab_size=VOCAB, max_seq_len=MAX_LEN)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    return serving.Engine(model, params, slots=SLOTS, max_len=MAX_LEN,
                          prefill_len=PREFILL_LEN,
                          chunk_len=CHUNK_LEN or None, top_k=TOP_K,
                          registry=registry)


def main():
    import jax

    from apex_tpu import serving, telemetry

    tele = telemetry.from_env()     # APEX_TPU_TELEMETRY streams per-run
    reg = tele if tele is not None else telemetry.MetricsRegistry()

    engine = _build_engine()

    rng = np.random.default_rng(0)
    rates = []
    for w in range(WINDOWS + 1):          # window 0 = compile warmup
        engine.reset()
        if w == 1:
            # attach telemetry only after warmup: first-trace compile
            # latency must not poison the TTFT/step histograms
            engine.set_registry(reg)
        sched = serving.Scheduler(engine, max_queue=max(REQUESTS, 1),
                                  registry=reg if w else None)
        t0 = time.perf_counter()
        tok0 = engine.tokens_generated
        done = sched.run(_requests(rng))
        dt = time.perf_counter() - t0
        toks = engine.tokens_generated - tok0
        assert len(done) == REQUESTS
        if w > 0:
            rates.append(toks / dt)

    snap = reg.snapshot()
    ttft = snap["histograms"].get("serving.ttft_s", {})
    qwait = snap["histograms"].get("serving.queue_wait_s", {})
    chunk = snap["histograms"].get("serving.prefill_chunk_s", {})
    step = snap["histograms"].get("serving.decode.step_s", {})
    occ = snap["histograms"].get("serving.slot_occupancy", {})
    value = _median(rates)
    spread = (max(rates) - min(rates)) / value * 100.0 if value else 0.0
    print(json.dumps({
        "metric": METRIC,
        "value": round(value, 2),
        "unit": "tokens/s",
        "min": round(min(rates), 2),
        "spread_pct": round(spread, 1),
        "windows": WINDOWS,
        "compiled_programs": engine.compiled_programs,
        "model": SIZE,
        "slots": SLOTS,
        "max_len": MAX_LEN,
        "prefill_len": PREFILL_LEN,
        "chunk_len": engine.chunk_len,
        "requests_per_window": REQUESTS,
        "cache_dtype": np.dtype(engine.cache.dtype).name,
        "cache_mib": round(engine.cache.nbytes() / 2**20, 2),
        "ttft_p50_ms": round(ttft.get("p50", 0.0) * 1e3, 3),
        "ttft_p95_ms": round(ttft.get("p95", 0.0) * 1e3, 3),
        "ttft_p99_ms": round(ttft.get("p99", 0.0) * 1e3, 3),
        "queue_wait_p99_ms": round(qwait.get("p99", 0.0) * 1e3, 3),
        "prefill_chunk_p50_ms": round(chunk.get("p50", 0.0) * 1e3, 3),
        "prefill_chunk_p99_ms": round(chunk.get("p99", 0.0) * 1e3, 3),
        "decode_step_p50_ms": round(step.get("p50", 0.0) * 1e3, 3),
        "decode_step_p95_ms": round(step.get("p95", 0.0) * 1e3, 3),
        "decode_step_p99_ms": round(step.get("p99", 0.0) * 1e3, 3),
        "slot_occupancy_mean": round(occ.get("mean", 0.0), 3),
        "padding_waste_mean": round(1.0 - occ.get("mean", 0.0), 3),
        "backend": jax.default_backend(),
    }))
    if tele is not None:
        tele.emit_snapshot()
        tele.close()


def _serve_mixed(chunked: bool):
    """Serve WINDOWS measured windows (plus compile warmup) of the mixed
    stream in one mode; returns (median tokens/s, per-request rows)."""
    from apex_tpu import serving, telemetry

    reg = telemetry.MetricsRegistry()
    engine = _build_engine()
    rng = np.random.default_rng(1)
    rates, all_reqs = [], []
    for w in range(WINDOWS + 1):
        engine.reset()
        if w == 1:
            engine.set_registry(reg)
        sched = serving.Scheduler(engine, max_queue=max(REQUESTS, 1),
                                  registry=reg if w else None,
                                  chunked=chunked,
                                  chunk_budget=CHUNK_BUDGET)
        reqs = _mixed_requests(rng)
        t0 = time.perf_counter()
        tok0 = engine.tokens_generated
        done = sched.run(reqs)
        dt = time.perf_counter() - t0
        toks = engine.tokens_generated - tok0
        assert len(done) == REQUESTS
        if w > 0:
            rates.append(toks / dt)
            all_reqs.extend(reqs)
    return _median(rates), all_reqs, engine


def _ttft_percentiles(reqs, short: bool):
    sel = [r.ttft_s for r in reqs
           if (len(r.prompt) <= SHORT_LEN) == short and r.ttft_s]
    if not sel:
        return 0.0, 0.0
    return (float(np.percentile(sel, 50)) * 1e3,
            float(np.percentile(sel, 99)) * 1e3)


def main_mixed():
    import jax

    rows = {}
    outputs = {}
    for mode, chunked in (("monolithic", False), ("chunked", True)):
        rate, reqs, engine = _serve_mixed(chunked)
        s50, s99 = _ttft_percentiles(reqs, short=True)
        l50, l99 = _ttft_percentiles(reqs, short=False)
        chunks = [r.chunks for r in reqs]
        rows[mode] = {
            "metric": f"{MIXED_METRIC}.{mode}",
            "value": round(rate, 2),
            "unit": "tokens/s",
            "ttft_short_p50_ms": round(s50, 3),
            "ttft_short_p99_ms": round(s99, 3),
            "ttft_long_p50_ms": round(l50, 3),
            "ttft_long_p99_ms": round(l99, 3),
            "chunks_per_prompt_mean": round(float(np.mean(chunks)), 2),
            "chunks_per_prompt_max": int(np.max(chunks)),
            "compiled_programs": engine.compiled_programs,
            "chunk_len": engine.chunk_len,
            "chunk_budget": CHUNK_BUDGET,
        }
        print(json.dumps(rows[mode]))
        # all-greedy stream: per-window request order is deterministic,
        # so both modes should emit identical token streams
        outputs[mode] = [list(r.output_tokens) for r in reqs]
    # reported, not asserted: at the default bf16 policy the two modes'
    # first tokens come from two separately-fused programs, so a
    # near-tie argmax can legitimately flip a low bit — that is a
    # numerics observation, not a broken serving stack (the O0 bitwise
    # pin lives in tests/L0/test_serving.py). Zero is the expected
    # reading on every backend we have measured.
    mismatches = sum(a != b for a, b in zip(outputs["chunked"],
                                            outputs["monolithic"]))
    mono, chk = rows["monolithic"], rows["chunked"]
    imp = (mono["ttft_short_p99_ms"] - chk["ttft_short_p99_ms"]) \
        / mono["ttft_short_p99_ms"] * 100.0 if mono["ttft_short_p99_ms"] \
        else 0.0
    print(json.dumps({
        "metric": MIXED_METRIC,
        "value": chk["value"],
        "unit": "tokens/s",
        "baseline_tokens_per_s": mono["value"],
        "throughput_vs_monolithic_pct": round(
            (chk["value"] - mono["value"]) / mono["value"] * 100.0, 1)
        if mono["value"] else 0.0,
        "ttft_short_p99_ms": chk["ttft_short_p99_ms"],
        "ttft_short_p99_ms_monolithic": mono["ttft_short_p99_ms"],
        "ttft_short_p99_improvement_pct": round(imp, 1),
        "ttft_long_p99_ms": chk["ttft_long_p99_ms"],
        "ttft_long_p99_ms_monolithic": mono["ttft_long_p99_ms"],
        "token_exact_vs_monolithic": mismatches == 0,
        "token_mismatched_requests": mismatches,
        "windows": WINDOWS,
        "requests_per_window": REQUESTS,
        "short_len_max": SHORT_LEN,
        "prefill_len": PREFILL_LEN,
        "chunk_len": chk["chunk_len"],
        "slots": SLOTS,
        "model": SIZE,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    from apex_tpu.telemetry import guard_bench_main

    if "--mixed-prompts" in sys.argv[1:]:
        guard_bench_main(main_mixed, MIXED_METRIC)
    else:
        guard_bench_main(main, METRIC)
